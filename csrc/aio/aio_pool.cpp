// Async file I/O thread pool — the NVMe swap engine's host op.
//
// Reference: csrc/aio/py_lib/deepspeed_aio_thread.cpp + py_aio_handle
// (libaio O_DIRECT request queues behind AsyncIOBuilder, powering
// ZeRO-Infinity's tensor swapping). This implementation uses a
// portable pthread pool over pread/pwrite — same asynchronous
// submit/drain contract, no libaio/liburing dependency — with
// O_DIRECT optionally enabled by the caller.
//
// Contract (mirrors the reference handle):
//   aio_open(path, nbytes, n_threads) -> handle (file created/sized)
//   aio_submit_read / aio_submit_write(handle, buf, nbytes, offset)
//     enqueue and return immediately; caller must keep buf alive
//   aio_wait_all(handle) -> 0 on success, -errno of the first failure
//   aio_close(handle)

#include <atomic>
#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct IoTask {
  bool write;
  void* buf;
  int64_t nbytes;
  int64_t offset;
};

struct AioHandle {
  int fd = -1;
  std::vector<std::thread> workers;
  std::deque<IoTask> queue;
  std::mutex mu;
  std::condition_variable cv_task;
  std::condition_variable cv_done;
  int64_t inflight = 0;
  std::atomic<int> first_error{0};
  bool stopping = false;

  void worker_loop() {
    for (;;) {
      IoTask task;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_task.wait(lock, [&] { return stopping || !queue.empty(); });
        if (stopping && queue.empty()) return;
        task = queue.front();
        queue.pop_front();
      }
      int err = 0;
      char* p = static_cast<char*>(task.buf);
      int64_t left = task.nbytes;
      int64_t off = task.offset;
      while (left > 0) {
        ssize_t n = task.write ? pwrite(fd, p, left, off)
                               : pread(fd, p, left, off);
        if (n < 0) {
          err = errno ? errno : EIO;
          break;
        }
        if (n == 0) {  // short read past EOF
          err = EIO;
          break;
        }
        p += n;
        off += n;
        left -= n;
      }
      if (err) {
        int expected = 0;
        first_error.compare_exchange_strong(expected, err);
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        if (--inflight == 0 && queue.empty()) cv_done.notify_all();
      }
    }
  }
};

}  // namespace

extern "C" {

void* aio_open(const char* path, int64_t nbytes, int n_threads) {
  int fd = open(path, O_RDWR | O_CREAT, 0644);
  if (fd < 0) return nullptr;
  if (nbytes > 0) {
    struct stat st;
    if (fstat(fd, &st) == 0 && st.st_size < nbytes) {
      if (ftruncate(fd, nbytes) != 0) {
        close(fd);
        return nullptr;
      }
    }
  }
  auto* h = new AioHandle();
  h->fd = fd;
  if (n_threads < 1) n_threads = 1;
  for (int i = 0; i < n_threads; ++i)
    h->workers.emplace_back([h] { h->worker_loop(); });
  return h;
}

static int64_t submit(AioHandle* h, bool write, void* buf, int64_t nbytes,
                      int64_t offset) {
  {
    std::lock_guard<std::mutex> lock(h->mu);
    h->queue.push_back(IoTask{write, buf, nbytes, offset});
    ++h->inflight;
  }
  h->cv_task.notify_one();
  return nbytes;
}

int64_t aio_submit_write(void* handle, const void* buf, int64_t nbytes,
                         int64_t offset) {
  return submit(static_cast<AioHandle*>(handle), true,
                const_cast<void*>(buf), nbytes, offset);
}

int64_t aio_submit_read(void* handle, void* buf, int64_t nbytes,
                        int64_t offset) {
  return submit(static_cast<AioHandle*>(handle), false, buf, nbytes,
                offset);
}

int aio_wait_all(void* handle) {
  auto* h = static_cast<AioHandle*>(handle);
  std::unique_lock<std::mutex> lock(h->mu);
  h->cv_done.wait(lock, [&] { return h->inflight == 0 && h->queue.empty(); });
  return -h->first_error.exchange(0);
}

int64_t aio_pending(void* handle) {
  auto* h = static_cast<AioHandle*>(handle);
  std::lock_guard<std::mutex> lock(h->mu);
  return h->inflight;
}

void aio_fsync(void* handle) {
  auto* h = static_cast<AioHandle*>(handle);
  fsync(h->fd);
}

void aio_close(void* handle) {
  auto* h = static_cast<AioHandle*>(handle);
  {
    std::lock_guard<std::mutex> lock(h->mu);
    h->stopping = true;
  }
  h->cv_task.notify_all();
  for (auto& t : h->workers) t.join();
  close(h->fd);
  delete h;
}

}  // extern "C"
