// Host-side vectorized Adam for ZeRO-Offload.
//
// TPU-native equivalent of the reference's CPU Adam extension
// (reference: csrc/adam/cpu_adam_impl.cpp, csrc/includes/cpu_adam.h:47
// Adam_Optimizer::Step_AVX — AVX2/AVX512 SIMD + OpenMP). Here the inner
// loop is written scalar-simple and compiled with -O3 -march=native
// -fopenmp: the compiler emits the same fused AVX mul/add pattern the
// reference hand-codes, and OpenMP splits leaves across host cores.
//
// Math matches optax.adamw (decoupled weight decay when adamw_mode) /
// classic L2 Adam otherwise, with bias correction:
//   m <- b1*m + (1-b1)*g ; v <- b2*v + (1-b2)*g^2
//   update = (m/(1-b1^t)) / (sqrt(v/(1-b2^t)) + eps) [+ wd*p if adamw]
//   p <- p - lr*update
//
// C ABI only (loaded via ctypes; no pybind11 in this toolchain).

#include <cmath>
#include <cstdint>

extern "C" {

void ds_adam_step(float* p, const float* g, float* m, float* v,
                  int64_t n, float lr, float beta1, float beta2, float eps,
                  float weight_decay, int64_t step, int adamw_mode) {
    const float bc1 = 1.0f - powf(beta1, (float)step);
    const float bc2 = 1.0f - powf(beta2, (float)step);
    const float one_m_b1 = 1.0f - beta1;
    const float one_m_b2 = 1.0f - beta2;

#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        float grad = g[i];
        if (!adamw_mode && weight_decay > 0.0f) grad += weight_decay * p[i];
        float mi = beta1 * m[i] + one_m_b1 * grad;
        float vi = beta2 * v[i] + one_m_b2 * grad * grad;
        m[i] = mi;
        v[i] = vi;
        float upd = (mi / bc1) / (sqrtf(vi / bc2) + eps);
        if (adamw_mode && weight_decay > 0.0f) upd += weight_decay * p[i];
        p[i] -= lr * upd;
    }
}

// fp32 -> bf16 (round-to-nearest-even) for pushing updated master params
// back to the device without a Python-side conversion pass.
void ds_f32_to_bf16(const float* src, uint16_t* dst, int64_t n) {
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
        union { float f; uint32_t u; } x;
        x.f = src[i];
        if ((x.u & 0x7fffffff) > 0x7f800000) {  // NaN: rounding would
            dst[i] = 0x7fc0;                    // overflow into Inf
            continue;
        }
        uint32_t rounding = 0x7fff + ((x.u >> 16) & 1);
        dst[i] = (uint16_t)((x.u + rounding) >> 16);
    }
}

}  // extern "C"
