#!/usr/bin/env python
"""Repo lint: flag module-level mutable containers that only grow.

A process that serves traffic for weeks dies by a thousand unbounded
caches: a module-level ``dict``/``list``/``set`` that gains entries on
a hot path and never evicts is a leak with a delay fuse (the
post-restore XLA-CPU abort this repo root-caused was exactly
process-lifetime growth — see runtime/lifecycle.py). This lint walks
``deepspeed_tpu/`` and reports every MODULE-LEVEL container literal
that some code in the module grows (``x[k] = ...``, ``.append``,
``.add``, ``.setdefault``, ``.update``, ...) while nothing ever
shrinks it (``.pop``, ``.popitem``, ``.clear``, ``.remove``,
``del x[...]``, slice deletion, or wholesale reassignment).

Sanctioned escapes:

* use ``runtime.lifecycle.BoundedCache`` — bounded, observable,
  explicitly evictable (assignments whose value is a
  ``BoundedCache(...)`` call are skipped), or
* annotate the assignment line with ``# unbounded-ok: <why>`` when the
  growth is genuinely bounded by construction (e.g. a warn-once set
  keyed by a fixed vocabulary) — the reason is mandatory.

Usage: python tools/lint_unbounded_caches.py [root_dir]
Exit code 0 = clean, 1 = violations found.
"""

import ast
import os
import sys

_CONTAINER_CALLS = ("dict", "list", "set", "OrderedDict", "defaultdict",
                    "deque", "Counter")
_GROW_METHODS = ("append", "add", "setdefault", "update", "insert",
                 "extend", "appendleft", "move_to_end")
_SHRINK_METHODS = ("pop", "popitem", "clear", "remove", "discard",
                   "popleft", "invalidate")
_ANNOTATION = "# unbounded-ok:"


def _is_container_literal(value) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set)):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None)
        return name in _CONTAINER_CALLS
    return False


def _is_bounded_cache(value) -> bool:
    if not isinstance(value, ast.Call):
        return False
    fn = value.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name == "BoundedCache"


def _module_level_containers(tree):
    """{name: lineno} of top-level container-literal assignments.
    ``deque(maxlen=...)`` is bounded by construction and skipped."""
    out = {}
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        if _is_bounded_cache(value) or not _is_container_literal(value):
            continue
        if isinstance(value, ast.Call) and any(
                kw.arg == "maxlen" for kw in value.keywords):
            continue
        for t in targets:
            if isinstance(t, ast.Name):
                out[t.id] = node.lineno
    return out


def _usage_sets(tree, names):
    """(grown, shrunk): which of ``names`` the module grows/shrinks."""
    grown, shrunk = set(), set()

    def base_name(expr):
        return expr.id if isinstance(expr, ast.Name) else None

    for node in ast.walk(tree):
        # x[k] = v  /  del x[k]
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    n = base_name(t.value)
                    if n in names:
                        grown.add(n)
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    n = base_name(t.value)
                    if n in names:
                        shrunk.add(n)
                elif isinstance(t, ast.Name) and t.id in names:
                    shrunk.add(t.id)
        # x.method(...)
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            n = base_name(node.func.value)
            if n in names:
                if node.func.attr in _GROW_METHODS:
                    grown.add(n)
                elif node.func.attr in _SHRINK_METHODS:
                    shrunk.add(n)
    # reassignment anywhere below module level counts as a reset path
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and node.col_offset > 0:
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id in names:
                    shrunk.add(t.id)
    return grown, shrunk


def find_unbounded_caches(path):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    containers = _module_level_containers(tree)
    if not containers:
        return []
    lines = src.splitlines()
    annotated = {
        name for name, lineno in containers.items()
        if lineno <= len(lines) and _ANNOTATION in lines[lineno - 1]}
    grown, shrunk = _usage_sets(tree, set(containers))
    hits = []
    for name in sorted(grown - shrunk - annotated):
        hits.append((
            containers[name],
            f"module-level container {name!r} grows but has no "
            f"eviction path — use runtime.lifecycle.BoundedCache or "
            f"annotate '{_ANNOTATION} <reason>'"))
    return sorted(hits)


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deepspeed_tpu")
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            for lineno, msg in find_unbounded_caches(full):
                violations.append(f"{full}:{lineno}: {msg}")
    if violations:
        print("\n".join(violations))
        print(f"\n{len(violations)} unbounded module-level cache(s) "
              "found (see tools/lint_unbounded_caches.py)")
        return 1
    print("lint_unbounded_caches: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
