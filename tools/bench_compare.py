#!/usr/bin/env python
"""Bench regression gate: diff two bench artifacts with per-config
thresholds and a CI-friendly exit code.

The bench trajectory (BENCH_r01 -> r05: config 1 at 1.07x, config 4
stuck at 0.58x, ...) has been eyeballed across PR descriptions; this
tool makes "did this PR regress a tracked config" a command:

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json
    python tools/bench_compare.py old.json new.json \\
        --threshold 0.10 --per-config 4=0.25,5_int4=0.30 \\
        --require 1,3,4,7_frontend

``TRACKED_CONFIGS`` lists configs that must never silently VANISH:
once one appears in the old artifact it is implicitly ``--require``d,
so a future run that drops it (a refactor losing the bench wiring)
fails the gate instead of passing with one fewer row. Artifacts
predating a tracked config still compare clean.
``TRACKED_DECOMP_KEYS`` applies the same arming rule one level down:
a decomposition key (config 5/7's ``speculation`` block) published by
the old row may not vanish from the new one.

``FLOOR_CONFIGS`` (extend with ``--floor 4=0.8``) pins absolute
vs_baseline minimums: once the lineage has cleared a floor, any new
run below it fails the gate even when each individual drop stayed
within the relative threshold — the anti-creep backstop for config
4's streaming-wire target.

Accepts both artifact shapes: the raw bench head (``bench.py``'s JSON
line, configs under ``"configs"``) and the driver wrapper
(``{"parsed": <head>, ...}`` as the checked-in BENCH_r*.json are).

Comparison metric: ``vs_baseline`` — the one field that is
higher-is-better for EVERY tracked config (throughput rows normalize
MFU, serving rows normalize decode tok/s), where raw ``value`` flips
direction per config (tokens/s up vs TTFT/MTTR down). A config is a
REGRESSION when ``new < old * (1 - threshold)``; configs missing from
either side, skipped, errored, or without ``vs_baseline`` are
reported but only fail the gate when named in ``--require``.

Exit codes: 0 = clean, 1 = regression (or a required config missing/
unparseable), 2 = usage/artifact error.
"""

import argparse
import json
import sys


def load_configs(path):
    """-> {config_key: row_dict} from either artifact shape."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench artifact (expected an "
                         "object)")
    configs = doc.get("configs")
    if isinstance(configs, dict) and configs:
        return configs
    # single-config artifact (bench.py --config N prints one row)
    if "metric" in doc:
        return {"_single": doc}
    raise ValueError(f"{path}: no 'configs' table and no bench row")


def parse_per_config(text):
    out = {}
    if not text:
        return out
    for entry in text.split(","):
        entry = entry.strip()
        if not entry:
            continue
        key, sep, val = entry.partition("=")
        if not sep:
            raise ValueError(
                f"bad --per-config entry {entry!r} (want key=frac)")
        out[key.strip()] = float(val)
    return out


# configs that must not vanish from the lineage: present in the old
# artifact -> required comparable in the new one (see module docstring)
TRACKED_CONFIGS = ("7_frontend", "8_fleet", "9_bigmodel")

# decomposition keys that must not vanish from a config's lineage:
# once the OLD artifact's row publishes the key, a new row without it
# fails the gate (a refactor silently losing the speculation block
# would otherwise pass with one fewer number). Artifacts predating
# the key's introduction compare clean — same arming rule as
# TRACKED_CONFIGS, applied one level down. Dotted entries reach
# INSIDE a block ("cache.cache_demote_overlapped_ms"): the async
# overlap splits are individually load-bearing — a refactor keeping
# the cache block but dropping the split must still fail.
TRACKED_DECOMP_KEYS = {"5": ("speculation",),
                       "7_frontend": ("speculation", "cache",
                                      "cache.cache_demote_exposed_ms",
                                      "cache.cache_demote_overlapped_ms",
                                      "cache.cache_promote_exposed_ms",
                                      "cache.cache_promote_overlapped_ms"),
                       "8_fleet": ("transport", "bootstrap",
                                   "blockxfer",
                                   "blockxfer.fetch_hit_rate",
                                   "blockxfer.fetch_exposed_ms",
                                   "blockxfer.fetch_overlapped_ms",
                                   # disagg handoff: the overlap split
                                   # is the number the pipelined push
                                   # exists for; itl_p99_ms only
                                   # appears on --disagg rows, so it
                                   # arms per-lineage like the rest
                                   "handoff",
                                   "handoff.handoff_exposed_ms",
                                   "handoff.handoff_overlapped_ms",
                                   "itl_p99_ms"),
                       "9_bigmodel": ("param_stream",
                                      "param_stream.param_drop_exposed_ms",
                                      "param_stream.param_drop_overlapped_ms")}


def _decomp_has(decomp, key):
    """Dotted-path membership in a decomposition dict: "a.b" means
    decomp["a"]["b"] exists (each level a dict along the way)."""
    node = decomp
    for part in key.split("."):
        if not isinstance(node, dict) or part not in node:
            return False
        node = node[part]
    return True

# absolute vs_baseline floors: once a config's LINEAGE has cleared
# the bar (old side >= floor), no new run may fall back under it —
# even via a slow creep of individually-within-threshold drops. The
# floor stays dormant while the old artifact is still below it, so
# pre-lift history (r04 -> r05 with config 4 at 0.58) compares clean.
# Config 4's 0.8 floor backs the streaming-wire target (ISSUE 10:
# 0.58x -> >=0.9x on the accelerator-box sweep, gate at 0.8).
# Override/extend with --floor.
FLOOR_CONFIGS = {"4": 0.8}


def compare(old, new, threshold, per_config, require, floors=None):
    """-> (rows, regressions, missing_required); each row is a dict
    for the report table. ``floors``: {config: absolute vs_baseline
    minimum} EXTENDING (never replacing) the built-in FLOOR_CONFIGS —
    a caller adding one floor must not drop the tracked ones."""
    require = set(require) | {k for k in TRACKED_CONFIGS if k in old}
    merged_floors = dict(FLOOR_CONFIGS)
    merged_floors.update(floors or {})
    floors = merged_floors
    rows, regressions, missing = [], [], []
    # required configs absent from BOTH sides must still surface (a
    # gate that silently passes when the scored row vanished from the
    # artifacts entirely is no gate)
    keys = sorted(set(old) | set(new) | set(require), key=str)
    for key in keys:
        o, n = old.get(key), new.get(key)
        thr = per_config.get(key, threshold)
        row = {"config": key, "threshold": thr}
        ob = (o or {}).get("vs_baseline")
        nb = (n or {}).get("vs_baseline")
        if o is None or n is None or ob is None or nb is None:
            why = ("absent from old" if o is None else
                   "absent from new" if n is None else
                   (o if ob is None else n).get("skipped")
                   or (o if ob is None else n).get("error", "")[:60]
                   or "no vs_baseline")
            row.update(status="skipped", note=str(why))
            if key in require:
                missing.append(key)
                row["status"] = "MISSING-REQUIRED"
        else:
            ob, nb = float(ob), float(nb)
            delta = (nb - ob) / ob if ob else 0.0
            floor = floors.get(key)
            regressed = nb < ob * (1.0 - thr)
            below_floor = floor is not None and ob >= float(floor) \
                and nb < float(floor)
            # decomposition-key vanish gate: armed per key once the
            # old row publishes it (pre-introduction rows arm nothing)
            lost = [dk for dk in TRACKED_DECOMP_KEYS.get(key, ())
                    if _decomp_has(o.get("decomposition") or {}, dk)
                    and not _decomp_has(n.get("decomposition") or {}, dk)]
            row.update(old=ob, new=nb, delta=delta,
                       status="REGRESSION" if regressed
                       else "BELOW-FLOOR" if below_floor
                       else "MISSING-DECOMP" if lost else "ok",
                       metric=(n.get("metric") or ""))
            if floor is not None:
                row["floor"] = float(floor)
            if lost:
                row["note"] = "decomposition lost: " + ", ".join(lost)
                missing.extend(f"{key}.decomposition.{dk}"
                               for dk in lost)
            if regressed or below_floor:
                regressions.append(key)
        rows.append(row)
    return rows, regressions, missing


def render(rows):
    out = [f"{'config':<12} {'old':>9} {'new':>9} {'delta':>8} "
           f"{'thr':>6}  status"]
    for r in rows:
        if "old" in r:
            note = f" ({r['note']})" if r.get("note") else ""
            out.append(
                f"{r['config']:<12} {r['old']:>9.4f} {r['new']:>9.4f} "
                f"{r['delta']:>+7.1%} {r['threshold']:>6.0%}  "
                f"{r['status']}{note}")
        else:
            out.append(f"{r['config']:<12} {'-':>9} {'-':>9} {'-':>8} "
                       f"{r['threshold']:>6.0%}  {r['status']} "
                       f"({r.get('note', '')})")
    return "\n".join(out)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="python tools/bench_compare.py",
        description="diff two bench artifacts; exit 1 on regression")
    p.add_argument("old")
    p.add_argument("new")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="allowed vs_baseline drop fraction "
                        "(default 0.10)")
    p.add_argument("--per-config", default="",
                   help="per-config overrides, e.g. '4=0.25,5=0.3'")
    p.add_argument("--floor", default="",
                   help="absolute vs_baseline floors, e.g. '4=0.8' "
                        "(extends the built-in FLOOR_CONFIGS; armed "
                        "once the old artifact clears the bar)")
    p.add_argument("--require", default="",
                   help="comma list of configs that MUST be "
                        "comparable (else exit 1)")
    p.add_argument("--json", action="store_true",
                   help="emit the comparison as one JSON line")
    args = p.parse_args(argv)
    try:
        old = load_configs(args.old)
        new = load_configs(args.new)
        per_config = parse_per_config(args.per_config)
        floors = parse_per_config(args.floor)  # compare() merges
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    require = {k.strip() for k in args.require.split(",") if k.strip()}
    rows, regressions, missing = compare(
        old, new, args.threshold, per_config, require, floors=floors)
    if args.json:
        print(json.dumps({"rows": rows, "regressions": regressions,
                          "missing_required": missing}))
    else:
        print(render(rows))
        if regressions:
            print(f"\nREGRESSION in config(s): "
                  f"{', '.join(regressions)}")
        if missing:
            print(f"required config(s) not comparable: "
                  f"{', '.join(sorted(missing))}")
        if not regressions and not missing:
            print("\nbench gate clean")
    return 1 if (regressions or missing) else 0


if __name__ == "__main__":
    sys.exit(main())
