#!/usr/bin/env python
"""Repo lint: every trace-span site string must be registered.

The timeline sibling of ``lint_fault_sites.py``: a typo'd name passed
to ``telemetry.trace.span("...")`` records fine at runtime (unknown
names degrade gracefully, by design), but every consumer that filters
on the REGISTERED name — the ``view`` CLI groupings, dashboards, the
tests that assert "per-bucket d2h spans exist" — silently loses the
site. This lint closes the loop statically:

* every literal name at a ``span(...)`` / ``tracer.span(...)`` /
  ``tracer.instant(...)`` call in ``deepspeed_tpu/`` must be declared
  in ``deepspeed_tpu/telemetry/span_sites.py:SPAN_SITES``;
* non-literal name arguments (computed strings) must carry a
  ``# span-site-ok: <why>`` annotation on the call line;
* registry entries no site ever opens are reported as warnings
  (dead registry entries hide the reverse typo) — warnings don't
  fail the lint, because tests may open a span directly.

Usage: python tools/lint_span_sites.py [root_dir]
Exit code 0 = clean, 1 = violations found.
"""

import ast
import os
import sys

_ANNOTATION = "# span-site-ok:"
# call shapes that open spans: the module-level ``span(...)`` (the
# threaded import), and ``<tracer-ish>.span(...)`` / ``.instant(...)``
_METHOD_NAMES = ("span", "instant")


def _iter_py(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _is_span_call(node):
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "span"
    if isinstance(fn, ast.Attribute) and fn.attr in _METHOD_NAMES:
        recv = fn.value
        name = None
        if isinstance(recv, ast.Name):
            name = recv.id
        elif isinstance(recv, ast.Attribute):
            name = recv.attr
        return name is not None and "trace" in name.lower()
    return False


def scan_file(path, registry):
    """-> (violations, used_sites)"""
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")], set()
    lines = src.splitlines()
    violations, used = [], set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_span_call(node):
            continue
        if not node.args:
            continue
        name_arg = node.args[0]
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        if isinstance(name_arg, ast.Constant) and \
                isinstance(name_arg.value, str):
            name = name_arg.value
            used.add(name)
            if name not in registry:
                violations.append(
                    (path, node.lineno,
                     f"span {name!r} is not declared in "
                     "telemetry/span_sites.py:SPAN_SITES"))
        elif _ANNOTATION not in line:
            violations.append(
                (path, node.lineno,
                 "non-literal span name; annotate the line with "
                 f"'{_ANNOTATION} <why>' if the value is closed over "
                 "registered names"))
    return violations, used


def main(root=None):
    here = os.path.dirname(os.path.abspath(__file__))
    root = root or os.path.join(os.path.dirname(here), "deepspeed_tpu")
    sys.path.insert(0, os.path.dirname(root))
    from deepspeed_tpu.telemetry.span_sites import SPAN_SITES
    registry = set(SPAN_SITES)
    violations, used = [], set()
    for path in sorted(_iter_py(root)):
        # the tracer's own module opens no registered spans; its
        # docstring examples and helpers would false-positive
        v, u = scan_file(path, registry)
        violations.extend(v)
        used |= u
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    unused = sorted(registry - used)
    for name in unused:
        print(f"warning: registered span {name!r} is never opened "
              f"from {os.path.basename(root)}/ (dead entry, or "
              "test-only)")
    if violations:
        print(f"\n{len(violations)} span-site violation(s).")
        return 1
    print(f"span-site lint clean: {len(used)} spans opened, "
          f"{len(registry)} registered"
          + (f", {len(unused)} registered-but-unopened" if unused
             else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
