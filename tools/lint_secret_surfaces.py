#!/usr/bin/env python
"""Repo lint: bootstrap secrets must never reach an observable surface.

The fleet bootstrap handshake deals in a shared-secret token and HMAC
material (token / mac / nonce). One careless ``logger.warning(f"...
{token}")`` or ``span("fleet.join", token=...)`` and the secret is in
every log file, JSONL telemetry stream and operator dashboard — the
kind of leak that ships silently because nothing functional breaks.
This lint closes the loop statically:

* at every OBSERVABLE-SURFACE call in ``deepspeed_tpu/`` — logger
  methods (``logger.debug/info/warning/error/critical/exception``),
  trace ``span(...)`` calls, and ``.write(...)`` on sink-like
  receivers — no argument subtree may reference a secret-named
  identifier (``token``, ``secret``, ``mac``, ``nonce``, ``hmac``,
  ``password``, ...; exact-name match, so ``tokens_emitted`` /
  ``max_new_tokens`` stay usable);
* a subtree wrapped in ``redact_auth(...)`` is exempt — that IS the
  sanctioned way to put bootstrap state on a surface;
* a line annotated ``# secret-ok: <why>`` is exempt (for the false
  positive where an identifier merely shares a name).

Usage: python tools/lint_secret_surfaces.py [root_dir]
Exit code 0 = clean, 1 = violations found.
"""

import ast
import os
import sys

_SECRET_NAMES = frozenset((
    "token", "secret", "mac", "nonce", "hmac", "password",
    "auth_token", "shared_secret", "ssl_keyfile_password"))
_LOG_METHODS = ("debug", "info", "warning", "error", "critical",
                "exception")
_ANNOTATION = "# secret-ok:"


def _iter_py(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _recv_name(fn):
    recv = fn.value
    if isinstance(recv, ast.Name):
        return recv.id
    if isinstance(recv, ast.Attribute):
        return recv.attr
    return ""


def _is_surface_call(node):
    """Logger methods on logger-like receivers, ``span(...)``, and
    ``.write(...)`` on sink-like receivers — the three ways data
    leaves the process as observability in this codebase."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id == "span"
    if not isinstance(fn, ast.Attribute):
        return False
    if fn.attr == "span":
        return True
    if fn.attr in _LOG_METHODS:
        return "log" in _recv_name(fn).lower()
    if fn.attr == "write":
        return "sink" in _recv_name(fn).lower()
    return False


def _secret_refs(node):
    """Secret-named identifiers (Name / Attribute / keyword) anywhere
    in this subtree, NOT descending into ``redact_auth(...)`` calls —
    redaction is the sanctioned exit."""
    if isinstance(node, ast.Call):
        fn = node.func
        fname = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else "")
        if fname == "redact_auth":
            return
    name = None
    if isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.keyword):
        name = node.arg
    if name and name.lower() in _SECRET_NAMES:
        yield name
    for child in ast.iter_child_nodes(node):
        yield from _secret_refs(child)


def scan_file(path):
    """-> violations [(path, lineno, msg)]"""
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    lines = src.splitlines()
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_surface_call(node):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        if _ANNOTATION in line:
            continue
        refs = set()
        for arg in list(node.args) + list(node.keywords):
            refs.update(_secret_refs(arg))
        if refs:
            violations.append(
                (path, node.lineno,
                 f"secret-named identifier(s) {sorted(refs)} reach an "
                 f"observable surface; wrap in redact_auth(...) or "
                 f"annotate with '{_ANNOTATION} <why>'"))
    return violations


def main(root=None):
    here = os.path.dirname(os.path.abspath(__file__))
    root = root or os.path.join(os.path.dirname(here), "deepspeed_tpu")
    violations = []
    n_files = 0
    for path in sorted(_iter_py(root)):
        n_files += 1
        violations.extend(scan_file(path))
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} secret-surface violation(s).")
        return 1
    print(f"secret-surface lint clean: {n_files} files scanned, "
          f"{len(_SECRET_NAMES)} guarded names")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
