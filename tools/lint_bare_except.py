#!/usr/bin/env python
"""Repo lint: fail on bare ``except:`` clauses — and on silent
``except Exception: pass`` — in deepspeed_tpu/.

A bare except swallows KeyboardInterrupt/SystemExit and — worse for the
resilience subsystem — the typed faults (CollectiveTimeout,
CheckpointCorruptionError, ...) that recovery layers key on. The
``except Exception: pass`` form is barely better: it still silently
eats every typed fault AND every real transfer/runtime error (the
offload ``copy_to_host_async`` guard did exactly this before the
transfer-engine PR). Every handler must name what it can actually
recover from; a broad handler must at least DO something (log,
re-raise, return a fallback) rather than ``pass``.

Usage: python tools/lint_bare_except.py [root_dir]
Exit code 0 = clean, 1 = violations found.
"""

import ast
import os
import sys

_BROAD = ("Exception", "BaseException")


def _names(type_node):
    """Exception class names a handler catches (best effort)."""
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    out = []
    for n in nodes:
        if isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
    return out


def find_bare_excepts(path):
    with open(path, encoding="utf-8") as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(e.lineno or 0, f"syntax error: {e.msg}")]
    hits = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            hits.append((node.lineno, "bare 'except:' clause"))
            continue
        body_is_pass = all(isinstance(st, ast.Pass) for st in node.body)
        if body_is_pass and any(n in _BROAD for n in _names(node.type)):
            hits.append((node.lineno,
                         "silent 'except Exception: pass' — narrow the "
                         "types or handle (log/fallback) the failure"))
    return hits


def main(argv):
    root = argv[1] if len(argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "deepspeed_tpu")
    violations = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            full = os.path.join(dirpath, name)
            for lineno, msg in find_bare_excepts(full):
                violations.append(f"{full}:{lineno}: {msg}")
    if violations:
        print("\n".join(violations))
        print(f"\n{len(violations)} bare except clause(s) found — name "
              "the exceptions the handler can recover from "
              "(see tools/lint_bare_except.py)")
        return 1
    print("lint_bare_except: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
