"""Decode-shape WOQ matmul A/B on the real chip: dense bf16 vs
XLA dequant-in-jit (status quo) vs the Pallas woq_matmul kernel.

Shapes mimic the config-5 bench: Llama-7B geometry, B=16 decode.
Each variant runs a scan of DEPTH chained matmuls (like a decode step
walking the layer stack) so weight reads dominate, timed over ITERS
dispatches.
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.quantization import quantize_weight
from deepspeed_tpu.ops.pallas_kernels.woq_matmul import (
    woq_matmul, woq_matmul_reference)

B, K, N, DEPTH, ITERS = 16, 4096, 11008, 8, 5
REPEATS = 50      # fori_loop repeats inside ONE dispatch: the tunnel's
                  # ~130 ms dispatch RTT must drown in device time


def time_it(fn, *args):
    np.asarray(fn(*args))       # compile + settle; HARD barrier
    ts = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        np.asarray(fn(*args))   # device->host copy forces completion
        ts.append(time.perf_counter() - t0)
    return sorted(ts)[len(ts) // 2]


def main():
    rng = np.random.default_rng(0)
    ws = [jnp.asarray(rng.standard_normal((K, N)), jnp.bfloat16) * 0.02
          for _ in range(DEPTH)]
    # chain shape-compatible: use W then W.T alternately via two dots
    x = jnp.asarray(rng.standard_normal((B, K)), jnp.bfloat16)
    leaves = [quantize_weight(w, 8, 128) for w in ws]
    qs = [l["woq_q"] for l in leaves]
    ss = [l["woq_scales"] for l in leaves]
    leaves4 = [quantize_weight(w, 4, 256) for w in ws]
    qs4 = [l["woq_q"] for l in leaves4]
    ss4 = [l["woq_scales"] for l in leaves4]

    def repeat(layer_scan):
        def body(x, *w):
            def it(i, c):
                return layer_scan(c, *w)
            return jax.lax.fori_loop(0, REPEATS, it, x)
        return jax.jit(body)

    def dense_scan(c0, ws):
        def step(c, w):
            y = jax.lax.dot_general(c, w, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            return y[:, :K].astype(jnp.bfloat16), ()
        c, _ = jax.lax.scan(step, c0, ws)
        return c

    def xla_scan(c0, qs, ss):
        def step(c, qw):
            q, s = qw
            y = woq_matmul_reference(c, q, s, jnp.bfloat16)
            return y[:, :K], ()
        c, _ = jax.lax.scan(step, c0, (qs, ss))
        return c

    def pallas_scan(c0, qs, ss):
        def step(c, qw):
            q, s = qw
            y = woq_matmul(c, q, s, jnp.bfloat16)
            return y[:, :K], ()
        c, _ = jax.lax.scan(step, c0, (qs, ss))
        return c

    dense = repeat(dense_scan)
    xla_deq = repeat(xla_scan)
    pallas = repeat(pallas_scan)
    ws = jnp.stack(ws)
    qs = jnp.stack(qs)
    ss = jnp.stack(ss)
    qs4 = jnp.stack(qs4)
    ss4 = jnp.stack(ss4)

    bytes_bf16 = REPEATS * DEPTH * K * N * 2
    bytes_int8 = REPEATS * DEPTH * K * N * 1
    bytes_int4 = REPEATS * DEPTH * K * N // 2
    for name, fn, args, byt in [
            ("dense_bf16", dense, (x, ws), bytes_bf16),
            ("xla_dequant", xla_deq, (x, qs, ss), bytes_int8),
            ("pallas_woq", pallas, (x, qs, ss), bytes_int8),
            ("pallas_woq4", pallas, (x, qs4, ss4), bytes_int4)]:
        t = time_it(fn, *args)
        print(f"{name:12s} {t*1e3:8.3f} ms  "
              f"{byt/t/1e9:7.1f} GB/s effective-weight-read")


if __name__ == "__main__":
    main()
