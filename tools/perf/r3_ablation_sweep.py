"""Round-3 perf ablation: where does the missing ~50% of peak go?

Each config runs in a subprocess (fresh XLA) on the real chip and prints
one RESULT line with tokens/s and MFU from XLA's own post-fusion flop
count (same math as bench.py).
"""
import json
import os
import subprocess
import sys
import time

import numpy as np

CONFIGS = {
    # name: (micro, gas, seq, flash, loss_chunk, vocab)
    "base":        (32, 32, 512, True, 0, 50304),
    "chunk128":    (32, 32, 512, True, 128, 50304),
    "chunk256":    (32, 32, 512, True, 256, 50304),
    "micro64":     (64, 16, 512, True, 0, 50304),
    "micro64ch":   (64, 16, 512, True, 256, 50304),
    "noflash":     (32, 32, 512, False, 0, 50304),
    "tinyvocab":   (32, 32, 512, True, 0, 768),     # CE/unembed ablation
    "seq1024":     (16, 32, 1024, True, 0, 50304),
    "micro16":     (16, 64, 512, True, 0, 50304),
}


def run_one(name):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.profiling.flops_profiler import peak_tflops

    micro, gas, seq, flash, chunk, vocab = CONFIGS[name]
    cfg = GPT2Config(vocab_size=vocab, n_positions=1024, n_embd=768,
                     n_layer=12, n_head=12, dropout=0.0, use_flash=flash,
                     loss_chunk=chunk)
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), config=config)
    gb = engine.train_batch_size()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(gb, seq), dtype=np.int32)
    b = {"input_ids": ids, "labels": ids.copy()}

    float(engine.train_batch(batch=b))
    float(engine.train_batch(batch=b))
    times = []
    for _ in range(3):
        t0 = time.time()
        float(engine.train_batch(batch=b))
        times.append(time.time() - t0)
    per_step = sorted(times)[len(times) // 2]
    tps = gb * seq / per_step

    prof = engine.get_flops_profile()
    micro_tokens = micro * seq
    fpt = prof["flops"] / micro_tokens
    mfu = tps * fpt / 1e12 / peak_tflops()
    print(f"RESULT {name}: {tps:,.0f} tok/s  mfu={mfu:.3f} "
          f"vs54={mfu / 0.54:.3f} step={per_step * 1e3:.0f}ms "
          f"fpt={fpt / 1e6:.0f}MF", flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1:
        run_one(sys.argv[1])
    else:
        names = list(CONFIGS)
        for n in names:
            env = dict(os.environ)
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
            r = subprocess.run([sys.executable, __file__, n], env=env,
                               capture_output=True, text=True, timeout=1200)
            out = [l for l in r.stdout.splitlines() if l.startswith("RESULT")]
            print(out[0] if out else
                  f"{n} FAILED rc={r.returncode}: "
                  + (r.stderr.strip().splitlines()[-1][:300] if r.stderr else ""),
                  flush=True)
