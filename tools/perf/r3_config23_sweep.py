"""Config-2 (GPT-2-medium ZeRO-2) and config-3 (Llama-7B-shape ZeRO-3)
tuning probes: flash on/off x micro split."""
import os
import subprocess
import sys
import time

import numpy as np

CONFIGS = {
    # name: (which, micro, gas, flash)
    "c2_base":   ("c2", 16, 32, True),
    "c2_nf_m8":  ("c2", 8, 64, False),
    "c2_nf_m16": ("c2", 16, 32, False),
    "c2_nf_m4":  ("c2", 4, 128, False),
    "c3_base":   ("c3", 2, 8, True),
    "c3_nf":     ("c3", 2, 8, False),
    "c3_m1":     ("c3", 1, 16, True),
}


def run_one(name):
    import jax
    import deepspeed_tpu
    from deepspeed_tpu.profiling.flops_profiler import peak_tflops

    which, micro, gas, flash = CONFIGS[name]
    if which == "c2":
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        seq = 512
        cfg = GPT2Config(vocab_size=50304, n_positions=1024, n_embd=1024,
                         n_layer=24, n_head=16, dropout=0.0,
                         use_flash=flash)
        model = GPT2LMHeadModel(cfg)
        stage = 2
        vocab = cfg.vocab_size
    else:
        import dataclasses
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        seq = 2048
        cfg = dataclasses.replace(LlamaConfig.llama2_7b(),
                                  num_hidden_layers=2, use_remat=True,
                                  max_position_embeddings=seq)
        model = LlamaForCausalLM(cfg)
        stage = 3
        vocab = cfg.vocab_size
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    gb = engine.train_batch_size()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(gb, seq), dtype=np.int32)
    b = {"input_ids": ids, "labels": ids.copy()}
    float(engine.train_batch(batch=b))
    float(engine.train_batch(batch=b))
    times = []
    for _ in range(3):
        t0 = time.time()
        float(engine.train_batch(batch=b))
        times.append(time.time() - t0)
    per_step = sorted(times)[len(times) // 2]
    tps = gb * seq / per_step
    prof = engine.get_flops_profile()
    fpt = prof["flops"] / (micro * seq)
    mfu = tps * fpt / 1e12 / peak_tflops()
    print(f"RESULT {name}: {tps:,.0f} tok/s  mfu={mfu:.3f} "
          f"vs54={mfu / 0.54:.3f} step={per_step * 1e3:.0f}ms", flush=True)


if __name__ == "__main__":
    if len(sys.argv) > 1:
        run_one(sys.argv[1])
    else:
        for n in CONFIGS:
            env = dict(os.environ)
            repo = os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
            env["PYTHONPATH"] = repo + ":" + env.get("PYTHONPATH", "")
            r = subprocess.run([sys.executable, __file__, n], env=env,
                               capture_output=True, text=True,
                               timeout=1800)
            out = [l for l in r.stdout.splitlines()
                   if l.startswith("RESULT")]
            print(out[0] if out else
                  f"{n} FAILED rc={r.returncode}: "
                  + (r.stderr.strip().splitlines()[-1][:300]
                     if r.stderr else ""), flush=True)
