"""Where does config 4's wall clock go? Same model/shape as
bench_config4, with offload on/off — run ONE variant per process
(HBM not reclaimed across engines in-process).

usage: python tools/perf/r5_config4_probe.py {off,on,dpu}
"""

import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main(variant):
    use_flash = "noflash" not in variant
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    seq = 1024
    cfg = GPT2Config(vocab_size=50304, n_positions=seq, n_embd=768,
                     n_layer=12, n_head=12, dropout=0.0, use_flash=use_flash)
    config = {
        "train_micro_batch_size_per_gpu": 16,
        "gradient_accumulation_steps": 128,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    if variant.split("-")[0] != "off":
        config["zero_optimization"]["offload_optimizer"] = {
            "device": "cpu",
            "delayed_update": variant.startswith("dpu"),
            "grad_dtype": "int4",
            "upload_dtype": "int4_delta"}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), config=config)
    gb = engine.train_batch_size()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 50304, size=(gb, seq), dtype=np.int32)
    b = {"input_ids": ids, "labels": ids.copy()}
    float(engine.train_batch(batch=b))
    float(engine.train_batch(batch=b))
    ts = []
    for _ in range(4):
        t0 = time.time()
        float(engine.train_batch(batch=b))
        ts.append(time.time() - t0)
    per = sorted(ts)[len(ts) // 2]
    out = {"variant": variant, "per_step_s": round(per, 3),
           "tok_s": round(gb * seq / per, 1)}
    if engine._offload is not None:
        out["breakdown"] = {k: round(v / 1e3, 2) for k, v in
                            engine.get_offload_breakdown().items()}
    print(out)


if __name__ == "__main__":
    main(sys.argv[1])
