"""csrc benchmark evidence (round-4 verdict weak #5): the native C++
cpu_adam vs the numpy fallback, and the AIO pool vs buffered reads.

Prints one JSON line per measurement; numbers land in BASELINE.md's
notes so the 'thin but honest' csrc claim carries data.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def bench_cpu_adam(n=25_000_000, iters=5):
    from deepspeed_tpu.ops.adam.cpu_adam import DeepSpeedCPUAdam
    rng = np.random.default_rng(0)
    out = {}
    for native in (True, False):
        p = [rng.standard_normal(n).astype(np.float32)]
        g = [rng.standard_normal(n).astype(np.float32) * 1e-3]
        opt = DeepSpeedCPUAdam(p, lr=1e-3, use_native=native)
        if native and not opt.native:
            out["native"] = "unavailable"
            continue
        opt.step(g)                     # warm
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            opt.step(g)
            ts.append(time.perf_counter() - t0)
        med = sorted(ts)[len(ts) // 2]
        key = "native_cpp" if native else "numpy"
        out[key] = {"ms_per_step": round(med * 1e3, 1),
                    "gb_per_s": round(n * 4 * 4 / med / 1e9, 2)}
    print(json.dumps({"bench": "cpu_adam", "params": n, **out}))


def bench_aio(mb=512):
    from deepspeed_tpu.ops.aio import AsyncIOHandle
    n = (mb << 20) // 4
    data = np.random.default_rng(0).standard_normal(n) \
        .astype(np.float32)
    with tempfile.NamedTemporaryFile(dir="/tmp", delete=False) as f:
        path = f.name
    try:
        handle = AsyncIOHandle(path, nbytes=data.nbytes, n_threads=4)
        handle.pwrite(data, 0)
        handle.wait()
        arr = np.empty(n, np.float32)
        # evict page cache as best we can (fadvise DONTNEED)
        fd = os.open(path, os.O_RDONLY)
        try:
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        finally:
            os.close(fd)
        t0 = time.perf_counter()
        handle.pread(arr, 0)
        handle.wait()
        dt_pool = time.perf_counter() - t0
        handle.close()
        fd = os.open(path, os.O_RDONLY)
        try:
            os.posix_fadvise(fd, 0, 0, os.POSIX_FADV_DONTNEED)
        finally:
            os.close(fd)
        t0 = time.perf_counter()
        with open(path, "rb") as f:
            f.readinto(arr)
        dt_plain = time.perf_counter() - t0
        print(json.dumps({
            "bench": "aio_read", "mb": mb,
            "pool_gb_s": round(mb / 1024 / dt_pool, 2),
            "plain_read_gb_s": round(mb / 1024 / dt_plain, 2)}))
    finally:
        os.unlink(path)


if __name__ == "__main__":
    bench_cpu_adam()
    bench_aio()
