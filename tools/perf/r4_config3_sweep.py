"""Round-4 config-3 headroom sweep (VERDICT item 10): remat policy x
micro split x depth at Llama-7B geometry on one chip.

Round-3 recorded 0.923 with full remat, micro 2 x gas 8. Full remat
recomputes every block forward (+~1/3 FLOPs); at 2 layers / micro 2 the
activations are small enough that no-remat or a dots-saveable policy
may fit and buy the missing MFU.

Usage: python tools/perf/r4_config3_sweep.py
"""

import dataclasses
import itertools
import json
import time

import numpy as np


def run(micro, gas, remat, layers=2, seq=2048, steps=3,
        remat_policy="full"):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.parallel.mesh import mesh_manager
    from deepspeed_tpu.profiling.flops_profiler import peak_tflops

    mesh_manager.reset()
    cfg = dataclasses.replace(LlamaConfig.llama2_7b(),
                              num_hidden_layers=layers,
                              use_remat=remat,
                              remat_policy=remat_policy,
                              max_position_embeddings=seq)
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    model = LlamaForCausalLM(cfg)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    gb = engine.train_batch_size()
    ids = np.random.default_rng(0).integers(
        0, cfg.vocab_size, size=(gb, seq), dtype=np.int32)
    b = {"input_ids": ids, "labels": ids.copy()}
    float(engine.train_batch(batch=b))
    float(engine.train_batch(batch=b))
    times = []
    for _ in range(steps):
        t0 = time.time()
        float(engine.train_batch(batch=b))
        times.append(time.time() - t0)
    per_step = sorted(times)[len(times) // 2]
    tps = gb * seq / per_step
    prof = engine.get_flops_profile()
    fpt = prof["flops"] / (micro * seq)
    mfu = (tps * fpt / 1e12) / peak_tflops()
    return {"micro": micro, "gas": gas, "remat": remat,
            "remat_policy": remat_policy, "layers": layers,
            "tokens_per_sec": round(tps, 0),
            "mfu": round(mfu, 4), "vs_baseline": round(mfu / 0.54, 4)}


def main():
    import sys
    combos = [(2, 8, True, "full"), (2, 8, False, "full"),
              (4, 4, False, "full"), (1, 16, False, "full"),
              (4, 4, True, "full"),
              (2, 8, True, "dots"), (4, 4, True, "dots")]
    if len(sys.argv) > 1:      # e.g. "0,1" selects a subset
        keep = [int(i) for i in sys.argv[1].split(",")]
        combos = [combos[i] for i in keep]
    results = []
    for micro, gas, remat, policy in combos:
        try:
            r = run(micro, gas, remat, remat_policy=policy)
        except Exception as e:
            r = {"micro": micro, "gas": gas, "remat": remat,
                 "remat_policy": policy,
                 "error": f"{type(e).__name__}: {str(e)[:200]}"}
        print(json.dumps(r), flush=True)
        results.append(r)
    ok = [r for r in results if "mfu" in r]
    if ok:
        best = max(ok, key=lambda r: r["mfu"])
        print("BEST:", json.dumps(best))


if __name__ == "__main__":
    main()
