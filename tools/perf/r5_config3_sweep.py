"""Config-3 headroom sweep, continued: flash-kernel block sizes x
micro split x attention impl at Llama-7B geometry on one chip.

Round-4 recorded 0.88-0.96 (session drift) with flash 256/256, full
remat, micro 4 x gas 4. The flash kernel's cost is pure time under the
recorded metric (Pallas custom-call FLOPs are invisible to XLA cost
analysis), so shaving attention wall-clock converts 1:1 into MFU.

Usage: python tools/perf/r5_config3_sweep.py [idx,idx,...]
"""

import dataclasses
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def _xla_attention(q, k, v, causal=True):
    """Dense einsum attention with the flash_attention signature — the
    XLA-fused alternative (its s^2 matmuls ARE visible to cost analysis,
    unlike the Pallas custom call)."""
    import jax
    import jax.numpy as jnp
    B, Tq, Hq, D = q.shape
    Tk, Hkv = k.shape[1], k.shape[2]
    rep = Hq // Hkv
    qg = q.reshape(B, Tq, Hkv, rep, D)
    scores = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k).astype(jnp.float32)
    scores = scores / (D ** 0.5)
    if causal:
        qpos = (Tk - Tq + jnp.arange(Tq))[:, None]
        mask = jnp.arange(Tk)[None, :] <= qpos
        scores = jnp.where(mask[None, None, None], scores, float("-inf"))
    p = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bhrqk,bkhd->bqhrd", p, v)
    return out.reshape(B, Tq, Hq, D).astype(q.dtype)


def run(micro, gas, *, use_flash=True, block_q=256, block_k=256,
        layers=2, seq=2048, steps=5):
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.models import llama as llama_mod
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.ops.pallas_kernels import flash_attention as real_flash
    from deepspeed_tpu.parallel.mesh import mesh_manager
    from deepspeed_tpu.profiling.flops_profiler import peak_tflops

    mesh_manager.reset()
    # route the model's attention calls through the chosen variant
    if use_flash:
        llama_mod.flash_attention = functools.partial(
            real_flash, block_q=block_q, block_k=block_k)
    else:
        llama_mod.flash_attention = _xla_attention
    try:
        cfg = dataclasses.replace(LlamaConfig.llama2_7b(),
                                  num_hidden_layers=layers,
                                  use_remat=True,
                                  max_position_embeddings=seq)
        config = {
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
            "steps_per_print": 0,
        }
        model = LlamaForCausalLM(cfg)
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
        gb = engine.train_batch_size()
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(gb, seq), dtype=np.int32)
        b = {"input_ids": ids, "labels": ids.copy()}
        float(engine.train_batch(batch=b))
        float(engine.train_batch(batch=b))
        times = []
        for _ in range(steps):
            t0 = time.time()
            float(engine.train_batch(batch=b))
            times.append(time.time() - t0)
        per_step = sorted(times)[len(times) // 2]
        tps = gb * seq / per_step
        prof = engine.get_flops_profile()
        fpt = prof["flops"] / (micro * seq)
        mfu = (tps * fpt / 1e12) / peak_tflops()
        return {"micro": micro, "gas": gas, "flash": use_flash,
                "bq": block_q, "bk": block_k,
                "tokens_per_sec": round(tps, 0), "mfu": round(mfu, 4),
                "vs_baseline": round(mfu / 0.54, 4),
                "variance": round((max(times) - min(times)) / per_step, 3)}
    finally:
        llama_mod.flash_attention = real_flash


def main():
    import sys
    combos = [
        dict(micro=4, gas=4),                                # recorded baseline
        dict(micro=4, gas=4, block_q=512, block_k=512),
        dict(micro=4, gas=4, block_q=128, block_k=128),
        dict(micro=4, gas=4, block_q=512, block_k=1024),
        dict(micro=4, gas=4, block_q=1024, block_k=512),
        dict(micro=8, gas=2),
        dict(micro=8, gas=2, block_q=512, block_k=512),
        dict(micro=4, gas=4, use_flash=False),               # XLA attention
        dict(micro=8, gas=2, use_flash=False),
    ]
    if len(sys.argv) > 1:
        keep = [int(i) for i in sys.argv[1].split(",")]
        combos = [combos[i] for i in keep]
    results = []
    for kw in combos:
        try:
            r = run(**kw)
        except Exception as e:
            r = dict(kw, error=f"{type(e).__name__}: {str(e)[:200]}")
        print(json.dumps(r), flush=True)
        results.append(r)
    ok = [r for r in results if "mfu" in r]
    if ok:
        print("BEST:", json.dumps(max(ok, key=lambda r: r["mfu"])))


if __name__ == "__main__":
    main()
