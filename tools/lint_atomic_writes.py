#!/usr/bin/env python
"""Repo lint: persistent-file writes must go through the atomic
helpers.

A bare ``open(path, "wb")`` (or ``np.save``/``json.dump`` straight to
a final path) can crash mid-write and leave a torn file under the
name readers trust — exactly the corruption class the checkpoint
store, the fleet journal and the tiered block store were built to
survive. Those layers route every durable write through
``resilience/integrity.py`` (tmp + flush + fsync + rename), and this
lint keeps new code from quietly regressing the discipline:

* every ``open(..., mode)`` call whose mode writes bytes or text
  (``w``/``wb``/``w+``/``a`` with ``b``, etc.) in ``deepspeed_tpu/``
  must live either in the integrity module itself, inside a function
  whose name marks it as a tmp/scratch writer, or carry a
  ``# atomic-ok: <why>`` annotation on the call line;
* ``np.save``/``np.savez``/``pickle.dump``/``json.dump`` writing
  through a file object are traced to the same rule via their
  enclosing call line;
* append-mode journal fds opened via ``os.open(...O_APPEND...)`` are
  exempt by construction: appends are the crash-safe primitive the
  journals build on (a torn TAIL is tolerated by replay; renames
  can't express appends).

Legitimate escapes and what to write:
  ``# atomic-ok: scratch file, re-created every run``
  ``# atomic-ok: append-only journal, torn tail tolerated by replay``

Usage: python tools/lint_atomic_writes.py [root_dir]
Exit code 0 = clean, 1 = violations found.
"""

import ast
import os
import sys

_ANNOTATION = "# atomic-ok:"
# modules whose whole purpose is the atomic/tmp write machinery
_EXEMPT_FILES = ("resilience/integrity.py",)
# writer helpers like np.save(f, ...) / pickle.dump(obj, f) — flagged
# only when their file argument is a direct open(...) call (writing
# into an already-open handle is the handle's opener's problem)
_WRITER_FUNCS = {"save", "savez", "savez_compressed", "dump"}


def _iter_py(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _mode_writes(mode: str) -> bool:
    return any(c in mode for c in "wax+") and "r" not in mode.split(
        "+")[0].replace("b", "")


def _open_mode(node):
    """The literal mode of an ``open(...)`` call, or None when the
    call isn't a plain open / the mode is dynamic."""
    fn = node.func
    is_open = (isinstance(fn, ast.Name) and fn.id == "open") or \
        (isinstance(fn, ast.Attribute) and fn.attr == "open"
         and isinstance(fn.value, ast.Name) and fn.value.id == "io")
    if not is_open:
        return None
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return mode.value
    return "?"   # dynamic mode: treat as suspicious


def scan_file(path, rel):
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")]
    if any(rel.endswith(x) for x in _EXEMPT_FILES):
        return []
    lines = src.splitlines()
    violations = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        if _ANNOTATION in line:
            continue
        mode = _open_mode(node)
        if mode is not None and (mode == "?" or _mode_writes(mode)):
            violations.append(
                (path, node.lineno,
                 f"open(..., {mode!r}) writes to a path directly; "
                 "route durable writes through resilience/integrity "
                 f"helpers or annotate '{_ANNOTATION} <why>'"))
            continue
        fn = node.func
        if isinstance(fn, ast.Attribute) and \
                fn.attr in _WRITER_FUNCS and node.args:
            target = node.args[1] if fn.attr == "dump" and \
                len(node.args) > 1 else node.args[0]
            if isinstance(target, ast.Call) and \
                    _open_mode(target) is not None:
                violations.append(
                    (path, node.lineno,
                     f"{fn.attr}() into an inline open(): torn-file "
                     "hazard; use the integrity helpers or annotate "
                     f"'{_ANNOTATION} <why>'"))
    return violations


def main(root=None):
    here = os.path.dirname(os.path.abspath(__file__))
    root = root or os.path.join(os.path.dirname(here), "deepspeed_tpu")
    violations = []
    base = os.path.dirname(root.rstrip(os.sep))
    for path in sorted(_iter_py(root)):
        violations.extend(
            scan_file(path, os.path.relpath(path, base)))
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"\n{len(violations)} atomic-write violation(s).")
        return 1
    print("atomic-write lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
