#!/usr/bin/env python
"""Repo lint: every fault-injection site string must be registered.

A typo'd site passed to ``fault_injector.fire("...")`` /
``consume("...")`` is a silent hole in the recovery test surface: the
spec grammar accepts it, the drill runs green, and the fault never
fires — the failure path under test never executes (the injector only
WARNS about unknown sites, by design, so specs written for newer
builds degrade gracefully). This lint closes the loop statically:

* every literal site string at a ``fire``/``consume`` call in
  ``deepspeed_tpu/`` must be declared in the central registry
  (``deepspeed_tpu/resilience/fault_sites.py:FAULT_SITES``);
* non-literal site arguments (computed strings) must carry a
  ``# fault-site-ok: <why>`` annotation on the call line;
* registry entries no site ever fires are reported as warnings
  (dead registry entries hide the reverse typo) — warnings don't
  fail the lint, because tests may drive a site directly.

Usage: python tools/lint_fault_sites.py [root_dir]
Exit code 0 = clean, 1 = violations found.
"""

import ast
import os
import sys

_CALL_NAMES = ("fire", "consume")
_ANNOTATION = "# fault-site-ok:"


def _iter_py(root):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("__pycache__", ".git")]
        for f in filenames:
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _call_name(node):
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _is_injector_call(node):
    """``<something>.fire(...)`` / ``.consume(...)`` where the
    receiver smells like an injector (``fault_injector`` /
    ``injector`` / ``self.injector``), or a bare registry helper.
    Receiver filtering keeps unrelated ``.fire()`` APIs out."""
    fn = node.func
    if not isinstance(fn, ast.Attribute) or \
            fn.attr not in _CALL_NAMES:
        return False
    recv = fn.value
    name = None
    if isinstance(recv, ast.Name):
        name = recv.id
    elif isinstance(recv, ast.Attribute):
        name = recv.attr
    return name is not None and "injector" in name.lower()


def scan_file(path, registry):
    """-> (violations, used_sites)"""
    with open(path) as f:
        src = f.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"syntax error: {e.msg}")], set()
    lines = src.splitlines()
    violations, used = [], set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_injector_call(node):
            continue
        if not node.args:
            continue
        site_arg = node.args[0]
        line = lines[node.lineno - 1] if node.lineno <= len(lines) \
            else ""
        if isinstance(site_arg, ast.Constant) and \
                isinstance(site_arg.value, str):
            site = site_arg.value
            used.add(site)
            if site not in registry:
                violations.append(
                    (path, node.lineno,
                     f"site {site!r} is not declared in "
                     "resilience/fault_sites.py:FAULT_SITES"))
        elif _ANNOTATION not in line:
            violations.append(
                (path, node.lineno,
                 "non-literal fault site; annotate the line with "
                 f"'{_ANNOTATION} <why>' if the value is closed over "
                 "registered sites"))
    return violations, used


def main(root=None):
    here = os.path.dirname(os.path.abspath(__file__))
    root = root or os.path.join(os.path.dirname(here), "deepspeed_tpu")
    sys.path.insert(0, os.path.dirname(root))
    from deepspeed_tpu.resilience.fault_sites import FAULT_SITES
    registry = set(FAULT_SITES)
    violations, used = [], set()
    for path in sorted(_iter_py(root)):
        v, u = scan_file(path, registry)
        violations.extend(v)
        used |= u
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    unused = sorted(registry - used)
    for site in unused:
        print(f"warning: registered site {site!r} is never fired from "
              f"{os.path.basename(root)}/ (dead entry, or test-only)")
    if violations:
        print(f"\n{len(violations)} fault-site violation(s).")
        return 1
    print(f"fault-site lint clean: {len(used)} sites fired, "
          f"{len(registry)} registered"
          + (f", {len(unused)} registered-but-unfired" if unused
             else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else None))
