"""Test harness: simulated 8-device CPU mesh.

The reference exercises multi-rank logic by forking local processes
(tests/unit/common.py:380 DistributedTest) or monkey-patching a fake
process group (deepspeed/tools/pg_sim/pg.py).  The TPU-native analog is
XLA's host-platform device multiplexing: one process, 8 virtual CPU
devices, real collectives through the SPMD partitioner.
"""

import os

# Must be set before jax backend init.
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                           " --xla_force_host_platform_device_count=8")
os.environ["DS_ACCELERATOR"] = "cpu"

import jax  # noqa: E402

# The config update must come before any backend initialization; it also
# overrides environments (like axon TPU tunnels) whose site hooks force
# their own jax_platforms selection.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache (tier-1 wall, PR 17). The suite
# compiles near-identical tiny-model programs hundreds of times —
# across test modules in one run and again on every rerun; the cache
# is keyed on (HLO, compile options, backend), so hits are exactly the
# executables jit would have produced, and in-memory dispatch
# signatures (ScheduledStep caches, recompile-count assertions) are
# unaffected. It is opt-in PER PACKAGE via the named fixture below:
# once any cache write has happened in the process, the elasticity
# chaos drill (kill mid-dispatch + respawn) segfaults old jaxlib's CPU
# runtime — so the cache must stay off until every elasticity drill
# has run, and only the expensive packages that sort after elasticity
# opt in (their conftests wrap this fixture autouse).
# test_compile_cache.py saves/restores these knobs around its own
# engine-level cache assertions.
T1_COMPILE_CACHE_DIR = os.environ.get("DS_T1_COMPILE_CACHE",
                                      "/tmp/ds_tpu_t1_xla_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="package")
def persistent_compile_cache():
    """Enable the persistent XLA compile cache for one package (wrapped
    autouse by the opt-in package conftests). Package scope so it is
    active before module-scoped engine fixtures compile."""
    prev = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", T1_COMPILE_CACHE_DIR)
    yield
    jax.config.update("jax_compilation_cache_dir", prev)


@pytest.fixture(autouse=True)
def _reset_mesh():
    """Each test starts with a fresh (uninitialized) global mesh."""
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    yield
    mesh_manager.reset()


@pytest.fixture(autouse=True, scope="module")
def _lifecycle_sweep():
    """Per-module lifecycle sweep (runtime/lifecycle.py): the engine
    object graph is cyclic, so dead engines — device buffers, host
    optimizer state, AOT executables — pile up between Python's
    allocation-count-driven gen-2 GC passes. In a LONG single-process
    suite that accumulation is what flakily SIGABRTed old jaxlib's CPU
    runtime at the post-restore train_batch (the quarantine lifted by
    the lifecycle PR — root cause in runtime/lifecycle.py). One
    gc.collect per test module costs ~ms and keeps the process's
    retained set proportional to ONE module's engines."""
    yield
    from deepspeed_tpu.runtime.lifecycle import sweep
    sweep("test-module teardown")


@pytest.fixture
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs[:8]


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def tiny_lm_batch(rng, batch=8, seq=16, vocab=256):
    ids = rng.integers(0, vocab, size=(batch, seq), dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


# Shared version gate: jaxlib 0.4.x SPMD rejects PartitionId in
# partial-manual shard_map regions, so the pipeline schedule cannot run
# there. Import from test modules as `from tests.conftest import
# SKIP_OLD_XLA_PIPE` — ONE definition, four consumers.
from deepspeed_tpu.utils.jax_compat import OLD_XLA  # noqa: E402

SKIP_OLD_XLA_PIPE = pytest.mark.skipif(
    OLD_XLA,
    reason="jaxlib 0.4.x SPMD partitioner rejects PartitionId in "
           "partial-manual shard_map regions (the pipeline schedule)")
