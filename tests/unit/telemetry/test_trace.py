"""Span tracer (telemetry/trace.py): recording semantics, the ring
bound, the strict disabled no-op, Chrome-trace-format conformance,
and the view CLI's self-time decomposition."""

import json
import threading
import time

import pytest

from deepspeed_tpu.telemetry.trace import (Tracer, span, tracer,
                                           validate_chrome_trace)
from deepspeed_tpu.telemetry.span_sites import SPAN_SITES
from deepspeed_tpu.telemetry import view


@pytest.fixture(autouse=True)
def _clean_singleton():
    """The module singleton must never leak an armed state into other
    tests (the engine suite asserts the disabled path is free)."""
    yield
    tracer.disable()
    tracer.clear()


class TestRecording:

    def test_span_records_name_duration_thread(self):
        t = Tracer(capacity=16)
        t.configure(enabled=True, device_annotations=False)
        with t.span("engine.dispatch", label="train"):
            time.sleep(0.002)
        recs = t.snapshot()
        assert len(recs) == 1
        r = recs[0]
        assert r.name == "engine.dispatch"
        assert r.dur_ns >= 2e6
        assert r.tid == threading.get_ident()
        assert r.args == {"label": "train"}

    def test_nesting_and_threads_recorded_independently(self):
        t = Tracer(capacity=64)
        t.configure(enabled=True, device_annotations=False)

        def worker():
            with t.span("offload.host_step"):
                time.sleep(0.001)

        th = threading.Thread(target=worker)
        with t.span("engine.train_batch"):
            th.start()
            with t.span("engine.dispatch"):
                time.sleep(0.001)
            th.join()
        names = {r.name for r in t.snapshot()}
        tids = {r.tid for r in t.snapshot()}
        assert names == {"engine.train_batch", "engine.dispatch",
                         "offload.host_step"}
        assert len(tids) == 2

    def test_ring_is_bounded_and_counts_drops(self):
        t = Tracer(capacity=8)
        t.configure(enabled=True, device_annotations=False)
        for i in range(20):
            with t.span("schedule.step", i=i):
                pass
        assert len(t) == 8
        assert t.dropped == 12
        # the ring keeps the NEWEST spans
        assert [r.args["i"] for r in t.snapshot()] == list(range(12, 20))

    def test_exception_inside_span_still_records(self):
        t = Tracer(capacity=8)
        t.configure(enabled=True, device_annotations=False)
        with pytest.raises(RuntimeError):
            with t.span("checkpoint.save"):
                raise RuntimeError("boom")
        assert [r.name for r in t.snapshot()] == ["checkpoint.save"]

    def test_instant_marker(self):
        t = Tracer(capacity=8)
        t.configure(enabled=True, device_annotations=False)
        t.instant("supervisor.gate", step=3)
        (r,) = t.snapshot()
        assert r.dur_ns == 0

    def test_span_open_across_clear_does_not_leak(self):
        """A span still open when the window is cleared (the DPU
        worker's offload.host_step outliving a bench config's traced
        step) must not land in the NEXT window — its t0 predates the
        new origin and would export with a negative ts."""
        t = Tracer(capacity=8)
        t.configure(enabled=True, device_annotations=False)
        stale = t.span("offload.host_step")
        stale.__enter__()
        t.clear()                     # new window begins
        with t.span("engine.dispatch"):
            pass
        stale.__exit__(None, None, None)
        assert [r.name for r in t.snapshot()] == ["engine.dispatch"]
        # and a span open across disable() records nothing either
        stale2 = t.span("offload.host_step")
        stale2.__enter__()
        t.disable()
        stale2.__exit__(None, None, None)
        assert [r.name for r in t.snapshot()] == ["engine.dispatch"]


class TestDisabledPath:

    def test_disabled_records_nothing(self):
        assert not tracer.enabled
        with span("engine.train_batch", step=1):
            with span("engine.dispatch"):
                pass
        assert len(tracer) == 0

    def test_disabled_returns_shared_noop(self):
        a = span("engine.dispatch")
        b = span("transfer.d2h", stream=0, bucket=1)
        assert a is b  # one stateless instance, nothing allocated

    def test_configure_capacity_validates(self):
        t = Tracer()
        with pytest.raises(ValueError):
            t.configure(enabled=True, capacity=0)


class TestChromeExport:

    def _populated(self):
        t = Tracer(capacity=32)
        t.configure(enabled=True, device_annotations=False)
        with t.span("engine.train_batch", step=2):
            with t.span("transfer.d2h", stream=0, bucket=0):
                time.sleep(0.001)
        t.instant("alert")
        return t

    def test_export_is_conformant_and_loadable(self, tmp_path):
        t = self._populated()
        path = t.export(str(tmp_path / "trace.json"))
        with open(path) as f:
            obj = json.load(f)
        assert validate_chrome_trace(obj) == []
        evs = obj["traceEvents"]
        assert {e["name"] for e in evs} == {
            "engine.train_batch", "transfer.d2h", "alert"}
        x = [e for e in evs if e["ph"] == "X"]
        assert all("dur" in e for e in x)
        d2h = next(e for e in evs if e["name"] == "transfer.d2h")
        assert d2h["args"] == {"stream": 0, "bucket": 0}
        # child nests inside parent on the timeline
        parent = next(e for e in evs
                      if e["name"] == "engine.train_batch")
        assert parent["ts"] <= d2h["ts"]
        assert parent["ts"] + parent["dur"] >= d2h["ts"] + d2h["dur"]

    def test_validator_rejects_malformed(self):
        assert validate_chrome_trace([]) != []
        assert validate_chrome_trace({"traceEvents": [{"ph": "X"}]})
        bad = {"traceEvents": [{"name": "a", "ph": "X", "ts": 0.0,
                                "pid": 1, "tid": 1}]}  # no dur
        assert any("dur" in e for e in validate_chrome_trace(bad))

    def test_view_summarize_self_time(self, tmp_path):
        t = self._populated()
        stats = view.summarize(t.to_chrome_trace())
        tb = stats["engine.train_batch"]
        d2h = stats["transfer.d2h"]
        assert tb["count"] == 1 and d2h["count"] == 1
        # parent self-time excludes the nested child
        assert tb["self_ms"] <= tb["total_ms"] - d2h["total_ms"] + 1e-6
        out = view.render(stats, top=5)
        assert "transfer.d2h" in out

    def test_view_cli_main(self, tmp_path, capsys):
        t = self._populated()
        path = t.export(str(tmp_path / "t.json"))
        assert view.main([path, "--top", "3"]) == 0
        assert "engine.train_batch" in capsys.readouterr().out
        assert view.main([str(tmp_path / "missing.json")]) == 2


class TestDeviceAnnotations:

    def test_trace_annotation_co_capture_smoke(self):
        """device_annotations=True wraps the span in
        jax.profiler.TraceAnnotation (the xprof co-capture seam);
        recording must still work with it armed."""
        t = Tracer(capacity=8)
        t.configure(enabled=True, device_annotations=True)
        with t.span("schedule.compile", label="x"):
            pass
        assert len(t) == 1


def test_every_registered_span_name_is_dotted():
    """Naming contract: dots, never slashes (slash is the hub's
    namespace separator)."""
    for name in SPAN_SITES:
        assert "/" not in name and "." in name
