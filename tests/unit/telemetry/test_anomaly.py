"""Anomaly watchers (telemetry/anomaly.py): every watcher is a
deterministic function of the sample series — replaying a series
replays the alerts."""

import pytest

from deepspeed_tpu.telemetry.anomaly import (EwmaSpikeWatcher,
                                             SlopeWatcher,
                                             TelemetryAlert,
                                             ThresholdWatcher,
                                             default_watchers)


def _feed(w, series, metric):
    alerts = []
    for step, v in enumerate(series):
        alerts.extend(w.observe({metric: v}, step))
    return alerts


class TestEwmaSpike:

    def test_spike_fires_and_baseline_not_poisoned(self):
        w = EwmaSpikeWatcher("m", factor=3.0, warmup=2)
        series = [10, 10, 10, 10, 50, 10, 50]
        alerts = _feed(w, series, "m")
        # both 50s alert: the first spike must NOT teach the EWMA that
        # 50 is normal
        assert [round(a.value) for a in alerts] == [50, 50]
        a = alerts[0]
        assert a.kind == "ewma_spike" and a.metric == "m"
        assert a.step == 4 and a.threshold == pytest.approx(30.0)
        assert w.spikes == 2

    def test_warmup_is_silent(self):
        w = EwmaSpikeWatcher("m", factor=2.0, warmup=3)
        assert _feed(w, [1, 100, 1], "m") == []

    def test_missing_metric_skipped(self):
        w = EwmaSpikeWatcher("m", factor=2.0)
        assert w.observe({"other": 1.0}, 0) == []

    def test_factor_validated(self):
        with pytest.raises(ValueError):
            EwmaSpikeWatcher("m", factor=1.0)

    def test_replay_identity(self):
        series = [5, 5, 6, 5, 40, 5, 5, 41]
        a = _feed(EwmaSpikeWatcher("m", factor=3.0), series, "m")
        b = _feed(EwmaSpikeWatcher("m", factor=3.0), series, "m")
        assert [x.as_dict() for x in a] == [x.as_dict() for x in b]


class TestThreshold:

    def test_slo_breach_counter(self):
        w = ThresholdWatcher("serving/ttft_ms/p50", max_value=100.0)
        alerts = _feed(w, [50, 150, 80, 200], "serving/ttft_ms/p50")
        assert len(alerts) == 2
        assert w.breaches == 2
        assert alerts[0].kind == "slo_breach"
        assert "breach #1" in alerts[0].message
        assert "breach #2" in alerts[1].message


class TestSlope:

    def test_leak_alerts_and_plateau_ages_out(self):
        w = SlopeWatcher("memory/host_rss_gb",
                         max_slope_per_step=0.01, window=8)
        climb = [1.0 + 0.1 * i for i in range(8)]      # 0.1 GB/step
        alerts = _feed(w, climb, "memory/host_rss_gb")
        assert alerts and alerts[-1].kind == "slope_leak"
        assert alerts[-1].value == pytest.approx(0.1)
        # plateau: the window slides past the climb, slope drops, no
        # further alerts — a one-off jump must not alert forever
        flat_alerts = []
        for step in range(8, 24):
            flat_alerts.extend(
                w.observe({"memory/host_rss_gb": 1.8}, step))
        assert flat_alerts[-1:] == [] or len(flat_alerts) < 8

    def test_needs_four_points(self):
        w = SlopeWatcher("m", max_slope_per_step=0.0, window=8)
        assert _feed(w, [1, 2, 3], "m") == []
        with pytest.raises(ValueError):
            SlopeWatcher("m", 0.1, window=2)


class TestDefaults:

    def test_default_watchers_from_config(self):
        from deepspeed_tpu.runtime.config import TelemetryAnomalyConfig
        cfg = TelemetryAnomalyConfig.from_dict({
            "ttft_slo_ms": 500, "itl_slo_ms": 50,
            "rss_slope_gb_per_step": 0.05,
            "hbm_slope_gb_per_step": 0.1})
        ws = default_watchers(cfg)
        metrics = {getattr(w, "metric") for w in ws}
        assert metrics == {
            "train/step_time_ms", "offload/overlap_residue_ms",
            "serving/ttft_ms/p50", "serving/itl_ms/p50",
            "memory/host_rss_gb", "memory/device_gb_in_use",
            "cache/spill_backlog", "fleet/blockxfer/fetch_exposed_ms"}

    def test_zeros_disable(self):
        from deepspeed_tpu.runtime.config import TelemetryAnomalyConfig
        cfg = TelemetryAnomalyConfig.from_dict({
            "step_time_spike_factor": 0,
            "residue_spike_factor": 0,
            "spill_backlog_slope_per_step": 0,
            "blockxfer_stall_factor": 0})
        assert default_watchers(cfg) == []

    def test_blockxfer_stall_watcher_spikes(self):
        """The peer-fetch stall watch (fleet blockxfer): exposed fetch
        wall spiking against its own EWMA alerts through the standard
        ewma_spike kind — same schema, fleet/blockxfer namespace."""
        from deepspeed_tpu.runtime.config import TelemetryAnomalyConfig
        ws = default_watchers(TelemetryAnomalyConfig())
        w = next(x for x in ws
                 if x.metric == "fleet/blockxfer/fetch_exposed_ms")
        alerts = _feed(w, [5.0, 5.0, 5.0, 5.0, 40.0],
                       "fleet/blockxfer/fetch_exposed_ms")
        assert alerts and alerts[-1].kind == "ewma_spike"
        assert alerts[-1].metric == "fleet/blockxfer/fetch_exposed_ms"

    def test_alert_is_flat_jsonable(self):
        import json
        a = TelemetryAlert("ewma_spike", "m", 1.0, 2.0, 3, "msg")
        d = a.as_dict()
        assert set(d) == {"kind", "metric", "value", "threshold",
                          "step", "message", "severity"}
        json.dumps(d)
