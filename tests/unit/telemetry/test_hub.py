"""TelemetryHub (telemetry/hub.py): flattening rules, the JSONL sink
record schema + rotation + whole-line appends, MonitorMaster fan-out
(the v2-serving-scalars satellite), provider isolation, and sampling
cadence."""

import json
import os

import pytest

from deepspeed_tpu.telemetry.anomaly import EwmaSpikeWatcher
from deepspeed_tpu.telemetry.hub import (JsonlSink, TelemetryHub,
                                         flatten_metrics,
                                         memory_snapshot)


class TestFlatten:

    def test_rules(self):
        flat = flatten_metrics({
            "a": {"b": 1, "c": 2.5, "d": {"e": True}},
            "s": "skipped",
            "l": [1, 2, 3],
            "n": None,
            "f": False,
        })
        assert flat == {"a/b": 1.0, "a/c": 2.5, "a/d/e": 1.0,
                        "f": 0.0}

    def test_namespace_prefix(self):
        assert flatten_metrics({"x": 1}, "serving") == {"serving/x": 1.0}


class TestJsonlSink:

    def test_record_schema_and_whole_lines(self, tmp_path):
        sink = JsonlSink(str(tmp_path / "m.jsonl"))
        hub = TelemetryHub(sink=sink)
        hub.register("train", lambda: {"loss": 1.25, "step_time_ms": 3})
        hub.sample(7)
        recs = sink.read_records()
        assert len(recs) == 1
        r = recs[0]
        # the stable record schema (consumers parse these keys)
        assert set(r) == {"kind", "step", "t", "metrics"}
        assert r["kind"] == "sample" and r["step"] == 7
        assert r["metrics"] == {"train/loss": 1.25,
                                "train/step_time_ms": 3.0}
        # every line on disk parses independently (whole-line appends)
        with open(sink.path) as f:
            for line in f:
                json.loads(line)

    def test_rotation_bounds_disk(self, tmp_path):
        sink = JsonlSink(str(tmp_path / "m.jsonl"), max_bytes=2048)
        hub = TelemetryHub(sink=sink)
        hub.register("pad", lambda: {f"k{i}": i for i in range(40)})
        for i in range(50):
            hub.sample(i)
        assert os.path.getsize(sink.path) <= 2048 + 1024
        assert os.path.exists(sink.path + ".1")
        # nothing beyond two generations
        assert not os.path.exists(sink.path + ".2")
        # records survive rotation and still parse
        assert len(sink.read_records()) > 2

    def test_min_size_validates(self, tmp_path):
        with pytest.raises(ValueError):
            JsonlSink(str(tmp_path / "m.jsonl"), max_bytes=16)


class TestMonitorFanout:

    def test_serving_scalars_reach_csv_monitor(self, tmp_path):
        """THE satellite: v2 serving scalars flow through the hub into
        MonitorMaster's csv backend — historically _write_monitor only
        ever saw training metrics."""
        import dataclasses

        from deepspeed_tpu.monitor.monitor import csvMonitor

        @dataclasses.dataclass
        class CsvCfg:
            enabled: bool = True
            output_path: str = str(tmp_path)
            job_name: str = "job"

        mon = csvMonitor(CsvCfg())
        hub = TelemetryHub(monitor=mon)
        hub.register("serving", lambda: {
            "itl_ms": {"p50": 4.2}, "kv_util": {"max": 0.8},
            "recompiles": 1,
            "caches": {"noise": {"size": 3}},
        })
        hub.sample(5)
        written = os.listdir(os.path.join(str(tmp_path), "job"))
        assert "serving_itl_ms_p50.csv" in written
        assert "serving_recompiles.csv" in written
        # cache internals are filtered from the monitor fan-out
        assert not any("caches" in f for f in written)
        with open(os.path.join(str(tmp_path), "job",
                               "serving_itl_ms_p50.csv")) as f:
            rows = f.read().splitlines()
        assert rows[-1] == "5,4.2"

    def test_disabled_monitor_not_written(self):
        class Mon:
            enabled = False
            calls = 0

            def write_events(self, evs):
                self.calls += 1

        mon = Mon()
        hub = TelemetryHub(monitor=mon)
        hub.register("a", lambda: {"x": 1})
        hub.sample(0)
        assert mon.calls == 0


class TestHubBehavior:

    def test_provider_failure_is_isolated(self):
        hub = TelemetryHub()
        hub.register("bad", lambda: 1 / 0)
        hub.register("good", lambda: {"x": 1})
        flat = hub.sample(0)
        assert flat == {"good/x": 1.0}
        # and again without spamming (warn-once path)
        assert hub.sample(1) == {"good/x": 1.0}

    def test_sample_interval(self):
        hub = TelemetryHub(sample_interval_steps=5)
        hub.register("a", lambda: {"x": 1})
        assert hub.maybe_sample(3) is None
        assert hub.maybe_sample(5) == {"a/x": 1.0}
        assert hub.samples_taken == 1

    def test_reregister_replaces_and_namespace_validated(self):
        hub = TelemetryHub()
        hub.register("a", lambda: {"x": 1})
        hub.register("a", lambda: {"x": 2})
        assert hub.sample(0) == {"a/x": 2.0}
        with pytest.raises(ValueError):
            hub.register("a/b", lambda: {})
        hub.unregister("a")
        assert hub.sample(1) == {}

    def test_alerts_ride_sink_and_recovery_report(self, tmp_path):
        from deepspeed_tpu.resilience.recovery import RecoveryReport

        rec = RecoveryReport()
        sink = JsonlSink(str(tmp_path / "m.jsonl"))
        hub = TelemetryHub(
            sink=sink, recovery=rec,
            watchers=[EwmaSpikeWatcher("a/x", factor=2.0, warmup=1)])
        vals = iter([10.0, 10.0, 10.0, 100.0])
        hub.register("a", lambda: {"x": next(vals)})
        for i in range(4):
            hub.sample(i)
        assert len(hub.alerts) == 1
        assert hub.alert_counts() == {"ewma_spike": 1}
        alert_recs = [r for r in sink.read_records()
                      if r["kind"] == "alert"]
        assert len(alert_recs) == 1
        assert alert_recs[0]["alert"]["metric"] == "a/x"
        # the recovery report carries it too
        assert rec.as_dict()["alert_count"] == 1
        assert rec.as_dict()["alerts"][0]["kind"] == "ewma_spike"


def test_memory_snapshot_schema():
    snap = memory_snapshot()
    assert set(snap) == {"device_gb_in_use", "device_gb_peak",
                         "host_rss_gb", "live_executables",
                         "param_store_gb", "param_mirror_gb",
                         "param_device_gb"}
    assert snap["host_rss_gb"] > 0
    # param-residency gauges: always present, zero with no wire armed
    assert snap["param_store_gb"] == 0.0
    assert snap["param_mirror_gb"] == 0.0
    assert snap["param_device_gb"] == 0.0
