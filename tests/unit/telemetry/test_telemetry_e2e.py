"""End-to-end telemetry proof (ISSUE 8 acceptance) + the stable-key
schema contracts for every report surface.

One tiny ZeRO-Offload engine with the full telemetry config drives
the whole pipe: per-bucket d2h spans land in a Perfetto-loadable
trace, every report surface + the memory gauges flow through the
JSONL stream (the v2 serving engine attached to the SAME hub), and an
injected ``slow`` fault (the PR-7 injector kind) deterministically
raises a ``TelemetryAlert`` that reaches the hub, the JSONL sink and
the recovery report. The perf-marked smoke holds the DISABLED
tracer's instrumentation cost to <1% of a train-step microbench (the
tier-1 budget guard)."""

import json
import os
import time

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.telemetry import tracer, validate_chrome_trace

# steady-state steps before the injected stall: the spike watcher's
# warmup (3 samples: compile + settle) plus two baseline samples
_WARM_STEPS = 5
_SLOW_SECONDS = 2.5
_SPIKE_FACTOR = 3.0


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("telemetry_e2e")
    jsonl = str(tmp / "metrics.jsonl")
    cfg = {
        "train_micro_batch_size_per_gpu": 4,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 2,
            "offload_optimizer": {
                "device": "cpu",
                # fractional-MB buckets force a real multi-bucket d2h
                # schedule on the tiny model (the per-bucket spans the
                # trace must decompose)
                "transfer": {"enabled": True, "bucket_mb": 1 / 64}}},
        "steps_per_print": 0,
        "telemetry": {
            "enabled": True, "sample_interval_steps": 1,
            "jsonl_path": jsonl,
            "trace": {"enabled": True, "capacity": 16384},
            "anomaly": {"step_time_spike_factor": _SPIKE_FACTOR},
        },
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    for _ in range(_WARM_STEPS):
        float(engine.train_batch(batch=batch))

    # ---- the injected stall (PR-7 fault grammar, ``slow`` kind):
    # one bucket wait at the offload.d2h site sleeps, the step wall
    # spikes, the EWMA watcher must alert — every time
    fault_injector.configure(f"offload.d2h:slow~{_SLOW_SECONDS}")
    try:
        float(engine.train_batch(batch=batch))
    finally:
        fault_injector.reset()

    # ---- the v2 serving engine rides the SAME hub (the serving-
    # scalars satellite): one short run, then one more train step so
    # the hub samples every surface at once
    import jax
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.engine_v2 import \
        RaggedInferenceEngineConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    lcfg = LlamaConfig.tiny()
    lmodel = LlamaForCausalLM(lcfg)
    params = lmodel.init(jax.random.PRNGKey(0),
                         np.zeros((1, 8), np.int32))
    v2 = InferenceEngineV2(
        params, lcfg,
        RaggedInferenceEngineConfig(
            token_budget=32, max_ragged_sequence_count=4,
            n_kv_blocks=16, kv_block_size=8, max_blocks_per_seq=8,
            kv_dtype="float32"))
    v2.attach_telemetry(engine.telemetry)
    v2.generate_batch({1: [3, 1, 4], 2: [1, 5]}, max_new_tokens=4,
                      mode="lookahead")
    float(engine.train_batch(batch=batch))

    trace_path = tracer.export(str(tmp / "e2e.trace.json"))
    yield {"engine": engine, "v2": v2, "batch": batch,
           "jsonl": jsonl, "trace_path": trace_path}
    engine.close()
    tracer.disable()
    tracer.clear()


def _records(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


class TestEndToEnd:

    def test_trace_decomposes_per_bucket_d2h(self, setup):
        """(a) the exported trace is Perfetto-loadable and the
        per-bucket d2h spans visibly decompose the offload host step
        (the config-4 stall evidence class)."""
        with open(setup["trace_path"]) as f:
            obj = json.load(f)
        assert validate_chrome_trace(obj) == []
        evs = obj["traceEvents"]
        d2h = [e for e in evs if e["name"] == "transfer.d2h"]
        # one span per bucket per host step, carrying (stream, bucket)
        assert len(d2h) > _WARM_STEPS
        assert {("stream" in e["args"], "bucket" in e["args"])
                for e in d2h} == {(True, True)}
        assert len({e["args"]["bucket"] for e in d2h}) > 1
        names = {e["name"] for e in evs}
        assert {"engine.train_batch", "engine.dispatch",
                "offload.host_step", "offload.adam", "transfer.h2d",
                "schedule.compile", "schedule.step",
                "serving.schedule", "serving.dispatch",
                "serving.collect"} <= names
        # d2h waits nest inside the offload host step's interval
        host = [e for e in evs if e["name"] == "offload.host_step"]
        spans = [(h["ts"], h["ts"] + h["dur"]) for h in host]
        covered = sum(any(s <= e["ts"] and e["ts"] + e["dur"] <= t
                          for s, t in spans) for e in d2h)
        assert covered == len(d2h)

    def test_view_ranks_the_injected_stall(self, setup):
        """The CLI's self-time ranking must surface where the stalled
        step's time went: transfer.d2h self-time dominated by the
        injected sleep."""
        from deepspeed_tpu.telemetry import view
        with open(setup["trace_path"]) as f:
            stats = view.summarize(json.load(f))
        assert stats["transfer.d2h"]["max_ms"] >= _SLOW_SECONDS * 1e3
        assert stats["transfer.d2h"]["self_ms"] >= \
            _SLOW_SECONDS * 1e3

    def test_jsonl_stream_carries_all_four_surfaces(self, setup):
        """(b) one JSONL stream with samples from all four report
        surfaces + the memory gauges."""
        samples = [r for r in _records(setup["jsonl"])
                   if r["kind"] == "sample"]
        assert len(samples) >= _WARM_STEPS
        for r in samples:
            assert set(r) == {"kind", "step", "t", "metrics"}
        last = samples[-1]["metrics"]
        namespaces = {k.split("/")[0] for k in last}
        assert {"train", "schedule", "offload", "recovery", "memory",
                "serving"} <= namespaces
        # spot-check the load-bearing scalars of each surface
        assert last["offload/grad_d2h_ms"] >= 0
        assert last["schedule/collective_count"] >= 0
        assert last["serving/steady_decode_tps"] >= 0
        # the speculation block reaches the stream even when spec is
        # off (stable key set: acceptance rate is always publishable)
        assert last["serving/speculation/acceptance_rate"] >= 0
        assert last["memory/host_rss_gb"] > 0
        assert last["train/step_time_ms"] > 0

    def test_slow_fault_raises_deterministic_alert(self, setup):
        """(c) the injected ``slow`` fault alerts — in the hub, the
        JSONL stream, and the recovery report."""
        hub = setup["engine"].telemetry
        spikes = [a for a in hub.alerts if a.kind == "ewma_spike"
                  and a.metric == "train/step_time_ms"]
        assert spikes, f"no spike alert; alerts={list(hub.alerts)}"
        a = spikes[0]
        assert a.value >= _SLOW_SECONDS * 1e3
        # sampled AFTER the step's bookkeeping: the faulted step is
        # global step warm+1, exactly
        assert a.step == _WARM_STEPS + 1
        alert_recs = [r for r in _records(setup["jsonl"])
                      if r["kind"] == "alert"]
        assert any(r["alert"]["metric"] == "train/step_time_ms"
                   for r in alert_recs)
        rep = setup["engine"].get_recovery_report()
        assert rep["alert_count"] >= 1
        assert any(al["kind"] == "ewma_spike" for al in rep["alerts"])


class TestReportSchemas:
    """Stable-key contracts: downstream consumers (hub flattening,
    bench decompositions, dashboards) parse these dicts — a renamed
    key is a silent break, so renames must be deliberate (update here
    + README)."""

    def test_schedule_report_keys(self, setup):
        rep = setup["engine"].get_schedule_report()
        assert set(rep) == {
            "collective_count", "bytes_moved", "collectives", "flops",
            "bytes_accessed", "est_compute_ms", "est_comm_ms",
            "overlap_estimate", "options_applied", "options_dropped",
            "donation_refused", "process_memory", "param_stream"}
        for v in rep["collectives"].values():
            assert set(v) == {"count", "bytes"}
        assert set(rep["donation_refused"]) == {"count", "bytes"}
        # param-residency wire block: always present; collapsed to
        # {"enabled": False} when the wire is off (this fixture)
        assert rep["param_stream"] == {"enabled": False}

    def test_offload_breakdown_keys(self, setup):
        rep = setup["engine"].get_offload_breakdown()
        # d2h_exposed_ms/d2h_overlapped_ms: the wire-clock split of
        # grad_d2h_ms (PR 10) — present on the bucketed AND streamed
        # wires; streamed runs swap d2h_buckets for d2h_groups
        # the param_* keys are the param-residency wire's split
        # (runtime/zero/param_stream.py) — present as zeros whenever
        # ANY offload surface reports, so the stable schema holds
        # across configs with and without the wire
        assert set(rep) == {
            "grad_d2h_ms", "host_adam_ms", "param_h2d_ms",
            "d2h_buckets", "h2d_buckets", "overlap_residue_ms",
            "d2h_exposed_ms", "d2h_overlapped_ms",
            "post_restore_repairs",
            "param_d2h_exposed_ms", "param_d2h_overlapped_ms",
            "param_h2d_exposed_ms", "param_h2d_overlapped_ms",
            "param_fetch_ms",
            "param_drop_exposed_ms", "param_drop_overlapped_ms"}

    def test_recovery_report_keys(self, setup):
        rep = setup["engine"].get_recovery_report()
        assert set(rep) == {
            "detections", "ladder", "alerts", "alert_count",
            "rung_counts", "mttr_s", "resharded_bytes",
            "process_memory"}
        assert set(rep["mttr_s"]) == {"last", "mean", "max"}
        assert set(rep["rung_counts"]) == {
            "retry", "rollback", "shrink", "terminal"}

    def test_serving_report_keys(self, setup):
        rep = setup["v2"].get_serving_report()
        assert set(rep) == {
            "mode", "steps", "decode_steps", "tokens_emitted",
            "prompt_tokens", "recompiles", "blocking_syncs",
            "steady_steps", "steady_blocking_syncs",
            "steady_decode_tps", "cancelled_speculative_steps",
            "speculation", "admission", "requests",
            "request_latency_ms", "dispatch_ms", "sync_wait_ms",
            "step_ms", "ttft_ms", "itl_ms", "queue_depth", "kv_util",
            "process_memory"}
        assert set(rep["admission"]) == {"requested", "admitted",
                                         "shed", "shed_uids"}
        assert set(rep["requests"]) == {"submitted", "finished",
                                        "cancelled", "shed"}
        # the speculation block is ALWAYS present (zeros when off) so
        # JSONL/monitor streams keep a stable key set spec-on/off
        assert set(rep["speculation"]) == {
            "drafted_tokens", "accepted_tokens", "rejected_tokens",
            "emitted_tokens", "acceptance_rate", "verify_steps",
            "verify_rows", "mean_accepted_len", "emitted_per_verify",
            "throttled_uids", "draft_faults", "verify_dispatch_ms"}

    def test_process_memory_keys(self, setup):
        for rep in (setup["engine"].get_schedule_report(),
                    setup["engine"].get_recovery_report(),
                    setup["v2"].get_serving_report()):
            assert set(rep["process_memory"]) == {
                "device_bytes_in_use", "device_peak_bytes",
                "host_rss_gb", "live_executables", "caches"}


@pytest.mark.perf
class TestDisabledOverhead:
    """The tier-1 budget guard: instrumentation must be free when
    tracing is off."""

    def test_disabled_tracer_under_one_percent_of_train_step(
            self, setup):
        from deepspeed_tpu.telemetry.trace import span
        engine, batch = setup["engine"], setup["batch"]
        tracer.disable()
        # steady-state step wall, tracer disabled (already compiled)
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            float(engine.train_batch(batch=batch))
            times.append(time.perf_counter() - t0)
        step_s = sorted(times)[1]
        # measured cost of one disabled span() call (kwargs included)
        before = len(tracer)
        n = 20000
        t0 = time.perf_counter()
        for _ in range(n):
            with span("engine.dispatch", label="x"):
                pass
        per_span_s = (time.perf_counter() - t0) / n
        # strict no-op: nothing new recorded (the ring still holds the
        # e2e module's spans)
        assert len(tracer) == before
        # a heavily bucketed step opens O(100) spans; hold 1000 to the
        # budget for an order-of-magnitude safety margin
        overhead = 1000 * per_span_s
        assert overhead < 0.01 * step_s, (
            f"disabled tracing would cost {overhead * 1e3:.3f}ms on a "
            f"{step_s * 1e3:.1f}ms step (>1%)")
