"""bench.py's per-config telemetry artifacts: the traced step exports
a conformant Chrome trace, the hub sample lands in the JSONL sink,
and the row block carries the span census + artifact paths."""

import importlib.util
import json
import os

import pytest

from deepspeed_tpu.telemetry import validate_chrome_trace
from deepspeed_tpu.telemetry.trace import span, tracer

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    yield mod
    tracer.disable()
    tracer.clear()


def test_artifacts_block(bench, tmp_path, monkeypatch):
    monkeypatch.setenv("DSTPU_TRACE_DIR", str(tmp_path))

    def traced():
        with span("engine.train_batch", step=0):
            with span("engine.dispatch"):
                pass

    block = bench._telemetry_artifacts(
        "cfgX", {"train": lambda: {"loss": 2.0},
                 "memory": lambda: {"host_rss_gb": 1.0}},
        traced_fn=traced, step=7)
    # trace artifact: on disk, conformant, censused in the row
    with open(block["trace"]) as f:
        assert validate_chrome_trace(json.load(f)) == []
    assert block["spans"]["engine.dispatch"]["count"] == 1
    # hub sample: one record in the jsonl beside it
    with open(block["jsonl"]) as f:
        (rec,) = [json.loads(line) for line in f]
    assert rec["step"] == 7
    assert rec["metrics"] == {"train/loss": 2.0,
                              "memory/host_rss_gb": 1.0}
    assert block["metrics_sampled"] == 2
    assert block["namespaces"] == ["memory", "train"]
    # the tracer is disarmed afterwards (bench timing must not pay)
    assert not tracer.enabled


def test_no_traced_fn_still_samples(bench, tmp_path, monkeypatch):
    monkeypatch.setenv("DSTPU_TRACE_DIR", str(tmp_path))
    block = bench._telemetry_artifacts(
        "cfgY", {"a": lambda: {"x": 1}})
    assert "trace" not in block
    assert os.path.exists(block["jsonl"])
