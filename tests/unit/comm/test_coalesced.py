"""Eager gradient-coalescing collectives and the scatter divisibility
guard."""

import numpy as np
import pytest

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm import comm as comm_mod
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


def _init_data_mesh():
    mesh_manager.init(MeshConfig(data=-1))
    return mesh_manager.axis_size("data")


def test_all_reduce_coalesced_matches_per_tensor(eight_devices, rng):
    world = _init_data_mesh()
    # exactly-representable values -> per-tensor vs fused results must
    # be EQUAL, not merely close
    tensors = [rng.integers(-8, 8, size=(world * k, 3)
                            ).astype(np.float32)
               for k in (1, 2, 5, 1, 3)]
    ref = [np.asarray(dist.all_reduce(t, group="data")) for t in tensors]
    got = dist.all_reduce_coalesced(tensors, group="data")
    for r, g in zip(ref, got):
        np.testing.assert_array_equal(r, np.asarray(g))


def test_all_reduce_coalesced_fuses_dispatches(eight_devices, rng,
                                               monkeypatch):
    """N small same-dtype tensors ride ceil(total/bucket) collectives,
    not N — counted at the eager dispatch seam."""
    world = _init_data_mesh()
    tensors = [rng.normal(size=(world, 64)).astype(np.float32)
               for _ in range(8)]
    calls = []
    real = comm_mod._dispatch
    monkeypatch.setattr(comm_mod, "_dispatch",
                        lambda name, thunk: (calls.append(name),
                                             real(name, thunk))[1])
    big = 1 << 20
    dist.all_reduce_coalesced(tensors, group="data", bucket_bytes=big)
    assert len(calls) == 1          # everything fits one bucket
    calls.clear()
    # per-column budget = bucket_bytes // world; 64 cols of fp32 = 256 B
    dist.all_reduce_coalesced(tensors, group="data",
                              bucket_bytes=64 * 4 * world)
    total_cols = 64 * 8
    assert len(calls) == -(-total_cols // 64)  # ceil(cols/64) buckets
    assert len(calls) < 8 * 64                 # and far fewer than leaves


def test_all_reduce_coalesced_mixed_dtypes_and_avg(eight_devices, rng):
    world = _init_data_mesh()
    a = rng.integers(0, 4, size=(world, 5)).astype(np.float32)
    b = rng.integers(0, 4, size=(world * 2,)).astype(np.float64)
    ref = [np.asarray(dist.all_reduce(x, dist.ReduceOp.AVG,
                                      group="data")) for x in (a, b)]
    got = dist.all_reduce_coalesced([a, b], dist.ReduceOp.AVG,
                                    group="data")
    for r, g in zip(ref, got):
        np.testing.assert_allclose(r, np.asarray(g), rtol=1e-7)


def test_all_reduce_coalesced_promotes_like_per_tensor(eight_devices):
    """int inputs under AVG promote to float exactly like per-tensor
    all_reduce — writing results back into input-dtype buffers would
    silently truncate the fractional averages (review finding)."""
    world = _init_data_mesh()
    t = np.arange(world * 3, dtype=np.int32).reshape(world, 3)
    ref = np.asarray(dist.all_reduce(t, dist.ReduceOp.AVG, group="data"))
    (got,) = dist.all_reduce_coalesced([t], dist.ReduceOp.AVG,
                                       group="data")
    got = np.asarray(got)
    assert got.dtype == ref.dtype
    np.testing.assert_allclose(ref, got, rtol=1e-7)


def test_all_reduce_coalesced_zero_size_passthrough(eight_devices):
    world = _init_data_mesh()
    empty = np.zeros((0, 4), np.float32)
    full = np.ones((world, 2), np.float32)
    out = dist.all_reduce_coalesced([empty, full], group="data")
    assert np.asarray(out[0]).shape == (0, 4)
    np.testing.assert_array_equal(np.asarray(out[1]),
                                  np.asarray(dist.all_reduce(
                                      full, group="data")))


def test_all_reduce_coalesced_rejects_indivisible(eight_devices):
    world = _init_data_mesh()
    bad = np.zeros((world + 1, 2), np.float32)
    with pytest.raises(ValueError, match="not divisible by"):
        dist.all_reduce_coalesced([bad], group="data")


def test_all_reduce_coalesced_empty_list():
    assert dist.all_reduce_coalesced([]) == []


def test_scatter_rejects_truncating_shapes(eight_devices):
    """The old chunking used floor division and silently DROPPED the
    remainder rows; now a non-divisible leading dim is a loud error."""
    world = _init_data_mesh()
    ok = np.arange(world * 2 * 3, dtype=np.float32).reshape(world * 2, 3)
    out = np.asarray(dist.scatter(ok, group="data"))
    assert out.shape[0] * world == ok.shape[0] * world  # sanity: ran
    bad = np.zeros((world * 2 + 1, 3), np.float32)
    with pytest.raises(ValueError, match="silently"):
        dist.scatter(bad, group="data")
