"""Deeper collective-facade coverage: p2p/permute ops, multi-axis
groups, eager-vs-traced parity, and the bandwidth-accounting math
(reference pattern: tests/unit/comm/test_dist.py + the NCCL-tests busbw
convention asserted by deepspeed/utils/comms_logging.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

import deepspeed_tpu.comm as dist
from deepspeed_tpu.comm.comms_logging import (calc_bw_log, convert_size,
                                              get_msg_size_from_args)
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


@pytest.fixture
def data8(eight_devices):
    mesh_manager.init(MeshConfig(data=8))
    yield


@pytest.fixture
def data4_fsdp2(eight_devices):
    mesh_manager.init(MeshConfig(data=4, fsdp=2))
    yield


def test_ppermute_ring_shift(data8):
    x = jnp.arange(8, dtype=jnp.float32)       # shard i holds i
    perm = [(i, (i + 1) % 8) for i in range(8)]
    out = dist.ppermute(x, perm, group="data")
    np.testing.assert_allclose(np.asarray(out),
                               np.roll(np.arange(8, dtype=np.float32), 1))


def test_send_recv_next_is_unit_ring_shift(data8):
    x = jnp.arange(8, dtype=jnp.float32)
    out = dist.send_recv_next(x, group="data")
    np.testing.assert_allclose(np.asarray(out),
                               np.roll(np.arange(8, dtype=np.float32), 1))


def test_reduce_and_scatter_ops(data8):
    x = jnp.arange(8, dtype=jnp.float32)
    out = dist.reduce(x, dst=2, group="data")
    # every shard's value summed; SPMD result visible on all shards
    assert np.asarray(out).max() == 28.0
    y = jnp.arange(8, dtype=jnp.float32)
    s = dist.scatter(y, src=0, group="data")
    np.testing.assert_allclose(np.asarray(s), np.arange(8, dtype=np.float32))


def test_broadcast_object_list(data8):
    objs = [{"a": 1, "b": [2, 3]}, None]
    out = dist.broadcast_object_list(objs, src=0)
    assert out[0] == {"a": 1, "b": [2, 3]}


def test_world_and_rank_queries(data4_fsdp2):
    assert dist.get_world_size() == 8
    assert dist.get_world_size(group="data") == 4
    assert dist.get_world_size(group="fsdp") == 2
    assert dist.get_world_size(group=("data", "fsdp")) == 8
    assert dist.get_rank() == 0          # SPMD single-process view
    assert dist.is_initialized()


def test_all_reduce_over_joint_axes(data4_fsdp2):
    """A group naming two mesh axes must reduce over their product —
    the ZeRO 'data+fsdp are both data-parallel' invariant."""
    mesh = mesh_manager.mesh

    def fn(x):
        return dist.all_reduce(x, group=("data", "fsdp"))

    wrapped = shard_map(fn, mesh=mesh, in_specs=(P(("data", "fsdp")),),
                        out_specs=P(("data", "fsdp")), check_vma=False)
    x = jnp.ones((8,), jnp.float32)
    np.testing.assert_allclose(np.asarray(jax.jit(wrapped)(x)),
                               np.full(8, 8.0))


def test_all_reduce_over_single_axis_of_2d_mesh(data4_fsdp2):
    """Reducing over only the fsdp axis must keep data-axis values
    distinct."""
    mesh = mesh_manager.mesh

    def fn(x):
        return dist.all_reduce(x, group="fsdp")

    wrapped = shard_map(fn, mesh=mesh,
                        in_specs=(P(("data", "fsdp")),),
                        out_specs=P(("data", "fsdp")), check_vma=False)
    # shard (d, f) holds value d  ->  after fsdp-reduce: 2*d
    x = jnp.repeat(jnp.arange(4, dtype=jnp.float32), 2)
    out = jax.jit(wrapped)(x)
    np.testing.assert_allclose(np.asarray(out),
                               np.repeat(2 * np.arange(4, dtype=np.float32), 2))


def test_eager_traced_parity_all_gather(data8):
    """The facade must produce identical bytes whether called eagerly
    or inside a jitted shard_map region."""
    mesh = mesh_manager.mesh
    x = jnp.arange(8, dtype=jnp.float32)

    eager = np.asarray(dist.all_gather(x, group="data"))

    def fn(xs):
        return dist.all_gather(xs, group="data")

    traced = jax.jit(shard_map(fn, mesh=mesh, in_specs=(P("data"),),
                               out_specs=P(), check_vma=False))(x)
    np.testing.assert_array_equal(eager, np.asarray(traced)[:8])


def test_eager_traced_parity_reduce_scatter(data8):
    mesh = mesh_manager.mesh
    x = jnp.ones((8, 4), jnp.float32)
    eager = np.asarray(dist.reduce_scatter(x, group="data"))

    def fn(xs):
        return dist.reduce_scatter(xs, group="data")

    traced = jax.jit(shard_map(fn, mesh=mesh, in_specs=(P(None, None),),
                               out_specs=P("data", None), check_vma=False))(x)
    np.testing.assert_allclose(eager, np.asarray(traced))


# ---------------- bandwidth accounting ----------------

def test_busbw_follows_nccl_tests_convention():
    size, dur, n = 1 << 30, 1000.0, 8      # 1 GiB in 1 s on 8 ranks
    gib = (1 << 30) / 1e9
    alg, bus = calc_bw_log("all_reduce", size, dur, n)
    assert alg == pytest.approx(2 * gib)
    assert bus == pytest.approx(gib * 2 * 7 / 8)
    alg, bus = calc_bw_log("all_gather", size, dur, n)
    assert alg == pytest.approx(8 * gib)
    assert bus == pytest.approx(8 * gib * 7 / 8)
    alg, bus = calc_bw_log("all_to_all_single", size, dur, n)
    assert alg == pytest.approx(gib)
    assert bus == pytest.approx(gib * 7 / 8)
    alg, bus = calc_bw_log("broadcast", size, dur, n)
    assert alg == bus == pytest.approx(gib)


def test_bw_log_handles_zero_duration_and_ranks():
    alg, bus = calc_bw_log("all_reduce", 1024, 0.0, 0)
    assert np.isfinite(alg) and np.isfinite(bus)


def test_msg_size_counts_pytree_bytes():
    tree = {"a": jnp.zeros((4, 4), jnp.float32),
            "b": [jnp.zeros((8,), jnp.bfloat16)]}
    assert get_msg_size_from_args(tree) == 4 * 4 * 4 + 8 * 2
    assert get_msg_size_from_args({}) == 0


def test_convert_size_units():
    assert convert_size(0) == "0B"
    assert convert_size(512) == "512.0 B"
    assert convert_size(1536) == "1.5 KB"
    assert convert_size(1 << 20) == "1.0 MB"


def test_summary_aggregates_multiple_ops(data8):
    # the logger is a module-global singleton: start from a clean slate
    # (other tests in a full-suite run may have recorded ops already)
    dist.comms_logger.comms_dict.clear()
    dist.configure(enabled=True)
    try:
        x = jnp.ones((64,), jnp.float32)
        for _ in range(3):
            dist.all_reduce(x, group="data")
        dist.all_gather(x, group="data")
        stats = dist.comms_logger.log_all(print_log=False)
        assert "all_reduce" in stats and "all_gather" in stats
        # 3 calls of the same op at the same size aggregate under one key
        records = stats["all_reduce"][64 * 4]
        assert records["count"] == 3
        assert records["total_latency_ms"] >= records["avg_latency_ms"]
    finally:
        dist.configure(enabled=False)
