"""Compressed-collective internals: 1-bit wire packing, the
error-feedback compressor contract, and quantizer edge cases
(reference shape: tests/onebit/test_nccl_backend.py — wire-level
correctness of the compressed allreduce — plus quantizer unit tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.comm.compressed import (_block_dequantize,
                                           _block_quantize, _pack_signs,
                                           _unpack_signs, onebit_allreduce,
                                           onebit_compress)
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


def test_sign_pack_unpack_roundtrip(rng):
    n = 64
    signs = jnp.asarray(rng.integers(0, 2, n).astype(bool))
    packed = _pack_signs(signs)
    assert packed.shape == (n // 8,) and packed.dtype == jnp.uint8
    back = _unpack_signs(packed[None], n)[0]
    np.testing.assert_array_equal(np.asarray(back) > 0, np.asarray(signs))
    # exactly one bit per element on the wire
    assert packed.size * 8 == n


def test_unpack_truncates_padding():
    signs = jnp.asarray([True, False, True, False, False])  # n=5, pad 3
    packed = _pack_signs(jnp.concatenate([signs, jnp.zeros(3, bool)]))
    back = _unpack_signs(packed[None], 5)[0]
    assert back.shape == (5,)
    np.testing.assert_array_equal(np.asarray(back),
                                  [1.0, -1.0, 1.0, -1.0, -1.0])


def test_onebit_compressor_is_l1_scaled_sign(rng):
    x = jnp.asarray(rng.standard_normal(256).astype(np.float32))
    err = jnp.zeros_like(x)
    compressed, new_err = onebit_compress(x, err)
    scale = float(jnp.mean(jnp.abs(x)))
    np.testing.assert_allclose(np.asarray(jnp.abs(compressed)),
                               np.full(256, scale), rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(compressed) >= 0,
                                  np.asarray(x) >= 0)
    # residual definition: x + err - compressed
    np.testing.assert_allclose(np.asarray(new_err),
                               np.asarray(x - compressed), rtol=1e-5)


def test_error_feedback_accumulates_unsent_mass(rng):
    """The defining property of error feedback: what compression drops
    this step is re-injected next step, so the RUNNING SUM of
    compressed outputs tracks the running sum of inputs."""
    x = jnp.asarray(rng.standard_normal(128).astype(np.float32))

    def drift_after(T):
        err = jnp.zeros_like(x)
        sent = jnp.zeros_like(x)
        for _ in range(T):
            c, err = onebit_compress(x, err)
            sent = sent + c
        # telescoping: sum(sent) = T*x + err_0 - err_T
        # => drift = |err_T| / T, which must shrink with the horizon
        return np.abs(np.asarray(sent / T - x)).max()

    d10, d50, d200 = drift_after(10), drift_after(50), drift_after(200)
    assert d50 < d10 and d200 < d50, (d10, d50, d200)
    assert d200 < d10 / 2, (d10, d200)
    # a compressor WITHOUT error feedback never improves: its drift is
    # constant at |x - sign(x)*mean|x|| regardless of horizon
    no_ef = np.abs(np.asarray(
        x - jnp.where(x >= 0, jnp.mean(jnp.abs(x)),
                      -jnp.mean(jnp.abs(x))))).max()
    assert d200 < no_ef


def test_onebit_allreduce_agrees_with_mean(eight_devices, rng):
    mesh_manager.reset()
    mesh = mesh_manager.init(MeshConfig(data=8), devices=eight_devices)
    per_shard = 32
    x = rng.standard_normal((8 * per_shard,)).astype(np.float32)

    def body(xs):
        out, err = onebit_allreduce(xs, jnp.zeros_like(xs), "data")
        return out

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"), check_vma=False))(
        jnp.asarray(x))
    # each shard compressed its chunk to sign*scale; the mean of the
    # compressed contributions preserves the sign structure of the mean
    got = np.asarray(out).reshape(8, per_shard)
    # all shards' outputs must be IDENTICAL (it is an allreduce)
    for k in range(1, 8):
        np.testing.assert_allclose(got[k], got[0], rtol=1e-6)


def test_block_quantize_edge_cases():
    # all-zero input: scale must not divide by zero
    z = jnp.zeros((64,), jnp.float32)
    q, s = _block_quantize(z)
    back = _block_dequantize(q, s, 64, jnp.float32)
    np.testing.assert_array_equal(np.asarray(back), 0.0)
    # single huge outlier: its block saturates at int8 range, exact at
    # the extremes
    x = jnp.zeros((64,), jnp.float32).at[7].set(1000.0)
    q, s = _block_quantize(x)
    back = _block_dequantize(q, s, 64, jnp.float32)
    assert float(back[7]) == pytest.approx(1000.0, rel=1e-2)


def test_block_quantize_non_multiple_length(rng):
    # n not a multiple of the block: padding must round-trip cleanly
    x = jnp.asarray(rng.standard_normal(100).astype(np.float32))
    q, s = _block_quantize(x)
    back = _block_dequantize(q, s, 100, jnp.float32)
    assert back.shape == (100,)
    np.testing.assert_allclose(np.asarray(back), np.asarray(x),
                               atol=float(jnp.abs(x).max()) / 100)
