"""Collective facade correctness on the simulated 8-device mesh
(reference test pattern: tests/unit/comm/test_dist.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu.comm as dist
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


@pytest.fixture(autouse=True)
def _mesh(eight_devices):
    mesh_manager.init(MeshConfig(data=8))
    yield


def test_all_reduce_sum():
    x = jnp.arange(8, dtype=jnp.float32)  # shard i holds value i
    out = dist.all_reduce(x, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_all_reduce_avg():
    x = jnp.arange(8, dtype=jnp.float32)
    out = dist.all_reduce(x, op=dist.ReduceOp.AVG, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.5))


def test_all_reduce_max_min():
    x = jnp.arange(8, dtype=jnp.float32)
    out = dist.all_reduce(x, op=dist.ReduceOp.MAX, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full(8, 7.0))
    out = dist.all_reduce(x, op=dist.ReduceOp.MIN, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full(8, 0.0))


def test_all_gather():
    x = jnp.arange(8, dtype=jnp.float32)
    out = dist.all_gather(x, group="data")
    # each shard's single element gathered -> every shard sees [0..7]
    assert out.shape == (8,)
    np.testing.assert_allclose(np.asarray(out), np.arange(8, dtype=np.float32))


def test_reduce_scatter():
    x = jnp.ones((8, 4), dtype=jnp.float32)  # replicated input
    out = dist.reduce_scatter(x, group="data")
    assert out.shape == (8, 4)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 4), 8.0))


def test_all_to_all():
    # 8 shards each with 8 elements == transpose of blocks
    x = jnp.arange(64, dtype=jnp.float32)
    out = dist.all_to_all_single(x, group="data")
    expect = np.arange(64, dtype=np.float32).reshape(8, 8).T.reshape(-1)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_broadcast():
    x = jnp.arange(8, dtype=jnp.float32)
    out = dist.broadcast(x, src=3, group="data")
    np.testing.assert_allclose(np.asarray(out), np.full(8, 3.0))


def test_barrier():
    assert dist.barrier()


def test_traced_usage_inside_shard_map():
    from deepspeed_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = mesh_manager.mesh

    def fn(x):
        return dist.all_reduce(x, group="data")

    wrapped = shard_map(fn, mesh=mesh, in_specs=(P("data"),),
                        out_specs=P("data"), check_vma=False)
    x = jnp.ones((8,), jnp.float32)
    out = jax.jit(wrapped)(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 8.0))


def test_comms_logger():
    dist.configure(enabled=True)
    x = jnp.ones((8,), jnp.float32)
    dist.all_reduce(x, group="data")
    stats = dist.comms_logger.log_all(print_log=False)
    assert "all_reduce" in stats
    dist.configure(enabled=False)
