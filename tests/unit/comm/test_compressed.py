"""Quantized-collective tests (ZeRO++ analog; reference shape:
tests/unit/runtime/zero/test_zeropp.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.compressed import (compression_error_bound,
                                           quantized_all_gather,
                                           quantized_psum_scatter)
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


@pytest.fixture
def mesh(eight_devices):
    mesh_manager.reset()
    return mesh_manager.init(MeshConfig(data=8), devices=eight_devices)


def test_roundtrip_error_small(rng):
    x = jnp.asarray(rng.standard_normal((1024,)).astype(np.float32))
    err = compression_error_bound(x)
    # int8 symmetric: error <= amax/127 per block
    assert err <= float(jnp.abs(x).max()) / 127 + 1e-6


def test_quantized_all_gather_matches_fp(mesh, rng):
    x = rng.standard_normal((64, 16)).astype(np.float32)
    xd = jax.device_put(x, NamedSharding(mesh, P("data")))

    def body(xs):
        return quantized_all_gather(xs, "data")

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("data"),
                            out_specs=P("data"), check_vma=False))(xd)
    # every shard holds the full gathered array; compare one shard's view
    full = np.asarray(out)[:64]
    np.testing.assert_allclose(full, x, atol=np.abs(x).max() / 100)


def test_quantized_psum_scatter_matches_fp(mesh, rng):
    # per-shard contribution [W*s]; compare against exact psum_scatter
    x = rng.standard_normal((8 * 32,)).astype(np.float32)
    xd = jax.device_put(np.tile(x, (8, 1)).reshape(-1),
                        NamedSharding(mesh, P("data")))

    def q_body(xs):
        return quantized_psum_scatter(xs, "data")

    def exact_body(xs):
        return jax.lax.psum_scatter(
            xs.reshape(8, -1), "data", scatter_dimension=0,
            tiled=False).reshape(-1)

    q = np.asarray(jax.jit(shard_map(q_body, mesh=mesh,
                                     in_specs=P("data"),
                                     out_specs=P("data"),
                                     check_vma=False))(xd))
    e = np.asarray(jax.jit(shard_map(exact_body, mesh=mesh,
                                     in_specs=P("data"),
                                     out_specs=P("data"),
                                     check_vma=False))(xd))
    np.testing.assert_allclose(q, e, atol=8 * np.abs(x).max() / 100)
