"""tools/lint_atomic_writes.py: bare write-mode ``open()`` calls (and
writer helpers into inline opens) are flagged as torn-file hazards,
the ``# atomic-ok:`` annotation escapes with a reason, append-only
``os.open`` journal fds are exempt by construction, and the shipped
package is clean under the lint."""

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
    "tools"))
from lint_atomic_writes import scan_file  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")


def _scan(tmp_path, src, rel="deepspeed_tpu/mod.py"):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    return scan_file(str(p), rel)


def test_bare_write_open_flagged(tmp_path):
    v = _scan(tmp_path, """
        def save(path, blob):
            with open(path, "wb") as f:
                f.write(blob)
    """)
    assert len(v) == 1 and "'wb'" in v[0][2]


def test_read_open_passes(tmp_path):
    v = _scan(tmp_path, """
        def load(path):
            with open(path) as f:
                return f.read()

        def load_b(path):
            with open(path, "rb") as f:
                return f.read()
    """)
    assert v == []


def test_dynamic_mode_is_suspicious(tmp_path):
    v = _scan(tmp_path, """
        def save(path, mode):
            with open(path, mode) as f:
                f.write(b"")
    """)
    assert len(v) == 1 and "'?'" in v[0][2]


def test_annotation_on_the_call_line_escapes(tmp_path):
    v = _scan(tmp_path, """
        def save(path, blob):
            with open(path, "wb") as f:  # atomic-ok: scratch file
                f.write(blob)
    """)
    assert v == []


def test_annotation_on_another_line_does_not_escape(tmp_path):
    """The annotation must sit ON the flagged call's line — a stray
    comment above it doesn't vouch for anything."""
    v = _scan(tmp_path, """
        def save(path, blob):
            # atomic-ok: scratch file
            with open(path, "wb") as f:
                f.write(blob)
    """)
    assert len(v) == 1


def test_writer_helper_into_inline_open_flagged(tmp_path):
    v = _scan(tmp_path, """
        import json
        import numpy as np

        def save(path, obj, arr):
            json.dump(obj, open(path, "w"))
            np.save(open(path + ".npy", "wb"), arr)
    """)
    # each line carries TWO hazards: the inline open itself and the
    # writer pouring into it
    assert len(v) == 4


def test_writer_into_existing_handle_is_the_openers_problem(tmp_path):
    v = _scan(tmp_path, """
        import json

        def save(f, obj):
            json.dump(obj, f)
    """)
    assert v == []


def test_os_open_append_journal_is_exempt(tmp_path):
    """Append-only journal fds are the crash-safe primitive the
    stores build on — ``os.open(...O_APPEND)`` isn't a plain open()
    and must pass unflagged."""
    v = _scan(tmp_path, """
        import os

        def open_journal(path):
            return os.open(path, os.O_WRONLY | os.O_CREAT |
                           os.O_APPEND, 0o644)
    """)
    assert v == []


def test_integrity_module_is_exempt(tmp_path):
    v = _scan(tmp_path, """
        def atomic_write_bytes(path, writer):
            with open(path + ".tmp", "wb") as f:
                writer(f)
    """, rel="deepspeed_tpu/resilience/integrity.py")
    assert v == []


def test_package_is_clean():
    """The shipped tree passes its own lint (annotated escapes and
    the integrity module aside) — the CI wiring the README documents."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "lint_atomic_writes.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout
