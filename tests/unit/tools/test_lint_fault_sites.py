"""tools/lint_fault_sites.py: typo'd site strings at
``fault_injector.fire``/``consume`` calls are flagged against the
central registry, annotated non-literal sites pass, and the shipped
package is clean under the lint."""

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
    "tools"))
from lint_fault_sites import scan_file  # noqa: E402

from deepspeed_tpu.resilience.fault_sites import (FAULT_SITES,
                                                  KNOWN_SITES)

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")


def _scan(tmp_path, src, registry=frozenset(FAULT_SITES)):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    violations, used = scan_file(str(p), registry)
    return violations, used


def test_registered_literal_site_passes(tmp_path):
    v, used = _scan(tmp_path, """
        from deepspeed_tpu.resilience.fault_injector import \\
            fault_injector

        def save():
            fault_injector.fire("checkpoint.save")
            fault_injector.consume("pg_sim.step")
    """)
    assert v == []
    assert used == {"checkpoint.save", "pg_sim.step"}


def test_typoed_site_flagged(tmp_path):
    """The exact failure class this lint exists for: the spec grammar
    would accept 'checkpoint.svae' and the drill would silently never
    fire."""
    v, _ = _scan(tmp_path, """
        from deepspeed_tpu.resilience.fault_injector import \\
            fault_injector

        def save():
            fault_injector.fire("checkpoint.svae")
    """)
    assert len(v) == 1 and "checkpoint.svae" in v[0][2]


def test_non_literal_site_needs_annotation(tmp_path):
    v, _ = _scan(tmp_path, """
        def drill(injector, site):
            injector.fire(site)
    """)
    assert len(v) == 1 and "non-literal" in v[0][2]
    v, _ = _scan(tmp_path, """
        def drill(injector, site):
            injector.fire(site)  # fault-site-ok: caller passes a registered site
    """)
    assert v == []


def test_unrelated_fire_apis_ignored(tmp_path):
    v, used = _scan(tmp_path, """
        def shoot(missile):
            missile.fire("at will")
    """)
    assert v == [] and used == set()


def test_registry_and_docstring_agree():
    """The injector module re-exports KNOWN_SITES from the registry —
    one source of truth."""
    from deepspeed_tpu.resilience.fault_injector import \
        KNOWN_SITES as injector_sites
    assert tuple(injector_sites) == tuple(KNOWN_SITES)
    assert all(FAULT_SITES[s] for s in FAULT_SITES)  # described


def test_package_is_clean():
    """Every site fired in deepspeed_tpu/ is registered (the lint the
    README wires next to lint_unbounded_caches)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "lint_fault_sites.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "clean" in out.stdout
