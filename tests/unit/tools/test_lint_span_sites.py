"""tools/lint_span_sites.py: typo'd span names at ``span(...)`` /
``tracer.span(...)`` calls are flagged against the registry,
annotated non-literal names pass, and the shipped package is clean
under the lint."""

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
    "tools"))
from lint_span_sites import scan_file  # noqa: E402

from deepspeed_tpu.telemetry.span_sites import SPAN_SITES

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")


def _scan(tmp_path, src, registry=frozenset(SPAN_SITES)):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    violations, used = scan_file(str(p), registry)
    return violations, used


def test_registered_literal_span_passes(tmp_path):
    v, used = _scan(tmp_path, """
        from deepspeed_tpu.telemetry.trace import span, tracer

        def step():
            with span("engine.dispatch"):
                pass
            with tracer.span("transfer.d2h", stream=0, bucket=1):
                pass
            tracer.instant("supervisor.gate")
    """)
    assert v == []
    assert used == {"engine.dispatch", "transfer.d2h",
                    "supervisor.gate"}


def test_typoed_span_flagged(tmp_path):
    """The failure class this lint exists for: the tracer records
    'transfer.dh2' happily and every consumer filtering on the
    registered name silently loses the site."""
    v, _ = _scan(tmp_path, """
        from deepspeed_tpu.telemetry.trace import span

        def step():
            with span("transfer.dh2"):
                pass
    """)
    assert len(v) == 1 and "transfer.dh2" in v[0][2]


def test_non_literal_span_needs_annotation(tmp_path):
    v, _ = _scan(tmp_path, """
        from deepspeed_tpu.telemetry.trace import span

        def step(name):
            with span(name):
                pass
    """)
    assert len(v) == 1 and "non-literal" in v[0][2]
    v, _ = _scan(tmp_path, """
        from deepspeed_tpu.telemetry.trace import span

        def step(name):
            with span(name):  # span-site-ok: closed over KNOWN_SPANS
                pass
    """)
    assert v == []


def test_unrelated_span_methods_ignored(tmp_path):
    """Only tracer-ish receivers count — a bs4/soup-style ``.span``
    call must not trip the lint."""
    v, used = _scan(tmp_path, """
        def render(doc):
            return doc.span("not-a-trace-site")
    """)
    assert v == [] and used == set()


def test_shipped_package_is_clean():
    """Every literal span name in deepspeed_tpu/ is registered, and
    the CLI exits 0 (the README lint-list contract)."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools",
                                      "lint_span_sites.py")],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "span-site lint clean" in proc.stdout
