"""tools/bench_compare.py: the bench regression gate — artifact-shape
handling, threshold semantics (global + per-config), required-config
enforcement, and the CI exit-code contract."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
    "tools"))
from bench_compare import (compare, load_configs, main,  # noqa: E402
                           parse_per_config)

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")


def _artifact(tmp_path, name, configs, wrapped=False):
    head = {"metric": "m", "configs": configs}
    doc = {"n": 1, "rc": 0, "parsed": head} if wrapped else head
    p = tmp_path / name
    p.write_text(json.dumps(doc))
    return str(p)


def _row(vs):
    return {"metric": "m", "value": 1.0, "vs_baseline": vs}


class TestLoad:

    def test_both_artifact_shapes(self, tmp_path):
        raw = _artifact(tmp_path, "raw.json", {"1": _row(1.0)})
        wrapped = _artifact(tmp_path, "wr.json", {"1": _row(1.0)},
                            wrapped=True)
        assert load_configs(raw) == load_configs(wrapped)

    def test_checked_in_artifacts_load(self):
        cfgs = load_configs(os.path.join(REPO, "BENCH_r05.json"))
        assert "1" in cfgs and "4" in cfgs

    def test_garbage_rejected(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text("[1, 2]")
        with pytest.raises(ValueError):
            load_configs(str(p))


class TestCompare:

    def test_within_threshold_ok(self):
        rows, reg, miss = compare({"1": _row(1.0)},
                                  {"1": _row(0.95)}, 0.10, {}, set())
        assert reg == [] and miss == []
        assert rows[0]["status"] == "ok"
        assert rows[0]["delta"] == pytest.approx(-0.05)

    def test_regression_detected(self):
        _, reg, _ = compare({"1": _row(1.0)}, {"1": _row(0.85)},
                            0.10, {}, set())
        assert reg == ["1"]

    def test_per_config_threshold_overrides(self):
        # config 4's session band is wider than the scored rows'
        _, reg, _ = compare({"4": _row(0.58)}, {"4": _row(0.45)},
                            0.10, {"4": 0.30}, set())
        assert reg == []
        assert parse_per_config("4=0.3,5_int4=0.5") == {
            "4": 0.3, "5_int4": 0.5}
        with pytest.raises(ValueError):
            parse_per_config("4:0.3")

    def test_tracked_config_cannot_silently_vanish(self):
        """7_frontend is implicitly required once the OLD artifact has
        it: a new artifact that dropped the row fails the gate.
        Artifacts predating it still compare clean."""
        old = {"1": _row(1.0), "7_frontend": _row(1.2)}
        _, reg, miss = compare(old, {"1": _row(1.0)}, 0.10, {}, set())
        assert miss == ["7_frontend"] and reg == []
        _, _, miss = compare(old, {"1": _row(1.0),
                                   "7_frontend": _row(1.15)},
                             0.10, {}, set())
        assert miss == []
        # pre-introduction lineage: absent from BOTH sides is clean
        _, _, miss = compare({"1": _row(1.0)}, {"1": _row(1.0)},
                             0.10, {}, set())
        assert miss == []

    def test_8_fleet_joins_the_vanish_gate(self):
        """ISSUE 11: 8_fleet is tracked exactly like 7_frontend —
        present-in-old => implicitly required in new; an artifact
        predating its introduction still compares clean."""
        from bench_compare import TRACKED_CONFIGS
        assert "8_fleet" in TRACKED_CONFIGS
        pre = {"1": _row(1.0), "7_frontend": _row(1.2)}
        post = {"1": _row(1.0), "7_frontend": _row(1.2),
                "8_fleet": _row(0.9)}
        # pre-introduction artifact (no 8_fleet row) on the OLD side:
        # nothing required, the gate stays clean
        _, reg, miss = compare(pre, post, 0.10, {}, set())
        assert reg == [] and miss == []
        # once the lineage carries it, dropping the row fails the gate
        _, reg, miss = compare(post, pre, 0.10, {}, set())
        assert miss == ["8_fleet"] and reg == []
        _, reg, miss = compare(post, dict(post), 0.10, {}, set())
        assert reg == [] and miss == []

    def test_tracked_decomposition_key_cannot_silently_vanish(self):
        """ISSUE 13: once a lineage's config-5/7 row publishes the
        ``speculation`` decomposition block, a new artifact whose row
        lost it fails the gate — but artifacts PREDATING the block
        (no key on the old side) compare clean, so the gate can be
        introduced without invalidating checked-in history."""
        from bench_compare import TRACKED_DECOMP_KEYS
        assert "speculation" in TRACKED_DECOMP_KEYS["5"]
        assert "speculation" in TRACKED_DECOMP_KEYS["7_frontend"]

        def row_with(decomp):
            r = _row(1.0)
            r["decomposition"] = decomp
            return r

        pre = {"7_frontend": _row(1.0)}           # predates the block
        post = {"7_frontend": row_with({"speculation": {
            "emitted_per_verify": 1.7}})}
        bare = {"7_frontend": row_with({"steps": 9})}
        # pre-introduction old side arms nothing
        _, reg, miss = compare(pre, post, 0.10, {}, set())
        assert reg == [] and miss == []
        _, reg, miss = compare(pre, bare, 0.10, {}, set())
        assert reg == [] and miss == []
        # armed: the new row dropped the published block -> gate fails
        rows, reg, miss = compare(post, bare, 0.10, {}, set())
        assert miss == ["7_frontend.decomposition.speculation"]
        assert reg == []
        assert rows[0]["status"] == "MISSING-DECOMP"
        assert "speculation" in rows[0]["note"]
        # keeping the block is clean
        _, reg, miss = compare(post, dict(post), 0.10, {}, set())
        assert reg == [] and miss == []
        # untracked configs never arm decomposition keys
        _, _, miss = compare(
            {"2": row_with({"speculation": {}})},
            {"2": row_with({})}, 0.10, {}, set())
        assert miss == []

    def test_dotted_decomp_keys_reach_inside_blocks(self):
        """ISSUE 18: the async overlap splits are tracked one level
        INSIDE their blocks — a new row keeping the ``cache`` block
        but dropping ``cache_demote_overlapped_ms`` from it still
        fails the gate; lineages predating the split arm nothing."""
        from bench_compare import TRACKED_DECOMP_KEYS
        for dk in ("cache.cache_demote_exposed_ms",
                   "cache.cache_demote_overlapped_ms",
                   "cache.cache_promote_exposed_ms",
                   "cache.cache_promote_overlapped_ms"):
            assert dk in TRACKED_DECOMP_KEYS["7_frontend"]
        for dk in ("param_stream.param_drop_exposed_ms",
                   "param_stream.param_drop_overlapped_ms"):
            assert dk in TRACKED_DECOMP_KEYS["9_bigmodel"]

        def row_with(decomp):
            r = _row(1.0)
            r["decomposition"] = decomp
            return r

        full = {"9_bigmodel": row_with({"param_stream": {
            "param_drop_exposed_ms": 0.1,
            "param_drop_overlapped_ms": 9.0}})}
        split_lost = {"9_bigmodel": row_with({"param_stream": {
            "streamed_tps": 100.0}})}
        pre = {"9_bigmodel": row_with({"param_stream": {
            "streamed_tps": 90.0}})}
        # armed lineage, new row kept the block but lost the split
        rows, reg, miss = compare(full, split_lost, 0.10, {}, set())
        assert reg == []
        assert rows[0]["status"] == "MISSING-DECOMP"
        assert sorted(miss) == [
            "9_bigmodel.decomposition.param_stream.param_drop_exposed_ms",
            "9_bigmodel.decomposition.param_stream.param_drop_overlapped_ms"]
        # pre-split lineage arms neither the dotted keys nor a false
        # positive on the still-present block
        _, reg, miss = compare(pre, split_lost, 0.10, {}, set())
        assert reg == [] and miss == []
        # keeping the split is clean
        _, reg, miss = compare(full, dict(full), 0.10, {}, set())
        assert reg == [] and miss == []

    def test_disagg_handoff_keys_join_the_vanish_gate(self):
        """Disagg PR: the 8_fleet lineage tracks the ``handoff``
        block, its overlap split and the disagg row's ``itl_p99_ms``.
        Arming is per key: pre-disagg artifacts (blockxfer era, no
        handoff block) compare clean, plain post-disagg rows arm the
        handoff block but NOT itl_p99_ms (only ``--disagg`` rows
        publish it), and an armed lineage that loses either fails."""
        from bench_compare import TRACKED_DECOMP_KEYS
        for dk in ("handoff", "handoff.handoff_exposed_ms",
                   "handoff.handoff_overlapped_ms", "itl_p99_ms"):
            assert dk in TRACKED_DECOMP_KEYS["8_fleet"]

        def row_with(decomp):
            r = _row(1.0)
            r["decomposition"] = decomp
            return r

        ho = {"enabled": 0, "landed": 0,
              "handoff_exposed_ms": 0.0, "handoff_overlapped_ms": 0.0}
        pre = {"8_fleet": row_with({"blockxfer": {}})}
        plain = {"8_fleet": row_with({"blockxfer": {},
                                      "handoff": dict(ho)})}
        disagg = {"8_fleet": row_with({"blockxfer": {},
                                       "handoff": dict(ho),
                                       "itl_p99_ms": 4.2})}
        # pre-disagg lineage arms nothing
        _, reg, miss = compare(pre, plain, 0.10, {}, set())
        assert reg == [] and miss == []
        # plain rows arm the handoff block; a new row losing it fails
        rows, reg, miss = compare(plain, pre, 0.10, {}, set())
        assert reg == []
        assert rows[0]["status"] == "MISSING-DECOMP"
        assert sorted(miss) == [
            "8_fleet.decomposition.handoff",
            "8_fleet.decomposition.handoff.handoff_exposed_ms",
            "8_fleet.decomposition.handoff.handoff_overlapped_ms"]
        # keeping the overlap split inside the block is what's gated:
        # a row that keeps "handoff" but drops the split still fails
        split_lost = {"8_fleet": row_with({"blockxfer": {},
                                           "handoff": {"landed": 3}})}
        _, reg, miss = compare(plain, split_lost, 0.10, {}, set())
        assert sorted(miss) == [
            "8_fleet.decomposition.handoff.handoff_exposed_ms",
            "8_fleet.decomposition.handoff.handoff_overlapped_ms"]
        # a plain row never arms the disagg-only ITL key...
        _, reg, miss = compare(plain, dict(plain), 0.10, {}, set())
        assert reg == [] and miss == []
        # ...but a --disagg lineage does
        _, reg, miss = compare(disagg, plain, 0.10, {}, set())
        assert miss == ["8_fleet.decomposition.itl_p99_ms"]
        _, reg, miss = compare(disagg, dict(disagg), 0.10, {}, set())
        assert reg == [] and miss == []

    def test_floor_trips_after_lineage_clears_it(self):
        """Config 4's 0.8 floor: dormant while the lineage is still
        below the bar (r04->r05 era compares clean), armed once the
        old side clears it — then even a within-threshold drop that
        crosses under fails the gate (anti-creep)."""
        # pre-lift history: both sides under the floor -> clean
        _, reg, _ = compare({"4": _row(0.48)}, {"4": _row(0.58)},
                            0.10, {"4": 0.30}, set())
        assert reg == []
        # armed: 0.82 -> 0.79 is within a 10% threshold but under 0.8
        rows, reg, _ = compare({"4": _row(0.82)}, {"4": _row(0.79)},
                               0.10, {}, set())
        assert reg == ["4"]
        assert rows[0]["status"] == "BELOW-FLOOR"
        assert rows[0]["floor"] == 0.8
        # staying over the bar is clean
        _, reg, _ = compare({"4": _row(0.92)}, {"4": _row(0.88)},
                            0.10, {}, set())
        assert reg == []
        # explicit floors EXTEND the built-ins (never replace them):
        # adding a floor for config 1 must not drop config 4's
        rows, reg, _ = compare(
            {"1": _row(1.2), "4": _row(0.82)},
            {"1": _row(1.1), "4": _row(0.79)},
            0.10, {}, set(), floors={"1": 1.15})
        assert reg == ["1", "4"]
        assert all(r["status"] == "BELOW-FLOOR" for r in rows)

    def test_floor_cli_flag(self, tmp_path):
        old = _artifact(tmp_path, "fo.json", {"2": _row(1.2)})
        new = _artifact(tmp_path, "fn.json", {"2": _row(1.1)})
        assert main([old, new]) == 0
        assert main([old, new, "--floor", "2=1.15"]) == 1

    def test_missing_config_skipped_unless_required(self):
        rows, reg, miss = compare({"1": _row(1.0)},
                                  {"1": _row(1.0),
                                   "6": {"metric": "mttr",
                                         "value": 0.2}},
                                  0.10, {}, set())
        assert reg == [] and miss == []
        assert [r["status"] for r in rows] == ["ok", "skipped"]
        _, _, miss = compare({}, {"1": _row(1.0)}, 0.10, {}, {"1"})
        assert miss == ["1"]


class TestCLI:

    def test_exit_codes(self, tmp_path, capsys):
        old = _artifact(tmp_path, "old.json",
                        {"1": _row(1.0), "2": _row(1.0)})
        good = _artifact(tmp_path, "good.json",
                         {"1": _row(1.05), "2": _row(0.99)})
        bad = _artifact(tmp_path, "bad.json",
                        {"1": _row(0.5), "2": _row(1.0)})
        assert main([old, good]) == 0
        assert "bench gate clean" in capsys.readouterr().out
        assert main([old, bad]) == 1
        assert "REGRESSION" in capsys.readouterr().out
        assert main([old, good, "--require", "9"]) == 1
        assert main([str(tmp_path / "nope.json"), good]) == 2

    def test_json_output_parses(self, tmp_path, capsys):
        old = _artifact(tmp_path, "o.json", {"1": _row(1.0)})
        new = _artifact(tmp_path, "n.json", {"1": _row(0.5)})
        assert main([old, new, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"] == ["1"]

    def test_real_artifacts_via_subprocess(self):
        """The README workflow end-to-end on the checked-in bench
        history (r04 -> r05 improved everywhere)."""
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "tools", "bench_compare.py"),
             os.path.join(REPO, "BENCH_r04.json"),
             os.path.join(REPO, "BENCH_r05.json")],
            capture_output=True, text=True, cwd=REPO)
        assert proc.returncode == 0, proc.stdout + proc.stderr
