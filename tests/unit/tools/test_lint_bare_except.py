"""The bare-except lint: flags bare ``except:`` AND the silent
``except Exception: pass`` form (the shape the old offload
``copy_to_host_async`` guard had), and the shipped package is clean."""

import os
import sys

TOOLS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "..", "..", "..", "tools")
sys.path.insert(0, os.path.abspath(TOOLS))

from lint_bare_except import find_bare_excepts, main  # noqa: E402


def _hits(tmp_path, src):
    p = tmp_path / "mod.py"
    p.write_text(src)
    return find_bare_excepts(str(p))


def test_flags_bare_except(tmp_path):
    hits = _hits(tmp_path, "try:\n    x()\nexcept:\n    pass\n")
    assert len(hits) == 1 and "bare" in hits[0][1]


def test_flags_silent_except_exception_pass(tmp_path):
    src = ("try:\n    x()\nexcept Exception:   # platform quirk\n"
           "    pass\n")
    hits = _hits(tmp_path, src)
    assert len(hits) == 1 and "silent" in hits[0][1]


def test_flags_silent_tuple_with_base_exception(tmp_path):
    src = "try:\n    x()\nexcept (ValueError, BaseException):\n    pass\n"
    assert len(_hits(tmp_path, src)) == 1


def test_allows_narrow_pass_and_handled_broad(tmp_path):
    src = ("try:\n    x()\nexcept (ImportError, AttributeError):\n"
           "    pass\n"
           "try:\n    y()\nexcept Exception as e:\n"
           "    log(e)\n")
    assert _hits(tmp_path, src) == []


def test_package_is_clean():
    assert main(["lint_bare_except.py"]) == 0
