"""The secret-surface lint itself must work: it is the only static
guarantee that bootstrap tokens / HMAC material never reach logs,
spans, or JSONL sinks (README "Fleet serving" / Bootstrap)."""

import os
import subprocess
import sys
import textwrap

import pytest

_TOOLS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "..", "..", "..", "tools")
sys.path.insert(0, os.path.abspath(_TOOLS))

import lint_secret_surfaces as lint  # noqa: E402


def _scan(tmp_path, src):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    return lint.scan_file(str(p))


class TestScan:
    def test_clean_logging_passes(self, tmp_path):
        v = _scan(tmp_path, """
            def f(logger, slot, n_tokens):
                logger.info(f"worker {slot} emitted {n_tokens} tokens")
        """)
        assert v == []

    def test_token_in_log_flagged(self, tmp_path):
        v = _scan(tmp_path, """
            def f(logger, token):
                logger.warning(f"joining with {token}")
        """)
        assert len(v) == 1
        assert "token" in v[0][2]

    def test_secret_attribute_flagged(self, tmp_path):
        v = _scan(tmp_path, """
            def f(logger, cfg):
                logger.info("auth=%s", cfg.shared_secret)
        """)
        assert len(v) == 1
        assert "shared_secret" in v[0][2]

    def test_span_kwarg_flagged(self, tmp_path):
        v = _scan(tmp_path, """
            def f(span, nonce):
                with span("fleet.join", nonce=nonce):
                    pass
        """)
        assert len(v) == 1
        assert "nonce" in v[0][2]

    def test_sink_write_flagged(self, tmp_path):
        v = _scan(tmp_path, """
            def f(sink, mac):
                sink.write({"mac": mac})
        """)
        # keyword-free dict: the Name node `mac` is what trips it
        assert len(v) == 1

    def test_redact_auth_wrap_passes(self, tmp_path):
        v = _scan(tmp_path, """
            def f(logger, redact_auth, cfg):
                logger.info("bootstrap=%s", redact_auth(cfg.token))
        """)
        assert v == []

    def test_annotation_escape(self, tmp_path):
        v = _scan(tmp_path, """
            def f(logger, mac):
                logger.info(f"checksum {mac}")  # secret-ok: frame CRC, not auth
        """)
        assert v == []

    def test_exact_name_match_only(self, tmp_path):
        # tokens / n_tokens / token_budget / machine are NOT secrets —
        # substring matching would make the whole serving telemetry
        # surface unlintable.
        v = _scan(tmp_path, """
            def f(logger, tokens, n_tokens, token_budget, machine):
                logger.info(f"{len(tokens)} {n_tokens} "
                            f"{token_budget} {machine}")
        """)
        assert v == []

    def test_non_surface_calls_ignored(self, tmp_path):
        # Sending the MAC over the handshake socket is the PROTOCOL,
        # not a leak; only observability surfaces are linted.
        v = _scan(tmp_path, """
            def f(sock, send_frame, mac, token):
                send_frame(sock, {"kind": "JOIN_AUTH", "mac": mac})
                derive(token)
        """)
        assert v == []

    def test_syntax_error_reported(self, tmp_path):
        v = _scan(tmp_path, "def f(:\n")
        assert len(v) == 1
        assert "syntax error" in v[0][2]


class TestPackage:
    def test_package_is_clean(self):
        tool = os.path.join(_TOOLS, "lint_secret_surfaces.py")
        r = subprocess.run([sys.executable, tool],
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stdout + r.stderr
        assert "clean" in r.stdout

    def test_guarded_names_agree_with_transport(self):
        # the lint's name list and transport.redact_auth's field list
        # must not drift apart: a key redacted at runtime should also
        # be flagged statically.
        from deepspeed_tpu.inference.v2.serving.fleet.transport import \
            _AUTH_FIELDS
        missing = set(_AUTH_FIELDS) - set(lint._SECRET_NAMES)
        assert not missing, f"lint misses runtime-redacted keys: {missing}"
