"""tools/lint_unbounded_caches.py: module-level grow-only containers
are flagged; eviction paths, BoundedCache, and annotated exceptions
pass; and the shipped package is clean under the lint."""

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "..",
    "tools"))
from lint_unbounded_caches import find_unbounded_caches  # noqa: E402

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "..", "..")


def _lint(tmp_path, src):
    p = tmp_path / "mod.py"
    p.write_text(textwrap.dedent(src))
    return find_unbounded_caches(str(p))


def test_grow_only_dict_flagged(tmp_path):
    hits = _lint(tmp_path, """
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v
    """)
    assert len(hits) == 1 and "_CACHE" in hits[0][1]


def test_grow_only_list_and_set_flagged(tmp_path):
    hits = _lint(tmp_path, """
        _SEEN = set()
        _LOG = []

        def note(x):
            _SEEN.add(x)
            _LOG.append(x)
    """)
    assert len(hits) == 2


def test_eviction_path_passes(tmp_path):
    assert _lint(tmp_path, """
        _CACHE = {}

        def put(k, v):
            while len(_CACHE) > 8:
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[k] = v
    """) == []


def test_clear_counts_as_eviction(tmp_path):
    assert _lint(tmp_path, """
        _CACHE = {}

        def put(k, v):
            _CACHE[k] = v

        def reset():
            _CACHE.clear()
    """) == []


def test_bounded_cache_passes(tmp_path):
    assert _lint(tmp_path, """
        from deepspeed_tpu.runtime.lifecycle import BoundedCache
        _CACHE = BoundedCache("x", max_entries=8)

        def put(k, v):
            _CACHE.put(k, v)
    """) == []


def test_annotation_with_reason_passes(tmp_path):
    assert _lint(tmp_path, """
        _WARNED = set()  # unbounded-ok: fixed key vocabulary

        def warn_once(k):
            _WARNED.add(k)
    """) == []


def test_read_only_container_passes(tmp_path):
    assert _lint(tmp_path, """
        TABLE = {"a": 1, "b": 2}

        def get(k):
            return TABLE[k]
    """) == []


def test_function_local_containers_ignored(tmp_path):
    assert _lint(tmp_path, """
        def f(xs):
            out = []
            for x in xs:
                out.append(x)
            return out
    """) == []


def test_deque_maxlen_passes(tmp_path):
    assert _lint(tmp_path, """
        from collections import deque
        _RING = deque(maxlen=16)

        def push(x):
            _RING.append(x)
    """) == []


def test_package_is_clean():
    """The shipped package passes its own lint (hits are either
    BoundedCache-backed or carry an unbounded-ok reason)."""
    proc = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "lint_unbounded_caches.py"),
         os.path.join(REPO, "deepspeed_tpu")],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
