"""TensorLogger debugging tool (reference:
deepspeed/tools/tensor_logger/tensor_logger.py — windowed capture of
activations/gradients/inputs, hierarchy round-trip through save)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.tools import TensorLogger
from deepspeed_tpu.tools.tensor_logger import (BWD_GRAD, FWD_ACT,
                                               MODEL_INPUTS, load_tensor_log)


@pytest.fixture
def model_and_vars(rng):
    model = GPT2LMHeadModel(GPT2Config.tiny())
    ids = rng.integers(0, 256, size=(2, 8), dtype=np.int32)
    batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
    variables = model.init(jax.random.PRNGKey(0), batch["input_ids"])
    return model, variables, batch


def test_disabled_by_default_end_iteration_zero(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, log_activations_enabled=True)
    with tl.log_iteration(1):
        tl.capture(variables, batch)
    assert len(tl.data) == 0


def test_capture_respects_window(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=2, end_iteration=3,
                      log_inputs_enabled=True)
    for i in range(1, 5):
        with tl.log_iteration(i):
            tl.capture(variables, batch)
    assert sorted(tl.data) == [2, 3]
    assert "model.input_ids" in tl.data[2][MODEL_INPUTS]


def test_capture_requires_active_context(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=1, end_iteration=9,
                      log_inputs_enabled=True)
    tl.set_iteration(1)
    tl.capture(variables, batch)     # not inside a context -> inactive
    assert len(tl.data) == 0


def test_activations_cover_submodules(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=1, end_iteration=1,
                      log_activations_enabled=True)
    with tl.log_iteration(1):
        tl.capture(variables, batch)
    names = list(tl.data[1][FWD_ACT])
    # flax capture_intermediates records each submodule's outputs
    assert any("h_0" in n for n in names), names
    assert all(n.startswith("model.") for n in names)
    arr = next(iter(tl.data[1][FWD_ACT].values()))[0]
    assert isinstance(arr, np.ndarray)


@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_grads_match_direct_jax_grad(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=1, end_iteration=1,
                      log_grads_enabled=True)
    with tl.log_iteration(1):
        tl.capture(variables, batch)

    def loss(v):
        out = model.apply(v, **batch)
        return out[0] if isinstance(out, tuple) else out

    expect = jax.grad(loss)(variables)
    from deepspeed_tpu.utils.tree import named_leaves
    for name, leaf in named_leaves(expect):
        got = tl.data[1][BWD_GRAD][f"model.{name}"]
        assert len(got) == 1, name
        np.testing.assert_allclose(got[0], np.asarray(leaf),
                                   rtol=1e-5, atol=1e-6)


@pytest.mark.slow  # tier-1 diet (PR 17): capture/activation/save-load smokes stay
def test_grad_accumulation_appends(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=1, end_iteration=1,
                      log_grads_enabled=True)
    with tl.log_iteration(1):
        tl.capture(variables, batch)
        tl.capture(variables, batch)    # second micro-batch, same iter
    any_name = next(iter(tl.data[1][BWD_GRAD]))
    assert len(tl.data[1][BWD_GRAD][any_name]) == 2


def test_save_load_roundtrip(tmp_path, model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=1, end_iteration=2,
                      log_inputs_enabled=True, log_activations_enabled=True)
    for i in (1, 2):
        with tl.log_iteration(i):
            tl.capture(variables, batch)
    path = tl.save(str(tmp_path / "log" / "tensors.npz"))
    assert len(tl.data) == 0            # save() clears
    back = load_tensor_log(path)
    assert sorted(back) == [1, 2]
    np.testing.assert_array_equal(
        back[1][MODEL_INPUTS]["model.input_ids"][0],
        np.asarray(batch["input_ids"]))
    assert len(back[1][FWD_ACT]) > 0


def test_custom_prefix_and_loss_fn(model_and_vars):
    model, variables, batch = model_and_vars
    tl = TensorLogger(model, start_iteration=1, end_iteration=1,
                      log_grads_enabled=True, prefix="policy")
    with tl.log_iteration(1):
        def double_loss(v, b):
            out = model.apply(v, **b)
            return (out[0] if isinstance(out, tuple) else out) * 2.0

        tl.capture(variables, batch, loss_fn=double_loss)
    name = next(iter(tl.data[1][BWD_GRAD]))
    assert name.startswith("policy.")
