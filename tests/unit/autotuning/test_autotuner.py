"""Autotuner tests (reference shape: tests/unit/autotuning/)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.autotuning import Autotuner, AutotuningConfig
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager


@pytest.fixture
def factories():
    def engine_factory(overrides):
        mesh_manager.reset()
        config = {
            "train_micro_batch_size_per_gpu":
                overrides["train_micro_batch_size_per_gpu"],
            "gradient_accumulation_steps":
                overrides.get("gradient_accumulation_steps", 1),
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": overrides.get("zero_optimization",
                                               {"stage": 0}),
            "steps_per_print": 0,
        }
        model = GPT2LMHeadModel(GPT2Config.tiny())
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config=config)
        return engine

    def batch_factory(engine):
        ids = np.random.default_rng(0).integers(
            0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
        return {"input_ids": ids, "labels": ids.copy()}

    return engine_factory, batch_factory


def test_candidate_enumeration():
    t = AutotuningConfig(enabled=True, micro_batch_sizes=[2, 4],
                         zero_stages=[0, 1], max_trials=10)
    a = Autotuner({}, None, None, tuning=t)
    cands = a.candidates()
    assert len(cands) == 4
    assert {c["train_micro_batch_size_per_gpu"] for c in cands} == {2, 4}
    assert {c["zero_optimization"]["stage"] for c in cands} == {0, 1}


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_tune_picks_feasible_best(factories, tmp_path):
    ef, bf = factories
    t = AutotuningConfig(enabled=True, micro_batch_sizes=[2, 4],
                         zero_stages=[1], trial_steps=2, warmup_steps=1,
                         results_dir=str(tmp_path))
    a = Autotuner({}, ef, bf, tuning=t)
    best = a.tune()
    assert best.feasible and best.tokens_per_sec > 0
    assert len(a.results) == 2
    import json
    with open(tmp_path / "results.json") as f:
        rows = json.load(f)
    assert len(rows) == 2


def test_infeasible_trial_is_caught(factories):
    ef, bf = factories

    def exploding_factory(overrides):
        raise MemoryError("RESOURCE_EXHAUSTED: fake OOM")

    t = AutotuningConfig(enabled=True, micro_batch_sizes=[2],
                         zero_stages=[0])
    a = Autotuner({}, exploding_factory, bf, tuning=t)
    with pytest.raises(RuntimeError, match="no feasible"):
        a.tune()
    assert a.results[0].error.startswith("oom")


def test_memory_estimate_monotone():
    e = Autotuner.estimate_bytes
    # more shards -> less per-chip state
    assert e(int(1e9), 3, 4096, 4096, 32, world=8) < \
        e(int(1e9), 1, 4096, 4096, 32, world=8) < \
        e(int(1e9), 0, 4096, 4096, 32, world=8)


@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_launched_autotuner_runs_real_experiments(tmp_path):
    """LaunchedAutotuner (reference: runner.py:361 run_autotuning):
    each candidate runs the user's training script through the dstpu
    launcher in a fresh process and reports back through a result
    json; crashes only fail their own trial."""
    import json
    import os
    import textwrap

    from deepspeed_tpu.autotuning import (AutotuningConfig,
                                          LaunchedAutotuner)

    script = tmp_path / "trial.py"
    script.write_text(textwrap.dedent("""
        import argparse, json
        p = argparse.ArgumentParser()
        p.add_argument("--ds-config"); p.add_argument("--result")
        a = p.parse_args()
        cfg = json.load(open(a.ds_config))
        micro = cfg["train_micro_batch_size_per_gpu"]
        if micro == 4:
            raise SystemExit(1)   # simulated OOM trial
        # toy objective: bigger micro "measures" faster
        json.dump({"tokens_per_sec": 1000.0 * micro,
                   "step_time_ms": 100.0 / micro},
                  open(a.result, "w"))
    """))
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    tuner = LaunchedAutotuner(
        base_config={"train_batch_size": 8,
                     "zero_optimization": {"stage": 0}},
        trial_script=str(script),
        tuning=AutotuningConfig(enabled=True,
                                micro_batch_sizes=[1, 2, 4],
                                zero_stages=[0], max_trials=3,
                                results_dir=str(tmp_path / "res")),
        env=env, trial_timeout=120)
    best = tuner.tune()
    # micro=4 crashed; micro=2 is the best surviving trial
    assert best.config["train_micro_batch_size_per_gpu"] == 2
    assert best.tokens_per_sec == 2000.0
    failed = [r for r in tuner.results if not r.feasible]
    assert len(failed) == 1
    # per-experiment config written for reproduction (reference exps/)
    exp_cfg = json.load(open(tmp_path / "res" / "exp_1" /
                             "ds_config.json"))
    assert exp_cfg["train_batch_size"] == 8
    assert "train_micro_batch_size_per_gpu" in exp_cfg
