"""Elastic agent: a training run killed mid-step is restarted by the
supervisor and CONTINUES from the newest committed checkpoint —
loss-curve continuation, not a restart from step 0 (reference:
elasticity/elastic_agent.py:32 worker-group restarts + checkpoint
resume)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

# The worker: tiny GPT-2 training that logs (step, loss) per step,
# saves a checkpoint every step, resumes via the elastic contract, and
# on its FIRST incarnation kills itself (simulated preemption) at step
# 3 — AFTER committing step 2's checkpoint, BEFORE committing step 3's.
WORKER = textwrap.dedent("""
    import json, os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.elasticity import resume_latest
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

    log_path = sys.argv[1]
    total_steps = int(sys.argv[2])
    ckpt = os.environ["DSTPU_ELASTIC_CKPT_DIR"]
    incarnation = int(os.environ.get("DSTPU_ELASTIC_RESTART", "0"))
    world = int(os.environ.get("DSTPU_ELASTIC_WORLD", "1"))

    mesh_manager.init(MeshConfig(data=-1))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config=config)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.init_params(batch)
    resumed = resume_latest(engine, ckpt)
    with open(log_path, "a") as f:
        f.write(json.dumps({"event": "start",
                            "incarnation": incarnation,
                            "world": world,
                            "resumed": resumed,
                            "resume_step": engine.global_steps}) + "\\n")
    while engine.global_steps < total_steps:
        loss = float(engine.train_batch(batch=batch))
        step = engine.global_steps
        if incarnation == 0 and step == 3:
            # preemption: die before committing this step's checkpoint
            os._exit(9)
        engine.save_checkpoint(ckpt)
        with open(log_path, "a") as f:
            f.write(json.dumps({"event": "step", "step": step,
                                "loss": loss}) + "\\n")
    sys.exit(0)
""")


# tier-1 diet (PR 5): both e2e kill/resume incarnations ride the slow
# tier — the cheap elasticity planning/backoff tests below keep the
# subsystem's tier-1 smoke
@pytest.mark.parametrize("via_cli", [
    pytest.param(False, marks=pytest.mark.slow),
    pytest.param(True, marks=pytest.mark.slow)],
    ids=["api", "dstpu-elastic"])
def test_agent_survives_injected_failure(tmp_path, via_cli):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    log = tmp_path / "log.jsonl"
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["DS_ACCELERATOR"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_force_host_platform_device_count=2")

    if via_cli:
        rc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bin", "dstpu"),
             "elastic", "--run", str(script), "--ckpt-dir", str(ckpt),
             "--max-restarts", "2", str(log), "6"],
            env=env, timeout=900).returncode
    else:
        from deepspeed_tpu.elasticity import DSElasticAgent
        agent = DSElasticAgent(str(script), [str(log), "6"],
                               ckpt_dir=str(ckpt), max_restarts=2,
                               backoff_seconds=0.1, env=env)
        rc = agent.run()
    assert rc == 0

    events = [json.loads(l) for l in log.read_text().splitlines()]
    starts = [e for e in events if e["event"] == "start"]
    steps = [e for e in events if e["event"] == "step"]
    # two incarnations: the original and one restart
    assert [s["incarnation"] for s in starts] == [0, 1]
    assert starts[0]["resumed"] is False
    # restart resumed from the newest COMMITTED checkpoint (step 2 —
    # the step-3 kill happened before that step's save)
    assert starts[1]["resumed"] is True
    assert starts[1]["resume_step"] == 2
    # loss-curve continuation: step 3 re-runs after resume, then 4..6;
    # no restart from step 0, losses keep decreasing end-to-end
    seq = [s["step"] for s in steps]
    assert seq == [1, 2, 3, 4, 5, 6], seq
    losses = [s["loss"] for s in steps]
    assert losses[-1] < losses[0]
    assert losses[3] < losses[1]     # post-resume continues the curve


@pytest.mark.fault
def test_restart_budget_backoff_and_terminal_exit(tmp_path,
                                                  monkeypatch):
    """A crash-looping worker exhausts max_restarts through
    exponentially-backed-off (jittered) restarts, then the agent exits
    with the DISTINCT terminal code — not the worker's rc."""
    from deepspeed_tpu.elasticity import DSElasticAgent
    from deepspeed_tpu.elasticity.elastic_agent import \
        RESTART_BUDGET_EXHAUSTED

    script = tmp_path / "always_fails.py"
    script.write_text("import sys; sys.exit(3)\n")
    sleeps = []
    monkeypatch.setattr("deepspeed_tpu.elasticity.elastic_agent"
                        ".time.sleep", sleeps.append)
    agent = DSElasticAgent(str(script), ckpt_dir=str(tmp_path / "c"),
                           max_restarts=3, backoff_seconds=0.5,
                           backoff_factor=2.0, max_backoff_seconds=1.5,
                           backoff_jitter=0.0,
                           device_probe=lambda: 1)
    rc = agent.run()
    assert rc == RESTART_BUDGET_EXHAUSTED and rc != 3
    assert agent.restart_count == 3
    # exponential ramp, capped: 0.5, 1.0, then clamped to 1.5
    assert sleeps == [0.5, 1.0, 1.5]

    # jitter spreads the fleet: delays stay within [base, base*(1+j)]
    sleeps.clear()
    agent = DSElasticAgent(str(script), ckpt_dir=str(tmp_path / "c"),
                           max_restarts=2, backoff_seconds=1.0,
                           backoff_factor=1.0, backoff_jitter=0.5,
                           device_probe=lambda: 1)
    assert agent.run() == RESTART_BUDGET_EXHAUSTED
    assert all(1.0 <= s <= 1.5 for s in sleeps), sleeps


def test_plan_recomputed_on_shrink(tmp_path):
    """On restart the agent re-probes devices and recomputes the
    (batch, chips) plan with the elasticity math."""
    from deepspeed_tpu.elasticity import DSElasticAgent

    script = tmp_path / "probe_worker.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        with open(sys.argv[1], "a") as f:
            f.write(json.dumps({
                "world": os.environ["DSTPU_ELASTIC_WORLD"],
                "batch": os.environ.get("DSTPU_ELASTIC_BATCH"),
                "micro": os.environ.get("DSTPU_ELASTIC_MICRO_BATCH"),
            }) + "\\n")
        # first incarnation "is preempted"; the restart exits cleanly
        sys.exit(5 if os.environ["DSTPU_ELASTIC_RESTART"] == "0"
                 else 0)
    """))
    log = tmp_path / "plans.jsonl"
    worlds = iter([8, 2])
    ds_config = {"elasticity": {
        "enabled": True, "max_train_batch_size": 64,
        "micro_batch_sizes": [2, 4], "min_gpus": 1, "max_gpus": 8,
        "version": 0.2, "ignore_non_elastic_batch_info": True}}
    agent = DSElasticAgent(str(script), [str(log)],
                           ds_config=ds_config,
                           ckpt_dir=str(tmp_path / "c"),
                           max_restarts=3, backoff_seconds=0.0,
                           device_probe=lambda: next(worlds))
    assert agent.run() == 0
    plans = [json.loads(l) for l in log.read_text().splitlines()]
    assert [p["world"] for p in plans] == ["8", "2"]
    # the plan shrank with the slice: fewer chips -> smaller or equal
    # global batch, micro batch still from the allowed ladder
    assert int(plans[1]["batch"]) <= int(plans[0]["batch"])
    assert int(plans[1]["micro"]) in (2, 4)


# ---- ISSUE-7 satellite: direct coverage of the worker-side resume
# contract and the agent env plumbing ----

def test_resume_latest_without_checkpoint_is_a_noop(tmp_path):
    """No ``latest`` file -> False, and the engine is never touched
    (a fresh run must not pay a load attempt)."""
    from deepspeed_tpu.elasticity import resume_latest

    class Boom:
        def load_checkpoint(self, *a, **k):
            raise AssertionError("must not be called")

    assert resume_latest(Boom(), str(tmp_path)) is False
    assert resume_latest(Boom(), str(tmp_path / "missing")) is False


def test_resume_latest_env_dir_fallback(tmp_path, monkeypatch):
    """ckpt_dir defaults to $DSTPU_ELASTIC_CKPT_DIR (the agent's
    worker contract)."""
    from deepspeed_tpu.elasticity import resume_latest
    monkeypatch.setenv("DSTPU_ELASTIC_CKPT_DIR",
                       str(tmp_path / "nope"))

    class Boom:
        def load_checkpoint(self, *a, **k):
            raise AssertionError("must not be called")

    assert resume_latest(Boom()) is False


@pytest.mark.fault
@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_resume_latest_stale_latest_recovers_previous_good(
        tmp_path, eight_devices):
    """``latest`` names a tag whose payload is gone (kill between the
    tag write and a later cleanup, or a corrupted shard): resume must
    fall back to the previous good tag, repoint ``latest``, and
    return True — the agent's restarted worker keeps training instead
    of crash-looping on the stale pointer."""
    import shutil

    import deepspeed_tpu
    from deepspeed_tpu.elasticity import resume_latest
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config=config)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path))          # global_step1
    engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path))          # global_step2
    # the newest tag's payload vanishes; ``latest`` still names it
    shutil.rmtree(tmp_path / "global_step2")
    assert (tmp_path / "latest").read_text() == "global_step2"

    assert resume_latest(engine, str(tmp_path)) is True
    assert engine.global_steps == 1
    # and the pointer now names what actually loaded
    assert (tmp_path / "latest").read_text() == "global_step1"


def test_spawn_env_contract_without_elasticity(tmp_path):
    """The agent always exports world/ckpt/restart-ordinal to the
    worker; the batch plan only appears when the config has an
    elasticity section."""
    from deepspeed_tpu.elasticity import DSElasticAgent

    script = tmp_path / "dump.py"
    script.write_text(textwrap.dedent("""
        import json, os, sys
        keys = [k for k in os.environ if k.startswith("DSTPU_ELASTIC")]
        with open(sys.argv[1], "w") as f:
            json.dump({k: os.environ[k] for k in keys}, f)
        sys.exit(0)
    """))
    out = tmp_path / "env.json"
    agent = DSElasticAgent(str(script), [str(out)],
                           ckpt_dir=str(tmp_path / "ck"),
                           device_probe=lambda: 3)
    assert agent.run() == 0
    env = json.loads(out.read_text())
    assert env["DSTPU_ELASTIC_WORLD"] == "3"
    assert env["DSTPU_ELASTIC_RESTART"] == "0"
    assert env["DSTPU_ELASTIC_CKPT_DIR"] == str(tmp_path / "ck")
    assert "DSTPU_ELASTIC_BATCH" not in env
