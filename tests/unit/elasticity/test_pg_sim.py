"""pg_sim mechanics: virtual-worker partitioning, the fault-injected
failure modes (kill/hang/slow/corrupt), heartbeat/progress accounting,
respawn semantics, and the comm-layer health gate — all deterministic
from spec strings (reference idea: deepspeed/tools/pg_sim/pg.py runs
multi-rank logic in one process)."""

import jax
import pytest

from deepspeed_tpu.resilience.errors import WorkerFailureError
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.tools.pg_sim import (SimProcessGroup, install_domain,
                                        installed_domain,
                                        uninstall_domain)
from deepspeed_tpu.tools.pg_sim.pg import check_collective_health


@pytest.fixture(autouse=True)
def _clean_injector_and_domain():
    fault_injector.reset()
    uninstall_domain()
    yield
    fault_injector.reset()
    uninstall_domain()


def _run_steps(domain, n, start=0):
    for s in range(start, start + n):
        domain.begin_step(s)
        domain.complete_step(s)


class TestPartitioning:

    def test_contiguous_equal_slices(self, eight_devices):
        d = SimProcessGroup(4, devices=eight_devices)
        assert [len(w.devices) for w in d.workers] == [2, 2, 2, 2]
        flat = [dev for w in d.workers for dev in w.devices]
        assert flat == list(eight_devices)

    def test_indivisible_rejected(self, eight_devices):
        with pytest.raises(ValueError, match="not divisible"):
            SimProcessGroup(3, devices=eight_devices)

    def test_ordinal_addressing(self, eight_devices):
        d = SimProcessGroup(4, devices=eight_devices)
        assert d.spec_for(2, 3, "kill") == "pg_sim.step:kill@14"
        assert d.spec_for(0, 0, "hang", duration=2) == \
            "pg_sim.step:hang@0~2"
        with pytest.raises(ValueError, match="unknown sim mode"):
            d.spec_for(0, 0, "explode")


class TestFailureModes:

    def test_kill_is_permanent_and_loses_devices(self, eight_devices):
        d = SimProcessGroup(4, devices=eight_devices)
        fault_injector.configure(d.spec_for(1, 2, "kill"))
        _run_steps(d, 5)
        w = d.worker(1)
        assert not w.alive
        assert d.dead_ranks() == [1]
        # dead at step 2: never heartbeat past step 1
        assert w.last_heartbeat == 1
        surv = d.survivor_devices()
        assert len(surv) == 6
        assert all(dev not in w.devices for dev in surv)
        # ordinals consumed for dead slots too: placement stays
        # step-addressed after the kill
        assert fault_injector.call_count("pg_sim.step") == 5 * 4

    def test_hang_clears_after_duration(self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(d.spec_for(0, 1, "hang", duration=2))
        _run_steps(d, 2)           # steps 0,1: hang applied at 1
        assert d.hung_ranks() == [0]
        assert d.worker(0).last_heartbeat == 0   # missed step 1
        _run_steps(d, 1, start=2)  # second hung step
        _run_steps(d, 1, start=3)  # countdown expired -> healthy
        assert d.hung_ranks() == []
        assert d.worker(0).last_heartbeat == 3

    def test_slow_heartbeats_without_progress(self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(d.spec_for(1, 0, "slow", duration=2))
        _run_steps(d, 2)
        w = d.worker(1)
        assert w.alive and w.state == "healthy"
        assert w.last_heartbeat == 1     # alive the whole time
        assert w.progress == -1          # but no progress yet
        _run_steps(d, 2, start=2)
        assert d.worker(1).progress == 3  # caught up after 2 steps

    def test_corrupt_window_defaults_to_one_step(self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(d.spec_for(0, 1, "corrupt"))
        d.begin_step(0), d.complete_step(0)
        assert d.poisoned_ranks() == []
        d.begin_step(1)
        assert d.poisoned_ranks() == [0]
        d.complete_step(1)
        d.begin_step(2)
        assert d.poisoned_ranks() == []

    def test_classic_error_kind_degrades_to_one_step_stall(
            self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure("pg_sim.step:error@2")  # w0 at step 1
        d.begin_step(0), d.complete_step(0)
        d.begin_step(1)
        assert d.hung_ranks() == [0]   # stalls THIS step's dispatch
        d.complete_step(1)
        assert d.hung_ranks() == []    # and clears at its end


class TestRecoveryLevers:

    def test_respawn_restores_health_and_ledger(self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(d.spec_for(0, 1, "kill"))
        _run_steps(d, 3)
        assert not d.worker(0).alive
        assert d.respawn(0) is True
        w = d.worker(0)
        assert w.alive and w.state == "healthy" and w.respawns == 1
        assert w.last_heartbeat == d.step

    def test_non_respawnable_forces_shrink(self, eight_devices):
        d = SimProcessGroup(4, devices=eight_devices,
                            respawnable=False)
        fault_injector.configure(d.spec_for(3, 0, "kill"))
        _run_steps(d, 1)
        assert d.respawn(3) is False
        surv = d.shrink()
        assert len(surv) == 6
        # shrunk-away worker keeps its rank slot (ordinal stability)
        # but is no longer a participant owed a recovery action
        assert d.dead_ranks() == []
        assert d.worker(3).state == "removed"
        assert len(d.alive_workers()) == 3

    def test_respawn_clears_transient_modes_too(self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(
            d.spec_for(1, 0, "hang"))  # hang forever (no ~arg)
        _run_steps(d, 2)
        assert d.hung_ranks() == [1]
        assert d.respawn(1) is True
        assert d.hung_ranks() == []

    def test_idle_tick_drains_hang_without_consuming_ordinals(
            self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(d.spec_for(0, 0, "hang", duration=1))
        d.begin_step(0)
        assert d.hung_ranks() == [0]
        before = fault_injector.call_count("pg_sim.step")
        d.idle_tick()
        assert d.hung_ranks() == []
        assert fault_injector.call_count("pg_sim.step") == before


class TestCollectiveGate:

    def test_install_uninstall(self, eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        install_domain(d)
        assert installed_domain() is d
        uninstall_domain()
        assert installed_domain() is None

    def test_gate_raises_typed_on_dead_participant(self,
                                                   eight_devices):
        d = SimProcessGroup(2, devices=eight_devices)
        fault_injector.configure(d.spec_for(1, 0, "kill"))
        d.begin_step(0)
        fault_injector.reset()
        install_domain(d)
        with pytest.raises(WorkerFailureError) as ei:
            check_collective_health("barrier")
        assert ei.value.rank == 1 and ei.value.mode == "kill"

    def test_eager_collective_goes_through_the_gate(self,
                                                    eight_devices):
        """comm/comm.py's eager dispatch consults the installed
        domain: a hung participant turns an eager all-reduce into a
        typed WorkerFailureError instead of a silent success."""
        import jax.numpy as jnp

        import deepspeed_tpu.comm as dist
        from deepspeed_tpu.parallel.mesh import (MeshConfig,
                                                 mesh_manager)
        mesh_manager.init(MeshConfig(data=-1),
                          devices=eight_devices)
        d = SimProcessGroup(2, devices=eight_devices)
        x = jnp.ones((8,))
        install_domain(d)
        # healthy: passes through
        dist.all_reduce(x, group="data")
        fault_injector.configure(d.spec_for(0, 0, "hang"))
        d.begin_step(0)
        fault_injector.reset()
        with pytest.raises(WorkerFailureError):
            dist.all_reduce(x, group="data")
        uninstall_domain()
        dist.all_reduce(x, group="data")  # gate removed with domain
