"""Chaos harness drills: randomized fault site x step x mode under the
elastic supervisor, with the recovery invariants asserted inside
``run_chaos_drill`` (tools/pg_sim/chaos.py):

* the run recovers and finishes all steps;
* the recovery report carries a non-empty MTTR/ladder record;
* replay identity — restoring the recovery's tag reproduces the
  post-recovery loss trajectory bitwise.

Tier-1 runs a seed-matrixed smoke (one corrupt-mode, one hang-mode
draw); the wider sweep (incl. the kill draw and a shrink drill) rides
the slow tier.
"""

import numpy as np
import pytest

from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.tools.pg_sim import uninstall_domain
from deepspeed_tpu.tools.pg_sim.chaos import run_chaos_drill

from tests.unit.elasticity.test_supervisor import _batch, make_engine


@pytest.fixture(autouse=True)
def _clean():
    fault_injector.reset()
    uninstall_domain()
    yield
    fault_injector.reset()
    uninstall_domain()


def _factory(devices, batch_plan):
    # the sentinel is the corrupt-mode detector (NaN budget -> its
    # own recorded rollback); harmless for the other modes
    return make_engine(devices=devices, batch_plan=batch_plan,
                       sentinel=True)


def _drill(seed, tmp_path, **kw):
    return run_chaos_drill(seed, _factory, str(tmp_path), _batch(),
                           num_steps=5, world_size=4, **kw)


# seed draws (deterministic from the seed, printed by the harness):
# 0 -> corrupt w2@s2, 1 -> hang w2@s3
@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.parametrize("seed", [
    0,
    # tier-1 diet (ISSUE 8): one smoke seed in tier-1, the second
    # rides with the slow sweep
    pytest.param(1, marks=pytest.mark.slow),
])
def test_chaos_smoke(seed, tmp_path, eight_devices):
    out = _drill(seed, tmp_path)
    rep = out["report"]
    assert rep["ladder"] and rep["mttr_s"]["last"] > 0


# the full sweep: every mode class appears (11 draws kill), recovery
# rungs vary with the draw — each drill asserts the invariants
@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.slow
@pytest.mark.parametrize("seed", [2, 4, 6, 9, 11, 14])
def test_chaos_sweep(seed, tmp_path, eight_devices):
    out = _drill(seed, tmp_path)
    assert out["report"]["ladder"]


@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.slow
def test_chaos_shrink_drill(tmp_path, eight_devices):
    """Kill with respawn disabled: the drill must recover through the
    shrink rung, and replay identity holds at the cross-topology
    tolerance (the harness relaxes bitwise to 1e-5 for shrink)."""
    out = _drill(11, tmp_path, modes=("kill",), respawnable=False,
                 supervisor_kwargs={})
    rungs = [r["rung"] for r in out["report"]["ladder"]]
    assert rungs == ["shrink"]
    assert out["report"]["resharded_bytes"] > 0
