"""Elastic batch-math parity tests.

Expected values pinned from the reference's own suite
(reference: tests/unit/elasticity/test_elastic.py — batch 9792 with 23
valid chip counts for the 10k config, mbsize 17 at world 64, etc.).
"""

import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity import (ElasticityConfigError, ElasticityError,
                                      ElasticityIncompatibleWorldSize,
                                      compute_elastic_config)


@pytest.fixture
def ds_config():
    return {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 10000,
            "micro_batch_sizes": [8, 12, 16, 17],
            "min_gpus": 32,
            "max_gpus": 1500,
            "min_time": 20,
            "version": 0.1,
        }
    }


def test_basic_10k(ds_config):
    batch, valid = compute_elastic_config(ds_config)
    for w in valid:
        assert batch % w == 0
        per = batch // w
        assert any(per % mb == 0
                   for mb in ds_config["elasticity"]["micro_batch_sizes"])
    assert len(valid) == 23
    assert batch == 9792


def test_disabled(ds_config):
    ds_config["elasticity"]["enabled"] = False
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(ds_config)


def test_valid_world_size(ds_config):
    batch, valid, mbsize = compute_elastic_config(ds_config, world_size=64)
    assert mbsize == 17


def test_invalid_world_size(ds_config):
    with pytest.raises(ElasticityIncompatibleWorldSize):
        compute_elastic_config(ds_config, world_size=128)


def test_future_elastic_version(ds_config):
    ds_config["elasticity"]["version"] = 0.3
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(ds_config)


def test_missing_max_batch(ds_config):
    del ds_config["elasticity"]["max_train_batch_size"]
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(ds_config)


def test_missing_micro_batch(ds_config):
    del ds_config["elasticity"]["micro_batch_sizes"]
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(ds_config)


def test_empty_config():
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config({"elasticity": {"enabled": True}})


@pytest.mark.parametrize("key,value", [
    ("micro_batch_sizes", [1, "a", 3]),
    ("micro_batch_sizes", [1, 0, 3]),
    ("micro_batch_sizes", "not-a-list"),
    ("min_gpus", 0),
    ("max_gpus", 0),
])
def test_invalid_config_values(key, value, ds_config):
    ds_config["elasticity"][key] = value
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(ds_config)


def test_model_parallel_v1_invalid(ds_config):
    ds_config["elasticity"]["model_parallel_size"] = 4
    with pytest.raises(ElasticityConfigError):
        compute_elastic_config(ds_config)


def test_model_parallel_v2_valid(ds_config, monkeypatch):
    ds_config["elasticity"].update(
        model_parallel_size=4, num_gpus_per_node=8, version=0.2)
    monkeypatch.setenv("WORLD_SIZE", "16")
    compute_elastic_config(ds_config)


def test_model_parallel_v2_invalid(ds_config):
    ds_config["elasticity"].update(
        model_parallel_size=16, num_gpus_per_node=8, version=0.2)
    with pytest.raises(ElasticityError):
        compute_elastic_config(ds_config, world_size=16)


def test_proper_mbsz(ds_config):
    ds_config["elasticity"].update(
        max_train_batch_size=32, micro_batch_sizes=[1, 2, 3, 7], min_gpus=1)
    batch, valid, mbsize = compute_elastic_config(ds_config, world_size=7)
    assert mbsize == 3


def test_v02_determinism(ds_config):
    ds_config["elasticity"].update(version=0.2, num_gpus_per_node=4)
    a = compute_elastic_config(ds_config, world_size=64)
    b = compute_elastic_config(ds_config, world_size=64)
    assert a == b
