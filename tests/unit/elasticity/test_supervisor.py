"""Elastic supervisor: detection + escalation ladder over pg_sim.

The core invariants (ISSUE 7 acceptance):
* kill/hang under pg_sim -> the supervised run recovers and its
  post-recovery loss trajectory is BITWISE identical to an unfaulted
  run restored from the same step (deterministic resume: data cursor +
  PRNG + sentinel state ride the checkpoint);
* shrink-and-reshard round-trips optimizer state EXACTLY
  (gather-and-compare);
* ``get_recovery_report()`` publishes non-empty MTTR/ladder records.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.elasticity import ElasticSupervisor
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
from deepspeed_tpu.resilience.errors import UnrecoverableWorkerFailure
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.tools.pg_sim import SimProcessGroup, uninstall_domain
from deepspeed_tpu.utils.tree import flatten_with_names

SEQ = 16


def make_engine(devices=None, batch_plan=None, sentinel=False):
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1), devices=devices)
    config = {
        "train_batch_size": 16,
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "steps_per_print": 0,
    }
    if sentinel:
        config["resilience"] = {"sentinel": {
            "enabled": True, "failure_budget": 1, "max_rollbacks": 8}}
    if batch_plan:
        config.update(batch_plan)
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config=config)
    return engine


def _batch(n=16):
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(n, SEQ), dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


@pytest.fixture(autouse=True)
def _clean():
    fault_injector.reset()
    uninstall_domain()
    yield
    fault_injector.reset()
    uninstall_domain()


def _gather(tree):
    names, leaves, _ = flatten_with_names(tree)
    return {n: np.asarray(l) for n, l in zip(names, leaves)}


@pytest.mark.fault
class TestLadder:

    @pytest.mark.slow  # tier-1 diet (PR 17): chaos_smoke[0] keeps the kill -> rollback -> bitwise-replay e2e tier-1
    def test_kill_rolls_back_and_replays_bitwise(self, tmp_path,
                                                 eight_devices):
        """Kill at step 3 -> immediate detection at the dispatch gate,
        rollback rung (respawn + resume_latest), and the post-recovery
        trajectory is bitwise what an unfaulted restore produces."""
        eng = make_engine()
        domain = SimProcessGroup(4)
        fault_injector.configure(domain.spec_for(2, 3, "kill"))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=make_engine)
        b = _batch()
        losses = [float(x) for x in sup.run(5, batch=b)]
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [d["mode"] for d in rep["detections"]] == ["kill"]
        assert [r["rung"] for r in rep["ladder"]] == ["rollback"]
        rec = rep["ladder"][0]
        assert rec["mttr_s"] > 0 and rec["restored_step"] == 3
        assert rep["mttr_s"]["last"] > 0
        assert domain.worker(2).respawns == 1
        # bitwise replay identity from the restored tag
        sup.engine.load_checkpoint(str(tmp_path), tag="global_step3")
        ctrl = [float(sup.engine.train_batch(batch=b))
                for _ in range(2)]
        assert losses[-2:] == ctrl
        sup.close()

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_transient_hang_recovers_via_retry_rung(self, tmp_path,
                                                    eight_devices):
        """A one-step hang clears on the retry rung: no rollback, no
        checkpoint restore, engine state untouched."""
        eng = make_engine()
        domain = SimProcessGroup(4)
        fault_injector.configure(
            domain.spec_for(1, 2, "hang", duration=1))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=make_engine)
        b = _batch()
        losses = [float(x) for x in sup.run(4, batch=b)]
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [r["rung"] for r in rep["ladder"]] == ["retry"]
        assert [d["mode"] for d in rep["detections"]] == ["hang"]
        assert len(losses) == 4 and np.isfinite(losses).all()
        # retry is still replay-consistent with the commit point
        sup.engine.load_checkpoint(str(tmp_path), tag="global_step2")
        ctrl = [float(sup.engine.train_batch(batch=b))
                for _ in range(2)]
        assert losses[-2:] == ctrl
        sup.close()

    @pytest.mark.slow  # tier-1 diet (ISSUE 8): retry success + kill->
    # rollback stay tier-1; the hang ESCALATION path rides full-suite
    def test_persistent_hang_escalates_to_rollback(self, tmp_path,
                                                   eight_devices):
        """A hang that outlives the retry budget escalates: respawn +
        rollback, and the run still completes."""
        eng = make_engine()
        domain = SimProcessGroup(4)
        fault_injector.configure(domain.spec_for(0, 2, "hang"))  # forever
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=make_engine,
                                max_step_retries=2)
        losses = sup.run(4, batch=_batch())
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [r["rung"] for r in rep["ladder"]] == ["rollback"]
        assert sup.engine.global_steps == 4
        assert domain.worker(0).respawns == 1
        assert np.isfinite([float(x) for x in losses]).all()
        sup.close()

    @pytest.mark.slow
    def test_external_iterator_rollback_replays_bitwise(
            self, tmp_path, eight_devices):
        """The README flow: sup.run(..., data_iter=<caller iterator>)
        with NO checkpointable cursor. The supervisor's batch log must
        re-feed the batches consumed past the restore point, so the
        post-rollback trajectory is still bitwise the restored-control
        one (review regression: the replayed steps used to pull FRESH
        samples and silently skip the rolled-back ones)."""
        def stream():
            rng = np.random.default_rng(5)
            while True:
                ids = rng.integers(0, 256,
                                   size=(16, SEQ)).astype(np.int32)
                yield {"input_ids": ids, "labels": ids.copy()}

        eng = make_engine()
        eng.init_params(next(stream()))
        domain = SimProcessGroup(4)
        # save_interval=2: the batch feeding step 2 is NOT covered by
        # a commit when the kill at step 3 rolls back to tag 2 — it
        # must come from the supervisor's replay log
        fault_injector.configure(domain.spec_for(1, 3, "kill"))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=make_engine,
                                save_interval=2)
        losses = [float(x) for x in sup.run(5, data_iter=stream())]
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [r["rung"] for r in rep["ladder"]] == ["rollback"]
        assert rep["ladder"][0]["restored_step"] == 2
        # control: restore tag 2 and feed the same stream suffix
        # (draws 2..4 of a fresh stream — the supervised run consumed
        # draws 0..4, with draw 2 replayed from the log)
        ctrl = make_engine()
        ctrl.init_params(next(stream()))
        ctrl.load_checkpoint(str(tmp_path), tag="global_step2")
        data = stream()
        batches = [next(data) for _ in range(5)]
        ctrl_losses = [float(ctrl.train_batch(batch=b))
                       for b in batches[2:5]]
        assert losses[-3:] == ctrl_losses
        sup.close()

    @pytest.mark.slow
    def test_engine_dataloader_rollback_no_double_feed(
            self, tmp_path, eight_devices):
        """Engine-OWNED dataloader (checkpointed cursor) with
        save_interval=2 and a kill past the commit: the rollback must
        rewind through the cursor ALONE — the supervisor's replay log
        must not re-feed those batches on top (review regression:
        double-feed left the stream one batch behind)."""
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import (GPT2Config,
                                               GPT2LMHeadModel)
        rng = np.random.default_rng(3)
        data = [{"input_ids": row, "labels": row.copy()}
                for row in rng.integers(
                    0, 256, size=(128, SEQ)).astype(np.int32)]

        def build(devices=None, batch_plan=None):
            mesh_manager.reset()
            mesh_manager.init(MeshConfig(data=-1), devices=devices)
            config = {
                "train_batch_size": 16,
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 0,
            }
            if batch_plan:
                config.update(batch_plan)
            eng, _, _, _ = deepspeed_tpu.initialize(
                model=GPT2LMHeadModel(GPT2Config.tiny()),
                config=config, training_data=data)
            return eng

        eng = build()
        b0 = {"input_ids": np.stack([d["input_ids"]
                                     for d in data[:16]]),
              "labels": np.stack([d["labels"] for d in data[:16]])}
        eng.init_params(b0)
        domain = SimProcessGroup(4)
        fault_injector.configure(domain.spec_for(1, 3, "kill"))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=build,
                                save_interval=2)
        losses = [float(x) for x in sup.run(5)]
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [r["rung"] for r in rep["ladder"]] == ["rollback"]
        assert rep["ladder"][0]["restored_step"] == 2
        # control: fresh process-equivalent restore of tag 2, driven
        # by ITS restored cursor — bitwise continuation
        ctrl = build()
        ctrl.init_params(b0)
        ctrl.load_checkpoint(str(tmp_path), tag="global_step2")
        ctrl_losses = [float(ctrl.train_batch()) for _ in range(3)]
        assert losses[-3:] == ctrl_losses
        sup.close()

    @pytest.mark.slow  # tier-1 diet (ISSUE 8): the terminal rung keeps
    # its cheap stub-engine gate (test_persistent_wedged_barrier_
    # reaches_terminal); this full-engine drill rides full-suite
    def test_terminal_exit_75_when_nothing_left(self, tmp_path,
                                                eight_devices):
        """Permanent loss with no engine_factory: the ladder runs dry
        and raises the typed terminal error carrying exit code 75 —
        the elastic agent's EX_TEMPFAIL contract."""
        eng = make_engine()
        domain = SimProcessGroup(4, respawnable=False)
        fault_injector.configure(domain.spec_for(3, 2, "kill"))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=None)
        with pytest.raises(UnrecoverableWorkerFailure) as ei:
            sup.run(5, batch=_batch())
        fault_injector.reset()
        assert ei.value.exit_code == 75
        assert ei.value.detections
        # running out of ladder is itself a recorded ladder action
        rep = eng.get_recovery_report()
        assert rep["rung_counts"]["terminal"] == 1
        assert rep["ladder"][-1]["rung"] == "terminal"
        sup.close()


def test_plan_shrink_batch_keeps_global_batch():
    """Pure shrink arithmetic: the global batch is invariant and dp
    never exceeds the survivors (incl. the dp << survivors corner the
    device-trim must respect — review regression)."""
    from deepspeed_tpu.elasticity.reshard import plan_shrink_batch
    assert plan_shrink_batch(16, 2, 6) == (4, 2, 2)
    assert plan_shrink_batch(16, 2, 8) == (8, 2, 1)
    # largest feasible dp is far below the survivor count: 10/2=5
    # slots, only dp=1 divides with 4 survivors ruled out (5%4!=0)
    assert plan_shrink_batch(10, 2, 4) == (1, 2, 5)
    for g, m, s in [(16, 2, 6), (10, 2, 4), (24, 3, 5)]:
        dp, micro, gas = plan_shrink_batch(g, m, s)
        assert micro * gas * dp == g and dp <= s


@pytest.mark.fault
class TestShrinkReshard:

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_reshard_round_trips_state_exactly(self, tmp_path,
                                               eight_devices):
        """Gather-and-compare: every master/optimizer leaf resharded
        onto the survivor mesh is BITWISE the checkpointed leaf (the
        transfer-engine bucket path is exact concat/slice)."""
        from deepspeed_tpu.elasticity.reshard import \
            reshard_from_manifest
        eng = make_engine()
        b = _batch()
        for _ in range(2):
            eng.train_batch(batch=b)
        eng.save_checkpoint(str(tmp_path))
        want = _gather(eng.state)

        eng2 = make_engine(devices=eight_devices[:4],
                           batch_plan={"gradient_accumulation_steps": 2})
        eng2.init_params(b)
        state, client_state, nbytes = reshard_from_manifest(
            str(tmp_path), eng2.state)
        got = _gather(state)
        assert set(got) == set(want)
        for name in want:
            np.testing.assert_array_equal(
                got[name], want[name], err_msg=name)
        assert nbytes == sum(a.nbytes for a in want.values())
        assert client_state["global_steps"] == 2

        # the reshard.h2d site is LIVE, not decorative: a transient
        # injected I/O fault retries (staging is immutable, replay is
        # exact) and the result still round-trips bitwise...
        with fault_injector.inject("reshard.h2d:ioerror"):
            state2, _, _ = reshard_from_manifest(str(tmp_path),
                                                 eng2.state)
            assert fault_injector.fired
        got2 = _gather(state2)
        for name in want:
            np.testing.assert_array_equal(got2[name], want[name],
                                          err_msg=name)
        # ...and a persistent injected fault PROPAGATES to the
        # caller's ladder instead of being silently absorbed by the
        # per-leaf fallback (the inert-site bug class the registry
        # lint exists to catch)
        from deepspeed_tpu.resilience.errors import InjectedFault
        with fault_injector.inject("reshard.h2d:error@0xinf"):
            with pytest.raises(InjectedFault):
                reshard_from_manifest(str(tmp_path), eng2.state)

        # stale-``latest`` contract matches the rollback rung's
        # loader: a newer tag whose payload vanished must fall back
        # to the previous good tag, not fail the shrink (review
        # regression)
        import shutil
        eng.train_batch(batch=b)
        eng.save_checkpoint(str(tmp_path))     # global_step3
        shutil.rmtree(tmp_path / "global_step3")
        assert (tmp_path / "latest").read_text() == "global_step3"
        state3, cs3, _ = reshard_from_manifest(str(tmp_path),
                                               eng2.state)
        assert cs3["_loaded_tag"] == "global_step2"
        got3 = _gather(state3)
        for name in want:
            np.testing.assert_array_equal(got3[name], want[name],
                                          err_msg=name)

    @pytest.mark.slow
    def test_two_simultaneous_kills_shrink_once(self, tmp_path,
                                                eight_devices):
        """Both dead workers are retired by ONE shrink (review
        regression: retiring only the detected rank made the monitor
        re-detect the other removed worker and forced a spurious
        second rebuild)."""
        eng = make_engine()
        domain = SimProcessGroup(4, respawnable=False)
        fault_injector.configure(",".join([
            domain.spec_for(1, 2, "kill"),
            domain.spec_for(3, 2, "kill")]))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=make_engine)
        losses = [float(x) for x in sup.run(4, batch=_batch())]
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [r["rung"] for r in rep["ladder"]] == ["shrink"]
        assert rep["ladder"][0]["world_after"] == 2
        assert len(domain.alive_workers()) == 2
        assert np.isfinite(losses).all()
        sup.close()

    @pytest.mark.slow
    def test_supervised_shrink_end_to_end(self, tmp_path,
                                          eight_devices):
        """Non-respawnable kill -> shrink rung: the job continues on
        the survivor mesh with the global batch preserved, the report
        records resharded bytes, and the post-shrink trajectory
        matches the restored-control run at the PR-3 cross-program
        bound (1e-5; a different mesh/gas decomposition reassociates
        reductions, so bitwise is not an XLA guarantee here)."""
        eng = make_engine()
        domain = SimProcessGroup(2, respawnable=False)
        fault_injector.configure(domain.spec_for(1, 2, "kill"))
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                engine_factory=make_engine)
        b = _batch()
        losses = [float(x) for x in sup.run(4, batch=b)]
        fault_injector.reset()
        rep = sup.engine.get_recovery_report()
        assert [r["rung"] for r in rep["ladder"]] == ["shrink"]
        rec = rep["ladder"][0]
        assert rec["resharded_bytes"] > 0
        assert rec["world_before"] == 2 and rec["world_after"] == 1
        assert rep["resharded_bytes"] == rec["resharded_bytes"]
        # survivor engine: half the devices, same global batch
        assert sup.engine.train_batch_size() == 16
        assert sup.engine.gradient_accumulation_steps() == 2
        assert dict(zip(sup.engine.mesh.axis_names,
                        sup.engine.mesh.devices.shape))["data"] == 4
        # control continuation from the restored tag (original mesh)
        ctrl_eng = make_engine()
        ctrl_eng.init_params(b)
        ctrl_eng.load_checkpoint(str(tmp_path), tag="global_step2")
        ctrl = [float(ctrl_eng.train_batch(batch=b)) for _ in range(2)]
        np.testing.assert_allclose(losses[-2:], ctrl, rtol=1e-5)
        sup.close()


@pytest.mark.fault
class TestUnattributableTimeout:

    class _StubEngine:
        """Just enough engine surface for the gate loop: the stall
        lives entirely in the dispatch gate, so no real device work
        is needed to drive the escalation bound."""

        def __init__(self, ckpt_dir):
            self._config = type("C", (), {})()
            self._sentinel = None
            self._params_initialized = True
            self._recovery = None
            self.global_steps = 0
            self._ckpt_dir = ckpt_dir

        def recovery(self):
            from deepspeed_tpu.resilience.recovery import \
                RecoveryReport
            if self._recovery is None:
                self._recovery = RecoveryReport()
            return self._recovery

        def save_checkpoint(self, d, **kw):
            import os
            os.makedirs(d, exist_ok=True)
            with open(os.path.join(d, "latest"), "w") as f:
                f.write("global_step0")

        def load_checkpoint(self, d, **kw):
            return d, {}

    def test_persistent_wedged_barrier_reaches_terminal(
            self, tmp_path, eight_devices):
        """A gate that times out under the collective watchdog with
        NO attributable worker (everyone looks healthy) must not
        retry/roll back forever: after the retry budget + ladder
        actions the supervisor raises the typed terminal error
        (review regression — the empty rank list made the retry rung
        vacuously 'succeed' and rollback always 'respawn')."""
        from deepspeed_tpu.resilience.watchdog import \
            collective_watchdog
        eng = self._StubEngine(str(tmp_path))
        domain = SimProcessGroup(2)
        sup = ElasticSupervisor(eng, domain, str(tmp_path),
                                max_step_retries=1)
        collective_watchdog.configure(0.05)
        # every pg_sim.collective fire hangs past the gate deadline;
        # no worker is ever hung/dead, so detections carry rank=-1
        fault_injector.configure("pg_sim.collective:hang@0xinf~0.3")
        try:
            with pytest.raises(UnrecoverableWorkerFailure) as ei:
                sup.step(batch=None)
        finally:
            collective_watchdog.configure(None)
            fault_injector.reset()
            sup.close()
        assert ei.value.exit_code == 75
        rep = eng.recovery()
        # no vacuous 'stall cleared' retry records
        assert rep.rung_counts["retry"] == 0
        assert rep.rung_counts["rollback"] >= 1
        assert rep.rung_counts["terminal"] == 1


@pytest.mark.fault
class TestReportSurface:

    def test_recovery_report_schema_pre_run(self, eight_devices):
        """Schema is always present (like the PR-6 report surfaces):
        empty history + process_memory gauges before any incident."""
        eng = make_engine()
        rep = eng.get_recovery_report()
        assert rep["detections"] == [] and rep["ladder"] == []
        assert rep["mttr_s"] == {"last": 0.0, "mean": 0.0, "max": 0.0}
        assert rep["resharded_bytes"] == 0
        assert "host_rss_gb" in rep["process_memory"]
