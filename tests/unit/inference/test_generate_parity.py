"""End-to-end GREEDY GENERATION parity against HF transformers —
stronger than logits parity: conversion + KV-cache decode + sampling
glue must all agree token-for-token (reference evidence tier:
tests/unit/inference/test_inference.py query/response checks)."""

import numpy as np
import pytest

import deepspeed_tpu


@pytest.fixture(scope="module")
def hf_and_ours():
    transformers = pytest.importorskip("transformers")
    import torch

    from deepspeed_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                            from_hf_state_dict)
    cfg = LlamaConfig.tiny()
    # derive the HF twin from OUR config so a tiny() change can't
    # silently skew the conversion under test
    hf_cfg = transformers.LlamaConfig(
        vocab_size=cfg.vocab_size, hidden_size=cfg.hidden_size,
        intermediate_size=cfg.intermediate_size,
        num_hidden_layers=cfg.num_hidden_layers,
        num_attention_heads=cfg.num_attention_heads,
        num_key_value_heads=cfg.num_key_value_heads,
        max_position_embeddings=cfg.max_position_embeddings,
        attention_dropout=0.0, rope_theta=cfg.rope_theta)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    params = from_hf_state_dict(hf.state_dict(), cfg)
    model = LlamaForCausalLM(cfg)
    return hf, model, params


def test_greedy_generate_matches_hf(hf_and_ours, eight_devices):
    import torch
    hf, model, params = hf_and_ours
    prompt = np.array([[11, 45, 3, 200, 7, 9]], np.int32)

    with torch.no_grad():
        ref = hf.generate(torch.tensor(prompt, dtype=torch.long),
                          max_new_tokens=8, do_sample=False,
                          pad_token_id=0).numpy()

    engine = deepspeed_tpu.init_inference(model, tp_size=1, dtype="float32")
    engine.set_params(params)
    ours = engine.generate(prompt, max_new_tokens=8, temperature=0.0)

    np.testing.assert_array_equal(ours, ref)


def test_greedy_generate_matches_hf_batched(hf_and_ours, eight_devices):
    """Batched prompts decode independently and still match HF."""
    import torch
    hf, model, params = hf_and_ours
    prompts = np.array([[11, 45, 3, 200], [90, 2, 150, 6]], np.int32)

    with torch.no_grad():
        ref = hf.generate(torch.tensor(prompts, dtype=torch.long),
                          max_new_tokens=6, do_sample=False,
                          pad_token_id=0).numpy()

    engine = deepspeed_tpu.init_inference(model, tp_size=1, dtype="float32")
    engine.set_params(params)
    ours = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(ours, ref)


def test_v2_ragged_greedy_matches_hf(hf_and_ours, eight_devices):
    """The ragged paged-KV engine's continuous-batching loop produces
    the same greedy tokens as HF generate — FastGen-path end-to-end."""
    import torch

    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.engine_v2 import \
        RaggedInferenceEngineConfig
    hf, model, params = hf_and_ours
    prompt = [11, 45, 3, 200, 7, 9]

    with torch.no_grad():
        ref = hf.generate(torch.tensor([prompt], dtype=torch.long),
                          max_new_tokens=8, do_sample=False,
                          pad_token_id=0).numpy()[0, len(prompt):]

    eng = InferenceEngineV2(
        params, model.config,
        RaggedInferenceEngineConfig(token_budget=64,
                                    max_ragged_sequence_count=4,
                                    n_kv_blocks=32, kv_block_size=8,
                                    max_blocks_per_seq=16,
                                    kv_dtype="float32"))
    out = eng.generate_batch({1: prompt}, max_new_tokens=8)
    np.testing.assert_array_equal(np.asarray(out[1]), ref)
