"""Sampling layer for the serving loops (SURVEY §2.7: generation lives
in DeepSpeed-MII in the reference; this framework ships it so both
engines serve end-to-end). Distribution-shape checks for temperature /
top-k / top-p, plus the v2 continuous-batching integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import (SamplingParams, filter_logits,
                                              make_sampler, ragged_sample,
                                              sample_token)


def _logits(vals):
    return np.asarray(vals, np.float32)


def test_temperature_zero_is_greedy_both_paths():
    logits = _logits([0.1, 3.0, -1.0, 2.0])
    assert sample_token(logits, np.random.default_rng(0)) == 1
    jit_sample = make_sampler(0.0)
    out = jit_sample(jnp.asarray(logits)[None], jax.random.PRNGKey(0))
    assert int(out[0]) == 1


def test_top_k_one_is_greedy_despite_temperature():
    logits = _logits([0.1, 3.0, -1.0, 2.0])
    rng = np.random.default_rng(0)
    for _ in range(20):
        assert sample_token(logits, rng, temperature=2.0, top_k=1) == 1


def test_top_k_restricts_support():
    logits = _logits([5.0, 4.0, 3.0, -50.0])
    rng = np.random.default_rng(0)
    seen = {sample_token(logits, rng, temperature=5.0, top_k=2)
            for _ in range(200)}
    assert seen <= {0, 1}
    assert len(seen) == 2       # high temperature reaches both


def test_top_p_keeps_smallest_nucleus():
    # probs ~ [0.97, 0.01, 0.01, ...]: p=0.5 nucleus is the top token
    logits = _logits([10.0, 5.0, 5.0, 5.0])
    rng = np.random.default_rng(0)
    seen = {sample_token(logits, rng, temperature=1.0, top_p=0.5)
            for _ in range(100)}
    assert seen == {0}


def test_top_p_one_keeps_everything():
    logits = _logits([1.0, 1.0, 1.0, 1.0])
    rng = np.random.default_rng(0)
    seen = {sample_token(logits, rng, temperature=1.0, top_p=1.0)
            for _ in range(300)}
    assert seen == {0, 1, 2, 3}


def test_top_k_larger_than_vocab_clamps():
    logits = _logits([1.0, 5.0, 2.0])
    rng = np.random.default_rng(0)
    # must not raise (jit path clamps via index clipping; host path
    # clamps explicitly)
    for _ in range(10):
        assert 0 <= sample_token(logits, rng, temperature=1.0,
                                 top_k=100) < 3


def test_sampling_is_seed_deterministic():
    logits = _logits(np.linspace(0, 2, 32))
    a = [sample_token(logits, np.random.default_rng(7), temperature=1.0)
         for _ in range(5)]
    b = [sample_token(logits, np.random.default_rng(7), temperature=1.0)
         for _ in range(5)]
    assert a == b


def test_jit_sampler_top_p_matches_support():
    logits = jnp.asarray([[10.0, 5.0, 5.0, 5.0]], jnp.float32)
    sample = make_sampler(1.0, top_p=0.5)
    toks = {int(sample(logits, jax.random.PRNGKey(i))[0])
            for i in range(50)}
    assert toks == {0}


def test_sampling_params_validation():
    with pytest.raises(ValueError):
        SamplingParams(temperature=-1.0)
    with pytest.raises(ValueError):
        SamplingParams(top_k=0)
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0)
    with pytest.raises(ValueError):
        SamplingParams(top_p=1.5)
    p = SamplingParams(temperature=0.7, top_k=50, top_p=0.9, seed=3)
    assert (p.temperature, p.top_k, p.top_p, p.seed) == (0.7, 50, 0.9, 3)


class TestSharedFilterParity:
    """The top-k/top-p math exists ONCE (``filter_logits``) and every
    sampler — jit, host numpy, fused ragged — must select identically
    on fixed logits."""

    LOGITS = np.asarray(
        [[5.0, 4.0, 4.0, 3.0, -1.0, 0.5, 2.0, 2.0],
         [0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8],
         [9.0, -9.0, 9.0, 0.0, 1.0, 2.0, 3.0, 4.0]], np.float32)

    @pytest.mark.parametrize("top_k,top_p", [
        (None, None), (1, None), (2, None), (3, 0.9), (None, 0.5),
        (100, None), (None, 1.0), (5, 0.25)])
    def test_host_vs_jit_filter_bitwise(self, top_k, top_p):
        host = filter_logits(self.LOGITS, top_k, top_p, xp=np)
        jit = np.asarray(filter_logits(jnp.asarray(self.LOGITS),
                                       top_k, top_p, xp=jnp))
        np.testing.assert_array_equal(np.isfinite(host),
                                      np.isfinite(jit))
        np.testing.assert_array_equal(host[np.isfinite(host)],
                                      jit[np.isfinite(jit)])

    @pytest.mark.parametrize("top_k,top_p", [
        (2, None), (None, 0.5), (3, 0.9)])
    def test_per_row_arrays_match_static(self, top_k, top_p):
        """The fused sampler's array-valued k/p (0 / 1.0 = off) selects
        the same support as the static jit/host paths."""
        B = self.LOGITS.shape[0]
        karr = np.full((B,), top_k if top_k else 0, np.int32)
        parr = np.full((B,), top_p if top_p is not None else 1.0,
                       np.float32)
        stat = filter_logits(self.LOGITS, top_k, top_p, xp=np)
        dyn = np.asarray(filter_logits(jnp.asarray(self.LOGITS),
                                       karr, parr, xp=jnp))
        np.testing.assert_array_equal(np.isfinite(stat),
                                      np.isfinite(dyn))

    def test_top_p_zero_keeps_the_top_token(self):
        """Degenerate top_p <= 0 (public API, unvalidated) must still
        keep the argmax token — the old roll-based keep[0]=True
        guarantee — on host, jit, and per-row-array paths."""
        logits = self.LOGITS
        # ties at the max survive together (same as the old roll-based
        # keep), so "the top token" means any max-valued index
        top = [set(np.flatnonzero(row == row.max())) for row in logits]
        got = [sample_token(row, np.random.default_rng(0),
                            temperature=1.0, top_p=0.0)
               for row in logits]
        assert all(g in t for g, t in zip(got, top)), (got, top)
        jit = np.asarray(make_sampler(1.0, top_p=0.0)(
            jnp.asarray(logits), jax.random.PRNGKey(0)))
        assert all(int(g) in t for g, t in zip(jit, top)), (jit, top)
        masked = filter_logits(logits, None, 0.0, xp=np)
        np.testing.assert_array_equal(
            np.isfinite(masked).sum(axis=-1), [len(t) for t in top])

    def test_greedy_parity_three_samplers(self):
        want = np.argmax(self.LOGITS, axis=-1)
        host = [sample_token(row, np.random.default_rng(0))
                for row in self.LOGITS]
        jit = make_sampler(0.0)(jnp.asarray(self.LOGITS),
                                jax.random.PRNGKey(0))
        B = self.LOGITS.shape[0]
        fused = ragged_sample(
            jnp.asarray(self.LOGITS), jnp.zeros((B,), jnp.float32),
            jnp.zeros((B,), jnp.int32), jnp.ones((B,), jnp.float32),
            jnp.arange(B, dtype=jnp.uint32),
            jnp.arange(B, dtype=jnp.uint32), jax.random.PRNGKey(0))
        np.testing.assert_array_equal(host, want)
        np.testing.assert_array_equal(np.asarray(jit), want)
        np.testing.assert_array_equal(np.asarray(fused), want)

    def test_ragged_sample_draw_is_batch_invariant(self):
        """A (seed, uid, position) triple draws the same token no
        matter which slot the row occupies — the property that makes
        sync and lookahead sampled streams identical."""
        row = jnp.asarray(np.linspace(0, 2, 16), jnp.float32)
        pad = jnp.asarray(np.linspace(-1, 1, 16), jnp.float32)
        key = jax.random.PRNGKey(5)

        def draw(logits, uids, pos):
            B = logits.shape[0]
            return np.asarray(ragged_sample(
                logits, jnp.full((B,), 0.9, jnp.float32),
                jnp.zeros((B,), jnp.int32), jnp.ones((B,), jnp.float32),
                jnp.asarray(uids, jnp.uint32),
                jnp.asarray(pos, jnp.uint32), key))

        a = draw(jnp.stack([row, pad]), [42, 7], [3, 0])
        b = draw(jnp.stack([pad, pad, row]), [7, 8, 42], [0, 0, 3])
        assert a[0] == b[2]


def test_v2_generate_batch_sampled(eight_devices):
    """The ragged serving loop must accept SamplingParams: sampled runs
    are reproducible by seed."""
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.engine_v2 import \
        RaggedInferenceEngineConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    eng = InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(token_budget=32,
                                    max_ragged_sequence_count=4,
                                    n_kv_blocks=16, kv_block_size=8,
                                    max_blocks_per_seq=8,
                                    kv_dtype="float32"))
    prompts = {1: [5, 6, 7], 2: [9, 10]}

    greedy = eng.generate_batch(dict(prompts), max_new_tokens=6)
    for uid in prompts:
        eng.flush(uid)
    s1 = eng.generate_batch(dict(prompts), max_new_tokens=6,
                            sampling=SamplingParams(temperature=1.5, seed=11))
    for uid in prompts:
        eng.flush(uid)
    s2 = eng.generate_batch(dict(prompts), max_new_tokens=6,
                            sampling=SamplingParams(temperature=1.5, seed=11))
    assert s1 == s2                       # seed-reproducible
    assert all(len(v) == 6 for v in s1.values())
    assert all(0 <= t < cfg.vocab_size for v in s1.values() for t in v)
    assert all(len(v) == 6 for v in greedy.values())
