"""TP inference engine (reference pattern: tests/unit/inference/)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


@pytest.fixture
def model_and_params():
    cfg = GPT2Config.tiny()
    model = GPT2LMHeadModel(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    return cfg, model, params


def test_forward_logits(model_and_params, eight_devices):
    cfg, model, params = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"tensor_parallel":
                                                         {"tp_size": 2}})
    engine.set_params(params)
    ids = np.array([[1, 2, 3, 4]], np.int32)
    logits = engine.forward(ids)
    assert logits.shape == (1, 4, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_tp_matches_single_device(model_and_params, eight_devices):
    """TP-sharded logits must match the unsharded forward."""
    cfg, model, params = model_and_params
    ids = np.array([[5, 6, 7, 8, 9]], np.int32)
    ref = model.apply(jax.tree_util.tree_map(
        lambda x: x.astype(np.float32), params), ids)

    engine = deepspeed_tpu.init_inference(model, tp_size=4, dtype="float32")
    engine.set_params(params)
    out = engine.forward(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_generate_greedy(model_and_params, eight_devices):
    _, model, params = model_and_params
    engine = deepspeed_tpu.init_inference(model, tp_size=2)
    engine.set_params(params)
    out = engine.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=4)
    assert out.shape == (1, 7)
    # greedy decode is deterministic
    out2 = engine.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=4)
    np.testing.assert_array_equal(out, out2)
