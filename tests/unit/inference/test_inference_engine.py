"""TP inference engine (reference pattern: tests/unit/inference/)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


@pytest.fixture
def model_and_params():
    cfg = GPT2Config.tiny()
    model = GPT2LMHeadModel(cfg)
    ids = np.zeros((1, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids)
    return cfg, model, params


def test_forward_logits(model_and_params, eight_devices):
    cfg, model, params = model_and_params
    engine = deepspeed_tpu.init_inference(model, config={"tensor_parallel":
                                                         {"tp_size": 2}})
    engine.set_params(params)
    ids = np.array([[1, 2, 3, 4]], np.int32)
    logits = engine.forward(ids)
    assert logits.shape == (1, 4, cfg.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


def test_tp_matches_single_device(model_and_params, eight_devices):
    """TP-sharded logits must match the unsharded forward."""
    cfg, model, params = model_and_params
    ids = np.array([[5, 6, 7, 8, 9]], np.int32)
    ref = model.apply(jax.tree_util.tree_map(
        lambda x: x.astype(np.float32), params), ids)

    engine = deepspeed_tpu.init_inference(model, tp_size=4, dtype="float32")
    engine.set_params(params)
    out = engine.forward(ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_generate_greedy(model_and_params, eight_devices):
    _, model, params = model_and_params
    engine = deepspeed_tpu.init_inference(model, tp_size=2)
    engine.set_params(params)
    out = engine.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=4)
    assert out.shape == (1, 7)
    # greedy decode is deterministic
    out2 = engine.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=4)
    np.testing.assert_array_equal(out, out2)


class TestKVCacheDecode:
    """Cached decode path (reference analog: softmax_context KV-cache
    attention, ops/transformer/inference/op_binding/softmax_context.py)."""

    @pytest.fixture
    def llama(self):
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        ids = np.zeros((1, 8), np.int32)
        params = model.init(jax.random.PRNGKey(0), ids)
        return cfg, model, params

    def test_cached_matches_recompute(self, llama, eight_devices):
        """KV-cache greedy decode must produce the same tokens as the
        full-recompute fallback."""
        cfg, model, params = llama
        prompt = np.array([[3, 1, 4, 1, 5]], np.int32)

        engine = deepspeed_tpu.init_inference(model, tp_size=2,
                                              dtype="float32")
        engine.set_params(params)
        assert hasattr(model, "init_cache")
        out_cached = engine.generate(prompt, max_new_tokens=6)

        out_recompute = engine._generate_recompute(
            prompt, 6, 0.0, None, None, jax.random.PRNGKey(0), None)
        np.testing.assert_array_equal(out_cached, np.asarray(out_recompute))

    def test_cached_decode_is_O_total(self, llama, eight_devices):
        """The scanned decode compiles two functions total (prefill +
        decode), regardless of token count."""
        cfg, model, params = llama
        engine = deepspeed_tpu.init_inference(model, tp_size=1,
                                              dtype="float32")
        engine.set_params(params)
        engine.generate(np.array([[1, 2]], np.int32), max_new_tokens=8)
        assert len(engine._decode_fns) == 1
        engine.generate(np.array([[1, 2]], np.int32), max_new_tokens=8)
        assert len(engine._decode_fns) == 1  # cache hit, no recompiles

    def test_eos_truncation(self, llama, eight_devices):
        from deepspeed_tpu.inference.engine import _truncate_at_eos
        full = np.array([[9, 9, 5, 2, 7, 2, 6]])
        out = _truncate_at_eos(full, 2, eos_token_id=2)
        # prompt [9,9] intact; generated [5,2,7,2,6] -> [5,2,2,2,2]
        np.testing.assert_array_equal(out, [[9, 9, 5, 2, 2, 2, 2]])

    def test_sampling_with_temperature(self, llama, eight_devices):
        cfg, model, params = llama
        engine = deepspeed_tpu.init_inference(model, tp_size=1)
        engine.set_params(params)
        out = engine.generate(np.array([[1, 2, 3]], np.int32),
                              max_new_tokens=5, temperature=0.8, top_k=10,
                              rng=jax.random.PRNGKey(7))
        assert out.shape == (1, 8)
        assert (np.asarray(out) < cfg.vocab_size).all()
