"""Weight-only-quantized serving (reference:
inference/quantization/quantization.py ZeroQuant PTQ serving,
module_inject/replace_module.py:43 GroupQuantizer int8, the FP6 WOQ
GEMM's role fp6_linear.cu) — int8/int4 weights consumed by BOTH
engines with bf16-tolerance logits parity and measured HBM savings."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.inference.quantization import (dequantize_weight,
                                                  quantize_param_tree,
                                                  quantize_weight,
                                                  tree_hbm_bytes)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return cfg, model, params


class TestQuantMath:

    @pytest.mark.parametrize("bits,tol", [(8, 0.01), (4, 0.10)])
    def test_roundtrip_error_bound(self, bits, tol):
        rng = np.random.default_rng(0)
        w = rng.standard_normal((64, 256)).astype(np.float32)
        leaf = quantize_weight(jax.numpy.asarray(w), num_bits=bits,
                               group_size=128)
        back = np.asarray(dequantize_weight(leaf, jax.numpy.float32))
        err = np.abs(back - w).max() / np.abs(w).max()
        assert err < tol, err

    def test_int4_packs_two_per_byte(self):
        w = jax.numpy.ones((16, 64))
        leaf = quantize_weight(w, num_bits=4)
        assert leaf["woq_q"].dtype == jax.numpy.uint8
        assert leaf["woq_q"].shape == (16, 32)

    def test_tree_quantization_skips_embeddings_and_small(self,
                                                          tiny_llama):
        _, _, params = tiny_llama
        q = quantize_param_tree(params, num_bits=8, min_size=1)
        from deepspeed_tpu.inference.quantization import is_woq_leaf
        from deepspeed_tpu.utils.tree import named_leaves
        names = [n for n, _ in named_leaves(params)]
        assert any("embed" in n for n in names)  # fixture sanity

        def find(node, path=""):
            if is_woq_leaf(node):
                yield path
            elif isinstance(node, dict):
                for k, v in node.items():
                    yield from find(v, f"{path}.{k}")
        woq_paths = list(find(q))
        assert woq_paths, "nothing quantized"
        assert not any("embed" in p for p in woq_paths)
        # projections got quantized
        assert any("proj" in p or "q_proj" in p for p in woq_paths)


class TestV1WOQ:

    @pytest.mark.parametrize("dtype,rtol", [("int8", 0.03),
                                            ("int4", 0.25)])
    def test_logits_parity_and_hbm_savings(self, tiny_llama,
                                           eight_devices, dtype, rtol):
        cfg, model, params = tiny_llama
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        ref_eng = deepspeed_tpu.init_inference(model, tp_size=1,
                                               dtype="float32")
        ref_eng.set_params(params)
        ids = np.array([[5, 6, 7, 8, 9]], np.int32)
        ref = np.asarray(ref_eng.forward(ids), np.float32)

        qeng = deepspeed_tpu.init_inference(model, tp_size=1,
                                            dtype=dtype,
                                            quantization_min_size=1)
        qeng.set_params(params)
        got = np.asarray(qeng.forward(ids), np.float32)
        # parity at quantization tolerance on the logits scale
        denom = np.abs(ref).max()
        assert np.abs(got - ref).max() / denom < rtol
        # HBM: quantized tree strictly smaller than the bf16 tree
        bf16_bytes = sum(
            x.size * 2 for x in jax.tree_util.tree_leaves(params)
            if np.issubdtype(np.asarray(x).dtype, np.floating))
        assert tree_hbm_bytes(qeng.params) < bf16_bytes

    def test_cached_generate_int8(self, tiny_llama, eight_devices):
        """The prefill + scanned-decode path serves the packed tree."""
        _, model, params = tiny_llama
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        ref_eng = deepspeed_tpu.init_inference(model, tp_size=1,
                                               dtype="float32")
        ref_eng.set_params(params)
        qeng = deepspeed_tpu.init_inference(model, tp_size=1,
                                            dtype="int8",
                                            quantization_min_size=1)
        qeng.set_params(params)
        prompt = np.array([[1, 2, 3]], np.int32)
        out = qeng.generate(prompt, max_new_tokens=5)
        assert out.shape == (1, 8)
        # int8 greedy decode usually matches fp32 on a tiny model; at
        # minimum it is deterministic and finite
        out2 = qeng.generate(prompt, max_new_tokens=5)
        np.testing.assert_array_equal(out, out2)

    def test_tp2_int8(self, tiny_llama, eight_devices):
        cfg, model, params = tiny_llama
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1, tensor=2))
        qeng = deepspeed_tpu.init_inference(model, tp_size=2,
                                            dtype="int8",
                                            quantization_min_size=1)
        qeng.set_params(params)
        ids = np.array([[5, 6, 7, 8]], np.int32)
        logits = np.asarray(qeng.forward(ids))
        assert np.isfinite(logits).all()


class TestV2WOQ:

    def test_ragged_decode_int8_matches_bf16_engine(self, tiny_llama):
        from deepspeed_tpu.inference.v2 import InferenceEngineV2
        from deepspeed_tpu.inference.v2.engine_v2 import \
            RaggedInferenceEngineConfig

        cfg, model, params = tiny_llama
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=16, kv_block_size=8, max_blocks_per_seq=8,
                  kv_dtype="float32")
        ref = InferenceEngineV2(params, cfg,
                                RaggedInferenceEngineConfig(**kw))
        q = InferenceEngineV2(
            params, cfg,
            RaggedInferenceEngineConfig(weight_dtype="int8",
                                        quantization_min_size=1, **kw))
        assert q._woq_bits == 8
        prompts = {1: [3, 1, 4, 1, 5], 2: [2, 7, 1]}
        out_ref = ref.generate_batch(dict(prompts), max_new_tokens=4)
        out_q = q.generate_batch(dict(prompts), max_new_tokens=4)
        # greedy decode over a tiny model: int8 tracks the dense path
        # (token-for-token on this fixture)
        assert out_q == out_ref
