"""Serving overload robustness (the long-run durability PR): admission
control with a bounded request queue, KV-utilization backpressure,
typed shedding, and the admission/process-memory additions to the
serving report. The watchdog/hang behavior lives with the other
fault-site tests (tests/unit/resilience/test_lifecycle_faults.py)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.resilience.errors import ServingOverloadError


@pytest.fixture(scope="module")
def model_and_params():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return cfg, params


def _engine(model_and_params, **cfg_kwargs):
    cfg, params = model_and_params
    return InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(token_budget=32,
                                    max_ragged_sequence_count=4,
                                    n_kv_blocks=16, kv_block_size=8,
                                    max_blocks_per_seq=8,
                                    kv_dtype="float32", **cfg_kwargs))


PROMPTS = {10: [3, 1, 4, 1, 5], 11: [2, 7, 1], 12: [9, 9]}


class TestAdmissionControl:

    def test_unbounded_by_default(self, model_and_params):
        eng = _engine(model_and_params)
        admitted, shed = eng.admit_requests(
            {uid: np.asarray(p) for uid, p in PROMPTS.items()})
        assert sorted(admitted) == sorted(PROMPTS) and shed == []

    def test_queue_depth_bound_sheds_arrival_order_tail(
            self, model_and_params):
        eng = _engine(model_and_params, max_queue_depth=2)
        admitted, shed = eng.admit_requests(
            {uid: np.asarray(p) for uid, p in PROMPTS.items()})
        assert sorted(admitted) == [10, 11]     # arrival order kept
        assert shed == [12]

    def test_active_counts_against_the_bound(self, model_and_params):
        eng = _engine(model_and_params, max_queue_depth=2)
        admitted, shed = eng.admit_requests(
            {uid: np.asarray(p) for uid, p in PROMPTS.items()}, active=2)
        assert admitted == {} and sorted(shed) == [10, 11, 12]

    def test_kv_util_threshold_refuses_new_work(self, model_and_params):
        eng = _engine(model_and_params,
                      admission_kv_util_threshold=0.25)
        # occupy a quarter of the pool: 2 sequences x 2 blocks
        eng.put([1], [list(range(16))])
        eng.put([2], [list(range(16))])
        assert eng.kv_utilization >= 0.25
        admitted, shed = eng.admit_requests({5: np.asarray([1, 2])})
        assert admitted == {} and shed == [5]
        # draining restores admission
        eng.flush(1)
        eng.flush(2)
        admitted, shed = eng.admit_requests({5: np.asarray([1, 2])})
        assert sorted(admitted) == [5] and shed == []

    def test_shedding_never_mutates_engine_state(self, model_and_params):
        eng = _engine(model_and_params, max_queue_depth=1)
        eng.admit_requests(
            {uid: np.asarray(p) for uid, p in PROMPTS.items()})
        assert not eng._state_manager.tracked_sequences
        assert eng.free_blocks == eng._config.n_kv_blocks


class TestGenerateBatchOverload:

    def test_raise_policy_is_typed_and_eager(self, model_and_params):
        eng = _engine(model_and_params, max_queue_depth=2)
        with pytest.raises(ServingOverloadError) as ei:
            eng.generate_batch(dict(PROMPTS), max_new_tokens=3)
        assert ei.value.shed_uids == (12,)
        assert not eng._state_manager.tracked_sequences

    def test_shed_policy_serves_admitted_subset(self, model_and_params):
        eng = _engine(model_and_params, max_queue_depth=2)
        out = eng.generate_batch(dict(PROMPTS), max_new_tokens=3,
                                 on_overload="shed")
        assert sorted(out) == [10, 11]
        assert all(len(v) == 3 for v in out.values())
        rep = eng.get_serving_report()
        assert rep["admission"] == {"requested": 3, "admitted": 2,
                                    "shed": 1, "shed_uids": [12]}
        # a shed uid resubmits verbatim once load drains
        out2 = eng.generate_batch({12: PROMPTS[12]}, max_new_tokens=3)
        assert len(out2[12]) == 3

    def test_shed_streams_match_unshed_run(self, model_and_params):
        """Backpressure must not perturb admitted streams: tokens for
        the admitted uids are identical with and without a shed
        sibling (draws are keyed per (seed, uid, position))."""
        eng = _engine(model_and_params)
        ref = eng.generate_batch({10: PROMPTS[10], 11: PROMPTS[11]},
                                 max_new_tokens=4)
        eng2 = _engine(model_and_params, max_queue_depth=2)
        got = eng2.generate_batch(dict(PROMPTS), max_new_tokens=4,
                                  on_overload="shed")
        assert got == ref

    def test_bad_on_overload_rejected(self, model_and_params):
        eng = _engine(model_and_params)
        with pytest.raises(ValueError, match="on_overload"):
            eng.generate_batch(dict(PROMPTS), max_new_tokens=2,
                               on_overload="drop")

    def test_stuck_workload_degrades_to_typed_error(
            self, model_and_params):
        """Sequences that outgrow the pool mid-run: the loop drains
        what it can (collect-only), then raises the typed overload —
        never a bare OutOfKVBlocks, never a wedge — and the aborted
        run's KV blocks are released, so a front-end that catches the
        error keeps serving instead of inheriting a pinned pool."""
        eng = _engine(model_and_params)
        prompts = {uid: list(range(30)) for uid in range(4)}
        with pytest.raises(ServingOverloadError) as ei:
            eng.generate_batch(prompts, max_new_tokens=40)
        assert "KV" in str(ei.value) or "kv" in str(ei.value)
        # mid-run abort must not leak the dead run's sequences/blocks
        assert not eng._state_manager.tracked_sequences
        assert eng.free_blocks == eng._config.n_kv_blocks
        out = eng.generate_batch({99: [1, 2, 3]}, max_new_tokens=3)
        assert len(out[99]) == 3

    def test_empty_admission_returns_empty(self, model_and_params):
        eng = _engine(model_and_params, max_queue_depth=1)
        out = eng.generate_batch(dict(PROMPTS), max_new_tokens=2,
                                 on_overload="shed")
        assert sorted(out) == [10]


class TestServingReportDurability:

    def test_report_always_carries_process_memory(self, model_and_params):
        eng = _engine(model_and_params)
        rep = eng.get_serving_report()       # before ANY run
        pm = rep["process_memory"]
        assert pm["host_rss_gb"] > 0
        assert "caches" in pm
        # the dispatch-signature cache is registered and bounded
        assert any(n.startswith("v2_dispatch_signatures")
                   for n in pm["caches"])

    def test_signature_cache_bounded(self, model_and_params):
        eng = _engine(model_and_params, max_dispatch_signatures=1)
        eng.generate_batch({1: [1, 2, 3]}, max_new_tokens=2)  # greedy
        eng.put([2], [[1, 2]])                                 # logits
        assert len(eng._seen_signatures) == 1   # LRU evicted the older
