"""Speculative decoding (draft-k-verify) over the v2 ragged engine:
prompt-lookup drafting, the on-device accept kernel, rollback of
rejected tails, the acceptance-EWMA throttle, and the bitwise
spec-on/off equivalence contract at engine and front-end level.

Tier-1 keeps the host-only units, the rollback hardening, ONE greedy
equivalence smoke and ONE front-end acceptance e2e; the heavy sampled
accept/reject sweeps, the churn soak and the win-proof run are marked
``slow`` (the tier-1 budget guard)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import SamplingParams
from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        ServingFrontend)
from deepspeed_tpu.inference.v2.spec import (PromptLookupDrafter,
                                             SpeculationConfig,
                                             SpecSession, make_drafter)
from deepspeed_tpu.inference.v2.metrics import ServingMetrics
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.resilience.fault_injector import fault_injector

PROMPTS = {10: [3, 1, 4, 1, 5], 11: [2, 7, 1], 12: [9, 9]}
SYS = list(range(1, 17))                 # 2 full 8-token shared blocks


@pytest.fixture(scope="module")
def params_cfg():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return params, cfg


def _engine(params_cfg, **kw):
    params, cfg = params_cfg
    eng_kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=32, kv_block_size=8,
                  max_blocks_per_seq=8, kv_dtype="float32")
    eng_kw.update(kw)
    return InferenceEngineV2(params, cfg,
                             RaggedInferenceEngineConfig(**eng_kw))


@pytest.fixture(scope="module")
def engine(params_cfg):
    return _engine(params_cfg)


def _clean(engine):
    cached = (engine.prefix_cache.stats()["cached_blocks"]
              if engine.prefix_cache else 0)
    assert not engine._state_manager.tracked_sequences
    assert engine.free_blocks == engine._config.n_kv_blocks - cached


# ---------------------------------------------------------------------------
# host-only units: drafter, config, throttle
# ---------------------------------------------------------------------------
class TestPromptLookupDrafter:

    def test_drafts_continuation_of_matched_ngram(self):
        d = PromptLookupDrafter(ngram_max=2)
        d.observe(7, [5, 6, 8, 9, 5, 6])
        assert d.draft(7, 2).tolist() == [8, 9]

    def test_longest_ngram_wins(self):
        # bigram [1, 2] occurs twice with different continuations; the
        # trigram [9, 1, 2] disambiguates to the second one
        d = PromptLookupDrafter(ngram_max=3)
        d.observe(7, [1, 2, 30, 9, 1, 2, 40, 0, 9, 1, 2])
        assert d.draft(7, 1).tolist() == [40]

    def test_most_recent_full_continuation_wins(self):
        d = PromptLookupDrafter(ngram_max=1)
        # token 4 occurs at positions 0 and 3; the later match still
        # has a full 2-token continuation and wins
        d.observe(7, [4, 10, 11, 4, 20, 21, 4])
        assert d.draft(7, 2).tolist() == [20, 21]

    def test_partial_draft_when_no_full_continuation(self):
        d = PromptLookupDrafter(ngram_max=1)
        d.observe(7, [4, 20, 4])
        # only one earlier occurrence, one follower available
        assert d.draft(7, 3).tolist() == [20, 4]

    def test_no_match_is_empty(self):
        d = PromptLookupDrafter()
        d.observe(7, [1, 2, 3, 4, 5])
        out = d.draft(7, 4)
        assert out.dtype == np.int32 and out.size == 0
        # unknown uid likewise
        assert d.draft(99, 4).size == 0

    def test_history_bound_clips_oldest(self):
        d = PromptLookupDrafter(ngram_max=2, max_history=8)
        d.observe(7, [5, 6, 8, 9])            # will be clipped away
        d.observe(7, list(range(100, 108)))   # fills the window
        assert d.draft(7, 2).size == 0        # [5, 6] evidence gone
        assert len(d._hist.get(7)) == 8

    def test_uid_bound_is_lru(self):
        d = PromptLookupDrafter(max_uids=2)
        d.observe(1, [1, 2, 1])
        d.observe(2, [1, 2, 1])
        d.observe(3, [1, 2, 1])               # evicts uid 1
        assert d.draft(1, 1).size == 0
        assert d.draft(3, 1).size == 1

    def test_forget_drops_state(self):
        d = PromptLookupDrafter()
        d.observe(7, [1, 2, 1])
        d.forget(7)
        assert d.draft(7, 1).size == 0

    def test_registry(self):
        assert isinstance(make_drafter("prompt_lookup"),
                          PromptLookupDrafter)
        with pytest.raises(ValueError, match="unknown drafter"):
            make_drafter("oracle")
        with pytest.raises(ValueError, match="ngram_min"):
            PromptLookupDrafter(ngram_max=1, ngram_min=2)


class TestSpeculationConfig:

    def test_resolve_variants(self):
        assert SpeculationConfig.resolve(None) is None
        assert SpeculationConfig.resolve(False) is None
        assert SpeculationConfig.resolve(True).k == 4
        assert SpeculationConfig.resolve({"k": 2}).k == 2
        cfg = SpeculationConfig(k=3)
        assert SpeculationConfig.resolve(cfg) is cfg
        with pytest.raises(TypeError):
            SpeculationConfig.resolve(7)

    def test_validation(self):
        with pytest.raises(ValueError):
            SpeculationConfig(k=0)
        with pytest.raises(ValueError):
            SpeculationConfig(acceptance_floor=1.5)
        with pytest.raises(ValueError):
            SpeculationConfig(ewma_alpha=0.0)
        with pytest.raises(ValueError):
            SamplingParams(speculation=-1)


class TestThrottle:

    def _session(self, **kw):
        kw.setdefault("acceptance_floor", 0.5)
        kw.setdefault("warmup_drafts", 3)
        m = ServingMetrics("lookahead", 4)
        return SpecSession(SpeculationConfig(**kw), metrics=m), m

    def test_per_request_k_clamped_to_deployment(self):
        s, _ = self._session(k=4)
        s.admit(1, [1, 2, 1, 2], k_req=9)
        assert s._state.get(1)[2] == 4
        s.admit(2, [1, 2, 1, 2], k_req=0)
        assert s.throttled(2)
        assert s.plan_row(2, 5, remaining=10) is None

    def test_wants_spec_respects_budget_headroom(self):
        s, _ = self._session(k=4)
        s.admit(1, [1, 2, 1, 2])
        assert s.wants_spec(1, remaining=10)
        # a verify row only pays off when it can emit > 1 token
        assert not s.wants_spec(1, remaining=1)
        assert not s.wants_spec(1, remaining=0)

    def test_plan_row_clamps_k_to_remaining(self):
        s, _ = self._session(k=4)
        s.admit(1, [8, 9, 8, 9, 8])
        row = s.plan_row(1, 9, remaining=3)    # k = min(4, 2)
        assert row is not None and len(row) <= 3
        assert row[0] == 9                     # t0 always leads

    def test_low_acceptance_throttles_permanently_after_warmup(self):
        s, m = self._session(k=4, acceptance_floor=0.5,
                             warmup_drafts=3, ewma_alpha=1.0)
        s.admit(1, [1, 2, 1, 2])
        for _ in range(2):
            s.record_result(1, 4, 0)
            assert not s.throttled(1)          # still in warmup
        s.record_result(1, 4, 0)
        assert s.throttled(1)
        assert m.spec_throttled_uids == 1
        assert s.plan_row(1, 5, remaining=10) is None
        # permanent: later perfect results don't resurrect it
        s.record_result(1, 4, 4)
        assert s.throttled(1)

    def test_high_acceptance_never_throttles(self):
        s, m = self._session(k=4, acceptance_floor=0.5,
                             warmup_drafts=2)
        s.admit(1, [1, 2, 1, 2])
        for _ in range(10):
            s.record_result(1, 4, 4)
        assert not s.throttled(1)
        assert m.spec_throttled_uids == 0

    def test_draft_fault_degrades_to_empty_draft(self):
        s, m = self._session(k=4)
        s.admit(1, [8, 9, 8, 9, 8])
        with fault_injector.inject("spec.draft:error"):
            row = s.plan_row(1, 9, remaining=10)
        assert row is not None and row.tolist() == [9]   # t0 only
        assert m.spec_draft_faults == 1


# ---------------------------------------------------------------------------
# rollback hardening for k > 1 (the satellite regression tests)
# ---------------------------------------------------------------------------
class TestRollbackRejected:

    def test_multi_token_rollback_stops_at_shared_prefix_boundary(
            self, params_cfg):
        eng = _engine(params_cfg)
        sm = eng._state_manager
        shared = sm.kv.allocator.allocate(1)         # one 8-token block
        seq = sm.adopt_prefix(77, shared, 8)
        seq.blocks.extend(sm.kv.allocator.allocate(1))
        seq.seen_tokens = 10
        # rolling back 5 crosses into the shared block's token span:
        # seen shrinks to 5 but the SHARED block must survive
        eng.rollback_rejected(77, 5)
        assert seq.seen_tokens == 5
        assert len(seq.blocks) == 1
        assert seq.blocks == shared
        sm.flush_sequence(77)
        sm.kv.allocator.free(shared)                 # cache's own ref
        _clean(eng)

    def test_rollback_across_block_edge_frees_partial_block(
            self, params_cfg):
        eng = _engine(params_cfg)
        sm = eng._state_manager
        seq = sm.get_or_create_sequence(78)
        seq.blocks.extend(sm.kv.allocator.allocate(3))
        seq.seen_tokens = 17                         # 3rd block: 1 token
        free_before = sm.free_blocks
        eng.rollback_rejected(78, 2)                 # 17 -> 15 tokens
        assert seq.seen_tokens == 15
        assert len(seq.blocks) == 2                  # 3rd block freed
        assert sm.free_blocks == free_before + 1
        eng.rollback_rejected(78, 4)                 # 15 -> 11: same blk
        assert len(seq.blocks) == 2
        sm.flush_sequence(78)
        _clean(eng)

    def test_rollback_within_block_keeps_it(self, params_cfg):
        eng = _engine(params_cfg)
        sm = eng._state_manager
        seq = sm.get_or_create_sequence(79)
        seq.blocks.extend(sm.kv.allocator.allocate(2))
        seq.seen_tokens = 12
        eng.rollback_rejected(79, 3)                 # 12 -> 9: 2 blocks
        assert seq.seen_tokens == 9 and len(seq.blocks) == 2
        assert eng.rollback_rejected(79, 0) is None  # no-op
        assert eng.rollback_rejected(999, 3) is None  # unknown uid
        sm.flush_sequence(79)
        _clean(eng)


# ---------------------------------------------------------------------------
# the tier-1 equivalence smoke + acceptance e2e
# ---------------------------------------------------------------------------
class TestEquivalenceSmoke:

    def test_greedy_bitwise_spec_on_off_incl_eos_inside_draft(
            self, engine):
        """THE speculative contract: greedy token streams are bitwise
        identical with speculation on and off — including when EOS
        lands inside an accepted span (discovered from the same packed
        verify output, never re-decoded)."""
        base = engine.generate_batch(dict(PROMPTS), max_new_tokens=8)
        spec = engine.generate_batch(dict(PROMPTS), max_new_tokens=8,
                                     speculation=True)
        assert base == spec
        rep = engine.get_serving_report()
        assert rep["speculation"]["verify_steps"] > 0
        assert rep["steady_blocking_syncs"] == 0
        # pick an eos that appears mid-stream so the EOS cut path runs
        eos = next(s[len(s) // 2] for s in base.values()
                   if len(set(s)) > 1)
        b = engine.generate_batch(dict(PROMPTS), max_new_tokens=8,
                                  eos_token_id=eos)
        s = engine.generate_batch(dict(PROMPTS), max_new_tokens=8,
                                  eos_token_id=eos, speculation=True)
        assert b == s
        _clean(engine)

    def test_eos_as_first_accepted_token_finishes_cleanly(
            self, params_cfg):
        """EOS inside the ACCEPTED span: zeros params emit token 0
        always, the prompt-lookup drafter drafts zeros, acceptance is
        full — and eos=0 must cut the stream at one token with every
        KV block back (flush handles the whole committed span)."""
        params, cfg = params_cfg
        zeros = jax.tree.map(np.zeros_like, params)
        eng = InferenceEngineV2(
            zeros, cfg, RaggedInferenceEngineConfig(
                token_budget=32, max_ragged_sequence_count=4,
                n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
                kv_dtype="float32"))
        out = eng.generate_batch({1: [0, 0, 0, 0]}, max_new_tokens=8,
                                 eos_token_id=0, speculation=True)
        assert out == {1: [0]}
        _clean(eng)

    def test_speculation_requires_lookahead(self, engine):
        with pytest.raises(ValueError, match="lookahead"):
            engine.generate_batch(dict(PROMPTS), mode="sync",
                                  speculation=True)


class TestFrontendAcceptanceE2E:

    def test_mixed_k_streams_bitwise_with_zero_steady_syncs(
            self, params_cfg, engine):
        """The ISSUE acceptance e2e: speculation on at the front-end
        with MIXED per-request draft lengths (deployment default,
        lowered, opted out) over shared-prefix (adopted) prompts —
        recompiles <= 1, steady_blocking_syncs == 0, greedy streams
        bitwise identical to the spec-off engine, and the speculation
        block reaches get_serving_report()."""
        prompts = {20: SYS + [3, 1, 4], 21: SYS + [2, 7],
                   22: SYS + [9], 23: SYS + [5, 3]}
        refs = engine.generate_batch(
            {u: np.asarray(prompts[u], np.int32) for u in (20, 21, 22)},
            max_new_tokens=6)
        refs.update(engine.generate_batch(
            {23: np.asarray(prompts[23], np.int32)}, max_new_tokens=6))
        eng = _engine(params_cfg)
        fe = ServingFrontend(eng, config={
            "speculation": {"enabled": True, "k": 4}})
        # mixed per-request k: deployment default (4), lowered (2),
        # opted out (0) — submitted BEFORE the first dispatch so the
        # verify executable pins once (a sampled join after a greedy
        # dispatch costs the documented one extra compile)
        samp = {20: None, 21: SamplingParams(speculation=2),
                22: SamplingParams(speculation=0)}
        reqs = {}

        def poll(f, step):
            if step == 0:
                for u in (20, 21, 22):
                    reqs[u] = f.submit(np.asarray(prompts[u], np.int32),
                                       uid=u, max_new_tokens=6,
                                       sampling=samp[u])
            if step == 4:
                # staggered arrival ADOPTS the cached shared-prefix
                # blocks mid-decode (the adopted-sequence equivalence
                # leg) — greedy, so the pinned signature is untouched
                reqs[23] = f.submit(np.asarray(prompts[23], np.int32),
                                    uid=23, max_new_tokens=6)
            return step < 5

        fe.serve(poll=poll)
        for u in prompts:
            assert reqs[u].tokens == refs[u], u
        rep = fe.get_serving_report()
        assert rep["recompiles"] <= 1
        assert rep["steady_blocking_syncs"] == 0
        assert rep["speculation"]["verify_steps"] > 0
        # prefix adoption engaged (the adopted-sequence equivalence leg)
        assert rep["prefix"]["hit_rate"] > 0
        _clean(eng)


# ---------------------------------------------------------------------------
# heavy sweeps + soak (slow tier)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestSampledSweep:

    def test_sampled_accept_reject_sweep(self, engine):
        """Rejection-sampling path under a sweep of sampling configs:
        every run completes with consistent KV accounting, greedy rows
        stay bitwise, and opted-out sampled rows match spec-off
        streams bitwise (the raw-key replacement-draw contract)."""
        for temp, tk, tp in [(0.7, None, None), (1.0, 5, None),
                             (0.9, None, 0.9), (1.2, 17, 0.95)]:
            samp = {10: SamplingParams(temperature=temp, top_k=tk,
                                       top_p=tp, seed=11),
                    11: SamplingParams(),          # greedy row
                    12: SamplingParams(temperature=temp, seed=11,
                                       speculation=0)}
            base = engine.generate_batch(dict(PROMPTS),
                                         max_new_tokens=10,
                                         sampling=samp)
            spec = engine.generate_batch(dict(PROMPTS),
                                         max_new_tokens=10,
                                         sampling=samp,
                                         speculation={"k": 3})
            assert base[11] == spec[11], (temp, tk, tp)
            assert base[12] == spec[12], (temp, tk, tp)
            assert all(len(v) == 10 for v in spec.values())
            _clean(engine)

    def test_rejection_sampling_preserves_marginals(self, params_cfg):
        """Statistical check on zeros params (uniform p over the tiny
        vocab): with drafts always proposing token 0, the accept rule
        must keep emission marginals close to uniform — a biased
        accept kernel shows up as mass piling on the draft token."""
        params, cfg = params_cfg
        zeros = jax.tree.map(np.zeros_like, params)
        eng = InferenceEngineV2(
            zeros, cfg, RaggedInferenceEngineConfig(
                token_budget=32, max_ragged_sequence_count=4,
                n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
                kv_dtype="float32"))
        V = cfg.vocab_size
        toks = []
        for r in range(6):
            samp = {1: SamplingParams(temperature=1.0, seed=r)}
            out = eng.generate_batch({1: [0, 0, 0]}, max_new_tokens=24,
                                     sampling=samp,
                                     speculation={"k": 3})
            toks.extend(out[1])
        freq0 = toks.count(0) / len(toks)
        # uniform target is 1/V; piling at the point-mass draft token
        # 0 would push this toward the acceptance rate instead
        assert freq0 < 10.0 / V, (freq0, V)
        _clean(eng)


@pytest.mark.slow
@pytest.mark.soak
class TestChurnSoak:

    def test_frontend_churn_with_speculation(self, params_cfg):
        """Open-world churn: staggered joins, cancels mid-flight and
        throttling traffic with speculation on — the engine ends
        clean, nothing recompiles after the first dispatch, and the
        speculation counters stay coherent."""
        eng = _engine(params_cfg, n_kv_blocks=48)
        fe = ServingFrontend(eng, config={
            "speculation": {"enabled": True, "k": 3,
                            "acceptance_floor": 0.4,
                            "warmup_drafts": 2}})
        rng = np.random.default_rng(0)
        live = []
        submitted = cancelled = 0

        def poll(f, step):
            nonlocal submitted, cancelled
            if step % 3 == 0 and submitted < 24:
                uid = 100 + submitted
                tail = rng.integers(1, 50, size=3).tolist()
                rep = ([7, 8, 9] * 4)[:rng.integers(4, 10)]
                f.submit(np.asarray(SYS[:8] + rep + tail, np.int32),
                         uid=uid, max_new_tokens=int(
                             rng.integers(2, 10)))
                live.append(uid)
                submitted += 1
            if step % 11 == 7 and live:
                uid = live.pop(0)
                req = f.get_request(uid)
                if req is not None and not req.done:
                    f.cancel(uid)
                    cancelled += 1
            return submitted < 24
        fe.serve(poll=poll)
        rep = fe.get_serving_report()
        assert rep["requests"]["finished"] + \
            rep["requests"]["cancelled"] == 24
        assert rep["recompiles"] <= 1
        sp = rep["speculation"]
        assert sp["drafted_tokens"] >= sp["accepted_tokens"] >= 0
        assert sp["verify_rows"] >= sp["verify_steps"]
        _clean(eng)


@pytest.mark.slow
class TestWinProof:

    def test_repetitive_traffic_multiplies_emissions_per_verify(
            self, params_cfg):
        """The tiny-scale win proof (bench config 7 publishes the same
        number): on repetitive traffic, mean emitted tokens per verify
        row clears 1.3 — each verify step does the work of >1 plain
        decode steps."""
        params, cfg = params_cfg
        zeros = jax.tree.map(np.zeros_like, params)
        eng = InferenceEngineV2(
            zeros, cfg, RaggedInferenceEngineConfig(
                token_budget=32, max_ragged_sequence_count=4,
                n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
                kv_dtype="float32"))
        eng.generate_batch({1: [0, 0, 0, 0], 2: [0, 0, 0]},
                           max_new_tokens=16, speculation={"k": 4})
        sp = eng.get_serving_report()["speculation"]
        assert sp["emitted_per_verify"] > 1.3, sp
        assert sp["acceptance_rate"] > 0.9, sp
        _clean(eng)
