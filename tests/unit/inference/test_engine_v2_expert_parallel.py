"""Expert-parallel v2 serving (reference:
v2/kernels/cutlass_ops/moe_gemm sharded across ranks +
model_implementations/sharding/): the expert bank lives E/ep per shard,
and decode output must be TOKEN-EXACT against the replicated-bank
engine — the psum assembly drops nothing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

# jaxlib 0.4.x: compiling the EP serving program SIGABRTs inside XLA
# CPU (process-fatal — unskippable at runtime), so the whole module is
# gated on the jax version.
from deepspeed_tpu.utils.jax_compat import OLD_XLA

pytestmark = pytest.mark.skipif(
    OLD_XLA,
    reason="XLA CPU aborts (SIGABRT) compiling expert-parallel serving "
           "programs on jaxlib 0.4.x")

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.parallel.mesh import (EXPERT_AXIS, MeshConfig,
                                         mesh_manager)


def _mixtral():
    from deepspeed_tpu.models.mixtral import (MixtralConfig,
                                              MixtralForCausalLM)
    cfg = MixtralConfig.tiny()          # 4 experts, top-2
    model = MixtralForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return model, params, cfg


def _v2(params, cfg, **over):
    kw = dict(token_budget=32, max_ragged_sequence_count=4,
              n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
              kv_dtype="float32")
    kw.update(over)
    return InferenceEngineV2(params, cfg,
                             RaggedInferenceEngineConfig(**kw))


PROMPTS = {1: [3, 1, 4, 1, 5], 2: [2, 7, 1]}


def test_ep_serving_token_exact_vs_replicated(eight_devices):
    model, params, cfg = _mixtral()
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    ref = _v2(params, cfg).generate_batch(PROMPTS, max_new_tokens=6)

    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1, expert=4))
    eng = _v2(params, cfg, ep_size=4)
    # the bank is actually sharded: each shard holds E/ep experts
    we = eng.tree["layers"][0]["we_gate"]
    assert EXPERT_AXIS in (we.sharding.spec or ())
    shard_rows = {s.data.shape[0] for s in we.addressable_shards}
    assert shard_rows == {we.shape[0] // 4}
    got = eng.generate_batch(PROMPTS, max_new_tokens=6)
    assert got == ref, (got, ref)


def test_ep_composes_with_tp(eight_devices):
    """expert x tensor mesh: bank sharded over experts AND ffn dim."""
    model, params, cfg = _mixtral()
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    ref = _v2(params, cfg).generate_batch(PROMPTS, max_new_tokens=5)

    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1, expert=4, tensor=2))
    eng = _v2(params, cfg, ep_size=4, tp_size=2)
    sp = tuple(eng.tree["layers"][0]["we_gate"].sharding.spec)
    assert sp[0] == EXPERT_AXIS and "tensor" in sp
    got = eng.generate_batch(PROMPTS, max_new_tokens=5)
    assert got == ref, (got, ref)


def test_ep_requires_divisible_experts(eight_devices):
    model, params, cfg = _mixtral()
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1, expert=4))
    with pytest.raises(ValueError, match="ep_size"):
        _v2(params, cfg, ep_size=3)


def test_ep_rejected_for_dense_models(eight_devices):
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1, expert=2))
    with pytest.raises(ValueError, match="MoE"):
        _v2(params, cfg, ep_size=2)
