"""Async serving loop (one-step lookahead), on-device sampling, and the
serving metrics layer — FastGen/MII serving-side behavior for the v2
ragged engine.

The load-bearing contract: the lookahead loop's token streams are
IDENTICAL to the synchronous loop's — bitwise under greedy, and also
bitwise under seeded sampling because draws are keyed by (seed, uid,
position), never by batch composition or loop mode.
"""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import SamplingParams
from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

PROMPTS = {10: [3, 1, 4, 1, 5], 11: [2, 7, 1], 12: [9, 9]}


@pytest.fixture(scope="module")
def engine():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(token_budget=32,
                                    max_ragged_sequence_count=4,
                                    n_kv_blocks=16, kv_block_size=8,
                                    max_blocks_per_seq=8,
                                    kv_dtype="float32"))


def _clean(engine):
    assert not engine._state_manager.tracked_sequences
    assert engine.free_blocks == engine._config.n_kv_blocks


class TestLoopEquivalence:

    def test_greedy_streams_bitwise_identical(self, engine):
        """lookahead == sync == sync_host (legacy host sampling), token
        for token, under greedy."""
        ref = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                    mode="sync")
        _clean(engine)
        look = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                     mode="lookahead")
        _clean(engine)
        legacy = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                       mode="sync_host")
        _clean(engine)
        assert look == ref
        assert legacy == ref

    def test_seeded_sampled_streams_identical(self, engine):
        """Per-(seed, uid, position) keyed draws make the sampled
        streams loop-mode-invariant (stronger than the distribution
        equivalence the contract requires)."""
        sp = SamplingParams(temperature=1.3, top_k=16, top_p=0.95,
                            seed=11)
        a = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                  sampling=sp, mode="sync")
        _clean(engine)
        b = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                  sampling=sp, mode="lookahead")
        _clean(engine)
        assert a == b
        assert all(len(v) == 6 for v in a.values())

    def test_per_uid_sampling_params(self, engine):
        """A per-uid dict mixes greedy and sampled rows in one batch;
        greedy rows must match the all-greedy run exactly."""
        greedy = engine.generate_batch(dict(PROMPTS), max_new_tokens=5,
                                       mode="lookahead")
        _clean(engine)
        mixed = engine.generate_batch(
            dict(PROMPTS), max_new_tokens=5,
            sampling={11: SamplingParams(temperature=2.0, seed=3)},
            mode="lookahead")
        _clean(engine)
        assert mixed[10] == greedy[10]
        assert mixed[12] == greedy[12]
        assert len(mixed[11]) == 5

    def test_per_uid_dict_seeds_honored_and_conflicts_raise(self,
                                                            engine):
        """Dict-mode sampling threads the (single) configured seed into
        the base key — changing it changes the streams — and
        conflicting per-uid seeds raise instead of silently picking
        one."""
        d1 = {u: SamplingParams(temperature=1.5, seed=5)
              for u in PROMPTS}
        a = engine.generate_batch(dict(PROMPTS), max_new_tokens=4,
                                  sampling=dict(d1))
        _clean(engine)
        b = engine.generate_batch(dict(PROMPTS), max_new_tokens=4,
                                  sampling=dict(d1))
        _clean(engine)
        d2 = {u: SamplingParams(temperature=1.5, seed=6)
              for u in PROMPTS}
        c = engine.generate_batch(dict(PROMPTS), max_new_tokens=4,
                                  sampling=d2)
        _clean(engine)
        assert a == b
        assert a != c
        with pytest.raises(ValueError, match="conflicting seeds"):
            engine.generate_batch(
                dict(PROMPTS), max_new_tokens=4,
                sampling={10: SamplingParams(temperature=1.0, seed=1),
                          11: SamplingParams(temperature=1.0, seed=2)})
        _clean(engine)

    def test_eos_overshoot_cancels_one_speculative_step(self, engine):
        """An EOS discovered one step late cancels exactly the
        sequence's speculative row: streams still match the sync loop
        and the host accounting (blocks, sequence table) is restored."""
        probe = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                      mode="lookahead")
        _clean(engine)
        # a token emitted mid-stream -> EOS discovered while its
        # speculative next step is already dispatched
        eos = probe[10][2]
        ref = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                    eos_token_id=eos, mode="sync")
        _clean(engine)
        out = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                    eos_token_id=eos, mode="lookahead")
        _clean(engine)
        assert out == ref
        assert len(out[10]) == 3 and out[10][-1] == eos
        rep = engine.get_serving_report()
        assert rep["cancelled_speculative_steps"] >= 1


class TestServingMetrics:

    def test_report_schema_and_counters(self, engine):
        out = engine.generate_batch(dict(PROMPTS), max_new_tokens=6,
                                    mode="lookahead")
        rep = engine.get_serving_report()
        for key in ("mode", "steps", "decode_steps", "tokens_emitted",
                    "recompiles", "blocking_syncs", "steady_steps",
                    "steady_blocking_syncs", "steady_decode_tps",
                    "cancelled_speculative_steps", "speculation",
                    "dispatch_ms", "sync_wait_ms", "step_ms",
                    "ttft_ms", "itl_ms", "queue_depth", "kv_util"):
            assert key in rep, key
        # speculation block always present, all-zero without spec
        assert rep["speculation"]["drafted_tokens"] == 0
        assert rep["speculation"]["acceptance_rate"] == 0.0
        assert rep["mode"] == "lookahead"
        assert rep["tokens_emitted"] == sum(len(v) for v in out.values())
        assert rep["ttft_ms"]["count"] == len(PROMPTS)
        assert rep["itl_ms"]["count"] == rep["tokens_emitted"] - len(
            PROMPTS)
        assert 0 < rep["kv_util"]["max"] <= 1.0

    def test_sync_loop_blocks_every_step(self, engine):
        engine.generate_batch(dict(PROMPTS), max_new_tokens=4,
                              mode="sync")
        rep = engine.get_serving_report()
        assert rep["blocking_syncs"] == rep["steps"]

    def test_lookahead_zero_blocking_syncs_in_steady_state(self, engine):
        """The acceptance counter: 0 blocking host syncs per decode
        step in steady state (vs 1/step for the sync loop)."""
        engine.generate_batch(dict(PROMPTS), max_new_tokens=8,
                              mode="lookahead")
        rep = engine.get_serving_report()
        assert rep["steady_steps"] > 0
        assert rep["steady_blocking_syncs"] == 0

    @pytest.mark.perf
    def test_zero_recompiles_in_steady_decode(self, engine):
        """After warmup, 16+ decode steps reuse ONE executable: the
        recompile counter stays at zero for the measured run."""
        engine.generate_batch({77: [5, 6, 7]}, max_new_tokens=3,
                              mode="lookahead")       # warmup/compile
        engine.generate_batch(dict(PROMPTS), max_new_tokens=18,
                              mode="lookahead")
        rep = engine.get_serving_report()
        assert rep["recompiles"] == 0
        assert rep["steady_steps"] >= 16
        assert rep["steady_blocking_syncs"] == 0
        assert rep["cancelled_speculative_steps"] == 0


class TestInputValidation:

    def test_empty_prompt_rejected(self, engine):
        with pytest.raises(ValueError, match="empty prompt"):
            engine.generate_batch({1: []}, max_new_tokens=4)
        _clean(engine)

    def test_bad_mode_preserves_previous_report(self, engine):
        engine.generate_batch({5: [1, 2]}, max_new_tokens=2)
        rep = engine.get_serving_report()
        with pytest.raises(ValueError, match="mode must be"):
            engine.generate_batch({6: [1, 2]}, max_new_tokens=2,
                                  mode="async")
        rep2 = engine.get_serving_report()
        # process_memory is LIVE gauges (RSS moves between calls);
        # everything the failed run could have clobbered must match
        rep.pop("process_memory")
        rep2.pop("process_memory")
        assert rep2 == rep
        _clean(engine)

    def test_wide_uids_key_distinct_streams(self, engine):
        """uids equal mod 2^32 must not fold to the same PRNG key."""
        import dataclasses
        rb = dataclasses.make_dataclass("RB", ["seq_lens"])(
            seq_lens=np.zeros(4, np.int32))
        from deepspeed_tpu.inference.sampling import SamplingParams
        sp = SamplingParams(temperature=1.0)
        a = engine._samp_arrays([5], rb, sp)["uid"][0]
        b = engine._samp_arrays([(1 << 32) + 5], rb, sp)["uid"][0]
        assert a != b


class TestSchedulerAging:

    def test_fcfs_aging_prevents_starvation(self, engine):
        """A block-starved prompt may not be queue-jumped by younger
        arrivals: it ages, holds the head of the line, and is admitted
        first once blocks free up (regression: the old skip-and-
        continue policy deferred it indefinitely)."""
        eng = engine
        # occupy most of the pool: 24 tokens -> 3 of 16 blocks... use a
        # dedicated engine-sized occupancy instead: 13 blocks
        eng.put([9], [np.arange(32)])          # 32 tokens -> 4 blocks
        eng.put([9], [np.arange(31)])          # 63 total  -> 8 blocks
        assert eng.free_blocks == 8
        eng.put([8], [np.arange(32)])          # 8 blocks free -> 4
        assert eng.free_blocks == 4
        small = np.arange(6)                   # 1 block
        big = np.arange(26)                    # 4 blocks (> 3 free soon)
        pending = {1: small, 2: big}
        uids, _ = eng.schedule(dict(pending), {})
        assert uids == [1]                     # small admitted: 3 left
        eng.put([1], [small])                  # 1 now holds a block
        del pending[1]
        assert eng.free_blocks == 3
        # big (4 blocks) starved; a younger small arrival must NOT jump
        pending[3] = np.arange(4)
        uids, _ = eng.schedule(dict(pending), {})
        assert uids == []
        assert eng._defer_age[2] >= 1
        # blocks free up -> the aged prompt is admitted FIRST
        eng.flush(8)
        uids, _ = eng.schedule(dict(pending), {})
        assert uids[0] == 2
        assert 2 not in eng._defer_age
        for uid in (9, 1):
            eng.flush(uid)
        _clean(eng)
