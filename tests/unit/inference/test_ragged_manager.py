"""Paged-KV host bookkeeping: allocator, sequence descriptors, state
manager (reference pattern: tests/unit/inference/v2/ragged/
test_blocked_allocator.py + test_manager_get/flush — allocation math,
exhaustion, uid lifecycle, block reuse after release)."""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.ragged_manager import (
    BlockedAllocator, BlockedKVCacheManager, DSStateManager,
    SchedulingError, SchedulingResult, SequenceDescriptor)


def test_allocator_hands_out_distinct_blocks():
    a = BlockedAllocator(16)
    got = a.allocate(10)
    assert len(set(got)) == 10
    assert a.free_blocks == 6
    assert all(0 <= b < 16 for b in got)


def test_allocator_exhaustion_is_typed_error():
    a = BlockedAllocator(4)
    a.allocate(3)
    with pytest.raises(SchedulingError) as ei:
        a.allocate(2)
    assert ei.value.result == SchedulingResult.OutOfKVBlocks
    # the failed allocate must not leak blocks
    assert a.free_blocks == 1


def test_allocator_reuses_freed_blocks():
    a = BlockedAllocator(8)
    first = a.allocate(8)
    assert a.free_blocks == 0
    a.free(first[:5])
    again = a.allocate(5)
    assert sorted(again) == sorted(first[:5])
    assert a.free_blocks == 0


@pytest.mark.parametrize("seen,inflight,new,block,expected", [
    (0, 0, 1, 128, 1),      # first token needs the first block
    (0, 0, 128, 128, 1),    # exactly one block
    (0, 0, 129, 128, 2),    # one past the boundary
    (127, 0, 1, 128, 0),    # fits in the already-allocated block
    (100, 28, 1, 128, 1),   # in-flight tokens count toward the total
    (128, 0, 0, 128, 0),    # zero new tokens never allocates
])
def test_kv_blocks_needed_ceiling_math(seen, inflight, new, block, expected):
    seq = SequenceDescriptor(uid=0, seen_tokens=seen,
                             in_flight_tokens=inflight)
    # blocks already allocated cover the seen+inflight prefix
    seq.blocks = list(range(-(-(seen + inflight) // block)))
    assert seq.kv_blocks_needed(new, block) == expected


def test_descriptor_forward_lifecycle():
    seq = SequenceDescriptor(uid=1)
    seq.pre_forward(100)
    assert seq.in_flight_tokens == 100 and seq.seen_tokens == 0
    seq.post_forward()
    assert seq.seen_tokens == 100 and seq.in_flight_tokens == 0
    seq.pre_forward(1)   # decode step
    seq.post_forward()
    assert seq.seen_tokens == 101


def test_kv_manager_allocates_lazily_and_releases_all():
    m = BlockedKVCacheManager(n_blocks=8, block_size=4)
    seq = SequenceDescriptor(uid=0)
    m.maybe_allocate(seq, 4)     # exactly one block
    assert seq.cur_allocated_blocks == 1 and m.free_blocks == 7
    seq.pre_forward(4); seq.post_forward()
    m.maybe_allocate(seq, 1)     # crosses into block 2
    assert seq.cur_allocated_blocks == 2
    m.maybe_allocate(seq, 0)     # no growth for zero tokens
    assert seq.cur_allocated_blocks == 2
    m.release(seq)
    assert m.free_blocks == 8 and seq.blocks == []


def test_state_manager_uid_lifecycle_and_capacity():
    sm = DSStateManager(max_tracked_sequences=3, n_blocks=16, block_size=4)
    s0 = sm.get_or_create_sequence(10)
    assert sm.get_or_create_sequence(10) is s0   # idempotent by uid
    sm.get_or_create_sequence(11)
    sm.get_or_create_sequence(12)
    with pytest.raises(SchedulingError) as ei:
        sm.get_or_create_sequence(13)
    assert ei.value.result == SchedulingResult.EngineFull
    sm.flush_sequence(11)
    assert sm.n_tracked_sequences == 2
    sm.get_or_create_sequence(13)    # slot freed
    sm.flush_sequence(99)            # unknown uid is a no-op
    assert sm.get_sequence(99) is None


def test_state_manager_churn_returns_every_block():
    """Many sequences growing and dying must leave the pool exactly
    full again — the leak check that matters for a long-lived server."""
    rng = np.random.default_rng(0)
    sm = DSStateManager(max_tracked_sequences=64, n_blocks=64, block_size=4)
    live = []
    for step in range(200):
        if live and rng.random() < 0.4:
            uid = live.pop(rng.integers(len(live)))
            sm.flush_sequence(uid)
        else:
            uid = int(step)
            seq = sm.get_or_create_sequence(uid)
            n = int(rng.integers(1, 9))
            try:
                sm.kv.maybe_allocate(seq, n)
            except SchedulingError:
                sm.flush_sequence(uid)
                continue
            seq.pre_forward(n); seq.post_forward()
            live.append(uid)
    for uid in live:
        sm.flush_sequence(uid)
    assert sm.free_blocks == 64
    assert sm.n_tracked_sequences == 0


def test_block_table_is_fixed_shape_and_padded():
    sm = DSStateManager(n_blocks=16, block_size=4)
    seq = sm.get_or_create_sequence(0)
    sm.kv.maybe_allocate(seq, 9)   # 3 blocks
    t = sm.block_table(seq, max_blocks=8)
    assert t.shape == (8,) and t.dtype == np.int32
    np.testing.assert_array_equal(t[:3], seq.blocks)
    np.testing.assert_array_equal(t[3:], 0)
