"""Multi-family ragged-engine parity: every registered family serves
through InferenceEngineV2 and matches a dense no-cache greedy decode.

Reference shape: deepspeed/inference/v2/model_implementations/* — the
FastGen engine runs llama/mistral/mixtral/opt/qwen/falcon/phi; here the
spec-driven ragged forward covers the shipped zoo families + Mixtral
MoE via grouped-GEMM routing.
"""

import dataclasses

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig


def _v2(params, cfg, **over):
    kw = dict(token_budget=32, max_ragged_sequence_count=4, n_kv_blocks=32,
              kv_block_size=8, max_blocks_per_seq=8, kv_dtype="float32")
    kw.update(over)
    return InferenceEngineV2(params, cfg, RaggedInferenceEngineConfig(**kw))


def _dense_greedy(model, params, prompt, n_new):
    """Teacher-forced greedy decode recomputing the full sequence each
    step with the plain flax module (no KV cache) — the ground truth the
    paged incremental path must reproduce."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = model.apply(params, np.asarray([toks], np.int32))
        toks.append(int(np.argmax(np.asarray(logits)[0, -1])))
    return toks[len(prompt):]


def _check_family(model, params, cfg, prompts=None, n_new=5):
    prompts = prompts or {1: [3, 1, 4, 1, 5], 2: [2, 7, 1]}
    engine = _v2(params, cfg)
    out = engine.generate_batch(prompts, max_new_tokens=n_new)
    for uid, prompt in prompts.items():
        ref = _dense_greedy(model, params, prompt, n_new)
        assert out[uid] == ref, (uid, out[uid], ref)


@pytest.fixture(autouse=True)
def _data_mesh():
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    yield


def _init(model, vocab=256):
    return model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_gptneox_family():
    from deepspeed_tpu.models.gptneox import (GPTNeoXConfig,
                                              GPTNeoXForCausalLM)
    cfg = GPTNeoXConfig.tiny()   # parallel residual + partial rotary
    model = GPTNeoXForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (ISSUE 7): a dozen cheaper family tests stay
def test_gptneox_sequential_residual():
    from deepspeed_tpu.models.gptneox import (GPTNeoXConfig,
                                              GPTNeoXForCausalLM)
    cfg = dataclasses.replace(GPTNeoXConfig.tiny(),
                              use_parallel_residual=False)
    model = GPTNeoXForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_opt_family():
    from deepspeed_tpu.models.opt import OPTConfig, OPTForCausalLM
    cfg = OPTConfig.tiny()       # learned positions (+2), relu FFN
    model = OPTForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


def test_gpt2_family():
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    cfg = GPT2Config.tiny()      # fused c_attn thirds, wpe, tied head
    model = GPT2LMHeadModel(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_bloom_family():
    from deepspeed_tpu.models.bloom import BloomConfig, BloomForCausalLM
    cfg = BloomConfig.tiny()     # ALiBi + embedding LayerNorm
    model = BloomForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


def test_mistral_sliding_window():
    from deepspeed_tpu.models.mistral import (MistralConfig,
                                              MistralForCausalLM)
    cfg = MistralConfig.tiny()   # sliding_window=16
    model = MistralForCausalLM(cfg)
    # long enough that the window actually clips context during decode
    prompts = {1: list(np.random.default_rng(0).integers(0, 256, 24))}
    engine = _v2(model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 8), np.int32)), cfg,
                 token_budget=64)
    out = engine.generate_batch(prompts, max_new_tokens=4)
    # dense reference: the flax module masks the window itself when the
    # sequence exceeds it
    ref = _dense_greedy(model, model.init(jax.random.PRNGKey(0),
                                          np.zeros((1, 8), np.int32)),
                        prompts[1], 4)
    assert out[1] == ref


@pytest.mark.slow  # tier-1 diet (ISSUE 16): gpt2/mistral/moe-routing smokes stay
def test_falcon_family():
    from deepspeed_tpu.models.falcon import (FalconConfig,
                                             FalconForCausalLM)
    cfg = FalconConfig.tiny()    # MQA + shared-LN parallel residual
    model = FalconForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (ISSUE 16): gpt2/mistral/moe-routing smokes stay
def test_phi_family():
    from deepspeed_tpu.models.phi import PhiConfig, PhiForCausalLM
    cfg = PhiConfig.tiny()       # partial rotary, parallel, biased head
    model = PhiForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (PR 17): gpt2/mistral/moe-routing smokes stay; rotary rides the llama/mistral paths
def test_gptj_family():
    from deepspeed_tpu.models.gptj import GPTJConfig, GPTJForCausalLM
    cfg = GPTJConfig.tiny()      # interleaved rotary, parallel residual
    model = GPTJForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (ISSUE 16): gpt2/mistral/moe-routing smokes stay
def test_qwen2_family():
    from deepspeed_tpu.models.qwen2 import Qwen2Config, Qwen2ForCausalLM
    cfg = Qwen2Config.tiny()     # llama arch + biased q/k/v
    model = Qwen2ForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


@pytest.mark.slow  # tier-1 diet (ISSUE 16): gpt2/mistral/moe-routing smokes stay
def test_mixtral_moe_family():
    from deepspeed_tpu.models.mixtral import (MixtralConfig,
                                              MixtralForCausalLM)
    cfg = MixtralConfig.tiny()   # 4 experts, top-2 routing
    model = MixtralForCausalLM(cfg)
    _check_family(model, _init(model), cfg)


def test_mixtral_moe_routing_is_sparse():
    """The ragged MoE path must agree with the dense one-hot combine —
    same routing, grouped GEMM instead of all-experts compute."""
    import jax.numpy as jnp
    from deepspeed_tpu.inference.v2.model import moe_mlp_ragged
    from deepspeed_tpu.models.mixtral import moe_route

    rng = np.random.default_rng(0)
    B, C, I, E, k = 12, 16, 24, 4, 2
    x = jnp.asarray(rng.normal(size=(B, C)), jnp.float32)
    router = jnp.asarray(rng.normal(size=(C, E)), jnp.float32)
    w1 = jnp.asarray(rng.normal(size=(E, C, I)), jnp.float32)
    w3 = jnp.asarray(rng.normal(size=(E, C, I)), jnp.float32)
    w2 = jnp.asarray(rng.normal(size=(E, I, C)), jnp.float32)

    out = moe_mlp_ragged(x, router, w1, w3, w2, k)

    w, idx = moe_route(x @ router, k)
    g = jnp.einsum("tc,eci->eti", x, w1)
    u = jnp.einsum("tc,eci->eti", x, w3)
    h = jax.nn.silu(g) * u
    o = jnp.einsum("eti,eic->etc", h, w2)
    onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)
    combine = jnp.einsum("tk,tke->te", w, onehot)
    expect = jnp.einsum("te,etc->tc", combine, o)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expect),
                               rtol=1e-4, atol=1e-4)
