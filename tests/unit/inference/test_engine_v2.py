"""FastGen-parity ragged engine tests (reference shape:
tests/unit/inference/v2/ — ragged batching, paged KV, scheduling)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (DSStateManager, InferenceEngineV2,
                                        RaggedBatchWrapper,
                                        SchedulingError, SchedulingResult)
from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def tiny_llama():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.int32))
    return cfg, model, params


def _engine(cfg, params, **over):
    kw = dict(token_budget=32, max_ragged_sequence_count=4, n_kv_blocks=16,
              kv_block_size=8, max_blocks_per_seq=8, kv_dtype="float32")
    kw.update(over)
    return InferenceEngineV2(params, cfg, RaggedInferenceEngineConfig(**kw))


class TestStateManager:

    def test_block_allocation_and_release(self):
        m = DSStateManager(n_blocks=8, block_size=4)
        s = m.get_or_create_sequence(1)
        m.kv.maybe_allocate(s, 10)   # 10 tokens -> 3 blocks of 4
        assert s.cur_allocated_blocks == 3
        assert m.free_blocks == 5
        s.pre_forward(10)
        s.post_forward()
        m.kv.maybe_allocate(s, 2)    # 12 tokens -> fits 3 blocks
        assert s.cur_allocated_blocks == 3
        m.kv.maybe_allocate(s, 3)    # 15 -> 4 blocks
        assert s.cur_allocated_blocks == 4
        m.flush_sequence(1)
        assert m.free_blocks == 8

    def test_allocator_exhaustion(self):
        m = DSStateManager(n_blocks=2, block_size=4)
        s = m.get_or_create_sequence(1)
        with pytest.raises(SchedulingError):
            m.kv.maybe_allocate(s, 100)


class TestRaggedWrapper:

    def test_packing(self):
        m = DSStateManager(n_blocks=16, block_size=8)
        w = RaggedBatchWrapper(token_budget=16, max_seqs=4,
                               max_blocks_per_seq=4)
        a = m.get_or_create_sequence(1)
        a.seen_tokens = 5            # resuming sequence
        m.kv.maybe_allocate(a, 3)
        a.pre_forward(3)
        b = m.get_or_create_sequence(2)
        m.kv.maybe_allocate(b, 4)
        b.pre_forward(4)
        w.insert_sequence(a, [7, 8, 9])
        w.insert_sequence(b, [1, 2, 3, 4])
        rb = w.finalize(m)
        np.testing.assert_array_equal(rb.token_ids[:7],
                                      [7, 8, 9, 1, 2, 3, 4])
        np.testing.assert_array_equal(rb.token_seq[:7],
                                      [0, 0, 0, 1, 1, 1, 1])
        np.testing.assert_array_equal(rb.token_pos[:7],
                                      [5, 6, 7, 0, 1, 2, 3])
        assert rb.token_seq[7] == 4  # padding slot
        np.testing.assert_array_equal(rb.seq_lens[:2], [8, 4])
        np.testing.assert_array_equal(rb.logits_idx[:2], [2, 6])

    def test_budget_enforced(self):
        m = DSStateManager()
        w = RaggedBatchWrapper(token_budget=4, max_seqs=4)
        s = m.get_or_create_sequence(1)
        with pytest.raises(SchedulingError):
            w.insert_sequence(s, [1, 2, 3, 4, 5])


class TestEngineV2:

    def test_put_prefill_then_decode_matches_v1(self, tiny_llama):
        """Ragged paged-KV decode == the v1 KV-cache engine, token for
        token, across sequences of different lengths."""
        import deepspeed_tpu
        from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

        cfg, model, params = tiny_llama
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        v1 = deepspeed_tpu.init_inference(model, tp_size=1, dtype="float32")
        v1.set_params(params)

        prompts = {10: [3, 1, 4, 1, 5], 11: [2, 7, 1], 12: [9, 9]}
        v2 = _engine(cfg, params)
        out = v2.generate_batch(prompts, max_new_tokens=6)

        for uid, prompt in prompts.items():
            ref = v1.generate(np.asarray([prompt], np.int32),
                              max_new_tokens=6)
            ref_new = list(np.asarray(ref)[0, len(prompt):])
            assert out[uid] == ref_new, (uid, out[uid], ref_new)

    def test_splitfuse_long_prompt_chunking(self, tiny_llama):
        """A prompt longer than the token budget is split across steps
        and still matches the one-shot result."""
        import deepspeed_tpu
        from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

        cfg, model, params = tiny_llama
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        v1 = deepspeed_tpu.init_inference(model, tp_size=1, dtype="float32")
        v1.set_params(params)

        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 256, size=(20,)).tolist()
        v2 = _engine(cfg, params)
        v2._config.token_budget = 8  # forces 3 prefill chunks
        out = v2.generate_batch({1: prompt}, max_new_tokens=4)
        ref = v1.generate(np.asarray([prompt], np.int32), max_new_tokens=4)
        assert out[1] == list(np.asarray(ref)[0, len(prompt):])

    def test_can_schedule_and_free_blocks(self, tiny_llama):
        cfg, _, params = tiny_llama
        v2 = _engine(cfg, params)
        assert v2.can_schedule([1], [16]) == SchedulingResult.Success
        assert v2.can_schedule([1], [100]) == SchedulingResult.BatchFull
        assert v2.can_schedule([1, 2, 3, 4, 5],
                               [1] * 5) == SchedulingResult.BatchFull
        free0 = v2.free_blocks
        v2.put([1], [np.arange(10)])
        assert v2.free_blocks < free0
        v2.flush(1)
        assert v2.free_blocks == free0

    def test_can_schedule_rejects_overlong_sequence(self, tiny_llama):
        """A sequence that would overrun max_blocks_per_seq * block_size
        is rejected up front (not mid-put), even when the KV pool has
        free blocks — and a resuming sequence's seen tokens count."""
        cfg, _, params = tiny_llama
        v2 = _engine(cfg, params, token_budget=128, n_kv_blocks=64,
                     max_blocks_per_seq=2)   # per-seq cap: 2*8 = 16 tokens
        assert v2.can_schedule([1], [16]) == SchedulingResult.Success
        assert (v2.can_schedule([1], [17])
                == SchedulingResult.SequenceTooLong)
        v2.put([1], [np.arange(12)])
        assert v2.can_schedule([1], [4]) == SchedulingResult.Success
        assert (v2.can_schedule([1], [5])
                == SchedulingResult.SequenceTooLong)

    def test_put_failure_rolls_back_host_accounting(self, tiny_llama):
        """A put() that fails mid-batch (overlong seq with do_checks off)
        must leave no trace: in-flight counts, block allocation, and the
        sequence table are restored, and the engine keeps serving."""
        cfg, _, params = tiny_llama
        v2 = _engine(cfg, params, token_budget=128, n_kv_blocks=64,
                     max_blocks_per_seq=2)   # per-seq cap: 16 tokens
        free0 = v2.free_blocks
        v2.put([7], [np.arange(10)])         # 10 seen tokens
        free_mid = v2.free_blocks
        seq = v2._state_manager.get_sequence(7)
        # batch of (existing seq overrunning its block table, fresh seq):
        # insert_sequence/finalize raises after host mutation started
        with pytest.raises(SchedulingError):
            v2.put([7, 8], [np.arange(10), np.arange(4)], do_checks=False)
        assert seq.in_flight_tokens == 0
        assert seq.seen_tokens == 10
        assert v2.free_blocks == free_mid
        assert v2._state_manager.get_sequence(8) is None  # rolled back
        # engine still serves both sequences within bounds
        v2.put([7, 8], [np.arange(4), np.arange(4)])
        assert v2._state_manager.get_sequence(7).seen_tokens == 14
        v2.flush(7)
        v2.flush(8)
        assert v2.free_blocks == free0


class TestEngineV2TP:

    def test_tp_sharded_matches_tp1(self, tiny_llama, eight_devices):
        """TP-sharded ragged engine produces the same tokens as tp=1
        (reference: FastGen runs TP4; here the sharding is GSPMD over
        the tensor axis incl. the KV pools on the kv-head dim)."""
        from deepspeed_tpu.parallel.mesh import (MeshConfig, TENSOR_AXIS,
                                                 mesh_manager)
        cfg, model, params = tiny_llama  # 2 kv heads
        prompts = {1: [3, 1, 4, 1, 5], 2: [2, 7]}

        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        ref = _engine(cfg, params).generate_batch(prompts,
                                                  max_new_tokens=5)

        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1, tensor=2))
        v2 = _engine(cfg, params, tp_size=2)
        # normalized tree actually sharded on the tensor axis
        qk = v2.tree["layers"][0]["wq"]
        assert TENSOR_AXIS in tuple(qk.sharding.spec)
        # KV pools sharded on the kv-head dim
        kp = v2.pools[0][0]
        assert TENSOR_AXIS in tuple(kp.sharding.spec)

        out = v2.generate_batch(prompts, max_new_tokens=5)
        assert out == ref
