"""Seeded fault drills for the tiered prefix cache — the ISSUE's
robustness contract:

* a ``store.write`` kill mid-demote leaves the trie entry INTACT in
  its old tier (no torn state, the block is simply still hot);
* a persistently unreadable spill tier DEGRADES TO RECOMPUTE: the
  serving stream is bitwise identical to the tiers-off run, the
  digest is quarantined, a ``cache_degraded`` alert is counted —
  never a wrong token, never a crashed step;
* a crash between the journal append and the payload write is
  recovered clean by the next open (entry dropped, counted);
* the seeded chaos matrix (slow tier): every fault spec in the matrix
  preserves bitwise streams end-to-end.
"""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (RequestState, ServingFrontend)
from deepspeed_tpu.inference.v2.serving.prefix import chain_digests
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime import store as store_mod
from deepspeed_tpu.runtime.store import DiskBlockStore

from .test_tiered_cache import (BS, _chain, _engine, _requests,
                                _serve_serial, _tiered, _tiers_cfg,
                                params_cfg)  # noqa: F401

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


class TestDemoteFaultDrills:

    @pytest.mark.parametrize("spec", ["store.write:kill@0xinf",
                                      "cache.demote:kill@0xinf",
                                      "store.write:ioerror@0xinf"])
    def test_failed_demotion_leaves_entry_intact(self, spec):
        """The drill contract: ALL fallible demote work (gather,
        encode, store write) happens before any trie/pool mutation —
        a kill anywhere in that window leaves the entry hot."""
        pc, a, kv = _tiered(max_blocks=2)
        pc.dram._io.retries = 0
        pc.dram._io.backoff_seconds = 0.0
        p1, b1 = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        with fault_injector.inject(spec):
            p3, _ = _chain(pc, a, kv, 200)   # overflow -> demote dies
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "hbm"     # intact, old tier
        assert pc.demote_failures >= 1
        assert pc.demoted_blocks == 0 and len(pc.dram) == 0
        assert pc.cached_blocks == 3             # over bound, but HOT
        assert np.array_equal(kv.data[b1[0]],
                              np.full((2, 2, BS, 2), 0, np.float32))
        # the fault cleared: the next insert demotes normally
        _chain(pc, a, kv, 300)
        assert pc.demoted_blocks > 0
        assert pc.match(p1)[1] == BS             # p1 still servable

    def test_single_shot_kill_skips_the_victim_not_the_pass(self):
        """A one-shot kill on the FIRST victim: that entry stays hot
        (skipped for the pass) while the next leaf demotes normally —
        the bound is still honored without torn state."""
        pc, a, kv = _tiered(max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)
        with fault_injector.inject("store.write:kill"):
            _chain(pc, a, kv, 200)
        d1, d2 = (chain_digests(p, BS)[0] for p in (p1, p2))
        assert pc.resident_tier(d1) == "hbm"     # the failed victim
        assert pc.resident_tier(d2) == "dram"    # the next leaf went
        assert pc.demote_failures == 1 and pc.demoted_blocks == 1
        assert pc.cached_blocks == 2             # bound still honored

    def test_failed_demotion_under_reclaim_falls_back_to_eviction(
            self):
        """need_free + dead store: the scheduler's pressure valve must
        still free pool blocks — demotion failure falls back to TRUE
        eviction (the entry dropped whole and counted as a reclaim
        eviction, its payload never half-landed anywhere), never to a
        reclaim that frees 0 forever while serving degrades to
        overload errors."""
        pc, a, kv = _tiered()
        _chain(pc, a, kv, 0)
        pc.dram._io.retries = 0
        with fault_injector.inject("store.write:kill@0xinf"):
            assert pc.reclaim(1) == 1
        assert pc.cached_blocks == 0 and pc.spilled_blocks == 0
        assert pc.demote_failures == 1 and len(pc.dram) == 0
        st = pc.stats()
        assert st["evicted_reclaim"] == 1 and st["demoted_blocks"] == 0
        assert a.free_blocks == 16          # actually back in the pool


class TestPromoteFaultDrills:

    def _spilled(self):
        pc, a, kv = _tiered(max_blocks=2)
        alerts = []
        pc.alert_sink = alerts.append
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)               # p1 -> dram
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "dram"
        return pc, a, kv, p1, d1, alerts

    @pytest.mark.parametrize("spec", ["store.read:ioerror@0xinf",
                                      "cache.promote:kill"])
    def test_unreadable_tier_degrades_and_quarantines(self, spec):
        pc, a, kv, p1, d1, alerts = self._spilled()
        pc.dram._io.retries = 0
        pc.dram._io.backoff_seconds = 0.0
        with fault_injector.inject(spec):
            blocks, n = pc.match(p1)
        assert n == 0 and blocks == []       # recompute, not a crash
        assert pc.degraded == 1
        assert d1 in pc._quarantine
        assert pc.resident_tier(d1) is None  # spilled copy purged
        (alert,) = [x for x in alerts if x.kind == "cache_degraded"]
        assert "degraded to recompute" in alert.message
        # a fresh prefill of the chain lifts the quarantine
        _chain(pc, a, kv, 0)
        assert d1 not in pc._quarantine
        assert pc.match(p1)[1] == BS

    def test_corrupt_disk_payload_degrades_not_serves(self, tmp_path):
        """Same-size bit rot in a spilled payload file: the blake2b
        check turns it into degrade-to-recompute, never adopted KV."""
        disk = DiskBlockStore(str(tmp_path))
        pc, a, kv = _tiered(max_blocks=1, dram_bytes=1, disk=disk)
        alerts = []
        pc.alert_sink = alerts.append
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "disk"
        path = disk._block_path(d1)
        with open(path, "rb") as f:
            raw = bytearray(f.read())
        raw[0] ^= 0xFF
        with open(path, "wb") as f:  # atomic-ok: test plants same-size corruption
            f.write(bytes(raw))
        assert pc.match(p1)[1] == 0
        assert pc.degraded == 1
        assert [x.kind for x in alerts] == ["cache_degraded"]
        pc.close()

    def test_degraded_parent_purges_spilled_subtree(self):
        """Children of an unreadable parent are unreachable by chain
        construction — they are retired with it, not stranded."""
        pc, a, kv = _tiered()
        prompt, _ = _chain(pc, a, kv, 0, n_blocks=3)
        pc._evict(count=3)                   # whole chain spilled
        ds = chain_digests(prompt, BS)
        pc.dram.delete(ds[0])                # lose the ROOT's payload
        assert pc.match(prompt)[1] == 0      # KeyError -> degrade
        assert pc.degraded == 1
        assert pc.spilled_blocks == 0        # subtree purged with it
        assert len(pc.dram) == 0
        assert not pc._spill_children        # the index emptied too


class TestCrashRecoveryDrill:

    def test_crash_between_journal_append_and_payload_write(
            self, tmp_path, monkeypatch):
        """The write protocol's one open crash window, driven through
        the REAL put path: the journal record lands, the process dies
        before the payload — the next open drops the entry with a
        counted typed error and every other entry survives."""
        s = DiskBlockStore(str(tmp_path), fsync_every=1)
        s.put(b"\x01", b"survivor", {})

        def die(path, writer, **kw):
            raise SystemExit("crash after journal append")

        monkeypatch.setattr(store_mod, "atomic_write_bytes", die)
        with pytest.raises(SystemExit):
            s.put(b"\x02", b"never-lands", {})
        # "crash": the fd just goes away, no close() bookkeeping
        import os
        os.close(s._jfd)
        s._jfd = None
        monkeypatch.undo()

        r = DiskBlockStore(str(tmp_path))
        assert r.recovery.recovered_entries == 1
        assert r.recovery.dropped_entries == 1
        assert r.recovery.corrupt_records == 1
        assert b"\x02" not in r
        assert r.get(b"\x01")[0] == b"survivor"
        r.close()


class TestServingDegradeSmoke:

    def test_degrade_to_recompute_stream_is_bitwise(self, params_cfg):
        """The tier-1 degrade smoke: warm the tiers, then nuke the
        DRAM tier's reads — the promotion path degrades and the
        serving stream still matches the tiers-off reference bitwise,
        with the ``cache_degraded`` alert on the frontend."""
        reqs = _requests()
        ref_eng = _engine(params_cfg)
        refs = {}
        for uid, prompt in reqs.items():
            fe = ServingFrontend(ref_eng)
            r = fe.submit(prompt, uid=uid, max_new_tokens=6)
            fe.drain()
            refs[uid] = list(r.tokens)

        fe = ServingFrontend(_engine(params_cfg), _tiers_cfg())
        try:
            pc = fe.engine.prefix_cache
            pc.dram._io.retries = 0
            pc.dram._io.backoff_seconds = 0.0
            uids = list(reqs)
            got = _serve_serial(fe, {u: reqs[u] for u in uids[:2]})
            assert pc.demoted_blocks > 0     # the tiers are warm
            with fault_injector.inject("store.read:ioerror@0xinf"):
                got.update(_serve_serial(
                    fe, {u: reqs[u] for u in uids[2:]}))
            assert got == refs               # BITWISE under the fault
            st = pc.stats()
            assert st["degraded"] > 0
            # quarantine was LIFTED again: the recomputed prefill
            # re-inserted each degraded chain with fresh live data
            assert st["quarantined"] == 0
            assert any(a.kind == "cache_degraded" for a in fe.alerts)
        finally:
            fe.close()


@pytest.mark.chaos
@pytest.mark.slow
class TestChaosMatrix:

    SPECS = ["cache.demote:kill@1x2",
             "cache.promote:kill",
             "store.write:ioerror@0x3",
             "store.write:kill@2x2",
             "store.read:ioerror@0x2",
             "store.read:ioerror@0xinf",
             "cache.demote:kill@0xinf,store.read:ioerror@1x3"]

    def test_streams_bitwise_under_every_spec(self, params_cfg,
                                              tmp_path):
        """The acceptance chaos matrix: DRAM+disk tiers with each
        seeded fault spec armed for the WHOLE serve — drop-outs,
        kills, transient and persistent read/write faults — and every
        greedy stream stays bitwise identical to the tiers-off
        reference. Deterministic: ordinal-windowed specs replay the
        identical drill."""
        reqs = _requests()
        ref_eng = _engine(params_cfg)
        refs = {}
        for uid, prompt in reqs.items():
            fe = ServingFrontend(ref_eng)
            r = fe.submit(prompt, uid=uid, max_new_tokens=6)
            fe.drain()
            refs[uid] = list(r.tokens)

        for i, spec in enumerate(self.SPECS):
            cfg = _tiers_cfg(tmp_path / f"run{i}")
            cfg["prefix"]["tiers"].update(io_retries=1,
                                          io_backoff_seconds=0.0)
            fe = ServingFrontend(_engine(params_cfg), cfg)
            try:
                with fault_injector.inject(spec):
                    got = _serve_serial(fe, reqs)
                assert got == refs, f"stream diverged under {spec!r}"
                st = fe.engine.prefix_cache.stats()
                # consistency: every spilled digest is in exactly one
                # tier's store, quarantine bounded
                pc = fe.engine.prefix_cache
                for d, s in pc._spilled.items():
                    tier_store = pc.dram if s.tier == "dram" \
                        else pc.disk
                    assert d in tier_store, (spec, s.tier)
                assert st["quarantined"] <= 1024
            finally:
                fe.close()
                fault_injector.reset()
