"""Open-world churn soak for the serving front-end (slow tier, per
the tier-1 budget guard): hundreds of requests joining/leaving/
cancelling over one persistent frontend, asserting the process-
lifetime invariants — block conservation, bounded request retention,
flat pool high-water — that a quick smoke cannot exercise."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, ServingFrontend)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

pytestmark = [pytest.mark.slow, pytest.mark.soak]

SYS = list(range(1, 17))


@pytest.fixture(scope="module")
def engine():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(
            token_budget=32, max_ragged_sequence_count=4,
            n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
            kv_dtype="float32"))


def test_open_world_churn_soak(engine):
    rng = np.random.default_rng(0)
    fe = ServingFrontend(engine, {"max_retained_requests": 32})
    N = 200
    arrive = np.cumsum(rng.poisson(0.7, size=N))
    state = {"next": 0, "live": [], "cancelled": 0}

    def poll(f, step):
        while state["next"] < N and step >= arrive[state["next"]]:
            k = state["next"]
            r = f.submit(SYS + [100 + (k % 40), k % 7 + 1],
                         max_new_tokens=int(rng.integers(2, 6)))
            state["live"].append(r)
            state["next"] += 1
        # cancel ~10% of live requests mid-flight
        if step % 9 == 4:
            live = [r for r in state["live"] if not r.done]
            if live:
                f.cancel(live[0].uid)
                state["cancelled"] += 1
        return state["next"] < N

    fe.serve(poll=poll)
    rep = fe.get_serving_report()
    done = [r.state for r in state["live"]]
    assert all(s in (RequestState.FINISHED, RequestState.CANCELLED,
                     RequestState.SHED) for s in done)
    assert rep["requests"]["finished"] >= N - state["cancelled"] - 5
    # conservation: nothing in flight, nothing tracked, pool restored
    # minus exactly the prefix cache's pins
    cached = engine.prefix_cache.stats()["cached_blocks"]
    assert not engine._state_manager.tracked_sequences
    assert engine.free_blocks == engine._config.n_kv_blocks - cached
    assert engine._state_manager.kv.allocator.live_blocks == cached
    # bounded retention: the request table does not scale with N
    assert len(fe._requests) <= 32 + fe.active_requests + 1
    # zero recompiles across all the churn (one signature, pinned)
    assert rep["recompiles"] <= 1
    # prefix reuse engaged across the shared head
    assert rep["prefix"]["hits"] > N // 2
