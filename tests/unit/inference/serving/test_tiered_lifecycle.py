"""Tiered-cache lifecycle: ``engine.close()`` /``frontend.close()``
reach the spill tiers' held OS resources (the disk tier owns an open
index-journal fd), and the open/spill/close soak asserts no fd or RSS
growth over repeated cycles — the PR 6 NVMe-store rule applied to the
block store."""

import os

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import ServingFrontend
from deepspeed_tpu.resilience.errors import StoreCorruptionError
from deepspeed_tpu.runtime.store import DiskBlockStore, HostBlockStore

from .test_tiered_cache import (_chain, _engine, _requests,
                                _serve_serial, _tiered, _tiers_cfg,
                                params_cfg)  # noqa: F401


def _n_fds():
    return len(os.listdir("/proc/self/fd"))


def _rss_kb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    raise RuntimeError("no VmRSS")


class TestEngineClose:

    def test_frontend_close_releases_the_disk_journal_fd(
            self, params_cfg, tmp_path):
        n0 = _n_fds()
        fe = ServingFrontend(_engine(params_cfg), _tiers_cfg(tmp_path))
        pc = fe.engine.prefix_cache
        assert _n_fds() == n0 + 1            # the held journal fd
        reqs = _requests()
        _serve_serial(fe, dict(list(reqs.items())[:3]))
        assert len(pc.disk) > 0              # spills actually landed
        fe.close()
        assert _n_fds() == n0
        assert pc.disk.closed
        fe.close()                           # idempotent
        assert _n_fds() == n0
        with pytest.raises(StoreCorruptionError, match="closed"):
            pc.disk.put(b"\x01", b"x", {})

    def test_engine_close_without_tiers_is_a_noop(self, params_cfg):
        eng = _engine(params_cfg)
        eng.close()                          # no cache at all
        fe = ServingFrontend(_engine(params_cfg),
                             {"prefix": {"enabled": True}})
        fe.close()                           # flat cache: no stores
        fe.close()

    def test_serving_survives_spills_after_a_reopen(self, params_cfg,
                                                    tmp_path):
        """Crash-safe recovery at the SERVING level: a second frontend
        over the same disk root recovers the journal cleanly (entries
        whose digests it no longer tracks are simply cold data)."""
        fe = ServingFrontend(_engine(params_cfg), _tiers_cfg(tmp_path))
        _serve_serial(fe, _requests())
        n_disk = len(fe.engine.prefix_cache.disk)
        fe.close()
        fe2 = ServingFrontend(_engine(params_cfg),
                              _tiers_cfg(tmp_path))
        try:
            rec = fe2.engine.prefix_cache.disk.recovery
            assert rec.corrupt_records == 0
            assert rec.recovered_entries == n_disk
            _serve_serial(fe2, _requests())  # serves fine on top
        finally:
            fe2.close()


@pytest.mark.slow
@pytest.mark.soak
class TestOpenSpillCloseSoak:

    def test_no_fd_or_rss_growth_over_20_cycles(self, tmp_path):
        """20 open/spill/close cycles over the full tiered stack
        (fresh DiskBlockStore + TieredPrefixCache each cycle, real
        demote/promote/rebalance traffic): the fd table returns to
        baseline every cycle and RSS stays flat — the journal fd and
        the DRAM tier's payload dict are actually released."""
        def cycle(i):
            disk = DiskBlockStore(str(tmp_path / f"c{i % 2}"))
            pc, a, kv = _tiered(n_blocks=8, max_blocks=2,
                                dram_bytes=4 * 2 * 2 * 4 * 2 * 4,
                                disk=disk)
            prompts = [_chain(pc, a, kv, 100 * j + i)[0]
                       for j in range(12)]
            for p in prompts[:6]:
                pc.match(p)                  # promotions + rolls
            assert pc.demoted_blocks > 0
            pc.clear()
            pc.close()

        cycle(0)                             # warmup: lazy imports
        fd0, rss0 = _n_fds(), _rss_kb()
        for i in range(20):
            cycle(i)
            assert _n_fds() == fd0, f"fd leak at cycle {i}"
        # RSS tolerance: allocator noise, not per-cycle growth (each
        # cycle moves ~12 payloads; a leak would compound 20x)
        assert _rss_kb() - rss0 < 20 * 1024, "RSS grew over the soak"

    def test_host_store_soak_releases_bytes(self):
        s = HostBlockStore(0)
        for i in range(20):
            for j in range(64):
                s.put(bytes([i, j]), os.urandom(4096), {})
            s.close()
            assert s.used_bytes == 0 and len(s) == 0
