"""TieredPrefixCache — HBM trie with DRAM/disk spill tiers: overflow
DEMOTES cold blocks down-tier instead of evicting them, ``match``
promotes spilled blocks back on the adoption path (bitwise-identical
payloads under codec "none"), DRAM overflow rebalances to disk, and
the serving-level gate: greedy streams identical with tiers off /
DRAM / DRAM+disk. The eviction-cause counter split and the
prefix-thrash detector (satellites) live at the bottom."""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, ServingFrontend)
from deepspeed_tpu.inference.v2.ragged_manager import BlockedAllocator
from deepspeed_tpu.inference.v2.serving.prefix import (PrefixCache,
                                                       chain_digests)
from deepspeed_tpu.inference.v2.serving.tiered import TieredPrefixCache
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.runtime.store import DiskBlockStore, HostBlockStore

BS = 4


class FakeKV:
    """Engine stand-in for host-level tests: a dict of per-block
    payload arrays (what the jitted gather/scatter pair moves)."""

    def __init__(self):
        self.data = {}

    def read_kv_block(self, block):
        return self.data[block]

    def write_kv_block(self, block, arr):
        self.data[block] = np.asarray(arr)


def _tiered(n_blocks=16, max_blocks=0, dram_bytes=0, disk=None,
            **kw):
    a = BlockedAllocator(n_blocks)
    kv = FakeKV()
    pc = TieredPrefixCache(BS, a, max_blocks=max_blocks, kv_io=kv,
                           dram_store=HostBlockStore(dram_bytes),
                           disk_store=disk, **kw)
    return pc, a, kv


def _chain(pc, a, kv, seed, n_blocks=1):
    """Insert one chain of ``n_blocks`` full blocks with deterministic
    per-block payloads; the caller's refs are released so the cache is
    sole owner (the state-manager flush idiom)."""
    prompt = np.arange(seed, seed + n_blocks * BS + 1, dtype=np.int32)
    blocks = a.allocate(n_blocks)
    for i, b in enumerate(blocks):
        kv.write_kv_block(b, np.full((2, 2, BS, 2), seed + i,
                                     np.float32))
    pc.insert(prompt, blocks)
    a.free(blocks)
    return prompt, blocks


class TestSpillAndReadopt:

    def test_overflow_demotes_instead_of_evicting(self):
        pc, a, kv = _tiered(max_blocks=2)
        pc.journal = []
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)
        p3, _ = _chain(pc, a, kv, 200)      # bound 2 -> LRU demoted
        assert pc.cached_blocks == 2
        assert pc.spilled_blocks == 1 and pc.demoted_blocks == 1
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "dram"
        assert ("tier", d1, "dram") in pc.journal
        # the spilled block's pool slot was returned to the allocator
        assert a.free_blocks == 16 - 2
        st = pc.stats()
        assert st["spilled_blocks"] == 1 and st["dram_blocks"] == 1
        assert st["evicted_blocks"] == 0    # demotion is not eviction

    def test_match_promotes_spilled_block_back_bitwise(self):
        pc, a, kv = _tiered(max_blocks=2)
        pc.journal = []
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "dram"
        blocks, n = pc.match(p1)
        assert n == BS and len(blocks) == 1
        assert pc.promoted_blocks == 1
        assert pc.resident_tier(d1) == "hbm"
        # the promoted payload is the demoted one, bitwise
        assert np.array_equal(kv.data[blocks[0]],
                              np.full((2, 2, BS, 2), 0, np.float32))
        assert len(pc.dram) == 0            # one tier at a time
        assert ("tier", d1, "hbm") in pc.journal

    def test_promotion_displaces_a_colder_block_under_pressure(self):
        """No free pool block at promote time: the cache demotes a
        colder HBM entry to make room (LRU displacement), so the hot
        set rotates through HBM without the pool growing."""
        pc, a, kv = _tiered(n_blocks=3, max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)
        p3, _ = _chain(pc, a, kv, 200)
        # pool: 2 cached + 1 free; soak the free block up
        hold = a.allocate(1)
        assert a.free_blocks == 0
        blocks, n = pc.match(p1)            # promote must displace
        assert n == BS
        assert pc.demoted_blocks >= 2       # the displaced victim
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "hbm"
        a.free(hold)

    def test_promotion_never_displaces_a_block_already_matched(self):
        """The mid-walk hazard: the entry matched immediately before a
        spilled child is a refcount-1 leaf (the adopter's incref lands
        only AFTER match returns), so the promotion's make-room
        eviction could pick it — freeing a pool block that is already
        on the list match() will hand back, letting the promotion
        scatter (or another sequence) overwrite KV the adopter then
        attends over. The walk guard must force a capacity stop
        instead."""
        pc, a, kv = _tiered(n_blocks=2)
        prompt, _ = _chain(pc, a, kv, 0, n_blocks=2)
        pc._evict(count=1)                  # the leaf child -> dram
        d0, d1 = chain_digests(prompt, BS)
        assert pc.resident_tier(d0) == "hbm"
        assert pc.resident_tier(d1) == "dram"
        hold = a.allocate(1)                # soak the freed block
        assert a.free_blocks == 0
        blocks, n = pc.match(prompt)
        # no room to promote the child without evicting the matched
        # parent: capacity stop — the parent serves, INTACT
        assert n == BS and len(blocks) == 1
        assert pc.resident_tier(d0) == "hbm"
        assert blocks[0] == pc._entries[d0].block
        assert a.refcount(blocks[0]) == 1   # never freed mid-match
        assert np.array_equal(kv.data[blocks[0]],
                              np.full((2, 2, BS, 2), 0, np.float32))
        assert pc.resident_tier(d1) == "dram"   # survived the stop
        a.free(hold)
        blocks, n = pc.match(prompt)        # room again: full adopt
        assert n == 2 * BS
        assert np.array_equal(kv.data[blocks[1]],
                              np.full((2, 2, BS, 2), 1, np.float32))

    def test_interior_parent_promotes_before_its_child(self):
        """A 2-block chain demoted leaf-first then fully re-adopted:
        the walk promotes parent and child in chain order."""
        pc, a, kv = _tiered()
        prompt, _ = _chain(pc, a, kv, 0, n_blocks=2)
        pc._evict(count=2)                  # both blocks to DRAM
        assert pc.cached_blocks == 0 and pc.spilled_blocks == 2
        blocks, n = pc.match(prompt)
        assert n == 2 * BS and pc.promoted_blocks == 2
        assert np.array_equal(kv.data[blocks[1]],
                              np.full((2, 2, BS, 2), 1, np.float32))

    def test_insert_supersedes_spilled_copy(self):
        """A fresh prefill of a spilled chain: the live KV is
        canonical — the spilled payload is retired, not promoted."""
        pc, a, kv = _tiered(max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "dram"
        _chain(pc, a, kv, 0)                # same tokens, new prefill
        assert pc.resident_tier(d1) == "hbm"
        assert d1 not in pc.dram
        assert pc.promoted_blocks == 0

    def test_clear_drops_hbm_and_spilled_state(self):
        pc, a, kv = _tiered(max_blocks=2)
        for seed in (0, 100, 200):
            _chain(pc, a, kv, seed)
        assert pc.spilled_blocks == 1
        freed = pc.clear()
        assert freed == 2
        assert pc.cached_blocks == 0 and pc.spilled_blocks == 0
        assert len(pc.dram) == 0
        assert a.free_blocks == 16

    def test_close_is_idempotent(self, tmp_path):
        disk = DiskBlockStore(str(tmp_path))
        pc, a, kv = _tiered(disk=disk)
        pc.close()
        pc.close()
        assert disk.closed


class TestDiskRebalance:

    def test_dram_overflow_rolls_down_to_disk(self, tmp_path):
        disk = DiskBlockStore(str(tmp_path))
        pc, a, kv = _tiered(max_blocks=1, dram_bytes=1, disk=disk)
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)      # demotes p1, over budget
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "disk"
        assert d1 in disk and d1 not in pc.dram
        # promotion from the disk tier is still bitwise
        blocks, n = pc.match(p1)
        assert n == BS
        assert np.array_equal(kv.data[blocks[0]],
                              np.full((2, 2, BS, 2), 0, np.float32))
        assert d1 not in disk               # retired on promote
        pc.close()

    def test_no_disk_tier_true_evicts_on_dram_overflow(self):
        pc, a, kv = _tiered(max_blocks=1, dram_bytes=1)
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) is None
        assert pc.spill_evicted_blocks == 1
        assert pc.match(p1)[1] == 0         # miss: gone for real

    def test_disk_budget_true_evicts_coldest(self, tmp_path):
        # room for exactly ONE spilled payload (2*2*BS*2 float32)
        disk = DiskBlockStore(str(tmp_path),
                              max_bytes=2 * 2 * BS * 2 * 4)
        pc, a, kv = _tiered(max_blocks=1, dram_bytes=1, disk=disk)
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)
        p3, _ = _chain(pc, a, kv, 200)
        # p1 rolled to disk then fell off its budget; p2 is in disk now
        d1, d2 = (chain_digests(p, BS)[0] for p in (p1, p2))
        assert pc.resident_tier(d1) is None
        assert pc.resident_tier(d2) == "disk"
        pc.close()


@pytest.mark.slow
class TestCapacitySweep:

    def test_hit_rate_holds_at_10x_hbm_budget(self, tmp_path):
        """The ISSUE acceptance sweep: insert 10x more chains than the
        HBM budget holds; with the spill tiers armed EVERY chain still
        hits (promoted back on match) — the flat cache would miss on
        all but the last ``max_blocks``."""
        disk = DiskBlockStore(str(tmp_path))
        pc, a, kv = _tiered(n_blocks=8, max_blocks=4,
                            dram_bytes=12 * 2 * 2 * BS * 2 * 4,
                            disk=disk)
        prompts = [_chain(pc, a, kv, 1000 * i)[0] for i in range(40)]
        st = pc.stats()
        assert st["cached_blocks"] <= 4
        assert st["spilled_blocks"] == 36
        assert st["disk_blocks"] > 0        # the DRAM budget rolled
        for i, p in enumerate(prompts):
            blocks, n = pc.match(p)
            assert n == BS, f"chain {i} missed"
            assert np.array_equal(
                kv.data[blocks[0]],
                np.full((2, 2, BS, 2), 1000 * i, np.float32))
        st = pc.stats()
        assert st["hits"] == 40 and st["degraded"] == 0
        assert st["hit_rate"] == 1.0
        pc.close()


# -- serving-level gate ---------------------------------------------------

SYS = list(range(1, 18))                 # 2 full 8-token shared blocks
SYS2 = list(range(101, 118))
TAILS = {0: [31, 32, 33], 1: [41, 42], 2: [51], 3: [61, 62]}


@pytest.fixture(scope="module")
def params_cfg():
    import jax
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return params, cfg


def _engine(params_cfg, **kw):
    params, cfg = params_cfg
    eng_kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=32, kv_block_size=8,
                  max_blocks_per_seq=8, kv_dtype="float32")
    eng_kw.update(kw)
    return InferenceEngineV2(params, cfg,
                             RaggedInferenceEngineConfig(**eng_kw))


def _requests():
    """A schedule that forces tier crossings under max_blocks=2: the
    SYS chain spills when SYS2 inserts, then promotes back."""
    return {900: SYS + TAILS[0], 901: SYS2 + TAILS[1],
            902: SYS + TAILS[2], 903: SYS2 + TAILS[3],
            904: SYS + TAILS[0][:1]}


def _serve_serial(fe, requests, max_new_tokens=6):
    out = {}
    for uid, prompt in requests.items():
        r = fe.submit(prompt, uid=uid, max_new_tokens=max_new_tokens)
        fe.drain()
        assert r.state == RequestState.FINISHED
        out[uid] = list(r.tokens)
    return out


def _tiers_cfg(tmp_path=None):
    # DRAM-only: a budget that HOLDS the spills. DRAM+disk: a budget
    # so tight every spill immediately rolls down to the disk tier.
    tiers = {"enabled": True,
             "dram_max_mb": 64.0 if tmp_path is None else 0.001}
    if tmp_path is not None:
        tiers.update(disk_enabled=True, disk_path=str(tmp_path))
    return {"prefix": {"enabled": True, "max_blocks": 2,
                       "tiers": tiers}}


class TestServingBitwiseGate:

    def test_streams_identical_tiers_off_dram_dram_disk(
            self, params_cfg, tmp_path):
        """THE acceptance gate: the same greedy request schedule
        served with tiers off / DRAM only / DRAM+disk produces
        bitwise-identical streams, with real tier crossings (demotions
        AND promotions) happening in the tiered runs."""
        reqs = _requests()
        # reference: tiers off, no prefix cache at all — each request
        # on a fresh frontend (no cross-request reuse)
        ref_eng = _engine(params_cfg)
        refs = {}
        for uid, prompt in reqs.items():
            fe = ServingFrontend(ref_eng)
            r = fe.submit(prompt, uid=uid, max_new_tokens=6)
            fe.drain()
            refs[uid] = list(r.tokens)

        for label, cfg in (
                ("dram", _tiers_cfg()),
                ("dram+disk", _tiers_cfg(tmp_path))):
            fe = ServingFrontend(_engine(params_cfg), cfg)
            try:
                got = _serve_serial(fe, reqs)
                assert got == refs, f"stream diverged with {label}"
                st = fe.engine.prefix_cache.stats()
                assert st["demoted_blocks"] > 0, label
                assert st["promoted_blocks"] > 0, label
                assert st["degraded"] == 0
                assert st["hits"] >= 3
            finally:
                fe.close()

    def test_frontend_arms_tiers_and_registers_cache_namespace(
            self, params_cfg, tmp_path):
        from deepspeed_tpu.telemetry.hub import TelemetryHub
        fe = ServingFrontend(_engine(params_cfg), _tiers_cfg(tmp_path))
        try:
            pc = fe.engine.prefix_cache
            assert isinstance(pc, TieredPrefixCache)
            assert pc.disk is not None
            hub = fe.attach_telemetry(TelemetryHub())
            sample = hub.sample(step=0)
            assert "cache/spilled_blocks" in sample
        finally:
            fe.close()

    def test_tier_swap_releases_the_flat_caches_blocks(
            self, params_cfg):
        """A flat trie armed before the tiered swap holds one
        allocator incref per cached block; the swap must clear() it or
        those blocks never return to the free list for the life of the
        process (the warmup-then-serve leak)."""
        eng = _engine(params_cfg)
        fe1 = ServingFrontend(eng, {"prefix": {"enabled": True}})
        _serve_serial(fe1, dict(list(_requests().items())[:2]))
        flat = eng.prefix_cache
        assert not isinstance(flat, TieredPrefixCache)
        assert flat.cached_blocks > 0
        fe2 = ServingFrontend(eng, _tiers_cfg())
        try:
            assert isinstance(eng.prefix_cache, TieredPrefixCache)
            assert flat.cached_blocks == 0      # refs released
            # nothing leaked: with no live sequences every pool block
            # is back on the free list
            assert eng.free_blocks == eng._config.n_kv_blocks
        finally:
            fe2.close()

    def test_warmed_tiered_cache_survives_a_second_frontend(
            self, params_cfg):
        """The warmup-frontend handoff: a second frontend over the
        same engine must KEEP the seeded tiered cache (and its spilled
        state), not build a fresh empty one."""
        eng = _engine(params_cfg)
        fe1 = ServingFrontend(eng, _tiers_cfg())
        _serve_serial(fe1, dict(list(_requests().items())[:2]))
        pc = eng.prefix_cache
        assert pc.demoted_blocks > 0
        fe2 = ServingFrontend(eng, _tiers_cfg())
        assert eng.prefix_cache is pc       # same instance, kept
        fe2.close()


# -- satellites: eviction-cause counters + thrash detector ----------------


class TestEvictionCauseCounters:

    def test_size_bound_vs_reclaim_split(self):
        a = BlockedAllocator(16)
        pc = PrefixCache(BS, a, max_blocks=2)
        for seed in (0, 100, 200):
            prompt = np.arange(seed, seed + BS + 1, dtype=np.int32)
            blocks = a.allocate(1)
            pc.insert(prompt, blocks)
            a.free(blocks)
        st = pc.stats()
        assert st["evicted_size_bound"] == 1
        assert st["evicted_reclaim"] == 0
        assert pc.reclaim(1) == 1
        st = pc.stats()
        assert st["evicted_reclaim"] == 1
        assert st["evicted_size_bound"] == 1
        assert st["evicted_blocks"] == 2    # the split sums to total


class TestPrefixThrashAlert:

    def test_window_with_more_evictions_than_insertions_alerts(
            self, params_cfg):
        fe = ServingFrontend(_engine(params_cfg),
                             {"prefix": {"enabled": True}})
        pc = fe.engine.prefix_cache
        win = ServingFrontend._THRASH_WINDOW
        # window 1: healthy (insertions keep pace) — no alert
        pc.inserted_blocks, pc.evicted_blocks = 10, 10
        fe._step_idx = win
        fe._check_prefix_thrash()
        assert not [x for x in fe.alerts if x.kind == "prefix_thrash"]
        # window 2: churn (evictions outpace insertions) — alert
        pc.inserted_blocks, pc.evicted_blocks = 12, 30
        fe._step_idx = 2 * win
        fe._check_prefix_thrash()
        (alert,) = [x for x in fe.alerts if x.kind == "prefix_thrash"]
        assert alert.value == 20.0 and alert.threshold == 2.0
        assert "tiers" in alert.message
        # off-window steps never sample
        pc.evicted_blocks = 99
        fe._step_idx = 2 * win + 1
        fe._check_prefix_thrash()
        assert len([x for x in fe.alerts
                    if x.kind == "prefix_thrash"]) == 1
