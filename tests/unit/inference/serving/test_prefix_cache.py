"""Prefix-aware KV block reuse: host-side invariants (no engine, no
device) — the refcounted allocator's double-free guard, the trie's
match/insert/evict semantics, and adopt/flush refcount conservation
under churn. The device-facing bitwise contract lives in
test_prefix_reuse.py."""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2.ragged_manager import (
    BlockedAllocator, BlockError, DSStateManager, SchedulingError)
from deepspeed_tpu.inference.v2.serving.prefix import PrefixCache


class TestAllocatorRefcounts:

    def test_double_free_raises_and_mutates_nothing(self):
        """The satellite regression: freeing a block id twice used to
        silently corrupt the free list (two sequences could be handed
        the same block) — now a typed BlockError, with the allocator
        untouched."""
        a = BlockedAllocator(8)
        got = a.allocate(3)
        a.free(got)
        free_before = a.free_blocks
        with pytest.raises(BlockError, match="double-free"):
            a.free([got[0]])
        assert a.free_blocks == free_before
        # a never-allocated id is the same bug
        with pytest.raises(BlockError, match="double-free"):
            a.free([7])

    def test_duplicate_ids_in_one_free_call_rejected_atomically(self):
        a = BlockedAllocator(8)
        (b,) = a.allocate(1)
        with pytest.raises(BlockError):
            a.free([b, b])
        # the failed call must not have dropped the single live ref
        assert a.refcount(b) == 1
        a.free([b])
        assert a.free_blocks == 8

    def test_shared_block_frees_on_last_reference(self):
        a = BlockedAllocator(4)
        (b,) = a.allocate(1)
        a.incref([b])
        assert a.refcount(b) == 2
        a.free([b])
        assert a.refcount(b) == 1
        assert a.free_blocks == 3          # still live
        a.free([b])
        assert a.refcount(b) == 0
        assert a.free_blocks == 4

    def test_incref_of_free_block_raises(self):
        a = BlockedAllocator(4)
        with pytest.raises(BlockError, match="non-live"):
            a.incref([2])
        (b,) = a.allocate(1)
        a.free([b])
        with pytest.raises(BlockError, match="non-live"):
            a.incref([b])


def _cache(n_blocks=16, bs=4, max_blocks=0):
    a = BlockedAllocator(n_blocks)
    return PrefixCache(bs, a, max_blocks=max_blocks), a


class TestPrefixTrie:

    def test_match_walks_full_block_chain_only(self):
        pc, a = _cache()
        prompt = np.arange(11, dtype=np.int32)   # 2 full blocks + 3
        blocks = a.allocate(3)
        assert pc.insert(prompt, blocks) == 2    # only full blocks
        got, n = pc.match(prompt)
        assert got == blocks[:2] and n == 8
        # divergence INSIDE block 2 -> chain stops at block 1
        div = prompt.copy()
        div[6] = 99
        got, n = pc.match(div)
        assert got == blocks[:1] and n == 4
        # divergence in block 1 -> no match at all
        div0 = prompt.copy()
        div0[0] = 99
        got, n = pc.match(div0)
        assert got == [] and n == 0

    def test_match_leaves_at_least_one_prompt_token(self):
        """A fully cached prompt must still put >= 1 token through the
        forward (the sampled-first-token row)."""
        pc, a = _cache()
        prompt = np.arange(8, dtype=np.int32)    # exactly 2 blocks
        pc.insert(prompt, a.allocate(2))
        got, n = pc.match(prompt)
        assert n == 4 and len(got) == 1          # second block unmatched
        longer = np.arange(9, dtype=np.int32)
        got, n = pc.match(longer)
        assert n == 8 and len(got) == 2

    def test_insert_existing_chain_keeps_canonical_block(self):
        pc, a = _cache()
        prompt = np.arange(8, dtype=np.int32)
        first = a.allocate(2)
        pc.insert(prompt, first)
        second = a.allocate(2)
        assert pc.insert(prompt, second) == 0    # nothing new
        got, _ = pc.match(prompt[:5])
        assert got == [first[0]]                 # canonical mapping
        assert a.refcount(second[0]) == 1        # no extra reference

    def test_hit_miss_token_stats(self):
        pc, a = _cache()
        prompt = np.arange(9, dtype=np.int32)
        pc.match(prompt)                         # cold: miss
        pc.insert(prompt, a.allocate(3))
        pc.match(prompt)                         # hit, 8 tokens
        s = pc.stats()
        assert s["hits"] == 1 and s["misses"] == 1
        assert s["hit_rate"] == 0.5
        assert s["tokens_reused"] == 8
        assert s["cached_blocks"] == 2

    def test_max_blocks_bound_evicts_lru_leaf_first(self):
        pc, a = _cache(max_blocks=2)
        p1 = np.arange(5, dtype=np.int32)
        p2 = np.arange(100, 105, dtype=np.int32)
        p3 = np.arange(200, 205, dtype=np.int32)
        b1, b2, b3 = a.allocate(2), a.allocate(2), a.allocate(2)
        pc.insert(p1, b1)
        pc.insert(p2, b2)
        assert pc.cached_blocks == 2
        pc.match(p1)                 # p1 is now MRU
        pc.insert(p3, b3)            # bound 2 -> evict LRU (p2's block)
        assert pc.cached_blocks == 2
        assert pc.match(p1)[1] == 4
        assert pc.match(p2)[1] == 0  # evicted
        assert pc.match(p3)[1] == 4

    def test_interior_entry_never_evicted_before_its_child(self):
        """Evicting a parent while its child survives would leave the
        child unreachable (a leaked cache reference) — eviction is
        leaf-first."""
        pc, a = _cache()
        prompt = np.arange(13, dtype=np.int32)   # 3 full blocks
        blocks = a.allocate(3)
        pc.insert(prompt, blocks)
        # evict exactly one entry: must be the DEEPEST (block 3)
        pc._evict(count=1)
        got, n = pc.match(prompt)
        assert n == 8 and got == blocks[:2]
        assert pc.cached_blocks == 2

    def test_reclaim_frees_unshared_blocks_only(self):
        pc, a = _cache(n_blocks=8)
        prompt = np.arange(9, dtype=np.int32)
        blocks = a.allocate(2)
        pc.insert(prompt, blocks)
        a.free(blocks)               # the "sequence" releases its refs
        assert a.free_blocks == 6    # cache still pins both
        freed = pc.reclaim(1)
        assert freed == 1 and a.free_blocks == 7
        # entries whose block a live owner still shares are NOT
        # evicted: freeing them reclaims nothing while destroying the
        # hot mapping — reclaim skips them and stops
        prompt2 = np.arange(50, 59, dtype=np.int32)
        blocks2 = a.allocate(2)      # owner keeps its references
        pc.insert(prompt2, blocks2)
        freed = pc.reclaim(8)
        assert freed == 1            # only the unshared leftover
        assert pc.cached_blocks == 2  # shared chain survives
        assert pc.match(prompt2)[1] == 8   # still a hit
        a.free(blocks2)
        assert pc.clear() == 2
        assert a.free_blocks == 8

    def test_clear_returns_every_cache_only_block(self):
        pc, a = _cache()
        prompt = np.arange(12, dtype=np.int32)
        blocks = a.allocate(3)
        pc.insert(prompt, blocks)
        a.free(blocks)
        assert pc.clear() == 3
        assert a.free_blocks == 16
        assert a.live_blocks == 0


class TestManagerAdoption:

    def test_adopt_flush_conserves_blocks_under_churn(self):
        """Join/leave churn over a shared prefix: refcounts conserve
        every block — after all sequences flush, exactly the cache's
        pins remain, and clearing the cache restores the full pool."""
        m = DSStateManager(n_blocks=16, block_size=4)
        pc = PrefixCache(4, m.kv.allocator)
        prompt = np.arange(8, dtype=np.int32)
        # seed: a "sequence" that prefilled the prompt head
        seed = m.get_or_create_sequence(1000)
        m.kv.maybe_allocate(seed, 8)
        seed.pre_forward(8)
        seed.post_forward()
        pc.insert(prompt, seed.blocks[:2])
        m.flush_sequence(1000)
        assert m.free_blocks == 14           # 2 pinned by the cache
        for round_ in range(5):
            uids = [10 * round_ + k for k in range(3)]
            for uid in uids:
                blocks, n = pc.match(np.concatenate(
                    [prompt, [100 + uid]]).astype(np.int32))
                assert n == 8
                seq = m.adopt_prefix(uid, blocks, n)
                assert seq.shared_prefix_blocks == 2
                # private tail: one more token -> one private block
                m.kv.maybe_allocate(seq, 1)
                seq.pre_forward(1)
                seq.post_forward()
            assert m.kv.allocator.refcount(blocks[0]) == 1 + len(uids)
            for uid in uids:
                m.flush_sequence(uid)
            assert m.free_blocks == 14
        assert pc.clear() == 2
        assert m.free_blocks == 16

    def test_adopt_rejects_partial_block_span(self):
        m = DSStateManager(n_blocks=8, block_size=4)
        seq = m.get_or_create_sequence(1)
        m.kv.maybe_allocate(seq, 8)
        with pytest.raises(ValueError, match="full blocks"):
            m.adopt_prefix(2, seq.blocks[:2], 7)
        with pytest.raises(ValueError, match="already tracked"):
            m.adopt_prefix(1, seq.blocks[:1], 4)

    def test_adopt_failure_does_not_leak_sequence_entry(self):
        m = DSStateManager(n_blocks=8, block_size=4)
        with pytest.raises(BlockError):
            m.adopt_prefix(5, [3], 4)    # block 3 was never allocated
        assert m.get_sequence(5) is None

    def test_rollback_cannot_cross_the_shared_span(self):
        m = DSStateManager(n_blocks=8, block_size=4)
        owner = m.get_or_create_sequence(1)
        m.kv.maybe_allocate(owner, 8)
        m.adopt_prefix(2, owner.blocks[:2], 8)
        with pytest.raises(BlockError, match="shared prefix"):
            m.rollback_tokens(2, 1, blocks_before=1)

    def test_engine_full_adoption_path_is_typed(self):
        m = DSStateManager(max_tracked_sequences=1, n_blocks=8,
                           block_size=4)
        owner = m.get_or_create_sequence(1)
        m.kv.maybe_allocate(owner, 4)
        with pytest.raises(SchedulingError):
            m.adopt_prefix(2, owner.blocks[:1], 4)
        # the refused adoption took no reference
        assert m.kv.allocator.refcount(owner.blocks[0]) == 1
