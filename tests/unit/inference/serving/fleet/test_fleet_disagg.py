"""Disaggregated prefill/decode serving: role-split placement, the
pipelined full-prompt KV handoff (phase-A pushes behind remaining
prefill compute, phase-B residue flush + SEQ_HANDOFF land), the
bitwise degrade-to-prefill-side-decode fallback, exactly-once land
semantics, and the disagg chaos cells (kill-prefill-mid-push,
corrupt-handoff-frame).

Tier-1 keeps the loopback e2e, the land-corrupt fallback drill, one
kill-prefill chaos smoke and the engine-free units; the socket e2e,
the mixed-fleet control cross-check and the full chaos matrix ride
the slow tier (the 870s-wall diet rule)."""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import RequestState
from deepspeed_tpu.inference.v2.serving.fleet.worker import WorkerCore
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime.store import blake2b_hex, encode_kv

from tests.unit.inference.serving.fleet.test_fleet_router import (
    SYS, _assert_replicas_clean, _router, _single_frontend_refs)
from tests.unit.inference.serving.fleet.test_fleet_transport import (
    _FakeFrontend)

ROLES = ["prefill", "prefill", "decode", "decode"]
# engine geometry shared with the other fleet modules (test_fleet_
# blockxfer.ENG); the socket leg pins the worker subprocesses to it
ENG = dict(token_budget=32, max_ragged_sequence_count=4,
           n_kv_blocks=48, kv_block_size=8, max_blocks_per_seq=8,
           kv_dtype="float32")

# 6 requests over the 3 shared heads, each with a unique 24-token
# tail: 41 prompt tokens > the 32-token budget, so SplitFuse chunks
# every prefill across >=2 steps — the window phase-A pushes pipeline
# behind (a sub-budget prompt parks in its first step and everything
# would flush exposed)
N_REQ, NEW = 6, 5
REQS = {900 + k: SYS[k % 3] + [(60 + 7 * k + j) % 250
                               for j in range(24)]
        for k in range(N_REQ)}


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


@pytest.fixture(scope="module")
def disagg_refs(params_cfg):
    """Undisturbed single-frontend control streams, once per module
    (every drill below asserts bitwise against these)."""
    return _single_frontend_refs(params_cfg, REQS, NEW)


def _disagg_serving(roles=ROLES, fleet=None):
    f = {"disagg": {"enabled": True, "roles": list(roles)}}
    f.update(fleet or {})
    # the DRAM tier is the landing pad for pushed handoff blocks
    # (BLOCK_PUSH -> adopt/promote); without it every handoff would
    # degrade (still bitwise, but nothing under test would run)
    return {"prefix": {"enabled": True,
                       "tiers": {"enabled": True,
                                 "dram_max_mb": 64.0}},
            "fleet": f}


def _serve_disagg(router, max_steps=500):
    """Staggered shared-prefix traffic; returns the handle map."""
    from deepspeed_tpu.resilience.errors import ServingOverloadError
    handles = {}

    def poll(r, step):
        k = len(handles)
        if step % 2 == 0 and k < N_REQ:
            uid = 900 + k
            try:
                handles[uid] = r.submit(REQS[uid], uid=uid,
                                        max_new_tokens=NEW)
            except ServingOverloadError:
                pass        # mid-recovery refusal; retry next poll
        return len(handles) < N_REQ

    router.serve(poll=poll, max_steps=max_steps)
    return handles


def _assert_bitwise(handles, refs):
    assert len(handles) == N_REQ
    for uid, r in handles.items():
        assert r.state == RequestState.FINISHED, (uid, r.state,
                                                  r.shed_reason)
        assert r.tokens == refs[uid], uid


class TestDisaggE2E:

    def test_disagg_e2e_bitwise_with_pipelined_handoff(
            self, params_cfg, disagg_refs):
        """The ISSUE acceptance e2e (loopback leg): 2 prefill + 2
        decode replicas, every stream bitwise identical to the
        undisturbed control, every handoff landed (no degrades), the
        push pipeline genuinely overlapped prefill compute, <= 1
        compile and 0 steady blocking syncs per replica."""
        router = _router(params_cfg, n=4, serving=_disagg_serving())
        handles = _serve_disagg(router)
        _assert_bitwise(handles, disagg_refs)
        rep = router.get_fleet_report()
        ho = rep["handoff"]
        assert ho["enabled"] == 1 and ho["roles"] == ROLES
        assert ho["landed"] == N_REQ
        assert ho["fallbacks"] == 0 and ho["fallback_reasons"] == {}
        assert ho["mixed_placements"] == 0
        # phase A ran (pushes pipelined behind remaining prefill
        # chunks) AND phase B ran (the residue flush + land)
        assert ho["pushes"] >= N_REQ
        assert ho["pushed_blocks"] >= 4 * N_REQ
        assert ho["push_bytes"] > 0 and ho["push_stalls"] == 0
        assert ho["handoff_overlapped_ms"] > 0.0
        assert ho["handoff_exposed_ms"] > 0.0
        # every request ended its life on a DECODE replica
        for uid in handles:
            assert router._entries[uid].slot in (2, 3), uid
        assert rep["router"]["replay_mismatches"] == 0
        # the role + prefill-backlog scoring signals ride the wire
        # (SNAPSHOT schema, satellite): the router's replica view
        # reports them for every slot
        for slot, snap in rep["replicas"].items():
            assert snap["role"] == ROLES[int(slot)], slot
            assert "prefill_backlog" in snap and "parked" in snap
        # the PR-9 contract holds through the handoff: one compile
        # per executable, zero steady blocking syncs — the landed
        # sequence's first decode step is a plain decode row, never a
        # new signature
        for slot in router.pooled_replicas:
            frep = router._replicas[slot].frontend.get_serving_report()
            assert frep["recompiles"] <= 1, slot
            assert frep["steady_blocking_syncs"] == 0, slot
        _assert_replicas_clean(router)

    def test_handoff_land_corrupt_degrades_bitwise(
            self, params_cfg, disagg_refs):
        """The handoff-failure drill: a corrupted SEQ_HANDOFF tail is
        refused by the decode worker's checksum (typed ERR), the
        router degrades that request to prefill-side decode via the
        resume op — and the stream is STILL bitwise identical (the
        fallback is a routing change, never a numerics change)."""
        router = _router(params_cfg, n=4, serving=_disagg_serving())
        fault_injector.configure("handoff.land:corrupt")
        try:
            handles = _serve_disagg(router)
        finally:
            fault_injector.reset()
        _assert_bitwise(handles, disagg_refs)
        ho = router.get_fleet_report()["handoff"]
        assert ho["fallbacks"] == 1
        assert ho["fallback_reasons"] == {"land_failed": 1}
        assert ho["resumes"] == 1
        assert ho["landed"] == N_REQ - 1
        _assert_replicas_clean(router)

    def test_bad_role_rejected(self, params_cfg):
        with pytest.raises(ValueError, match="role"):
            _router(params_cfg, n=2,
                    serving={"fleet": {"disagg": {
                        "enabled": True,
                        "roles": ["prefill", "router"]}}})

    @pytest.mark.slow
    def test_disagg_socket_e2e(self, params_cfg, disagg_refs):
        """The socket leg: one OS process per replica, the role
        assignments and the whole handoff pipeline crossing a real
        wire — still bitwise, still landed."""
        router = _router(
            params_cfg, n=4,
            serving=_disagg_serving(fleet={
                "transport": {"channel": "socket",
                              "worker_args": {"engine": dict(ENG)}}}))
        try:
            handles = _serve_disagg(router)
            _assert_bitwise(handles, disagg_refs)
            rep = router.get_fleet_report()
            ho = rep["handoff"]
            assert ho["landed"] == N_REQ and ho["fallbacks"] == 0
            assert ho["handoff_overlapped_ms"] > 0.0
            assert rep["transport"]["channel"] == "socket"
            for slot, snap in rep["replicas"].items():
                assert snap["role"] == ROLES[int(slot)], slot
                assert snap["recompiles"] <= 1, slot
        finally:
            for replica in router._replicas:
                try:
                    replica.detach()
                except Exception:
                    pass

    @pytest.mark.slow
    def test_disagg_matches_mixed_fleet_control(self, params_cfg):
        """The mixed-fleet cross-check: the SAME 4-replica fleet with
        disagg off produces byte-identical streams (roles are pure
        placement; fold_in(uid, pos) sampling keys never move)."""
        def run(serving):
            router = _router(params_cfg, n=4, serving=serving)
            handles = _serve_disagg(router)
            return {u: list(r.tokens) for u, r in handles.items()}

        mixed = run({"prefix": _disagg_serving()["prefix"]})
        disagg = run(_disagg_serving())
        assert disagg == mixed


# -- chaos cells ---------------------------------------------------------

def run_disagg_chaos_drill(params_cfg, refs, cell, seed=0):
    """One disagg chaos drill; cells:

    * ``kill_prefill_mid_push`` — a prefill replica dies while its
      handoff segments are in flight; the evacuation resets the plan,
      the requeue re-places through the disagg path, the respawn
      re-learns the slot's role over HELLO;
    * ``corrupt_push_frame`` — a pushed segment is poisoned after its
      checksum is stamped; the receiver refuses it, the phase-B flush
      re-pushes and the handoff still lands;
    * ``corrupt_both_frames`` — push + land corruption in one trace:
      the push stalls-and-recovers, the land degrades typed.

    Every cell asserts bitwise streams and block conservation."""
    rng = np.random.default_rng(seed)
    router = _router(params_cfg, n=4,
                     serving=_disagg_serving(fleet={
                         "heartbeat_timeout_steps": 1,
                         "progress_timeout_steps": 2}))
    if cell == "kill_prefill_mid_push":
        victim = int(rng.integers(0, 2))          # a PREFILL slot
        fault_step = int(rng.integers(2, 5))
        fault_injector.configure(
            router.spec_for(victim, fault_step, "kill"))
    elif cell == "corrupt_push_frame":
        fault_injector.configure("handoff.push:corrupt@0")
    elif cell == "corrupt_both_frames":
        fault_injector.configure(
            "handoff.push:corrupt@0,handoff.land:corrupt@1")
    else:
        raise ValueError(cell)
    try:
        handles = _serve_disagg(router)
    finally:
        fault_injector.reset()
    _assert_bitwise(handles, refs)
    rep = router.get_fleet_report()
    ho = rep["handoff"]
    assert rep["router"]["replay_mismatches"] == 0
    if cell == "kill_prefill_mid_push":
        rec = rep["recovery"]
        assert rec["deaths"] == 1 and rec["respawns"] == 1
        # the respawned slot re-learned its PREFILL role over HELLO
        assert sorted(router.pooled_replicas) == [0, 1, 2, 3]
    else:
        assert rep["recovery"]["deaths"] == 0
        assert ho["push_stalls"] >= 1      # the refused segment
        assert ho["landed"] >= 1
        if cell == "corrupt_both_frames":
            assert ho["fallbacks"] == 1
            assert ho["fallback_reasons"] == {"land_failed": 1}
    _assert_replicas_clean(router)
    return rep


@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.parametrize("cell,seed", [
    ("kill_prefill_mid_push", 0),
    # tier-1 diet: ONE kill smoke in tier-1; the frame-corruption
    # cells and the second kill draw ride the slow sweep
    pytest.param("kill_prefill_mid_push", 3, marks=pytest.mark.slow),
    pytest.param("corrupt_push_frame", 0, marks=pytest.mark.slow),
    pytest.param("corrupt_both_frames", 0, marks=pytest.mark.slow),
])
def test_disagg_chaos_cells(cell, seed, params_cfg, disagg_refs):
    rep = run_disagg_chaos_drill(params_cfg, disagg_refs, cell,
                                 seed=seed)
    assert rep["router"]["finished"] == N_REQ


# -- engine-free units ---------------------------------------------------

class TestSeqHandoffExactlyOnce:
    """SEQ_HANDOFF rides the worker's effectful reply cache: a
    duplicate land (the retried ask after a lost reply) must not
    ingest twice, and a typed refusal must not be pinned."""

    def _land_msg(self, msg_id=21, poison=False):
        payload, meta = encode_kv(np.zeros((2, 4), np.float32), "none")
        b2 = blake2b_hex(payload)
        if poison:
            payload = bytes([payload[0] ^ 0xFF]) + payload[1:]
        return {"v": 1, "id": msg_id, "kind": "SEQ_HANDOFF",
                "op": "land", "uid": 5, "prompt": [1, 2, 3],
                "first_token": 7, "remaining": 3, "max_new_tokens": 4,
                "tail": {"payload": payload.hex(), "b2": b2,
                         "meta": meta}}

    def test_duplicate_land_ingests_once(self):
        fe = _FakeFrontend()
        lands = []
        fe.ingest_handoff = lambda **kw: lands.append(kw["uid"])
        core = WorkerCore(0, fe)
        msg = self._land_msg()
        r1 = core.handle(dict(msg))
        r2 = core.handle(dict(msg))           # the re-asked duplicate
        assert r1["kind"] == "SEQ_HANDOFF_OK" and r1["landed"]
        assert r2 == r1
        assert lands == [5]                   # ONE effect
        # the first-token seed is in the collect buffer at position 0
        assert core._tokens[5] == [7]

    def test_corrupt_land_refused_typed_and_not_cached(self):
        fe = _FakeFrontend()
        lands = []
        fe.ingest_handoff = lambda **kw: lands.append(kw["uid"])
        core = WorkerCore(0, fe)
        r = core.handle(self._land_msg(msg_id=3, poison=True))
        assert r["kind"] == "ERR" and r["etype"] == "value"
        assert "checksum" in r["error"]
        assert lands == [] and 5 not in core._tokens
        # same id, intact frame: the ERR was not cached, the re-ask
        # re-executes (exactly-once holds for SUCCESS, not failure)
        r = core.handle(self._land_msg(msg_id=3))
        assert r["kind"] == "SEQ_HANDOFF_OK"
        assert lands == [5]

    def test_ingest_failure_rolls_back_token_buffer(self):
        fe = _FakeFrontend()

        def boom(**kw):
            raise ValueError("no KV headroom")
        fe.ingest_handoff = boom
        core = WorkerCore(0, fe)
        r = core.handle(self._land_msg(msg_id=9))
        assert r["kind"] == "ERR" and r["etype"] == "value"
        # the pre-seeded collect buffer was rolled back: the slot
        # holds no phantom first token for a sequence it never owned
        assert 5 not in core._tokens

    def test_unknown_op_is_a_value_error(self):
        core = WorkerCore(0, _FakeFrontend())
        r = core.handle({"v": 1, "id": 2, "kind": "SEQ_HANDOFF",
                         "op": "teleport", "uid": 1})
        assert r["kind"] == "ERR" and r["etype"] == "value"
