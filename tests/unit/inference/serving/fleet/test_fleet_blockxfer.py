"""Fleet-wide KV block transfer (blockxfer): the transfer policy
units, the tiered cache's export/land surface, the worker's
BLOCK_FETCH/BLOCK_PUSH handlers (chain truncation, checksum
re-verification, exactly-once), a real-socket RPC smoke, the loopback
acceptance e2e (peer fetch beats recompute, bitwise streams, seeded
corruption degrades to recompute, kill-mid-decode warm start, drain
push-ahead), and the chaos matrix with transfers armed.

Tier-1 keeps the policy/handler units, one socketpair smoke and the
loopback acceptance; the subprocess-socket acceptance and the chaos
matrix ride the slow tier (the 870s-wall diet rule)."""

import socket
import threading
import types

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (FleetRouter, InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, ServingFrontend)
from deepspeed_tpu.inference.v2.serving.fleet.blockxfer import (
    PeerBlockSource, TransferPolicy)
from deepspeed_tpu.inference.v2.serving.fleet.transport import (
    MSG_BLOCK_FETCH, MSG_BLOCK_PUSH, MSG_SHUTDOWN, RpcClient,
    SocketChannel)
from deepspeed_tpu.inference.v2.serving.fleet.worker import (
    WorkerCore, serve_socket)
from deepspeed_tpu.inference.v2.serving.prefix import chain_digests
from deepspeed_tpu.resilience.errors import ServingOverloadError
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime.config import (FleetTransferConfig,
                                          FleetTransportConfig)
from deepspeed_tpu.runtime.store import blake2b_hex, decode_kv

SYS = [list(range(1, 18)), list(range(101, 118)),
       list(range(201, 218))]

# engine geometry shared with every fleet test module; queue depth 1
# is the forcing function — a second same-prefix arrival OVERFLOWS the
# prefix's home replica, so the router must place it on the non-owner
# and the transfer path (fetch-instead-of-recompute) actually runs
ENG = dict(token_budget=32, max_ragged_sequence_count=4,
           n_kv_blocks=48, kv_block_size=8, max_blocks_per_seq=8,
           kv_dtype="float32")
TIERS = {"tiers": {"enabled": True, "dram_max_mb": 64.0}}


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def _factory(params_cfg, **kw):
    params, cfg = params_cfg
    eng_kw = dict(ENG)
    eng_kw.update(kw)

    def engine_factory(slot):
        return InferenceEngineV2(params, cfg,
                                 RaggedInferenceEngineConfig(**eng_kw))
    return engine_factory


def _router(params_cfg, n=2, serving=None, engine_kw=None, **kw):
    cfg = {"fleet": {"n_replicas": n}}
    for k, v in (serving or {}).items():
        if k == "fleet":
            cfg["fleet"].update(v)
        else:
            cfg[k] = v
    return FleetRouter(_factory(params_cfg, **(engine_kw or {})),
                       cfg, **kw)


def _xfer_serving(**fleet_kw):
    # recompute_ms_per_block pinned high: the e2e drills test the
    # TRANSFER machinery, so the fetch-vs-recompute policy must always
    # choose fetch — a CPU-host loopback "wire" measures slow enough
    # that the default 5 ms/block budget legitimately declines the
    # second fetch (the decline math has its own TransferPolicy units)
    fleet = {"transfer": {"enabled": True,
                          "recompute_ms_per_block": 1000.0}}
    fleet.update(fleet_kw)
    return {"prefix": dict(TIERS), "fleet": fleet}


def _single_frontend_refs(params_cfg, requests, max_new_tokens):
    eng = _factory(params_cfg)(0)
    refs = {}
    for uid, prompt in requests.items():
        fe = ServingFrontend(eng)
        r = fe.submit(prompt, uid=uid, max_new_tokens=max_new_tokens)
        fe.drain()
        assert r.state == RequestState.FINISHED
        refs[uid] = list(r.tokens)
    return refs


def _xcfg(**kw):
    base = {"enabled": True}
    base.update(kw)
    return FleetTransferConfig(**base)


class TestTransferPolicy:
    """Engine-free: the fetch-vs-recompute decision math."""

    def test_optimistic_before_first_measurement(self):
        p = TransferPolicy(_xcfg())
        assert p.est_fetch_ms(8) == 0.0
        assert p.should_fetch(1) and p.should_fetch(32)

    def test_min_fetch_blocks_gate(self):
        p = TransferPolicy(_xcfg(min_fetch_blocks=2))
        assert not p.should_fetch(1)
        assert p.should_fetch(2)

    def test_measured_rate_declines_a_slow_wire(self):
        # 10 B/ms, 1000 B/block -> 4 blocks cost ~400ms against a
        # 4 * 5ms recompute budget: recompute wins
        slow = TransferPolicy(_xcfg())
        slow.note_fetch(1000, 100.0, 1)
        assert slow.est_fetch_ms(4) == pytest.approx(400.0)
        assert not slow.should_fetch(4)
        # 100 kB/ms: fetching is ~free, fetch wins
        fast = TransferPolicy(_xcfg())
        fast.note_fetch(1000, 0.01, 1)
        assert fast.should_fetch(4)

    def test_ewma_blend_and_degenerate_samples(self):
        p = TransferPolicy(_xcfg(ewma_alpha=0.3))
        p.note_fetch(1000, 100.0, 1)           # rate 10, first sample
        p.note_fetch(1000, 50.0, 1)            # rate 20, blended
        assert p.bytes_per_ms == pytest.approx(0.7 * 10 + 0.3 * 20)
        before = p.bytes_per_ms
        p.note_fetch(0, 1.0, 1)                # degenerate: ignored
        p.note_fetch(1000, 0.0, 1)
        p.note_fetch(1000, 1.0, 0)
        assert p.bytes_per_ms == before

    def test_zero_stats_matches_live_schema(self):
        src = PeerBlockSource(_xcfg())
        assert set(PeerBlockSource.zero_stats()) == set(src.stats())


class TestWorkerBlockRpcs:
    """The two new RPCs against real tiered engines: export from HBM
    and from the spill tier, chain truncation at the first hole,
    receiver-side checksum re-verification, the chain-parent
    invariant, idempotence, and the exactly-once reply cache."""

    def test_fetch_push_handlers_roundtrip(self, params_cfg):
        prompt = SYS[0] + [31]
        da = chain_digests(np.asarray(prompt, np.int32), 8)
        fe = ServingFrontend(_factory(params_cfg)(0),
                             {"prefix": dict(TIERS)})
        wc = WorkerCore(0, fe)
        r = fe.submit(prompt, uid=1, max_new_tokens=4)
        fe.drain()
        assert r.state == RequestState.FINISHED
        ref_tokens = list(r.tokens)
        pc = fe.engine.prefix_cache

        # -- export straight from the HBM trie, chain order ----------
        rep = wc._block_fetch({"digests": [d.hex() for d in da]})
        assert rep["kind"] == "BLOCK_FETCH_OK" and not rep["missing"]
        assert [b["d"] for b in rep["blocks"]] == [d.hex() for d in da]
        for b in rep["blocks"]:
            payload = bytes.fromhex(b["payload"])
            assert blake2b_hex(payload) == b["b2"]
            assert b["tier"] == "hbm"
            decode_kv(payload, b["meta"])     # well-formed encoding

        # -- the walk stops at the first hole ------------------------
        hole = wc._block_fetch(
            {"digests": [da[0].hex(), "00" * 16, da[1].hex()]})
        assert [b["d"] for b in hole["blocks"]] == [da[0].hex()]
        assert hole["missing"] == ["00" * 16]

        # -- a spilled leaf exports from its tier, read-only ---------
        pc._evict(count=1)
        assert pc.spilled_blocks == 1
        rep2 = wc._block_fetch({"digests": [d.hex() for d in da]})
        assert [b["tier"] for b in rep2["blocks"]] == ["hbm", "dram"]
        assert pc.spilled_blocks == 1          # export moved nothing

        # -- receiver: land with re-verification ---------------------
        fe2 = ServingFrontend(_factory(params_cfg)(1),
                              {"prefix": dict(TIERS)})
        wc2 = WorkerCore(1, fe2)
        blocks = []
        parent = ""
        for b in rep2["blocks"]:
            blocks.append({"d": b["d"], "parent": parent,
                           "payload": b["payload"], "b2": b["b2"],
                           "meta": b["meta"]})
            parent = b["d"]

        # orphan child alone: the chain invariant refuses it
        orphan = wc2._block_push({"blocks": [blocks[1]]})
        assert orphan == {"kind": "BLOCK_PUSH_OK", "landed": 0,
                          "rejected": 1}
        # a payload that fails its checksum never lands
        bad = dict(blocks[0], payload="00" + blocks[0]["payload"][2:])
        assert wc2._block_push({"blocks": [bad]})["rejected"] == 1
        assert fe2.engine.prefix_cache.spilled_blocks == 0

        # the good chain lands exactly once through the reply cache
        msg = {"v": 1, "id": 77, "kind": MSG_BLOCK_PUSH,
               "blocks": blocks}
        r1 = wc2.handle(dict(msg))
        r2 = wc2.handle(dict(msg))             # the re-asked duplicate
        assert r1["kind"] == "BLOCK_PUSH_OK" and r1["landed"] == 2
        assert r2 == r1
        pc2 = fe2.engine.prefix_cache
        assert pc2.spilled_blocks == 2
        # re-landing resident digests is an idempotent True
        assert wc2._block_push({"blocks": blocks})["landed"] == 2

        # -- the landed chain adopts bitwise on the receiver ---------
        r2req = fe2.submit(prompt, uid=9, max_new_tokens=4)
        fe2.drain()
        assert list(r2req.tokens) == ref_tokens
        st = pc2.stats()
        assert st["promoted_blocks"] >= 2 and pc2.hits >= 1
        assert fe2.metrics.report()["prompt_tokens"] == len(prompt) - 16
        fe.close()
        fe2.close()

    def test_flat_trie_export_fallback_and_push_refusal(self):
        """A replica without spill tiers still FEEDS peers (HBM gather
        + exact encode) but refuses pushes — no tier to land into."""
        arr = np.arange(48, dtype=np.float32).reshape(2, 3, 8)
        d = bytes(range(16))
        pc = types.SimpleNamespace(
            _entries={d: types.SimpleNamespace(block=3)})
        eng = types.SimpleNamespace(prefix_cache=pc,
                                    read_kv_block=lambda b: arr)
        wc = WorkerCore(0, types.SimpleNamespace(engine=eng))
        rep = wc._block_fetch({"digests": [d.hex()]})
        blk = rep["blocks"][0]
        assert blk["tier"] == "hbm"
        payload = bytes.fromhex(blk["payload"])
        assert blake2b_hex(payload) == blk["b2"]
        np.testing.assert_array_equal(decode_kv(payload, blk["meta"]),
                                      arr)
        push = wc._block_push({"blocks": [dict(blk, parent="")]})
        assert push == {"kind": "BLOCK_PUSH_OK", "landed": 0,
                        "rejected": 1}


class TestSocketBlockRpcSmoke:
    """The tier-1 socket smoke: both RPCs over a REAL framed stream
    (OS socketpair + the worker serve loop — no subprocess; the
    subprocess fleet rides the slow-tier acceptance)."""

    def test_fetch_clear_push_adopt_over_socketpair(self, params_cfg):
        prompt = SYS[1] + [41]
        da = chain_digests(np.asarray(prompt, np.int32), 8)
        fe = ServingFrontend(_factory(params_cfg)(0),
                             {"prefix": dict(TIERS)})
        r = fe.submit(prompt, uid=1, max_new_tokens=4)
        fe.drain()
        ref_tokens = list(r.tokens)
        core = WorkerCore(0, fe)
        a, b = socket.socketpair()
        t = threading.Thread(target=serve_socket, args=(core, b),
                             daemon=True)
        t.start()
        ch = SocketChannel(lambda: (None, a))
        ch.connect()
        rpc = RpcClient(ch, 0, FleetTransportConfig(
            rpc_deadline_seconds=10.0, retry_backoff_seconds=0.0))
        try:
            rep = rpc.call(MSG_BLOCK_FETCH,
                           {"digests": [d.hex() for d in da]})
            assert rep["kind"] == "BLOCK_FETCH_OK" and not rep["missing"]
            blocks, parent = [], ""
            for blk in rep["blocks"]:
                payload = bytes.fromhex(blk["payload"])
                assert blake2b_hex(payload) == blk["b2"]
                blocks.append({"d": blk["d"], "parent": parent,
                               "payload": blk["payload"],
                               "b2": blk["b2"], "meta": blk["meta"]})
                parent = blk["d"]
            # wipe the trie, push the chain back over the wire, adopt
            fe.engine.prefix_cache.clear()
            push = rpc.call(MSG_BLOCK_PUSH, {"blocks": blocks})
            assert push["kind"] == "BLOCK_PUSH_OK"
            assert push["landed"] == 2 and push["rejected"] == 0
            assert fe.engine.prefix_cache.spilled_blocks == 2
            rpc.call(MSG_SHUTDOWN)
            t.join(timeout=10.0)
        finally:
            ch.close()
        r2 = fe.submit(prompt, uid=2, max_new_tokens=4)
        fe.drain()
        assert list(r2.tokens) == ref_tokens
        assert fe.engine.prefix_cache.stats()["promoted_blocks"] >= 2
        fe.close()


class TestAcceptanceLoopback:
    """The acceptance e2e over the loopback channel: shared-prefix
    traffic forced onto the non-owning replica is FETCHED, not
    recomputed — strictly fewer prefill tokens than the identical
    no-transfer run, bitwise-identical streams, <= 1 recompile and 0
    steady blocking syncs per replica — then seeded fetch corruption
    degrades to recompute, a kill-mid-decode respawn warm-starts from
    pushed blocks, and a graceful drain pushes the leaving replica's
    chains ahead.

    Tier-1 keeps the lean smoke (the 870s-wall diet); the full
    multi-phase drill with its no-transfer control fleet rides the
    slow tier."""

    def test_peer_fetch_loopback_smoke(self, params_cfg):
        """Tier-1: one forced off-home placement fetches instead of
        recomputing — 2 blocks cross the wire, the peer adopts them
        (16 of 18 prompt tokens never prefill), streams stay bitwise,
        and the hub publishes the blockxfer namespace."""
        from deepspeed_tpu.telemetry.hub import TelemetryHub
        prompts = {k: SYS[0] + [30 + k] for k in range(1, 4)}
        refs = _single_frontend_refs(params_cfg, prompts, 4)
        router = _router(params_cfg, n=2, serving=_xfer_serving(),
                         engine_kw={"max_queue_depth": 1})
        hub = TelemetryHub()
        router.attach_telemetry(hub)
        router.submit(prompts[1], uid=1, max_new_tokens=4)
        router.drain()
        home = router._entries[1].slot
        other = 1 - home
        router.submit(prompts[2], uid=2, max_new_tokens=4)
        router.submit(prompts[3], uid=3, max_new_tokens=4)
        assert router._entries[2].slot == home     # affinity held
        assert router._entries[3].slot == other    # forced off-home
        bx = router.get_fleet_report()["blockxfer"]
        assert bx["enabled"] == 1 and bx["fetch_hit_rate"] > 0
        assert bx["fetched_blocks"] == 2 == bx["pushed_blocks"]
        assert bx["fetch_bytes"] > 0 and bx["fetch_failures"] == 0
        router.drain()
        for uid in (1, 2, 3):
            r = router.get_request(uid)
            assert r.state == RequestState.FINISHED
            assert list(r.tokens) == refs[uid], uid   # bitwise
        # the non-owner ADOPTED the fetched chain: only the 2-token
        # tail prefilled, against the 18 a cold recompute pays
        peer_pc = router._replicas[other].engine.prefix_cache
        assert peer_pc.stats()["promoted_blocks"] >= 2
        assert peer_pc.hits >= 1 and peer_pc.misses == 0
        assert router._replicas[other].frontend.metrics \
            .report()["prompt_tokens"] == 2
        for s in router.pooled_replicas:
            frep = router._replicas[s].frontend.get_serving_report()
            assert frep["recompiles"] <= 1, s
            assert frep["steady_blocking_syncs"] == 0, s
        flat = hub.sample(1)
        assert flat["fleet/blockxfer/fetched_blocks"] == 2.0
        assert "fleet/blockxfer/fetch_exposed_ms" in flat

    @pytest.mark.slow
    def test_peer_fetch_acceptance(self, params_cfg):
        from deepspeed_tpu.telemetry.hub import TelemetryHub
        prompts = {k: SYS[0] + [30 + k] for k in range(1, 8)}
        refs = _single_frontend_refs(params_cfg, prompts, 4)
        serving = _xfer_serving()

        # -- control: same traffic, transfer OFF ---------------------
        ctl = _router(params_cfg, n=2,
                      serving={"prefix": dict(TIERS)},
                      engine_kw={"max_queue_depth": 1})
        c1 = ctl.submit(prompts[1], uid=1, max_new_tokens=4)
        ctl.drain()
        ctl.submit(prompts[2], uid=2, max_new_tokens=4)
        ctl.submit(prompts[3], uid=3, max_new_tokens=4)
        ctl.drain()
        assert c1.state == RequestState.FINISHED
        ctl_prefill = sum(
            ctl._replicas[s].frontend.metrics.report()["prompt_tokens"]
            for s in ctl.pooled_replicas)
        ctl_bx = ctl.get_fleet_report()["blockxfer"]
        assert ctl_bx["enabled"] == 0          # schema-stable when off
        assert ctl_bx["fetched_blocks"] == 0

        # -- transfer ON: the overflow placement fetches -------------
        router = _router(params_cfg, n=2, serving=serving,
                         engine_kw={"max_queue_depth": 1})
        hub = TelemetryHub()
        router.attach_telemetry(hub)
        r1 = router.submit(prompts[1], uid=1, max_new_tokens=4)
        router.drain()
        home = router._entries[1].slot
        other = 1 - home
        r2 = router.submit(prompts[2], uid=2, max_new_tokens=4)
        r3 = router.submit(prompts[3], uid=3, max_new_tokens=4)
        assert router._entries[2].slot == home     # affinity held
        assert router._entries[3].slot == other    # forced off-home
        bx = router.get_fleet_report()["blockxfer"]
        assert bx["enabled"] == 1 and bx["fetch_hit_rate"] > 0
        assert bx["fetched_blocks"] == 2 == bx["pushed_blocks"]
        assert bx["fetch_bytes"] > 0 and bx["fetch_failures"] == 0
        router.drain()
        for uid in (1, 2, 3):
            r = router.get_request(uid)
            assert r.state == RequestState.FINISHED
            assert list(r.tokens) == refs[uid], uid   # bitwise
        # the non-owner ADOPTED the fetched chain instead of
        # recomputing it: 16 of 18 prompt tokens never prefilled
        peer_pc = router._replicas[other].engine.prefix_cache
        assert peer_pc.stats()["promoted_blocks"] >= 2
        assert peer_pc.hits >= 1 and peer_pc.misses == 0
        xfer_prefill = sum(
            router._replicas[s].frontend.metrics
            .report()["prompt_tokens"] for s in router.pooled_replicas)
        assert xfer_prefill < ctl_prefill          # strictly below
        # the zero-recompile + steady-window contracts held
        for s in router.pooled_replicas:
            frep = router._replicas[s].frontend.get_serving_report()
            assert frep["recompiles"] <= 1, s
            assert frep["steady_blocking_syncs"] == 0, s
        # the hub publishes the blockxfer namespace flat
        flat = hub.sample(1)
        assert flat["fleet/blockxfer/fetched_blocks"] == 2.0
        assert "fleet/blockxfer/fetch_exposed_ms" in flat

        # -- seeded corruption degrades to recompute, still bitwise --
        rejects0 = bx["fetch_rejects"]
        fault_injector.configure("blockxfer.fetch:corrupt")
        try:
            router.submit(prompts[4], uid=4, max_new_tokens=4)
            router.submit(prompts[5], uid=5, max_new_tokens=4)
        finally:
            fault_injector.reset()
        router.drain()
        bx = router.get_fleet_report()["blockxfer"]
        assert bx["fetch_rejects"] == rejects0 + 1
        assert bx["recompute_fallbacks"] >= 1
        assert bx["pushed_blocks"] == 2       # the poisoned fetch: none
        for uid in (4, 5):
            assert list(router.get_request(uid).tokens) == refs[uid]

        # -- kill mid-decode: the respawn warm-starts from pushes ----
        r6 = router.submit(prompts[6], uid=6, max_new_tokens=4)
        for _ in range(2):
            router.step()
        owner_now = router._affinity_map.get(
            chain_digests(np.asarray(prompts[6], np.int32), 8)[0])[0]
        victim = 1 - owner_now
        fault_injector.configure(router.spec_for(victim, 0, "kill"))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r6.state == RequestState.FINISHED
        assert list(r6.tokens) == refs[6]
        rep = router.get_fleet_report()
        assert rep["recovery"]["deaths"] == 1
        assert rep["recovery"]["respawns"] == 1
        assert rep["recovery"]["warm_starts"] >= 1
        assert rep["blockxfer"]["warm_starts"] >= 1
        # the fresh worker's DRAM tier was seeded before traffic hit
        respawned_pc = router._replicas[victim].engine.prefix_cache
        assert respawned_pc.spilled_blocks >= 2 or \
            respawned_pc.cached_blocks >= 2

        # -- graceful drain pushes the leaver's chains ahead ---------
        warm0 = rep["blockxfer"]["warm_starts"]
        owner_now = router._affinity_map.get(
            chain_digests(np.asarray(prompts[6], np.int32), 8)[0])[0]
        router.drain_replica(owner_now)
        rep = router.get_fleet_report()
        assert rep["recovery"]["drains"] == 1
        assert rep["blockxfer"]["warm_starts"] >= warm0
        r7 = router.submit(prompts[7], uid=7, max_new_tokens=4)
        router.drain()
        assert list(r7.tokens) == refs[7]


class TestAcceptanceSocket:

    @pytest.mark.slow
    def test_peer_fetch_acceptance_socket(self, params_cfg):
        """The same forced-off-home drill over REAL worker processes:
        the chain crosses the frame protocol twice (fetch from the
        owner process, push into the peer process) and the peer
        adopts it — fetch_hit_rate > 0, streams bitwise, recompiles
        <= 1 per replica. Slow tier: two worker cold starts."""
        prompts = {k: SYS[2] + [60 + k] for k in range(1, 4)}
        refs = _single_frontend_refs(params_cfg, prompts, 4)
        worker_engine = dict(ENG, max_queue_depth=1,
                             max_tracked_sequences=16,
                             prefix_cache=True)
        serving = _xfer_serving(transport={
            "channel": "socket",
            "worker_args": {"engine": worker_engine}})
        serving["max_queue_depth"] = 1
        router = _router(params_cfg, n=2, serving=serving,
                         engine_kw={"max_queue_depth": 1})
        try:
            r1 = router.submit(prompts[1], uid=1, max_new_tokens=4)
            router.drain()
            assert r1.state == RequestState.FINISHED
            home = router._entries[1].slot
            router.submit(prompts[2], uid=2, max_new_tokens=4)
            router.submit(prompts[3], uid=3, max_new_tokens=4)
            placed = {router._entries[u].slot for u in (2, 3)}
            assert placed == {home, 1 - home}      # one forced off-home
            router.drain()
            bx = router.get_fleet_report()["blockxfer"]
            assert bx["fetch_hit_rate"] > 0
            assert bx["fetched_blocks"] >= 2
            assert bx["pushed_blocks"] >= 2
            for uid in (1, 2, 3):
                assert list(router.get_request(uid).tokens) == \
                    refs[uid], uid
            for slot in router.pooled_replicas:
                replica = router._replicas[slot]
                assert replica.frontend is None    # real processes
                assert replica.snapshot()["recompiles"] <= 1, slot
        finally:
            for slot in router.pooled_replicas:
                router._replicas[slot].kill("test teardown")


def _xfer_chaos_serve(params_cfg, specs, n_pairs=3, max_new_tokens=4):
    """Shared-prefix pressure through a 2-replica transfer-enabled
    fleet with chaos armed. Arrivals come in SAME-PREFIX pairs
    released only when the fleet is idle: with queue depth 1 the
    first of a pair takes the prefix's home replica and the second is
    forced onto the other one — from the second pair of a group on,
    that is a guaranteed live peer transfer under fire."""
    n_req = 2 * n_pairs
    reqs_in = {800 + k: SYS[(k // 2) % 2] + [50 + k]
               for k in range(n_req)}
    refs = _single_frontend_refs(params_cfg, reqs_in, max_new_tokens)
    router = _router(params_cfg, n=2, serving=_xfer_serving(),
                     engine_kw={"max_queue_depth": 1})
    handles = {}

    def poll(r, step):
        k = len(handles)
        if k < n_req and all(h.state == RequestState.FINISHED
                             for h in handles.values()):
            for uid in (800 + k, 800 + k + 1):   # the idle-burst pair
                try:
                    handles[uid] = r.submit(
                        reqs_in[uid], uid=uid,
                        max_new_tokens=max_new_tokens)
                except ServingOverloadError:
                    pass      # a replica refused; retry next step
        return len(handles) < n_req or any(
            h.state != RequestState.FINISHED for h in handles.values())
    fault_injector.configure(specs)
    try:
        router.serve(poll=poll, max_steps=800)
    finally:
        fault_injector.reset()
    router.drain()
    return router, handles, refs


def _assert_chaos_exact(router, handles, refs, n_req):
    assert len(handles) == n_req
    for uid, r in handles.items():
        assert r.state == RequestState.FINISHED, (uid, r.state,
                                                  r.shed_reason)
        assert r.tokens == refs[uid], uid
    rep = router.get_fleet_report()
    assert rep["router"]["replay_mismatches"] == 0
    assert rep["router"]["abandoned"] == 0
    return rep


class TestPrefetchDedup:
    """The in-flight prefetch dedup (disagg PR satellite): a
    placement wave landing several same-head requests on one cold
    replica must move the chain ONCE — keyed (dest slot, head
    digest), TTL'd in router steps, cleared early by the
    destination's TRIE_DELTA confirmation."""

    def test_inflight_dedup_ttl_and_delta_clear(self, params_cfg):
        prompts = {k: SYS[0] + [30 + k] for k in range(1, 4)}
        router = _router(params_cfg, n=2, serving=_xfer_serving(),
                         engine_kw={"max_queue_depth": 1})
        router.submit(prompts[1], uid=1, max_new_tokens=4)
        router.drain()
        home = router._entries[1].slot
        other = 1 - home
        router.submit(prompts[2], uid=2, max_new_tokens=4)
        router.submit(prompts[3], uid=3, max_new_tokens=4)
        e = router._entries[3]
        assert e.slot == other            # forced off-home: prefetched
        assert router.get_fleet_report()["blockxfer"][
            "fetched_blocks"] == 2
        key = (other, e.digests[0])
        assert router._prefetch_inflight[key] > router._step_idx
        # a second same-head placement inside the TTL window is pure
        # wire waste: skipped, counted, NO second fetch
        assert router._maybe_prefetch(e, other, home) == 0
        assert router.prefetch_dedup_skips == 1
        assert router.get_fleet_report()["blockxfer"][
            "fetched_blocks"] == 2
        assert router.get_fleet_report()["router"][
            "prefetch_dedup_skips"] == 1
        # an EXPIRED entry no longer suppresses the re-issue (and the
        # re-issue re-stamps a fresh TTL)
        router._prefetch_inflight[key] = router._step_idx
        router._maybe_prefetch(e, other, home)
        assert router.prefetch_dedup_skips == 1
        assert router._prefetch_inflight[key] > router._step_idx
        # the destination's TRIE_DELTA proves the head landed: the
        # in-flight entry clears before the TTL runs out
        router.drain()
        assert key not in router._prefetch_inflight
        for uid in (1, 2, 3):
            assert router.get_request(uid).state == \
                RequestState.FINISHED


class TestChaosWithTransfersArmed:
    """Satellite 3: the transport fault matrix OVER live peer
    transfers, plus seeded blockxfer corruption — bitwise streams, no
    lost/doubled tokens, poisoned fetches degrade to recompute."""

    @pytest.mark.slow
    @pytest.mark.parametrize("kind", ["drop", "delay", "dup",
                                      "reorder", "truncate"])
    def test_chaos_matrix(self, params_cfg, kind):
        router, handles, refs = _xfer_chaos_serve(
            params_cfg, f"transport.send:{kind}~0.15,"
                        f"transport.recv:{kind}~0.15")
        rep = _assert_chaos_exact(router, handles, refs, 6)
        assert rep["transport"]["injected"] > 0

    @pytest.mark.slow
    def test_chaos_with_seeded_fetch_corruption(self, params_cfg):
        """Drops both ways + every peer fetch poisoned: the checksum
        rejects each one, every off-home placement recomputes, and
        the streams stay bitwise — corruption can cost time, never
        a wrong token."""
        router, handles, refs = _xfer_chaos_serve(
            params_cfg, "transport.send:drop~0.1,"
                        "transport.recv:drop~0.1,"
                        "blockxfer.fetch:corruptx999")
        rep = _assert_chaos_exact(router, handles, refs, 6)
        bx = rep["blockxfer"]
        assert bx["fetch_rpcs"] > 0            # transfers really ran
        assert bx["fetch_rejects"] > 0
        assert bx["recompute_fallbacks"] > 0
        assert bx["fetch_hits"] == 0 and bx["pushed_blocks"] == 0
