"""Fleet × tiered prefix cache: the worker's TRIE_DELTA carries tier
residency for spilled digests (3-tuple journal records folded into a
``tiers`` map), the router's affinity map stores ``(slot, tier)`` and
scores spilled prefixes with the configured DRAM/disk discounts, and
the SNAPSHOT resync rebuilds residency from ``trie_tiers``."""

import types

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (FleetRouter, InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, ServingFrontend)
from deepspeed_tpu.inference.v2.serving.fleet.worker import WorkerCore
from deepspeed_tpu.inference.v2.serving.prefix import chain_digests
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

SYS = list(range(1, 18))                 # 2 full 8-token blocks


@pytest.fixture(scope="module")
def params_cfg():
    import jax
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return params, cfg


def _factory(params_cfg):
    params, cfg = params_cfg

    def engine_factory(slot):
        return InferenceEngineV2(
            params, cfg,
            RaggedInferenceEngineConfig(
                token_budget=32, max_ragged_sequence_count=4,
                n_kv_blocks=48, kv_block_size=8,
                max_blocks_per_seq=8, kv_dtype="float32"))
    return engine_factory


TIERS = {"prefix": {"max_blocks": 2,
                    "tiers": {"enabled": True, "dram_max_mb": 64.0}}}


def _router(params_cfg, serving=None, n=2):
    cfg = {"fleet": {"n_replicas": n}}
    cfg.update(serving or TIERS)
    return FleetRouter(_factory(params_cfg), cfg)


class TestDeltaTiersMap:

    def test_journal_folds_tier_records_into_the_tiers_map(
            self, params_cfg):
        fe = ServingFrontend(_factory(params_cfg)(0), TIERS)
        wc = WorkerCore(0, fe)
        d1, d2, d3, d4 = (bytes([i]) * 16 for i in range(1, 5))
        wc._journal[:] = [("add", d1), ("tier", d1, "dram"),
                          ("add", d2), ("del", d3),
                          ("tier", d4, "disk"), ("tier", d4, "hbm")]
        delta = wc._drain_delta()
        assert sorted(delta["add"]) == sorted(
            [d1.hex(), d2.hex(), d4.hex()])
        assert delta["del"] == [d3.hex()]
        # only non-hbm residents ride the tiers map; d4's later hbm
        # move (a promotion) nets the earlier spill away
        assert delta["tiers"] == {d1.hex(): "dram"}
        # no churn -> no delta, and the map key is absent when empty
        assert wc._drain_delta() is None
        wc._journal[:] = [("add", d2)]
        assert "tiers" not in wc._drain_delta()
        fe.close()

    def test_snapshot_lists_spilled_digests_with_residency(
            self, params_cfg):
        """The resync source of truth: spilled digests are SERVABLE
        (promote beats recompute) so the snapshot's trie includes
        them, with ``trie_tiers`` naming the tier."""
        fe = ServingFrontend(_factory(params_cfg)(0), TIERS)
        wc = WorkerCore(0, fe)
        r = fe.submit(SYS + [31], uid=1, max_new_tokens=2)
        fe.drain()
        assert r.state == RequestState.FINISHED
        pc = fe.engine.prefix_cache
        pc._evict(count=1)                   # spill the chain's leaf
        assert pc.spilled_blocks == 1
        snap = wc._full_snapshot("SNAPSHOT_OK")
        da = chain_digests(np.asarray(SYS + [31], np.int32), 8)
        assert set(snap["trie"]) == {d.hex() for d in da}
        assert snap["trie_tiers"] == {da[1].hex(): "dram"}
        fe.close()


class TestRouterAffinityTiers:

    def test_spill_demotes_affinity_weight_not_membership(
            self, params_cfg):
        """The fleet acceptance path: a replica-side demotion reaches
        the router as a residency update — the digest KEEPS pulling
        traffic to its home slot, at the configured DRAM discount —
        and the later promotion restores full weight."""
        router = _router(params_cfg)
        pa = np.asarray(SYS + [31], np.int32)
        pb = np.asarray(SYS[:8] + list(range(300, 310)), np.int32)
        da = chain_digests(pa, 8)

        r1 = router.submit(pa, uid=1, max_new_tokens=3)
        router.drain()
        assert r1.state == RequestState.FINISHED
        home = router._entries[1].slot
        assert all(router._affinity_map.get(d) == (home, "hbm")
                   for d in da)
        assert router._affinity(da) == (home, 2, 2.0)

        # pb shares block 0, overflows the 2-block trie on the same
        # replica -> pa's leaf DEMOTES (tiers on: not evicted)
        r2 = router.submit(pb, uid=2, max_new_tokens=3)
        assert router._entries[2].slot == home
        router.drain()
        assert r2.state == RequestState.FINISHED
        assert router._affinity_map.get(da[1]) == (home, "dram")
        slot, n, w = router._affinity(da)
        assert (slot, n) == (home, 2)
        assert w == pytest.approx(1.0 + 0.7)   # hbm + dram discount

        # resubmitting pa promotes the leaf back -> full weight again
        r3 = router.submit(pa, uid=3, max_new_tokens=3)
        router.drain()
        assert r3.state == RequestState.FINISHED
        assert router._affinity_map.get(da[1]) == (home, "hbm")
        assert router._affinity(da) == (home, 2, 2.0)
        st = router._replicas[home].engine.prefix_cache.stats()
        assert st["demoted_blocks"] >= 1
        assert st["promoted_blocks"] >= 1

    def test_tier_weights_come_from_the_fleet_config(self, params_cfg):
        cfg = {"prefix": TIERS["prefix"],
               "fleet": {"dram_affinity_weight": 0.5,
                         "disk_affinity_weight": 0.25}}
        router = _router(params_cfg, serving=cfg)
        assert router._tier_weights == {"hbm": 1.0, "dram": 0.5,
                                        "disk": 0.25}
        d = bytes(16)
        router._affinity_map.put(d, (0, "disk"))
        assert router._affinity([d]) == (0, 1, 0.25)

    def test_resync_rebuilds_tier_residency(self, params_cfg):
        """A router that lost deltas (seq gap) re-learns residency
        from the SNAPSHOT's ``trie_tiers`` — spilled digests come back
        as their tier, not as full-weight hbm."""
        router = _router(params_cfg)
        pa = np.asarray(SYS + [31], np.int32)
        da = chain_digests(pa, 8)
        r1 = router.submit(pa, uid=1, max_new_tokens=3)
        router.drain()
        home = router._entries[1].slot
        pc = router._replicas[home].engine.prefix_cache
        pc._evict(count=1)                   # out-of-band spill
        # poison the map, then force the resync path
        router._affinity_map.put(da[1], (home, "hbm"))
        router._resync(home, step=0)
        assert router._affinity_map.get(da[0]) == (home, "hbm")
        assert router._affinity_map.get(da[1]) == (home, "dram")
        slot, n, w = router._affinity(da)
        assert (slot, n, w) == (home, 2, pytest.approx(1.7))


class TestRemoteDiscountOrdering:
    """ISSUE 19 satellite: with peer block transfer enabled, remote
    residency scores through ``remote_affinity_discount`` ON TOP of
    the tier weight. The regression this pins: a replica's own DRAM
    hit must always outrank a peer's disk hit — an early transfer
    draft applied the discount to the HBM weight regardless of the
    remote tier, which ranked a peer's disk-spilled chain above local
    DRAM and shipped prefixes BACKWARD (fetching cold peer blocks
    while warm local ones sat unused)."""

    XFER = {"prefix": TIERS["prefix"],
            "fleet": {"n_replicas": 2, "transfer": {"enabled": True}}}

    def test_effective_weight_ladder(self, params_cfg):
        router = _router(params_cfg, serving=dict(self.XFER))
        disc = router._remote_discount
        w = router._tier_weights
        assert disc == pytest.approx(0.5)
        # local hbm > local dram > peer hbm > local disk
        #   > peer dram > peer disk — strictly, no ties
        ladder = [w["hbm"], w["dram"], disc * w["hbm"], w["disk"],
                  disc * w["dram"], disc * w["disk"]]
        assert ladder == sorted(ladder, reverse=True)
        assert len(set(ladder)) == len(ladder)
        # the pinned ordering itself
        assert w["dram"] > disc * w["disk"]

    def test_ranked_slots_keep_owner_ahead_of_discounted_peer(
            self, params_cfg):
        """A disk-resident chain on slot 1: slot 1 scores the full
        disk weight (0.4), slot 0 only the discounted remote value
        (0.2) — the owner stays first in the placement order."""
        router = _router(params_cfg, serving=dict(self.XFER))
        d = bytes(16)
        router._affinity_map.put(d, (1, "disk"))
        entry = types.SimpleNamespace(digests=[d])
        order, aff_slot, aff_n = router._ranked_slots(entry)
        assert (aff_slot, aff_n) == (1, 1)
        assert order[0] == 1
        assert router._affinity([d]) == \
            (1, 1, pytest.approx(router._tier_weights["disk"]))

    def test_transfer_off_scores_remote_residency_zero(self,
                                                       params_cfg):
        """Feature toggle off: the discount is exactly 0.0, so the
        scoring pass reproduces the pre-transfer behavior bit for
        bit (remote residency worth nothing)."""
        router = _router(params_cfg)
        assert router._remote_discount == 0.0
