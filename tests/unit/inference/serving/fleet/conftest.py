"""Shared fleet fixtures: ONE tiny-llama param init for the whole
package (the router and chaos modules both build engines from it;
a per-module init would pay the ~2s twice against the tier-1 wall)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="package")
def params_cfg():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return params, cfg
