"""The durable router: write-ahead request journal round-trip,
tolerant replay under corruption (the author crashed — a torn tail is
the expected case), the loopback crash-recover drill with bitwise
re-placed streams, unrecoverable-uid shedding, and the graceful
drain / rolling-restart ops."""

import json
import os

import pytest

from deepspeed_tpu.inference.v2 import FleetRouter, RequestState
from deepspeed_tpu.inference.v2.serving.fleet.journal import (
    JournalState, RequestJournal, replay)
from deepspeed_tpu.resilience.errors import (JournalCorruptionError,
                                             ServingOverloadError)
from deepspeed_tpu.resilience.fault_injector import fault_injector
from tests.unit.inference.serving.fleet.test_fleet_transport import (
    SYS, _factory, _router, _single_frontend_refs)


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


class TestJournalRoundtrip:

    def test_all_record_kinds_round_trip(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.note_epoch(3)
        j.note_submit(7, [1, 2, 3], {"max_new_tokens": 4})
        j.note_submit(8, [9], {"max_new_tokens": 2})
        j.note_place(7, 1)
        j.note_cursors({7: 2})
        j.note_cursors({7: 5, 8: 1})          # last writer wins
        j.note_cursors({})                    # empty batch: no record
        j.note_terminal(8, "FINISHED", 2)
        st = replay(p)
        assert st.exists and st.epoch == 3
        assert st.records_read == j.records_written == 7
        assert st.corrupt_records == 0
        assert st.submits[7]["prompt"] == [1, 2, 3]
        assert st.submits[7]["kwargs"]["max_new_tokens"] == 4
        assert st.placements == {7: 1}
        assert st.cursors == {7: 5, 8: 1}
        assert st.terminals[8] == {"state": "FINISHED", "n_tokens": 2}
        assert st.live_uids() == [7]          # 8 reached terminal

    def test_missing_journal_is_empty_not_an_error(self, tmp_path):
        st = replay(str(tmp_path / "never-written.jsonl"))
        assert not st.exists and st.records_read == 0
        assert st.live_uids() == [] and st.errors == []

    def test_fsync_batching(self, tmp_path):
        j = RequestJournal(str(tmp_path / "j.jsonl"), fsync_every=3)
        for uid in range(9):
            j.note_place(uid, 0)
        # first write syncs (an empty journal is the worst loss), then
        # one sync per batch — far fewer than one per record
        assert 1 <= j.fsyncs < 9
        assert j.as_dict()["records_written"] == 9

    def test_rotation_replays_both_generations(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        # ~140B/record against a 1KiB budget: exactly one rotation
        j = RequestJournal(p, max_bytes=1024)
        j.note_epoch(1)
        for uid in range(12):
            j.note_submit(uid, [100 + uid] * 20, {"max_new_tokens": 1})
        assert os.path.exists(p + ".1")
        st = replay(p)
        # the byte budget rotated the file; replay reads .1 then the
        # active generation and loses nothing
        assert set(st.submits) == set(range(12))
        assert st.submits[0]["prompt"] == [100] * 20

    def test_submit_kwargs_are_redacted(self, tmp_path):
        p = str(tmp_path / "j.jsonl")
        j = RequestJournal(p)
        j.note_submit(1, [1], {"max_new_tokens": 2, "token": "sssh"})
        with open(p) as f:
            raw = f.read()
        assert "sssh" not in raw              # a durable FILE surface
        assert replay(p).submits[1]["kwargs"]["token"] == "<redacted>"


class TestJournalCorruption:
    """The corruption drill: every damaged line degrades to a typed,
    counted ``JournalCorruptionError`` — replay NEVER raises on
    content (crashing on the dead router's journal would turn one
    outage into two)."""

    def _write(self, tmp_path, *lines):
        p = str(tmp_path / "j.jsonl")
        with open(p, "wb") as f:
            f.write(b"\n".join(lines) + b"\n")
        return p

    def test_torn_tail_and_garbage_degrade_typed(self, tmp_path):
        good = json.dumps({"rec": "submit", "uid": 1, "prompt": [5],
                           "kwargs": {}}).encode()
        p = self._write(
            tmp_path,
            json.dumps({"rec": "epoch", "epoch": 2}).encode(),
            good,
            b'{"rec": "place", "uid": 1, "slo',      # torn tail
            b"\x00\xff garbage bytes \xfe",          # binary noise
            b'[1, 2, 3]',                            # JSON, not a dict
            b'{"rec": "warp", "uid": 9}',            # unknown kind
            b'{"rec": "place", "uid": "NaN?"}')      # malformed field
        st = replay(p)
        assert st.epoch == 2 and st.live_uids() == [1]
        assert st.records_read == 2
        assert st.corrupt_records == 5
        assert all(isinstance(e, JournalCorruptionError)
                   for e in st.errors)
        assert st.as_dict()["corrupt_records"] == 5

    def test_recover_sheds_only_provably_unrecoverable(self, params_cfg,
                                                       tmp_path):
        """A uid referenced by place/cursor records whose SUBMIT line
        the journal lost has no prompt to replay from — the ONLY class
        recovery may shed; everything else is re-placed and finishes
        bitwise."""
        ref = _single_frontend_refs(params_cfg, {1: SYS[0] + [42]}, 4)
        p = self._write(
            tmp_path,
            json.dumps({"rec": "epoch", "epoch": 1}).encode(),
            json.dumps({"rec": "submit", "uid": 1,
                        "prompt": SYS[0] + [42],
                        "kwargs": {"max_new_tokens": 4}}).encode(),
            json.dumps({"rec": "place", "uid": 1, "slot": 0}).encode(),
            # uid 2's submit record never made it / was torn:
            json.dumps({"rec": "place", "uid": 2, "slot": 1}).encode(),
            json.dumps({"rec": "cursors", "c": {"2": 3}}).encode())
        router = FleetRouter.recover(_factory(params_cfg),
                                     {"fleet": {"n_replicas": 2}},
                                     journal_path=p)
        rs = router.recover_stats
        assert rs["shed_unrecoverable"] == 1 and rs["shed_uids"] == [2]
        assert rs["replaced"] == 1            # loopback: no survivors
        router.drain()
        req = router.get_request(1)
        assert req.state == RequestState.FINISHED
        assert list(req.tokens) == ref[1]     # bitwise from position 0
        # the shed was journaled terminal: a SECOND recovery of the
        # same journal does not re-shed (idempotent)
        router2 = FleetRouter.recover(_factory(params_cfg),
                                      {"fleet": {"n_replicas": 2}},
                                      journal_path=p)
        assert router2.recover_stats["shed_unrecoverable"] == 0
        assert router2.epoch == router.epoch + 1


class TestRouterJournalWiring:

    def _recs(self, path):
        out = []
        with open(path) as f:
            for line in f:
                if line.strip():
                    out.append(json.loads(line))
        return out

    def test_write_ahead_order_and_terminals(self, params_cfg,
                                             tmp_path):
        p = str(tmp_path / "j.jsonl")
        router = _router(params_cfg, n=2, journal=p)
        r = router.submit(SYS[0] + [30], uid=5, max_new_tokens=3)
        router.submit(SYS[1] + [31], uid=6, max_new_tokens=3)
        router.drain()
        assert r.state == RequestState.FINISHED
        recs = self._recs(p)
        assert recs[0] == {"rec": "epoch", "epoch": 1}
        for uid in (5, 6):
            kinds = [(i, rec["rec"]) for i, rec in enumerate(recs)
                     if rec.get("uid") == uid or
                     str(uid) in (rec.get("c") or {})]
            order = [k for _, k in kinds]
            # submit journals BEFORE place (write-ahead), terminal last
            assert order[0] == "submit" and order[1] == "place"
            assert order[-1] == "terminal"
        st = replay(p)
        assert st.live_uids() == []           # everything terminal
        assert st.terminals[5]["state"] == "FINISHED"
        assert st.terminals[5]["n_tokens"] == 3
        # delivered cursors were batched per step, not per token
        n_cursor_recs = sum(1 for rec in recs if rec["rec"] == "cursors")
        assert 0 < n_cursor_recs <= router._step_idx

    def test_refused_submit_journals_terminal_shed(self, params_cfg,
                                                   tmp_path):
        p = str(tmp_path / "j.jsonl")
        router = _router(params_cfg, n=1, journal=p,
                         serving={"max_queue_depth": 2})
        router.submit(SYS[0] + [1], uid=1, max_new_tokens=2)
        router.submit(SYS[1] + [2], uid=2, max_new_tokens=2)
        with pytest.raises(ServingOverloadError):
            router.submit(SYS[2] + [3], uid=3, max_new_tokens=2)
        st = replay(p)
        # the refused uid is submit+terminal SHED: a recovery of this
        # journal must not resurrect a request the caller saw refused
        assert st.terminals[3]["state"] == "SHED"
        assert sorted(st.live_uids()) == [1, 2]
        router.drain()

    def test_bootstrap_report_block(self, params_cfg, tmp_path):
        p = str(tmp_path / "j.jsonl")
        router = _router(params_cfg, n=1, journal=p)
        router.submit(SYS[0] + [9], max_new_tokens=2)
        router.drain()
        boot = router.get_fleet_report()["bootstrap"]
        assert boot["channel"] == "loopback" and boot["epoch"] == 1
        assert boot["journal"]["records_written"] > 0
        assert boot["listener"] is None and boot["recover"] is None
        assert boot["drains"] == 0 and boot["draining"] == []


class TestLoopbackCrashRecover:
    """Kill-router drill, loopback flavor: no workers survive a
    loopback crash (they live in the router process), so EVERY live
    uid exercises the re-place path — bitwise replay from position 0
    via the fold_in sampling-key contract."""

    @pytest.mark.slow  # tier-1 diet (PR 17): bootstrap's kill-router-mid-decode drill keeps journal recovery bitwise tier-1
    def test_crash_mid_decode_recover_replays_bitwise(self, params_cfg,
                                                      tmp_path):
        N = 4
        reqs = {600 + k: SYS[k % 3] + [20 + k] for k in range(N)}
        refs = _single_frontend_refs(params_cfg, reqs, 5)
        p = str(tmp_path / "j.jsonl")
        router = _router(params_cfg, n=2, journal=p)
        for uid, prompt in reqs.items():
            router.submit(prompt, uid=uid, max_new_tokens=5)
            router.step()
        live = [e for e in router._entries.values() if not e.req.done]
        assert live and any(e.seen > 0 for e in live)  # mid-decode
        live_uids = sorted(e.req.uid for e in live)

        router.crash()
        router2 = FleetRouter.recover(_factory(params_cfg),
                                      {"fleet": {"n_replicas": 2}},
                                      journal_path=p)
        assert router2.epoch == 2
        rs = router2.recover_stats
        assert rs["replaced"] == len(live_uids)
        assert rs["attached"] == 0            # loopback: none survive
        assert rs["shed_unrecoverable"] == 0
        router2.drain()
        for uid in live_uids:
            req = router2.get_request(uid)
            assert req.state == RequestState.FINISHED
            assert list(req.tokens) == refs[uid], uid
        assert router2.replay_mismatches == 0
        assert router2.abandoned == 0
        # zero double delivery: the recovered streams are exactly the
        # reference length, not reference + replayed prefix
        for uid in live_uids:
            assert len(router2.result(uid)) == len(refs[uid])


class TestDrainRollingRestart:

    def test_drain_replica_smoke(self, params_cfg):
        router = _router(params_cfg, n=2)
        reqs = {70 + k: SYS[k % 3] + [10 + k] for k in range(4)}
        refs = _single_frontend_refs(params_cfg, reqs, 4)
        handles = {uid: router.submit(pr, uid=uid, max_new_tokens=4)
                   for uid, pr in reqs.items()}
        victim = next(e.slot for e in router._entries.values())
        steps = router.drain_replica(victim)
        assert steps > 0
        assert victim not in router.pooled_replicas
        # the drained replica's work finished IN PLACE: no deaths, no
        # requeue, no replay
        rec = router.get_fleet_report()["recovery"]
        assert rec["drains"] == 1 and rec["deaths"] == 0
        assert rec["requeued"] == 0
        ev = rec["events"][-1]
        assert ev["mode"] == "drain" and ev["slot"] == victim
        assert not ev["requeued_uids"]
        # new work places on the survivor only
        r = router.submit(SYS[0] + [99], uid=99, max_new_tokens=3)
        assert router._entries[99].slot != victim
        router.drain()
        assert r.state == RequestState.FINISHED
        for uid, h in handles.items():
            assert h.state == RequestState.FINISHED
            assert list(h.tokens) == refs[uid], uid
        # the restart half: respawn re-admits the drained slot
        assert router._respawn(victim, router._step_idx)
        assert sorted(router.pooled_replicas) == [0, 1]

    def test_drain_unknown_slot_is_typed(self, params_cfg):
        router = _router(params_cfg, n=1)
        with pytest.raises(ValueError, match="not in the pool"):
            router.drain_replica(5)

    @pytest.mark.slow
    def test_rolling_restart_under_traffic(self, params_cfg):
        """The runbook drill: drain -> respawn each replica in turn
        while requests keep arriving; every stream bitwise, zero
        deaths, zero requeues — a rolling restart is invisible to
        callers."""
        N = 8
        reqs = {500 + k: SYS[k % 3] + [80 + k] for k in range(N)}
        refs = _single_frontend_refs(params_cfg, reqs, 4)
        router = _router(params_cfg, n=2)
        handles = {}
        uids = list(reqs)
        for phase_slot in (0, 1):
            for _ in range(2):
                uid = uids.pop(0)
                handles[uid] = router.submit(reqs[uid], uid=uid,
                                             max_new_tokens=4)
                router.step()
            router.drain_replica(phase_slot)
            assert router._respawn(phase_slot, router._step_idx)
        while uids:
            uid = uids.pop(0)
            handles[uid] = router.submit(reqs[uid], uid=uid,
                                         max_new_tokens=4)
            router.step()
        router.drain()
        for uid, h in handles.items():
            assert h.state == RequestState.FINISHED
            assert list(h.tokens) == refs[uid], uid
        rec = router.get_fleet_report()["recovery"]
        assert rec["drains"] == 2 and rec["deaths"] == 0
        assert rec["requeued"] == 0
        assert sorted(router.pooled_replicas) == [0, 1]
