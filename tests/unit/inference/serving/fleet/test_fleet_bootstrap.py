"""Multi-host fleet bootstrap: the dial-in JOIN handshake (HMAC
challenge-response auth + fencing epochs, both directions), secret
redaction, the SocketChannel teardown/orphan regression, per-target
channel faults, and the remote-channel acceptance e2e — the router
killed mid-decode, a fresh one ``recover()``-ing from the journal with
every finished stream bitwise identical to the undisturbed run."""

import json
import os
import socket
import subprocess
import threading
import time

import pytest

from deepspeed_tpu.inference.v2 import FleetRouter, RequestState
from deepspeed_tpu.inference.v2.serving.fleet.replica import Replica
from deepspeed_tpu.inference.v2.serving.fleet.transport import (
    MSG_HELLO, MSG_JOIN, MSG_JOIN_CHALLENGE, MSG_JOIN_OK, MSG_SHUTDOWN,
    PROTOCOL_VERSION, FleetListener, RpcClient, SocketChannel,
    encode_frame, join_mac, recv_frame, redact_auth, remote_connector,
    server_ssl_context, worker_join)
from deepspeed_tpu.inference.v2.serving.fleet.worker import (
    WorkerCore, run_dialin_worker, spawn_dialin_workers)
from deepspeed_tpu.inference.v2.serving.frontend import ServingFrontend
from deepspeed_tpu.resilience.errors import (BootstrapAuthError,
                                             FencingError,
                                             TransportConnectError,
                                             TransportDecodeError)
from deepspeed_tpu.resilience.fault_injector import fault_injector
from tests.unit.inference.serving.fleet.test_fleet_transport import (
    SYS, _FakeFrontend, _factory, _single_frontend_refs, _tcfg)

TOK = "bootstrap-drill-secret"
OPENSSL = "/usr/bin/openssl"


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def _dial(listener, *, slot=0, token="", epoch=0, caps=None,
          poll_s=6.0):
    """One worker-side dial + JOIN against a live listener (the
    listener's accept loop runs here, the dial in a thread — both
    halves of the handshake block on each other)."""
    out = {}

    def worker():
        try:
            s = socket.create_connection(
                (listener.host, listener.port), timeout=5.0)
        except OSError as e:
            out["exc"] = e
            return
        try:
            out["epoch"] = worker_join(s, slot=slot, token=token,
                                       epoch=epoch, capabilities=caps)
            out["sock"] = s
        except BaseException as e:
            out["exc"] = e
            s.close()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    deadline = time.monotonic() + poll_s
    while t.is_alive() and time.monotonic() < deadline:
        listener.poll_join(0.2)
    t.join(5.0)
    assert not t.is_alive(), "handshake deadlocked"
    return out


class TestRedaction:

    def test_exact_keys_redacted_deep(self):
        obj = {"token": "s3cret", "nested": [{"mac": "ff", "slot": 1}],
               "listener": {"nonce": "aa", "address": "h:1"}}
        r = redact_auth(obj)
        assert r["token"] == "<redacted>"
        assert r["nested"][0]["mac"] == "<redacted>"
        assert r["nested"][0]["slot"] == 1
        assert r["listener"]["nonce"] == "<redacted>"
        assert "s3cret" not in json.dumps(r)
        # the input is untouched (deep copy, not mutation)
        assert obj["token"] == "s3cret"

    def test_exact_match_not_substring(self):
        # telemetry names sharing a substring stay readable
        r = redact_auth({"tokens": [1, 2], "n_tokens": 7,
                         "token_budget": 32, "machine": "h9",
                         "token_env": "DSTPU_FLEET_TOKEN"})
        assert r == {"tokens": [1, 2], "n_tokens": 7,
                     "token_budget": 32, "machine": "h9",
                     "token_env": "DSTPU_FLEET_TOKEN"}

    def test_empty_values_pass_through(self):
        # an operator must be able to SEE that auth is unconfigured
        assert redact_auth({"token": ""}) == {"token": ""}


class TestJoinMac:

    def test_mac_binds_epoch_and_slot(self):
        base = join_mac(TOK, "nonce", 3, 1)
        assert join_mac(TOK, "nonce", 3, 1) == base
        assert join_mac(TOK, "nonce", 4, 1) != base   # epoch bound
        assert join_mac(TOK, "nonce", 3, 2) != base   # slot bound
        assert join_mac(TOK, "other", 3, 1) != base   # nonce bound
        assert join_mac("other", "nonce", 3, 1) != base


class TestRecvFrame:

    def test_torn_and_timeout_and_eof(self):
        a, b = socket.socketpair()
        try:
            # bad magic is a typed decode error
            b.sendall(b"XXXX" + b"\x00" * 6)
            with pytest.raises(TransportDecodeError):
                recv_frame(a, timeout=1.0)
            # nothing arriving is a ConnectionError, not a hang
            with pytest.raises(ConnectionError):
                recv_frame(a, timeout=0.2)
            # peer death mid-frame is a ConnectionError
            b.sendall(encode_frame({"id": 0, "kind": "JOIN"})[:5])
            b.close()
            with pytest.raises(ConnectionError):
                recv_frame(a, timeout=1.0)
        finally:
            a.close()


class TestJoinHandshake:

    def test_good_join_parks_slot_and_adopts_epoch(self):
        lst = FleetListener(token=TOK, epoch=3)
        try:
            out = _dial(lst, slot=1, token=TOK, epoch=0,
                        caps={"host": "w1"})
            assert out.get("epoch") == 3       # worker adopts it
            assert lst.parked_slots == (1,)
            assert lst.capabilities(1)["host"] == "w1"
            assert lst.joins == 1 and lst.auth_failures == 0
            out["sock"].close()
        finally:
            lst.close()

    def test_wrong_token_is_typed_and_not_parked(self):
        lst = FleetListener(token=TOK, epoch=1)
        try:
            out = _dial(lst, slot=0, token="wrong", epoch=0)
            assert isinstance(out.get("exc"), BootstrapAuthError)
            assert lst.auth_failures == 1 and lst.joins == 0
            assert lst.parked_slots == ()
        finally:
            lst.close()

    def test_newer_worker_epoch_is_fenced(self):
        """Split-brain: a worker already owned by a LATER router
        generation must be refused by this (stale) one."""
        lst = FleetListener(token=TOK, epoch=3)
        try:
            out = _dial(lst, slot=0, token=TOK, epoch=9)
            e = out.get("exc")
            assert isinstance(e, FencingError)
            assert e.worker_epoch == 9 and e.router_epoch == 3
            assert lst.fenced == 1 and lst.joins == 0
        finally:
            lst.close()

    def test_long_partitioned_stray_is_fenced(self):
        lst = FleetListener(token=TOK, epoch=5)
        try:
            out = _dial(lst, slot=0, token=TOK, epoch=1)
            assert isinstance(out.get("exc"), FencingError)
            assert lst.fenced == 1
        finally:
            lst.close()

    def test_admission_window_fresh_own_and_previous(self):
        lst = FleetListener(token=TOK, epoch=5)
        try:
            for slot, epoch in ((0, 0), (1, 5), (2, 4)):
                out = _dial(lst, slot=slot, token=TOK, epoch=epoch)
                assert out.get("epoch") == 5, (slot, epoch, out)
                out["sock"].close()
            assert lst.joins == 3 and lst.fenced == 0
        finally:
            lst.close()

    def test_worker_fences_stale_router(self):
        """The worker side of fencing: a stale router generation that
        somehow passes the listener check (or skips auth) must not
        reclaim a worker that already joined a newer one."""
        for reply in (
            {"v": PROTOCOL_VERSION, "id": 0,
             "kind": MSG_JOIN_CHALLENGE, "nonce": "n", "epoch": 1},
            {"v": PROTOCOL_VERSION, "id": 0, "kind": MSG_JOIN_OK,
             "epoch": 1},
        ):
            a, b = socket.socketpair()

            def stale_router(r=reply, sock=b):
                msg = recv_frame(sock, 5.0)
                assert msg["kind"] == MSG_JOIN
                sock.sendall(encode_frame(r))

            t = threading.Thread(target=stale_router, daemon=True)
            t.start()
            with pytest.raises(FencingError) as ei:
                worker_join(a, slot=0, token=TOK, epoch=5)
            assert ei.value.router_epoch == 1
            assert ei.value.worker_epoch == 5
            t.join(5.0)
            a.close()
            b.close()

    def test_split_brain_drill_newer_router_wins(self):
        """Two routers claim the fleet: the worker ends up owned by
        the NEWER epoch, and the older router cannot take it back."""
        old = FleetListener(token=TOK, epoch=2)
        new = FleetListener(token=TOK, epoch=3)
        try:
            out = _dial(new, slot=0, token=TOK, epoch=0)
            assert out.get("epoch") == 3
            out["sock"].close()
            # the stale router's reclaim attempt is refused typed
            out2 = _dial(old, slot=0, token=TOK, epoch=3)
            assert isinstance(out2.get("exc"), FencingError)
            assert old.fenced == 1 and old.joins == 0
            # the owning router re-admits its own epoch
            out3 = _dial(new, slot=0, token=TOK, epoch=3)
            assert out3.get("epoch") == 3
            out3["sock"].close()
        finally:
            old.close()
            new.close()

    def test_no_auth_mode_skips_challenge(self):
        lst = FleetListener(token="", epoch=1, require_auth=False)
        try:
            out = _dial(lst, slot=0, token="", epoch=0)
            assert out.get("epoch") == 1 and lst.joins == 1
            out["sock"].close()
        finally:
            lst.close()

    def test_require_auth_demands_a_token(self):
        with pytest.raises(ValueError, match="token"):
            FleetListener(token="", require_auth=True)

    def test_garbage_dialer_does_not_break_the_listener(self):
        lst = FleetListener(token=TOK, epoch=1)
        try:
            s = socket.create_connection((lst.host, lst.port),
                                         timeout=5.0)
            s.sendall(b"GET / HTTP/1.0\r\n\r\n")
            assert lst.poll_join(2.0) is None
            s.close()
            assert lst.handshake_errors == 1
            # and a real worker still gets in afterwards
            out = _dial(lst, slot=0, token=TOK)
            assert out.get("epoch") == 1
            out["sock"].close()
        finally:
            lst.close()

    def test_take_deadline_is_typed(self):
        lst = FleetListener(token=TOK, epoch=1)
        try:
            with pytest.raises(TransportConnectError, match="slot 3"):
                lst.take(3, deadline_s=0.2)
        finally:
            lst.close()

    def test_rejoin_replaces_parked_socket(self):
        lst = FleetListener(token=TOK, epoch=1)
        try:
            out1 = _dial(lst, slot=0, token=TOK)
            out2 = _dial(lst, slot=0, token=TOK, epoch=1)
            assert lst.joins == 2 and lst.parked_slots == (0,)
            taken = lst.take(0, deadline_s=1.0)
            assert taken is not out1["sock"]   # the re-dial won
            taken.close()
            out1["sock"].close()
            out2["sock"].close()
        finally:
            lst.close()

    def test_listener_report_is_secret_free(self):
        lst = FleetListener(token=TOK, epoch=2)
        try:
            d = lst.as_dict()
            assert d["require_auth"] is True and d["epoch"] == 2
            assert TOK not in json.dumps(d)
        finally:
            lst.close()


class _FakeProc:
    """Popen-shaped recorder for the teardown regression tests."""

    def __init__(self, ignores_terminate=False):
        self.returncode = None
        self.calls = []
        self._stubborn = ignores_terminate

    def poll(self):
        self.calls.append("poll")
        return self.returncode

    def terminate(self):
        self.calls.append("terminate")
        if not self._stubborn:
            self.returncode = -15

    def kill(self):
        self.calls.append("kill")
        self.returncode = -9

    def wait(self, timeout=None):
        self.calls.append("wait")
        if self.returncode is None:
            raise subprocess.TimeoutExpired("worker", timeout)
        return self.returncode


class TestSocketChannelTeardown:
    """The connect-failure audit: no orphaned worker process and no
    half-open socket survives any teardown path."""

    def test_close_reaps_child_and_shuts_socket(self):
        a, b = socket.socketpair()
        proc = _FakeProc()
        ch = SocketChannel(lambda: (proc, a))
        ch.connect()
        ch.close()
        assert "terminate" in proc.calls and "wait" in proc.calls
        assert proc.returncode == -15
        # the peer sees EOF, not a half-open hang
        b.settimeout(1.0)
        assert b.recv(1) == b""
        b.close()
        assert a.fileno() == -1               # really closed

    def test_close_is_idempotent(self):
        a, b = socket.socketpair()
        proc = _FakeProc()
        ch = SocketChannel(lambda: (proc, a))
        ch.connect()
        ch.close()
        n = len(proc.calls)
        ch.close()                            # second close: no-op
        ch.close()
        assert len(proc.calls) == n
        b.close()

    def test_close_escalates_to_kill(self):
        a, b = socket.socketpair()
        proc = _FakeProc(ignores_terminate=True)
        ch = SocketChannel(lambda: (proc, a))
        ch.connect()
        ch.close()
        assert "kill" in proc.calls           # past the grace period
        assert proc.returncode == -9
        assert proc.calls.count("wait") == 2  # reaped after the kill
        b.close()

    def test_worker_death_before_hello_leaks_nothing(self):
        """The regression: a worker that dialed back and died before
        answering HELLO used to leave a zombie child and a half-open
        socket pinned to the failed Replica."""
        a, b = socket.socketpair()
        b.close()                 # died between dial-back and HELLO
        proc = _FakeProc()
        with pytest.raises(Exception):
            Replica(0, lambda slot: SocketChannel(lambda: (proc, a)),
                    _tcfg(rpc_retries=0, rpc_deadline_seconds=0.5,
                          connect_deadline_seconds=0.5))
        assert proc.returncode is not None    # child reaped
        assert "wait" in proc.calls
        assert a.fileno() == -1               # socket closed


class TestDialinWorkerLoop:

    def test_serve_and_shutdown_roundtrip(self):
        lst = FleetListener(token=TOK, epoch=1)
        core = WorkerCore(0, _FakeFrontend())
        t = threading.Thread(target=run_dialin_worker,
                             args=(core, lst.address),
                             kwargs=dict(token=TOK), daemon=True)
        t.start()
        try:
            ch = SocketChannel(remote_connector(lst, 0, 10.0))
            ch.connect()
            rpc = RpcClient(ch, 0, _tcfg())
            assert rpc.call(MSG_HELLO)["kind"] == "HELLO_OK"
            assert rpc.call(MSG_SHUTDOWN)["kind"] == "BYE"
            t.join(10.0)
            assert not t.is_alive()           # SHUTDOWN ended the loop
            ch.close()
        finally:
            core.shutdown = True
            lst.close()
            t.join(5.0)

    def test_auth_refusal_propagates_and_is_not_retried(self):
        lst = FleetListener(token=TOK, epoch=1)
        core = WorkerCore(0, _FakeFrontend())
        box = {}

        def run():
            try:
                run_dialin_worker(core, lst.address, token="wrong",
                                  max_dials=5)
            except BootstrapAuthError as e:
                box["exc"] = e

        t = threading.Thread(target=run, daemon=True)
        t.start()
        deadline = time.monotonic() + 6.0
        try:
            while t.is_alive() and time.monotonic() < deadline:
                lst.poll_join(0.2)
            t.join(5.0)
            assert isinstance(box.get("exc"), BootstrapAuthError)
            # ONE refusal, not five: the same secret cannot start
            # passing, so hammering the router is forbidden
            assert lst.auth_failures == 1
        finally:
            core.shutdown = True
            lst.close()
            t.join(5.0)

    def test_redial_survives_listener_restart(self):
        """A router crash is just a dropped connection to the worker:
        the dial loop backs off and joins whichever generation answers
        the address next, adopting its epoch."""
        lst1 = FleetListener(token=TOK, epoch=1)
        port = lst1.port
        core = WorkerCore(0, _FakeFrontend())
        t = threading.Thread(target=run_dialin_worker,
                             args=(core, lst1.address),
                             kwargs=dict(token=TOK), daemon=True)
        t.start()
        lst2 = None
        try:
            s1 = lst1.take(0, deadline_s=10.0)
            lst1.close()                      # the crash
            s1.close()
            lst2 = FleetListener("127.0.0.1", port, token=TOK, epoch=2)
            s2 = lst2.take(0, deadline_s=10.0)
            assert lst2.joins == 1            # the worker re-dialed
            s2.close()
        finally:
            core.shutdown = True
            lst1.close()
            if lst2 is not None:
                lst2.close()
            t.join(5.0)


class TestPerTargetChannelFaults:
    """`transport.send@replica1:drop~0.2`-style specs through the real
    channel: the fault lands on ONE replica's traffic, counted on that
    target's own ordinal."""

    def test_targeted_drop_spares_the_other_replica(self):
        from deepspeed_tpu.inference.v2.serving.fleet.transport import (
            FaultyChannel, LoopbackChannel)
        from tests.unit.inference.serving.fleet.test_fleet_transport \
            import _EchoCore
        cores = {s: _EchoCore() for s in (0, 1)}
        chans = {s: FaultyChannel(LoopbackChannel(cores[s]), slot=s)
                 for s in (0, 1)}
        for ch in chans.values():
            ch.connect()
        fault_injector.configure("transport.send@replica1:drop~0.5")
        for i in range(40):
            for s in (0, 1):
                chans[s].send(encode_frame(
                    {"id": i, "kind": "HEARTBEAT"}))
        fault_injector.reset()
        assert cores[0].handled == 40          # untargeted: untouched
        assert 5 < cores[1].handled < 35       # targeted: ~50% dropped
        assert chans[0].injected == 0
        assert chans[1].injected > 0


def _start_workers(params_cfg, address, n, token, **dial_kw):
    """N dial-in worker THREADS with real tiny-llama engines — the
    tier-1 stand-in for out-of-band worker processes (real loopback
    TCP, real handshake; the process variant rides the slow tier)."""
    cores, threads = [], []
    for slot in range(n):
        fe = ServingFrontend(_factory(params_cfg)(slot),
                             {"on_overload": "raise"})
        core = WorkerCore(slot, fe)
        t = threading.Thread(target=run_dialin_worker,
                             args=(core, address),
                             kwargs=dict(token=token, **dial_kw),
                             daemon=True)
        t.start()
        cores.append(core)
        threads.append(t)
    return cores, threads


def _stop_workers(cores, threads, *listeners):
    for c in cores:
        c.shutdown = True
    for lst in listeners:
        if lst is not None:
            lst.close()
    for t in threads:
        t.join(10.0)


def _remote_cfg(journal_path=None):
    cfg = {"fleet": {"n_replicas": 2,
                     "transport": {"channel": "remote"},
                     "bootstrap": {"join_deadline_seconds": 30.0}}}
    if journal_path:
        cfg["fleet"]["bootstrap"]["journal_path"] = journal_path
    return cfg


def _kill_router_drill(params_cfg, router1, lst):
    """Shared core of the acceptance e2e: staggered traffic through
    ``router1``, killed mid-decode, a fresh router recovered from the
    journal + the surviving workers — returns (router2, refs,
    live_uids). Streams router1 finished BEFORE the crash are asserted
    bitwise here; the live ones are router2's to finish."""
    N = 6
    reqs = {800 + k: SYS[k % 3] + [50 + k] for k in range(N)}
    refs = _single_frontend_refs(params_cfg, reqs, 6)
    port = lst.port
    jpath = router1._journal.path

    handles = {}
    for uid, prompt in reqs.items():
        handles[uid] = router1.submit(prompt, uid=uid,
                                      max_new_tokens=6)
        router1.step()
    for _ in range(3):
        router1.step()
    live = [e for e in router1._entries.values() if not e.req.done]
    assert live, "drill must catch requests mid-flight"
    assert any(e.req.state == RequestState.DECODE for e in live)
    assert any(e.seen > 0 for e in live)      # tokens already streamed
    live_uids = sorted(e.req.uid for e in live)
    for uid, h in handles.items():            # pre-crash deliveries
        if uid not in live_uids and h.state == RequestState.FINISHED:
            assert list(h.tokens) == refs[uid], uid

    router1.crash()                           # die abruptly
    # the next generation answers the SAME advertised address
    lst2 = FleetListener("127.0.0.1", port, token=TOK, epoch=1)
    router2 = FleetRouter.recover(_factory(params_cfg),
                                  _remote_cfg(), journal_path=jpath,
                                  listener=lst2)
    assert router2.epoch == router1.epoch + 1
    assert router2._listener.epoch == router2.epoch
    rs = router2.recover_stats
    assert rs["attached"] + rs["replaced"] == len(live_uids)
    assert rs["attached"] >= 1                # survivors were reused
    assert rs["shed_unrecoverable"] == 0
    router2.drain()
    return router2, refs, live_uids


def _assert_bitwise_and_quiet(router2, refs, live_uids, frontends):
    for uid in live_uids:
        req = router2.get_request(uid)
        assert req is not None and req.state == RequestState.FINISHED
        assert list(req.tokens) == refs[uid], uid
    assert router2.replay_mismatches == 0
    assert router2.abandoned == 0
    for slot, fe in frontends.items():
        rep = fe.get_serving_report()
        assert rep["recompiles"] <= 1, slot
        assert rep["steady_blocking_syncs"] == 0, slot
    report = router2.get_fleet_report()
    blob = json.dumps(report)
    assert TOK not in blob                    # secrets never surface
    boot = report["bootstrap"]
    assert boot["channel"] == "remote" and boot["epoch"] == 2
    assert boot["recover"]["attached"] >= 1
    assert boot["journal"]["records_written"] > 0


class TestRemoteBootstrapE2E:
    """The acceptance drill, tier-1 flavor: dial-in worker THREADS
    over real loopback TCP with HMAC auth, the router killed
    mid-decode, recovery via journal replay + SNAPSHOT re-attach."""

    def test_kill_router_mid_decode_recovers_bitwise(self, params_cfg,
                                                     tmp_path):
        lst = FleetListener(token=TOK, epoch=1)
        cores, threads = _start_workers(params_cfg, lst.address, 2,
                                        TOK)
        router2 = None
        try:
            router1 = FleetRouter(
                _factory(params_cfg), _remote_cfg(),
                listener=lst,
                journal=str(tmp_path / "fleet.journal"))
            assert lst.joins >= 2             # both workers admitted
            router2, refs, live_uids = _kill_router_drill(
                params_cfg, router1, lst)
            frontends = {c.slot: c.frontend for c in cores}
            _assert_bitwise_and_quiet(router2, refs, live_uids,
                                      frontends)
        finally:
            if router2 is not None:
                for slot in list(router2.pooled_replicas):
                    router2._replicas[slot].detach()
            _stop_workers(cores, threads, lst,
                          router2._listener if router2 else None)

    @pytest.mark.skipif(not os.path.exists(OPENSSL),
                        reason="openssl binary unavailable")
    def test_ssl_dialin_variant(self, params_cfg, tmp_path):
        """Opt-in TLS on the dial-in channel (stdlib ssl, self-signed
        cert): handshake + one HELLO round-trip, report flags ssl."""
        cert, key = str(tmp_path / "c.pem"), str(tmp_path / "k.pem")
        subprocess.run(
            [OPENSSL, "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "1",
             "-subj", "/CN=127.0.0.1"],
            check=True, capture_output=True)
        lst = FleetListener(token=TOK, epoch=1,
                            ssl_context=server_ssl_context(cert, key))
        core = WorkerCore(0, _FakeFrontend())
        t = threading.Thread(target=run_dialin_worker,
                             args=(core, lst.address),
                             kwargs=dict(token=TOK, ssl_cafile=cert),
                             daemon=True)
        t.start()
        try:
            ch = SocketChannel(remote_connector(lst, 0, 15.0))
            ch.connect()
            rpc = RpcClient(ch, 0, _tcfg())
            assert rpc.call(MSG_HELLO)["kind"] == "HELLO_OK"
            assert lst.as_dict()["ssl"] is True
            rpc.call(MSG_SHUTDOWN)
            ch.close()
        finally:
            core.shutdown = True
            lst.close()
            t.join(10.0)

    @pytest.mark.slow
    def test_kill_router_with_real_worker_processes(self, params_cfg,
                                                    tmp_path):
        """The multi-HOST shape for real: workers are OS processes
        launched out-of-band (`spawn_dialin_workers`), the token
        travels via the environment, the router dies and a fresh one
        recovers — streams still bitwise vs the single-frontend run.
        Slow tier: two worker cold starts (jax import + engine)."""
        lst = FleetListener(token=TOK, epoch=1)
        procs = spawn_dialin_workers(
            2, lst.address,
            serving_cfg_dict={"on_overload": "raise"},
            extra_env={"DSTPU_FLEET_TOKEN": TOK})
        router2 = None
        try:
            router1 = FleetRouter(
                _factory(params_cfg), _remote_cfg(),
                listener=lst,
                journal=str(tmp_path / "fleet.journal"))
            router2, refs, live_uids = _kill_router_drill(
                params_cfg, router1, lst)
            for uid in live_uids:
                req = router2.get_request(uid)
                assert req.state == RequestState.FINISHED
                assert list(req.tokens) == refs[uid], uid
            assert router2.replay_mismatches == 0
            report = router2.get_fleet_report()
            assert TOK not in json.dumps(report)
            for slot in router2.pooled_replicas:
                snap = router2._replicas[slot].snapshot()
                assert snap["recompiles"] <= 1, slot
            # graceful goodbye: SHUTDOWN ends each worker process
            for slot in list(router2.pooled_replicas):
                router2._replicas[slot].detach()
            for p in procs:
                assert p.wait(timeout=30.0) == 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10.0)
            lst.close()
            if router2 is not None and router2._listener is not None:
                router2._listener.close()
