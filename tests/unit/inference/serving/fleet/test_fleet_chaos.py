"""Seeded fleet chaos drills: randomized (failure mode x victim
replica x fault step) injected through the ``fleet.dispatch`` site
under shared-prefix traffic, with the recovery invariants asserted
inside the drill (the ``tools/pg_sim/chaos.py`` pattern, serving
flavor):

* every accepted request FINISHES, its stream bitwise identical to an
  undisturbed single-frontend run (gap-free, duplicate-free across
  the requeue);
* block conservation on every pooled replica (no KV leaked by the
  evacuation);
* the recovery is recorded: one death in the drawn mode, MTTR > 0,
  zero replay mismatches.

Tier-1 keeps a 2-replica seed-matrixed smoke; the heavy variants
(N>=3 replicas, 100+ request churn) ride the slow+soak tier from the
start (the ISSUE 11 budget: whole fleet suite <= ~25s tier-1).
"""

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import RequestState
from deepspeed_tpu.resilience.fault_injector import fault_injector

from tests.unit.inference.serving.fleet.test_fleet_router import (
    SYS, _assert_replicas_clean, _router, _single_frontend_refs)

DEFAULT_MODES = ("kill", "hang", "slow")


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def run_fleet_chaos_drill(seed, params_cfg, n_replicas=2,
                          n_requests=6, max_new_tokens=4,
                          modes=DEFAULT_MODES, submit_every=1):
    """One randomized drill over a live fleet; asserts the invariants
    and returns a summary dict (the chaos.py shape: drawn mode/victim/
    step + the fleet report)."""
    rng = np.random.default_rng(seed)
    mode = str(rng.choice(list(modes)))
    victim = int(rng.integers(0, n_replicas))
    # fault after traffic is in flight and before the trace drains
    fault_step = int(rng.integers(2, 6))
    duration = 50 if mode in ("hang", "slow") else None

    mix = [int(rng.integers(0, len(SYS))) for _ in range(n_requests)]
    reqs_in = {1000 + k: SYS[mix[k]] + [300 + k]
               for k in range(n_requests)}
    refs = _single_frontend_refs(params_cfg, reqs_in, max_new_tokens)

    # tight logical deadlines so a hung/slow victim is detected within
    # a couple of router steps (drills stay cheap and deterministic)
    router = _router(params_cfg, n=n_replicas,
                     serving={"fleet": {
                         "n_replicas": n_replicas,
                         "heartbeat_timeout_steps": 1,
                         "progress_timeout_steps": 2}})
    spec = router.spec_for(victim, fault_step, mode, duration=duration)
    fault_injector.configure(spec)
    handles = {}

    def poll(r, step):
        while (len(handles) < n_requests
               and step >= submit_every * len(handles)):
            uid = 1000 + len(handles)
            handles[uid] = r.submit(reqs_in[uid], uid=uid,
                                    max_new_tokens=max_new_tokens)
        return len(handles) < n_requests

    try:
        router.serve(poll=poll)
    finally:
        fault_injector.reset()

    rep = router.get_fleet_report()
    # ---- invariants ----
    assert len(handles) == n_requests
    for uid, r in handles.items():
        assert r.state == RequestState.FINISHED, (spec, uid)
        assert r.tokens == refs[uid], (spec, uid)   # gap/dup-free
    rec = rep["recovery"]
    assert rec["deaths"] == 1, spec
    assert rec["events"][0]["mode"] == mode, spec
    assert rec["events"][0]["slot"] == victim, spec
    assert rec["mttr_s"]["last"] > 0
    assert rec["respawns"] == 1
    assert rep["router"]["replay_mismatches"] == 0
    assert sorted(router.pooled_replicas) == list(range(n_replicas))
    _assert_replicas_clean(router)
    return {"seed": seed, "mode": mode, "victim": victim,
            "step": fault_step, "spec": spec, "report": rep}


# seed draws (deterministic from the seed, recorded by the drill):
# 11 -> kill r0@s5, 0 -> slow r1@s4, 1 -> hang r1@s5, 6 -> hang r1@s4
@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.parametrize("seed", [
    11,
    # tier-1 diet: ONE kill-mode smoke in tier-1 (the whole fleet
    # suite budgets ~25s against the 870s wall, standing constraint
    # (a)); the slow/hang draws ride the slow sweep
    pytest.param(0, marks=pytest.mark.slow),
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(6, marks=pytest.mark.slow),
])
def test_fleet_chaos_smoke(seed, params_cfg):
    out = run_fleet_chaos_drill(seed, params_cfg)
    assert out["report"]["recovery"]["deaths"] == 1


@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.slow
@pytest.mark.soak
@pytest.mark.parametrize("seed", [1, 4, 6, 8, 9, 11])
def test_fleet_chaos_sweep_three_replicas(seed, params_cfg):
    """The wider sweep at N=3 (draws: kill r0, hang r1/r2, slow
    r0/r2): every mode class appears across the seeds, two survivors
    absorb each evacuation."""
    out = run_fleet_chaos_drill(seed, params_cfg, n_replicas=3,
                                n_requests=9)
    assert out["report"]["recovery"]["deaths"] == 1


@pytest.mark.chaos
@pytest.mark.fault
@pytest.mark.slow
@pytest.mark.soak
def test_fleet_chaos_churn(params_cfg):
    """100+ request churn through a 3-replica fleet with a mid-trace
    kill: sustained open-world arrival pressure across the recovery,
    every stream still bitwise clean, no block leaked anywhere."""
    out = run_fleet_chaos_drill(29, params_cfg, n_replicas=3,
                                n_requests=104, max_new_tokens=3,
                                modes=("kill",))
    rep = out["report"]
    assert rep["router"]["finished"] == 104
    assert rep["prefix"]["hits"] > 0      # shared heads reused
