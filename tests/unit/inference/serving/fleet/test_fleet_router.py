"""Fleet serving: the data-parallel replica router — placement
scoring, prefix-affinity vs round-robin, per-uid stickiness, typed
request errors, requeue-with-bitwise-replay on replica death, fleet
telemetry, and the ISSUE acceptance e2e."""

import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import SamplingParams
from deepspeed_tpu.inference.v2 import (FleetRouter, InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, RoundRobinPolicy,
                                        ScoringPolicy, ServingFrontend)
from deepspeed_tpu.inference.v2.metrics import ServingMetrics
from deepspeed_tpu.inference.v2.serving.prefix import chain_digests
from deepspeed_tpu.resilience.errors import (ServingError,
                                             ServingOverloadError,
                                             TerminalRequestError,
                                             UnknownRequestError)
from deepspeed_tpu.resilience.fault_injector import fault_injector

# 3 shared system prompts, 2 full 8-token blocks each (the config-7
# million-user common-prompt-head shape)
SYS = [list(range(1, 18)), list(range(101, 118)),
       list(range(201, 218))]


def _factory(params_cfg, **kw):
    params, cfg = params_cfg
    eng_kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=48, kv_block_size=8,
                  max_blocks_per_seq=8, kv_dtype="float32")
    eng_kw.update(kw)

    def engine_factory(slot):
        return InferenceEngineV2(params, cfg,
                                 RaggedInferenceEngineConfig(**eng_kw))
    return engine_factory


def _router(params_cfg, n=2, serving=None, engine_kw=None, **kw):
    cfg = {"fleet": {"n_replicas": n}}
    for k, v in (serving or {}).items():
        if k == "fleet":
            cfg["fleet"].update(v)
        else:
            cfg[k] = v
    return FleetRouter(_factory(params_cfg, **(engine_kw or {})),
                       cfg, **kw)


def _assert_replicas_clean(router):
    """Block conservation on every alive replica: no tracked
    sequences, every non-cached block back on the free list."""
    for slot in router.pooled_replicas:
        eng = router._replicas[slot].engine
        assert not eng._state_manager.tracked_sequences, slot
        cached = (eng.prefix_cache.cached_blocks
                  if eng.prefix_cache else 0)
        assert eng.free_blocks == eng._config.n_kv_blocks - cached, slot


def _single_frontend_refs(params_cfg, requests, max_new_tokens,
                          serving=None):
    """Undisturbed single-frontend control runs, one per request."""
    eng = _factory(params_cfg)(0)
    refs = {}
    for uid, prompt in requests.items():
        fe = ServingFrontend(eng, serving)
        r = fe.submit(prompt, uid=uid, max_new_tokens=max_new_tokens)
        fe.drain()
        assert r.state == RequestState.FINISHED
        refs[uid] = list(r.tokens)
    return refs


class TestHostUnits:
    """No-engine units: digest schema, policies, quick_stats."""

    def test_chain_digests_schema(self):
        toks = np.arange(1, 18, dtype=np.int32)        # 17 tokens
        d = chain_digests(toks, 8)
        # cap at len-1 exactly like PrefixCache.match: 2 full blocks
        assert len(d) == 2
        assert chain_digests(toks[:17], 8) == d
        assert len(chain_digests(toks[:16], 8)) == 1   # 16 -> cap 15
        # chained: a block-0 change reshapes EVERY digest downstream
        mut = toks.copy()
        mut[0] += 1
        d2 = chain_digests(mut, 8)
        assert d2[0] != d[0] and d2[1] != d[1]
        # a block-1 change leaves block 0's digest alone
        mut = toks.copy()
        mut[9] += 1
        d3 = chain_digests(mut, 8)
        assert d3[0] == d[0] and d3[1] != d[1]

    def test_scoring_policy_math(self):
        p = ScoringPolicy(affinity_weight=4.0, queue_weight=1.0,
                          kv_weight=2.0)
        idle = {"outstanding": 0, "capacity": 4, "kv_util": 0.0}
        busy = {"outstanding": 4, "capacity": 4, "kv_util": 0.5}
        assert p.score(idle, 0.0) > p.score(busy, 0.0)
        # full affinity outweighs a loaded replica at these weights
        assert p.score(busy, 1.0) > p.score(idle, 0.0)

    def test_round_robin_rotation(self):
        p = RoundRobinPolicy()
        assert p.rank([0, 1, 2]) == [0, 1, 2]
        assert p.rank([0, 1, 2]) == [1, 2, 0]
        assert p.rank([0, 1, 2]) == [2, 0, 1]

    def test_quick_stats_no_allocation_contract(self):
        m = ServingMetrics("test", n_kv_blocks=10)
        q0 = m.quick_stats()
        m.record_step(dispatch_s=0.0, sync_wait_s=0.0, wall_s=0.01,
                      new_tokens=3, prompt_tokens=0, n_seqs=3,
                      decode_only=True, recompiled=False,
                      blocking_sync=False, queue_depth=2, kv_free=6)
        # the SAME dict instance, updated in place
        assert m.quick_stats() is q0
        assert q0["steps"] == 1.0 and q0["tokens_emitted"] == 3.0
        assert q0["queue_depth"] == 2.0
        assert q0["kv_util"] == pytest.approx(0.4)
        m.record_step(dispatch_s=0.0, sync_wait_s=0.0, wall_s=0.01,
                      new_tokens=1, prompt_tokens=4, n_seqs=2,
                      decode_only=False, recompiled=True,
                      blocking_sync=True, queue_depth=0, kv_free=10)
        assert q0["steps"] == 2.0 and q0["recompiles"] == 1.0
        assert q0["blocking_syncs"] == 1.0 and q0["kv_util"] == 0.0

    def test_affinity_map_keys_match_the_trie(self, params_cfg):
        """Cross-module parity: the keys the router hashes a prompt to
        are exactly the keys a replica's trie registers it under —
        affinity predicting trie hits depends on it."""
        eng = _factory(params_cfg)(0)
        fe = ServingFrontend(eng)
        prompt = SYS[0] + [31]
        fe.submit(prompt, max_new_tokens=2)
        fe.drain()
        digests = chain_digests(np.asarray(prompt, np.int32), 8)
        assert digests
        trie_keys = set(eng.prefix_cache._entries.keys())
        assert set(digests) <= trie_keys


class TestRouterBasics:

    def test_streams_match_single_frontend_and_stick(self, params_cfg):
        reqs_in = {11: SYS[0] + [31], 12: SYS[1] + [41],
                   13: SYS[0] + [51]}
        refs = _single_frontend_refs(params_cfg, reqs_in, 4)
        router = _router(params_cfg, n=2)
        handles = {uid: router.submit(p, uid=uid, max_new_tokens=4)
                   for uid, p in reqs_in.items()}
        # sticky: the placement map answers for every live uid
        for uid in handles:
            assert router._entries[uid].slot in (0, 1)
        router.drain()
        for uid, r in handles.items():
            assert r.state == RequestState.FINISHED
            assert r.tokens == refs[uid], uid
            assert router.result(uid) == refs[uid]
        rep = router.get_fleet_report()
        assert rep["router"]["submitted"] == 3
        assert rep["router"]["finished"] == 3
        assert rep["router"]["replay_mismatches"] == 0
        _assert_replicas_clean(router)

    def test_stream_iterator_pumps_the_fleet(self, params_cfg):
        router = _router(params_cfg, n=2)
        refs = _single_frontend_refs(params_cfg, {21: SYS[2] + [61]}, 5)
        r = router.submit(SYS[2] + [61], uid=21, max_new_tokens=5)
        toks = list(router.stream(21))
        assert toks == refs[21] and r.state == RequestState.FINISHED

    def test_typed_request_errors(self, params_cfg):
        router = _router(params_cfg, n=2)
        with pytest.raises(UnknownRequestError) as ei:
            router.stream(999)
        assert ei.value.uid == 999 and "fleet router" in str(ei.value)
        with pytest.raises(UnknownRequestError):
            router.cancel(999)
        with pytest.raises(UnknownRequestError):
            router.result(999)
        r = router.submit(SYS[0] + [71], max_new_tokens=3)
        router.drain()
        assert r.state == RequestState.FINISHED
        with pytest.raises(TerminalRequestError) as ei:
            router.cancel(r.uid)
        assert ei.value.state == "FINISHED"
        assert isinstance(ei.value, ServingError)
        # terminal-but-retained: the stream still yields the buffer
        assert list(router.stream(r.uid)) == r.tokens

    def test_cancel_mid_flight_and_on_token(self, params_cfg):
        router = _router(params_cfg, n=2)
        seen = []
        r1 = router.submit(SYS[0] + [81], max_new_tokens=8,
                           on_token=seen.append)
        r2 = router.submit(SYS[1] + [82], max_new_tokens=3)
        for _ in range(4):
            router.step()
        assert not r1.done
        assert router.cancel(r1.uid) is True
        assert r1.state == RequestState.CANCELLED
        router.drain()
        assert r2.state == RequestState.FINISHED
        assert seen == r1.tokens        # ordered delivery, then stop
        rep = router.get_fleet_report()
        assert rep["router"]["cancelled"] == 1
        _assert_replicas_clean(router)

    def test_fleet_saturated_raises_typed_with_fleet_view(
            self, params_cfg):
        router = _router(params_cfg, n=2,
                         serving={"max_queue_depth": 1})
        router.submit(SYS[0] + [83], max_new_tokens=2)
        router.submit(SYS[1] + [84], max_new_tokens=2)
        with pytest.raises(ServingOverloadError) as ei:
            router.submit(SYS[2] + [85], max_new_tokens=2)
        view = ei.value.fleet_view
        assert set(view) == {0, 1}
        assert all(v["outstanding"] >= 1 for v in view.values())
        # never accepted => not counted (same unwind as a replica-side
        # validation error): the router totals stay conserved
        assert router.submitted == 2
        router.drain()
        rep = router.get_fleet_report()["router"]
        assert rep["submitted"] == rep["finished"] == 2
        # shed policy: the refused request comes back SHED instead
        router2 = _router(params_cfg, n=2,
                          serving={"max_queue_depth": 1,
                                   "on_overload": "shed"})
        router2.submit(SYS[0] + [86], max_new_tokens=2)
        router2.submit(SYS[1] + [87], max_new_tokens=2)
        shed = router2.submit(SYS[2] + [88], max_new_tokens=2)
        assert shed.state == RequestState.SHED
        router2.drain()
        assert router2.get_fleet_report()["router"]["shed"] == 1

    def test_per_request_seed_requires_deployment_pin(self, params_cfg):
        """(The matching-pin ACCEPT path decodes in the slow-tier
        sampled replay test — serving.seed 11 + per-request seed 11.)"""
        router = _router(params_cfg, n=2)
        with pytest.raises(ValueError, match="serving.seed"):
            router.submit(SYS[0] + [89],
                          sampling=SamplingParams(temperature=1.2,
                                                  seed=7))
        assert router.submitted == 0 and 1 not in router._entries

    def test_affinity_routes_shared_prefixes_together(self, params_cfg):
        """Same-prefix traffic lands on the replica whose trie holds
        the head; the router's map keys agree with the trie's."""
        router = _router(params_cfg, n=2)
        first = router.submit(SYS[0] + [90], max_new_tokens=2)
        home = router._entries[first.uid].slot
        router.drain()
        followers = [router.submit(SYS[0] + [91 + i], max_new_tokens=2)
                     for i in range(3)]
        placed = {router._entries[r.uid].slot for r in followers}
        assert placed == {home}
        router.drain()
        rep = router.get_fleet_report()
        assert rep["router"]["affinity_routed"] >= 3
        assert rep["prefix"]["hits"] >= 3

    def test_telemetry_hub_fleet_namespace_and_alerts(self, params_cfg,
                                                      tmp_path):
        from deepspeed_tpu.telemetry.hub import JsonlSink, TelemetryHub
        sink = JsonlSink(str(tmp_path / "fleet.jsonl"))
        hub = TelemetryHub(sink=sink)
        router = _router(params_cfg, n=2)
        router.attach_telemetry(hub)
        r = router.submit(SYS[0] + [95], max_new_tokens=6)
        victim = router._entries[r.uid].slot
        for _ in range(3):
            router.step()
        fault_injector.configure(router.spec_for(victim, 0, "kill"))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r.state == RequestState.FINISHED
        # typed alerts reached the bounded log AND the hub
        kinds = {a.kind for a in router.alerts}
        assert "replica_death" in kinds and "fleet_rebalance" in kinds
        assert hub.alert_counts().get("replica_death", 0) >= 1
        # per-replica scalars + router totals flow through the flat
        # stream under the fleet namespace
        flat = hub.sample(1)
        assert any(k.startswith("fleet/replicas/r0/") for k in flat)
        assert "fleet/router/submitted" in flat
        assert "fleet/prefix/hit_rate" in flat
        recs = sink.read_records()
        assert any(rec.get("kind") == "alert" for rec in recs)


class TestElasticRecovery:

    def test_kill_requeues_and_respawns(self, params_cfg):
        refs = _single_frontend_refs(params_cfg, {31: SYS[0] + [96]}, 6)
        router = _router(params_cfg, n=2)
        r = router.submit(SYS[0] + [96], uid=31, max_new_tokens=6)
        victim = router._entries[31].slot
        for _ in range(3):
            router.step()
        assert r.state == RequestState.DECODE      # mid-decode
        fault_injector.configure(router.spec_for(victim, 0, "kill"))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r.state == RequestState.FINISHED
        assert r.tokens == refs[31]                # gap/dup-free replay
        rep = router.get_fleet_report()
        rec = rep["recovery"]
        assert rec["deaths"] == 1 and rec["respawns"] == 1
        assert rec["requeued"] == 1
        assert rec["events"][0]["requeued_uids"] == [31] or \
            rec["events"][0]["requeued_uids"] == (31,)
        assert rec["mttr_s"]["last"] > 0
        assert rep["router"]["replay_mismatches"] == 0
        # the respawned replica rejoined the pool, generation bumped
        assert sorted(router.pooled_replicas) == [0, 1]
        assert router._replicas[victim].generation == 2
        _assert_replicas_clean(router)

    def test_hang_detected_by_heartbeat_deadline(self, params_cfg):
        router = _router(params_cfg, n=2,
                         serving={"fleet": {"heartbeat_timeout_steps": 1,
                                            "progress_timeout_steps": 2}})
        r = router.submit(SYS[1] + [97], max_new_tokens=6)
        victim = router._entries[r.uid].slot
        for _ in range(2):
            router.step()
        # silent for long enough that the ledger's deadline fires
        fault_injector.configure(
            router.spec_for(victim, 0, "hang", duration=50))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r.state == RequestState.FINISHED
        rec = router.get_fleet_report()["recovery"]
        assert rec["deaths"] == 1
        assert rec["events"][0]["mode"] == "hang"

    @pytest.mark.slow
    def test_slow_detected_by_progress_deadline(self, params_cfg):
        """Slow tier (tier-1 diet): the hang test above drives the
        same ledger sweep; the chaos sweep draws slow-mode drills."""
        router = _router(params_cfg, n=2,
                         serving={"fleet": {"heartbeat_timeout_steps": 3,
                                            "progress_timeout_steps": 1}})
        r = router.submit(SYS[2] + [98], max_new_tokens=6)
        victim = router._entries[r.uid].slot
        for _ in range(2):
            router.step()
        fault_injector.configure(
            router.spec_for(victim, 0, "slow", duration=50))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r.state == RequestState.FINISHED
        rec = router.get_fleet_report()["recovery"]
        assert rec["deaths"] == 1
        assert rec["events"][0]["mode"] == "slow"

    def test_replica_retired_before_sync_still_closes_handle(
            self, params_cfg):
        """max_retained_requests=1 + two requests finishing in the
        same replica step: the frontend retires the first before the
        router's sync sees it. The vanished uid must still close its
        router handle (FINISHED from the buffered tokens) — skipping
        it would leave a live handle nothing ever finishes and
        livelock serve()."""
        router = _router(params_cfg, n=1,
                         serving={"max_retained_requests": 1})
        # short prompts co-prefill inside one 32-token budget, same
        # length + budget => they finish in the same collect pass and
        # the frontend's retention bound evicts the first immediately
        a = router.submit(list(range(1, 9)), max_new_tokens=3)
        b = router.submit(list(range(11, 19)), max_new_tokens=3)
        steps = router.drain(max_steps=300)
        assert steps < 300                        # no livelock
        # the scenario really fired: the first-finished uid is GONE
        # from the replica's table (evicted by the retention bound)
        fe = router._replicas[0].frontend
        assert fe.get_request(a.uid) is None
        for r in (a, b):
            assert r.state == RequestState.FINISHED
            assert len(r.tokens) == 3
        assert router.get_fleet_report()["router"]["finished"] == 2

    def test_requeue_does_not_restart_the_deadline_clock(
            self, params_cfg):
        """A client's deadline_ms is end-to-end, not per-attempt: the
        survivor's gate sees only the remaining budget, so a request
        whose deadline elapsed while replica A held it is SHED on
        requeue, not served late with a fresh clock. Deterministic via
        the injected clock (1µs per observation + an explicit 10ms
        jump while A holds the request)."""
        t = {"now": 0.0}

        def clock():
            t["now"] += 1e-6
            return t["now"]

        router = _router(params_cfg, n=2, clock=clock)
        r = router.submit(SYS[0] + [66], max_new_tokens=64,
                          deadline_ms=1.0)
        victim = router._entries[r.uid].slot
        for _ in range(3):
            router.step()
        assert not r.done               # joined well under the 1ms
        t["now"] += 0.010               # 10ms pass mid-decode on A
        fault_injector.configure(router.spec_for(victim, 0, "kill"))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        # the requeue carried deadline_ms=0 (budget long blown), so
        # the survivor's gate shed it instead of serving it late
        assert r.done and r.state != RequestState.FINISHED
        assert r.shed_reason            # the gate's reason propagated
        rep = router.get_fleet_report()["router"]
        assert rep["shed"] == 1 and rep["replay_mismatches"] == 0
        _assert_replicas_clean(router)

    def test_all_replicas_dead_abandons_instead_of_livelock(
            self, params_cfg):
        """respawn=False and EVERY replica killed: the backlog cannot
        ever place again — the router abandons it typed (CANCELLED
        with the reason) and drain() terminates instead of spinning
        on a non-idle backlog forever."""
        router = _router(params_cfg, n=2,
                         serving={"fleet": {"respawn": False}})
        r1 = router.submit(SYS[0] + [61], max_new_tokens=8)
        r2 = router.submit(SYS[1] + [62], max_new_tokens=8)
        for _ in range(2):
            router.step()
        fault_injector.configure(",".join([
            router.spec_for(0, 0, "kill"),
            router.spec_for(1, 1, "kill")]))
        try:
            steps = router.drain(max_steps=200)
        finally:
            fault_injector.reset()
        assert steps < 200                       # terminated, no spin
        assert router.idle
        assert router.pooled_replicas == []
        for r in (r1, r2):
            assert r.state == RequestState.CANCELLED
            assert "no replicas left" in r.shed_reason
        rep = router.get_fleet_report()
        assert rep["router"]["abandoned"] == 2
        assert rep["recovery"]["deaths"] == 2

    def test_respawn_off_shrinks_the_pool(self, params_cfg):
        router = _router(params_cfg, n=2,
                         serving={"fleet": {"respawn": False}})
        r = router.submit(SYS[0] + [99], max_new_tokens=4)
        victim = router._entries[r.uid].slot
        router.step()
        fault_injector.configure(router.spec_for(victim, 0, "kill"))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r.state == RequestState.FINISHED    # survivor absorbed
        assert router.pooled_replicas == [1 - victim]
        rec = router.get_fleet_report()["recovery"]
        assert rec["deaths"] == 1 and rec["respawns"] == 0

    @pytest.mark.slow
    def test_sampled_requeue_replays_bitwise(self, params_cfg):
        """The replay contract under sampling: keys are
        fold_in(fold_in(seed, uid), position), so a requeued SAMPLED
        request regenerates the identical stream on the survivor.
        Slow tier (the sampled executable is a second compile); the
        greedy replay + acceptance e2e keep the contract in tier-1."""
        sp = SamplingParams(temperature=1.3, top_k=16, seed=11)
        serving = {"seed": 11, "executable": "sampled"}
        eng = _factory(params_cfg)(0)
        fe = ServingFrontend(eng, serving)
        ref = fe.submit(SYS[1] + [41], uid=51, max_new_tokens=6,
                        sampling=sp)
        fe.drain()
        router = _router(params_cfg, n=2, serving=serving)
        r = router.submit(SYS[1] + [41], uid=51, max_new_tokens=6,
                          sampling=sp)
        victim = router._entries[51].slot
        for _ in range(3):
            router.step()
        assert not r.done
        fault_injector.configure(router.spec_for(victim, 0, "kill"))
        try:
            router.drain()
        finally:
            fault_injector.reset()
        assert r.state == RequestState.FINISHED
        assert r.tokens == ref.tokens
        assert router.replay_mismatches == 0


class TestAcceptanceE2E:

    @pytest.mark.slow  # tier-1 diet (PR 17): bootstrap's kill-router-mid-decode drill keeps the kill path tier-1
    def test_fleet_kill_mid_decode_acceptance(self, params_cfg):
        """The ISSUE acceptance e2e: N=2 replicas, staggered
        shared-prefix requests through router.serve(); one replica
        killed mid-decode via the fleet.dispatch fault site; every
        accepted request finishes with its FULL stream bitwise equal
        to an undisturbed single-frontend run (gap-free,
        duplicate-free); per-replica recompiles <= 1 and
        steady_blocking_syncs == 0."""
        N = 8
        rng = np.random.default_rng(5)
        mix = [int(rng.integers(0, 3)) for _ in range(N)]
        reqs_in = {900 + k: SYS[mix[k]] + [60 + k] for k in range(N)}
        refs = _single_frontend_refs(params_cfg, reqs_in, 5)

        router = _router(params_cfg, n=2)
        handles = {}
        armed = {}

        def poll(r, step):
            if step % 2 == 0 and len(handles) < N:
                k = len(handles)
                uid = 900 + k
                handles[uid] = r.submit(reqs_in[uid], uid=uid,
                                        max_new_tokens=5)
            if step == 7 and not armed:
                # kill the replica currently decoding the most work —
                # mid-decode by construction (requests are in flight)
                live = [e for e in r._entries.values()
                        if not e.req.done and e.slot is not None]
                assert any(e.req.state == RequestState.DECODE
                           for e in live)
                slots = [e.slot for e in live]
                victim = max(set(slots), key=slots.count)
                fault_injector.configure(r.spec_for(victim, 0, "kill"))
                armed["victim"] = victim
            return len(handles) < N

        try:
            router.serve(poll=poll)
        finally:
            fault_injector.reset()
        assert len(handles) == N and "victim" in armed
        rep = router.get_fleet_report()
        # every accepted request finished, streams bitwise == the
        # undisturbed single-frontend runs — requeued ones included
        for uid, r in handles.items():
            assert r.state == RequestState.FINISHED, uid
            assert r.tokens == refs[uid], uid
        rec = rep["recovery"]
        assert rec["deaths"] == 1 and rec["requeued"] >= 1
        assert rep["router"]["replay_mismatches"] == 0
        # the PR 9 contract holds under routing and requeue: one
        # compile per (fresh or respawned) executable, then zero —
        # and zero blocking host syncs in every steady decode window
        for slot in router.pooled_replicas:
            frep = router._replicas[slot].frontend.get_serving_report()
            assert frep["recompiles"] <= 1, slot
            assert frep["steady_blocking_syncs"] == 0, slot
        _assert_replicas_clean(router)

    def test_affinity_beats_round_robin_on_seeded_traffic(
            self, params_cfg):
        """Cross-replica prefix-affinity routing yields a STRICTLY
        higher fleet prefix hit rate than round-robin on the same
        seeded traffic."""
        rng = np.random.default_rng(3)
        mix = [int(rng.integers(0, 3)) for _ in range(9)]

        def run(policy):
            router = _router(params_cfg, n=2,
                             serving={"fleet": {"policy": policy}})
            reqs = []

            def poll(r, step):
                if len(reqs) < len(mix) and step % 2 == 0:
                    k = len(reqs)
                    reqs.append(r.submit(SYS[mix[k]] + [230 + k],
                                         max_new_tokens=3))
                return len(reqs) < len(mix)

            router.serve(poll=poll)
            assert all(r.state == RequestState.FINISHED for r in reqs)
            return router.get_fleet_report()

        aff = run("affinity")
        rr = run("round_robin")
        assert aff["prefix"]["hit_rate"] > rr["prefix"]["hit_rate"]
        assert aff["router"]["affinity_routed"] > 0


class TestPollingOverhead:

    @pytest.mark.perf
    def test_router_polling_under_one_percent_of_decode_step(
            self, params_cfg):
        """The quick_stats satellite: the router's per-replica
        snapshot() poll must cost <1% of a steady decode step (the
        overhead-smoke pattern the telemetry suite uses)."""
        import time
        router = _router(params_cfg, n=2)
        r = router.submit(SYS[0] + [77], max_new_tokens=12)
        router.drain()
        assert r.state == RequestState.FINISHED
        rep = router._replicas[0].frontend.get_serving_report()
        step_ms = rep["step_ms"]["p50"]
        assert step_ms > 0
        replica = router._replicas[0]
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            replica.snapshot()
        per_poll_ms = (time.perf_counter() - t0) / n * 1e3
        assert per_poll_ms < 0.01 * step_ms, \
            f"snapshot() {per_poll_ms:.4f}ms vs step {step_ms:.3f}ms"
