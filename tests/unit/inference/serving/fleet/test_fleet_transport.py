"""Fleet transport: the wire protocol, FaultyChannel chaos semantics,
RpcClient deadline/retry/backoff, the worker's exactly-once reply
cache, the socket serve loop, the affinity-eviction regression
(stale router map entries after replica-side trie LRU eviction), the
seeded chaos fault matrix, and the transport acceptance e2e (kill +
send-drop over both channels)."""

import socket
import struct
import threading
import types

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (FleetRouter, InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, ServingFrontend)
from deepspeed_tpu.inference.v2.serving.fleet.transport import (
    MSG_HEARTBEAT, MSG_HELLO, MSG_SHUTDOWN, PROTOCOL_VERSION, Channel,
    FaultyChannel, HealthProber, LoopbackChannel, RpcClient,
    SocketChannel, TransportStats, _truncate_frame, decode_frame,
    encode_frame)
from deepspeed_tpu.inference.v2.serving.fleet.worker import (
    WorkerCore, serve_socket)
from deepspeed_tpu.inference.v2.serving.prefix import chain_digests
from deepspeed_tpu.resilience.errors import (ServingOverloadError,
                                             TerminalRequestError,
                                             TransportConnectError,
                                             TransportDecodeError,
                                             TransportError,
                                             TransportTimeout,
                                             UnknownRequestError)
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime.config import FleetTransportConfig

SYS = [list(range(1, 18)), list(range(101, 118)),
       list(range(201, 218))]


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def _tcfg(**kw):
    base = dict(rpc_deadline_seconds=5.0, rpc_retries=3,
                retry_backoff_seconds=0.0)
    base.update(kw)
    return FleetTransportConfig(**base)


# -- engine-free stand-ins ------------------------------------------------


class _EchoCore:
    """Worker-shaped handler with no reply cache: every delivered
    frame executes (exposes at-least-once delivery so the tests can
    count it)."""

    def __init__(self):
        self.handled = 0

    def handle(self, msg):
        self.handled += 1
        return {"kind": msg.get("kind", "?") + "_OK", "id": msg["id"],
                "v": PROTOCOL_VERSION}


class _FakeMetrics:
    def quick_stats(self):
        return {"steps": 0.0, "tokens_emitted": 0.0, "recompiles": 0.0,
                "blocking_syncs": 0.0}

    def report(self):
        return {"steady_blocking_syncs": 0}


class _FakeFrontend:
    """Just enough frontend surface for WorkerCore units (no engine,
    no jax): counts effectful calls so exactly-once is observable."""

    queued_requests = 0
    active_requests = 0

    def __init__(self):
        self.engine = types.SimpleNamespace(
            prefix_cache=None, kv_utilization=0.0, free_blocks=48,
            _config=types.SimpleNamespace(max_ragged_sequence_count=4,
                                          kv_block_size=8))
        self.metrics = _FakeMetrics()
        self.submits = []
        self.steps = 0
        self.fail_kind = None

    def submit(self, prompt, *, uid, on_token=None, **kw):
        if self.fail_kind is not None:
            raise self.fail_kind("injected frontend failure")
        self.submits.append(uid)

    def cancel(self, uid):
        raise UnknownRequestError(uid, surface="fake frontend")

    def step(self):
        if self.fail_kind is not None:
            raise self.fail_kind("injected frontend failure")
        self.steps += 1

    def get_request(self, uid):
        return None


class _NullChannel(Channel):
    """Accepts every send, never replies — a black-holed worker."""
    synchronous = True

    def connect(self):
        pass

    def send(self, data):
        pass

    def recv(self, timeout=0.0):
        return None

    def close(self):
        pass


class _ScriptChannel(Channel):
    """Replies per send from a script of callables (msg -> reply dict
    or list of reply dicts)."""
    synchronous = True

    def __init__(self, script):
        self._script = list(script)
        self._inbox = []

    def connect(self):
        pass

    def send(self, data):
        msg = decode_frame(data)
        if not self._script:
            return
        out = self._script.pop(0)(msg)
        if out is None:
            return
        for m in (out if isinstance(out, list) else [out]):
            self._inbox.append(encode_frame(m))

    def recv(self, timeout=0.0):
        return self._inbox.pop(0) if self._inbox else None

    def close(self):
        self._inbox.clear()


def _ok(msg, **extra):
    return {"kind": msg["kind"] + "_OK", "id": msg["id"],
            "v": PROTOCOL_VERSION, **extra}


# -- engine-backed helpers (mirror test_fleet_router's fixtures) ----------


def _factory(params_cfg, **kw):
    params, cfg = params_cfg
    eng_kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=48, kv_block_size=8,
                  max_blocks_per_seq=8, kv_dtype="float32")
    eng_kw.update(kw)

    def engine_factory(slot):
        return InferenceEngineV2(params, cfg,
                                 RaggedInferenceEngineConfig(**eng_kw))
    return engine_factory


def _router(params_cfg, n=2, serving=None, **kw):
    cfg = {"fleet": {"n_replicas": n}}
    for k, v in (serving or {}).items():
        if k == "fleet":
            cfg["fleet"].update(v)
        else:
            cfg[k] = v
    return FleetRouter(_factory(params_cfg), cfg, **kw)


def _single_frontend_refs(params_cfg, requests, max_new_tokens):
    eng = _factory(params_cfg)(0)
    refs = {}
    for uid, prompt in requests.items():
        fe = ServingFrontend(eng)
        r = fe.submit(prompt, uid=uid, max_new_tokens=max_new_tokens)
        fe.drain()
        assert r.state == RequestState.FINISHED
        refs[uid] = list(r.tokens)
    return refs


class TestWireProtocol:

    def test_roundtrip(self):
        msg = {"v": 1, "id": 7, "kind": "STEP",
               "cursors": {"4": 2}, "flag": None}
        assert decode_frame(encode_frame(msg)) == msg

    def test_decode_rejects_torn_frames(self):
        good = encode_frame({"id": 1, "kind": "HEARTBEAT"})
        with pytest.raises(TransportDecodeError):
            decode_frame(good[:3])                       # short
        with pytest.raises(TransportDecodeError):
            decode_frame(b"XXXX" + good[4:])             # bad magic
        bad_ver = struct.pack(">4sHI", b"DTPF", 99,
                              len(good) - 10) + good[10:]
        with pytest.raises(TransportDecodeError):
            decode_frame(bad_ver)                        # version
        with pytest.raises(TransportDecodeError):
            decode_frame(good + b"x")                    # length lie
        arr = b"\x00" * 5
        frame = struct.pack(">4sHI", b"DTPF", 1, len(arr)) + arr
        with pytest.raises(TransportDecodeError):
            decode_frame(frame)                          # not JSON
        body = b"[1,2,3]"
        frame = struct.pack(">4sHI", b"DTPF", 1, len(body)) + body
        with pytest.raises(TransportDecodeError):
            decode_frame(frame)                          # not a dict

    def test_truncate_keeps_framing_breaks_payload(self):
        frame = encode_frame({"id": 3, "kind": "STEP",
                              "cursors": {"9": 1}})
        t = _truncate_frame(frame)
        magic, ver, n = struct.unpack_from(">4sHI", t)
        assert magic == b"DTPF" and ver == PROTOCOL_VERSION
        assert len(t) == struct.calcsize(">4sHI") + n    # aligned
        with pytest.raises(TransportDecodeError):
            decode_frame(t)                              # JSON broken


class TestRpcClient:

    def test_deadline_exhaustion_is_typed(self):
        stats = TransportStats()
        rpc = RpcClient(_NullChannel(), 0, _tcfg(rpc_retries=2),
                        stats=stats)
        with pytest.raises(TransportTimeout):
            rpc.call(MSG_HEARTBEAT)
        assert stats.timeouts == 1 and stats.retries == 2
        assert stats.rpcs == 1

    def test_stale_frames_skipped(self):
        stats = TransportStats()
        ch = _ScriptChannel([
            lambda m: [{"id": m["id"] + 50, "kind": "LATE_OK", "v": 1},
                       _ok(m)]])
        rpc = RpcClient(ch, 0, _tcfg(), stats=stats)
        reply = rpc.call(MSG_HEARTBEAT)
        assert reply["kind"] == "HEARTBEAT_OK"
        assert stats.stale == 1

    def test_error_replies_raise_typed(self):
        def err(etype, **extra):
            ch = _ScriptChannel([lambda m: {
                "kind": "ERR", "id": m["id"], "v": 1, "etype": etype,
                "error": "boom", **extra}])
            return RpcClient(ch, 0, _tcfg())
        with pytest.raises(ServingOverloadError):
            err("overload", reason="full").call("SUBMIT")
        with pytest.raises(UnknownRequestError):
            err("unknown", uid=4).call("CANCEL")
        with pytest.raises(TerminalRequestError):
            err("terminal", uid=4, state="FINISHED").call("CANCEL")
        with pytest.raises(ValueError):
            err("value").call("SUBMIT")
        with pytest.raises(TransportError):
            err("").call("STEP")                # the generic fallback

    def test_same_rpc_id_across_retries(self):
        seen = []

        def record(m):
            seen.append(m["id"])
            return _ok(m) if len(seen) > 1 else None   # drop 1st reply
        rpc = RpcClient(_ScriptChannel([record, record]), 0, _tcfg())
        rpc.call(MSG_HEARTBEAT)
        assert len(seen) == 2 and seen[0] == seen[1]


class TestFaultyChannel:

    def _rpc(self, core=None, **cfg):
        core = core if core is not None else _EchoCore()
        ch = FaultyChannel(LoopbackChannel(core), slot=0)
        ch.connect()
        stats = TransportStats()
        return core, ch, RpcClient(ch, 0, _tcfg(**cfg), stats=stats), \
            stats

    def test_send_drop_recovers_via_retry(self):
        core, ch, rpc, stats = self._rpc()
        fault_injector.configure("transport.send:drop@0")
        assert rpc.call(MSG_HEARTBEAT)["kind"] == "HEARTBEAT_OK"
        assert stats.retries == 1 and core.handled == 1
        assert ch.injected == 1

    def test_recv_dup_counts_stale(self):
        core, ch, rpc, stats = self._rpc()
        fault_injector.configure("transport.recv:dup@0")
        rpc.call(MSG_HEARTBEAT)
        rpc.call(MSG_HEARTBEAT)
        assert stats.stale == 1               # the duplicated frame
        assert core.handled == 2

    def test_recv_truncate_recovers(self):
        core, ch, rpc, stats = self._rpc()
        fault_injector.configure("transport.recv:truncate@0")
        assert rpc.call(MSG_HEARTBEAT)["kind"] == "HEARTBEAT_OK"
        assert stats.decode_errors == 1 and stats.retries == 1

    def test_send_delay_released_by_channel_ops(self):
        core, ch, rpc, stats = self._rpc()
        fault_injector.configure("transport.send:delay@0~2")
        assert rpc.call(MSG_HEARTBEAT)["kind"] == "HEARTBEAT_OK"
        assert stats.retries >= 1             # first attempt held

    def test_reorder_swaps_adjacent_messages(self):
        core = _EchoCore()
        ch = FaultyChannel(LoopbackChannel(core), slot=0)
        ch.connect()
        fault_injector.configure("transport.send:reorder@0")
        ch.send(encode_frame({"id": 1, "kind": "A"}))
        ch.send(encode_frame({"id": 2, "kind": "B"}))
        ids = [decode_frame(ch.recv())["id"],
               decode_frame(ch.recv())["id"]]
        assert ids == [2, 1]                  # B overtook A

    def test_rate_spec_is_partial_and_deterministic(self):
        def run():
            core = _EchoCore()
            ch = FaultyChannel(LoopbackChannel(core), slot=0)
            ch.connect()
            fault_injector.configure("transport.send:drop~0.3")
            for i in range(100):
                ch.send(encode_frame({"id": i, "kind": "HEARTBEAT"}))
            fault_injector.reset()
            return core.handled
        a, b = run(), run()
        assert a == b                         # ordinal-hash replay
        assert 40 < a < 95                    # partial, ~70 expected

    def test_connect_fault_is_typed(self):
        ch = FaultyChannel(LoopbackChannel(_EchoCore()), slot=0)
        fault_injector.configure("transport.connect:error")
        with pytest.raises(TransportConnectError):
            ch.connect()

    def test_classic_kind_degrades_to_send_error(self):
        core, ch, rpc, stats = self._rpc()
        fault_injector.configure("transport.send:ioerror@0")
        assert rpc.call(MSG_HEARTBEAT)["kind"] == "HEARTBEAT_OK"
        assert stats.send_errors == 1         # InjectedIOError retried


class TestWorkerExactlyOnce:

    def test_duplicate_submit_executes_once(self):
        fe = _FakeFrontend()
        core = WorkerCore(0, fe)
        msg = {"v": 1, "id": 7, "kind": "SUBMIT", "uid": 5,
               "prompt": [1, 2, 3]}
        r1 = core.handle(dict(msg))
        r2 = core.handle(dict(msg))           # the re-asked duplicate
        assert r1["kind"] == "SUBMIT_OK" and r2 == r1
        assert fe.submits == [5]              # ONE effect

    def test_duplicate_step_steps_once(self):
        fe = _FakeFrontend()
        core = WorkerCore(0, fe)
        msg = {"v": 1, "id": 9, "kind": "STEP", "cursors": {}}
        r1 = core.handle(dict(msg))
        r2 = core.handle(dict(msg))
        assert r1["kind"] == "STEP_OK" and r2 == r1
        assert fe.steps == 1

    def test_error_replies_are_not_cached(self):
        fe = _FakeFrontend()
        fe.fail_kind = ValueError
        core = WorkerCore(0, fe)
        msg = {"v": 1, "id": 3, "kind": "SUBMIT", "uid": 5,
               "prompt": [1]}
        assert core.handle(dict(msg))["etype"] == "value"
        fe.fail_kind = None
        # the re-ask re-executes: a transient failure isn't pinned
        assert core.handle(dict(msg))["kind"] == "SUBMIT_OK"
        assert fe.submits == [5]

    def test_unknown_kind_is_a_value_error_reply(self):
        core = WorkerCore(0, _FakeFrontend())
        r = core.handle({"v": 1, "id": 1, "kind": "BOGUS"})
        assert r["kind"] == "ERR" and r["etype"] == "value"


class TestSocketServeLoop:
    """The socket worker loop over an OS socketpair — real framed
    stream, no subprocess (the subprocess path is the slow-marked
    socket acceptance + the graft fleet leg)."""

    def _serve(self, fe):
        a, b = socket.socketpair()
        core = WorkerCore(0, fe)
        t = threading.Thread(target=serve_socket, args=(core, b),
                             daemon=True)
        t.start()
        ch = SocketChannel(lambda: (None, a))
        ch.connect()
        return core, ch, RpcClient(ch, 0, _tcfg()), t

    def test_rpc_roundtrip_and_shutdown(self):
        core, ch, rpc, t = self._serve(_FakeFrontend())
        hello = rpc.call(MSG_HELLO, {"role": "decode"})
        assert hello["kind"] == "HELLO_OK"
        assert hello["kv_block_size"] == 8
        # disagg schema: the role rides HELLO both ways (the router
        # assigns it in the payload, the worker echoes what it
        # learned) and the snapshot carries the prefill-pool scoring
        # signals the router places from
        assert hello["role"] == "decode"
        snap = hello["snapshot"]
        assert snap["role"] == "decode"
        assert snap["prefill_backlog"] == 0 and snap["parked"] == 0
        # a role-less HELLO (reconnect without reassignment) keeps it
        assert rpc.call(MSG_HELLO)["role"] == "decode"
        assert rpc.call(MSG_HEARTBEAT)["kind"] == "HEARTBEAT_OK"
        assert rpc.call(MSG_SHUTDOWN)["kind"] == "BYE"
        t.join(timeout=10.0)
        assert not t.is_alive()
        ch.close()

    def test_handler_crash_answers_typed_and_keeps_serving(self):
        fe = _FakeFrontend()
        core, ch, rpc, t = self._serve(fe)
        fe.fail_kind = RuntimeError       # NOT a typed serving error
        with pytest.raises(TransportError):
            rpc.call("STEP", {"cursors": {}})
        fe.fail_kind = None
        # the process boundary held: the worker answers the next RPC
        assert rpc.call(MSG_HEARTBEAT)["kind"] == "HEARTBEAT_OK"
        rpc.call(MSG_SHUTDOWN)
        t.join(timeout=10.0)
        ch.close()


class TestAffinityEvictionRegression:
    """The satellite bugfix: the router's affinity map is fed by
    replica-reported TRIE_DELTAs, so a replica-side LRU eviction
    DROPS the corresponding map entry (the old placement-time writes
    kept routing traffic at KV that was gone)."""

    def test_eviction_drops_and_next_delta_refreshes(self, params_cfg):
        router = _router(params_cfg, n=2,
                         serving={"prefix": {"max_blocks": 2}})
        pa = np.asarray(SYS[0] + [31], np.int32)
        # shares block 0 with pa, diverges in block 1 -> its insert
        # overflows the 2-block trie and LRU-evicts pa's leaf block
        pb = np.asarray(SYS[0][:8] + list(range(300, 310)), np.int32)
        da, db = chain_digests(pa, 8), chain_digests(pb, 8)
        assert da[0] == db[0] and da[1] != db[1]

        r1 = router.submit(pa, uid=1, max_new_tokens=3)
        router.drain()
        assert r1.state == RequestState.FINISHED
        home = router._entries[1].slot
        assert all(router._affinity_map.get(d) == (home, "hbm")
                   for d in da)

        r2 = router.submit(pb, uid=2, max_new_tokens=3)
        assert router._entries[2].slot == home    # affinity pulled it
        router.drain()
        assert r2.state == RequestState.FINISHED
        # the replica evicted pa's leaf block; the delta's del reached
        # the map — no stale entry pulls traffic at evicted KV
        assert router._affinity_map.get(da[1]) is None
        assert router._affinity_map.get(db[1]) == (home, "hbm")
        assert router._affinity_map.get(da[0]) == (home, "hbm")
        # and the affinity walk degrades to the 1-block prefix cleanly
        assert router._affinity(da) == (home, 1, 1.0)

        # resubmitting the evicted chain re-inserts it: the NEXT delta
        # refreshes the map instead of leaving it stale forever
        r3 = router.submit(pa, uid=3, max_new_tokens=3)
        router.drain()
        assert r3.state == RequestState.FINISHED
        assert router._affinity_map.get(da[1]) == (home, "hbm")


def _chaos_serve(params_cfg, specs, n_req=6, max_new_tokens=4,
                 serving=None):
    """Staggered shared-prefix traffic through a 2-replica fleet
    (loopback unless ``serving`` picks the socket channel) with
    channel chaos armed; returns (router, handles, refs).
    Deterministic: rate faults hash the site ordinal, so a given spec
    string replays the identical drill."""
    reqs_in = {700 + k: SYS[k % 3] + [40 + k] for k in range(n_req)}
    refs = _single_frontend_refs(params_cfg, reqs_in, max_new_tokens)
    router = _router(params_cfg, n=2, serving=serving)
    handles = {}

    def poll(r, step):
        k = len(handles)
        if step % 2 == 0 and k < n_req:
            uid = 700 + k
            try:
                handles[uid] = r.submit(reqs_in[uid], uid=uid,
                                        max_new_tokens=max_new_tokens)
            except ServingOverloadError:
                pass          # chaos refused everywhere; retry later
        return len(handles) < n_req
    fault_injector.configure(specs)
    try:
        router.serve(poll=poll, max_steps=500)
    finally:
        fault_injector.reset()
    router.drain()            # close any tail with the channel clean
    return router, handles, refs


def _assert_chaos_exact(router, handles, refs, n_req):
    """No request lost, none double-delivered, every finished stream
    bitwise identical to the undisturbed run."""
    assert len(handles) == n_req
    for uid, r in handles.items():
        assert r.state == RequestState.FINISHED, (uid, r.state,
                                                  r.shed_reason)
        assert r.tokens == refs[uid], uid
    rep = router.get_fleet_report()
    assert rep["router"]["replay_mismatches"] == 0
    assert rep["router"]["abandoned"] == 0
    assert rep["transport"]["injected"] > 0      # chaos actually hit
    assert rep["transport"]["rpcs"] > 0


class TestChaosFaultMatrix:
    """Seeded chaos over the channel-fault kinds on both transport
    sites. Rate specs strike every message class — SUBMIT, STEP,
    TOKENS and HEARTBEAT frames alike — per the ordinal hash, so each
    (kind, rate) cell is one deterministic drill. Tier-1 runs the
    drop cell (the harshest: whole frames vanish both ways); the full
    matrix rides the slow tier."""

    def test_chaos_drop_smoke(self, params_cfg):
        router, handles, refs = _chaos_serve(
            params_cfg, "transport.send:drop~0.15,"
                        "transport.recv:drop~0.15")
        _assert_chaos_exact(router, handles, refs, 6)

    @pytest.mark.slow
    @pytest.mark.parametrize("kind", ["delay", "dup", "reorder",
                                      "truncate"])
    def test_chaos_matrix(self, params_cfg, kind):
        router, handles, refs = _chaos_serve(
            params_cfg, f"transport.send:{kind}~0.15,"
                        f"transport.recv:{kind}~0.15")
        _assert_chaos_exact(router, handles, refs, 6)

    @pytest.mark.slow
    def test_chaos_mixed_kinds(self, params_cfg):
        router, handles, refs = _chaos_serve(
            params_cfg, "transport.send:drop~0.1,"
                        "transport.recv:dup~0.1")
        _assert_chaos_exact(router, handles, refs, 6)

    @pytest.mark.slow
    @pytest.mark.soak
    def test_socket_churn_soak(self, params_cfg):
        """Sustained churn over the SOCKET channel with mixed chaos:
        18 staggered arrivals across two real worker processes while
        frames drop and duplicate — every stream bitwise, nothing
        lost or double-delivered, and the drops provably cost retried
        RPCs rather than lost requests."""
        router, handles, refs = _chaos_serve(
            params_cfg, "transport.send:drop~0.1,"
                        "transport.recv:dup~0.1",
            n_req=18, max_new_tokens=3,
            # short deadline: over a REAL socket a dropped frame
            # costs remaining/attempts of wall clock before the
            # retry, so the soak exercises the timeout path cheaply
            serving={"fleet": {"transport": {
                "channel": "socket", "rpc_deadline_seconds": 2.0}}})
        try:
            _assert_chaos_exact(router, handles, refs, 18)
            t = router.get_fleet_report()["transport"]
            assert t["channel"] == "socket"
            assert t["retries"] > 0         # drops actually cost RPCs
        finally:
            for slot in router.pooled_replicas:
                router._replicas[slot].kill("test teardown")


def _acceptance_drill(params_cfg, serving=None):
    """The ISSUE transport acceptance: staggered shared-prefix
    serve(), the busiest replica killed mid-decode, WITH
    ``transport.send:drop~0.1`` active throughout."""
    N = 8
    rng = np.random.default_rng(5)
    mix = [int(rng.integers(0, 3)) for _ in range(N)]
    reqs_in = {900 + k: SYS[mix[k]] + [60 + k] for k in range(N)}
    refs = _single_frontend_refs(params_cfg, reqs_in, 5)
    router = _router(params_cfg, n=2, serving=serving)
    handles = {}
    armed = {}
    DROP = "transport.send:drop~0.1"
    fault_injector.configure(DROP)

    def poll(r, step):
        if step % 2 == 0 and len(handles) < N:
            k = len(handles)
            uid = 900 + k
            try:
                handles[uid] = r.submit(reqs_in[uid], uid=uid,
                                        max_new_tokens=5)
            except ServingOverloadError:
                pass
        if step == 7 and not armed:
            live = [e for e in r._entries.values()
                    if not e.req.done and e.slot is not None]
            assert any(e.req.state == RequestState.DECODE
                       for e in live)
            slots = [e.slot for e in live]
            victim = max(set(slots), key=slots.count)
            # re-arm BOTH: configure() replaces the active rules
            fault_injector.configure(
                f"{r.spec_for(victim, 0, 'kill')},{DROP}")
            armed["victim"] = victim
        return len(handles) < N

    try:
        router.serve(poll=poll, max_steps=500)
    finally:
        fault_injector.reset()
    router.drain()
    assert len(handles) == N and "victim" in armed
    rep = router.get_fleet_report()
    for uid, r in handles.items():
        assert r.state == RequestState.FINISHED, (uid, r.state,
                                                  r.shed_reason)
        assert r.tokens == refs[uid], uid
    assert rep["recovery"]["deaths"] >= 1
    assert rep["router"]["replay_mismatches"] == 0
    assert rep["router"]["abandoned"] == 0
    assert rep["transport"]["injected"] > 0
    return router, rep


class TestTransportAcceptanceE2E:

    @pytest.mark.slow  # tier-1 diet (PR 17): bootstrap's kill-router drill + chaos_drop_smoke keep kill/drop recovery tier-1
    def test_kill_under_send_drop_loopback(self, params_cfg):
        """Loopback channel: kill mid-decode + drop~0.1, every stream
        bitwise; recompiles <= 1 and steady_blocking_syncs == 0 per
        surviving replica (the PR-9 contract holds under chaos)."""
        router, rep = _acceptance_drill(params_cfg)
        for slot in router.pooled_replicas:
            frep = router._replicas[slot].frontend.get_serving_report()
            assert frep["recompiles"] <= 1, slot
            assert frep["steady_blocking_syncs"] == 0, slot

    @pytest.mark.slow
    def test_kill_under_send_drop_socket(self, params_cfg):
        """SocketChannel: one real OS process per replica (the
        built-in tiny-llama worker factory reproduces the loopback
        params bitwise); the kill terminates the worker PROCESS and
        the respawn cold-starts a new one. Slow tier: two+ worker
        cold starts (jax import + engine build each)."""
        router, rep = _acceptance_drill(
            params_cfg,
            serving={"fleet": {"transport": {"channel": "socket"}}})
        for slot in router.pooled_replicas:
            replica = router._replicas[slot]
            assert replica.frontend is None    # real process isolation
            snap = replica.snapshot()
            assert snap["recompiles"] <= 1, slot
            full = replica.resync()
            assert full["steady_blocking_syncs"] == 0, slot
            proc = replica.channel.inner.proc
            assert proc is not None and proc.poll() is None
        # tear the worker processes down
        for slot in router.pooled_replicas:
            router._replicas[slot].kill("test teardown")


class TestTransportTelemetry:

    def test_fleet_report_transport_block(self, params_cfg):
        router = _router(params_cfg, n=2)
        r = router.submit(SYS[0] + [88], max_new_tokens=3)
        router.drain()
        assert r.state == RequestState.FINISHED
        t = router.get_fleet_report()["transport"]
        assert t["channel"] == "loopback"
        assert t["rpcs"] > 0 and t["bytes_sent"] > 0
        assert t["probes"] > 0                      # the probe pass
        assert set(t["probe_latency_ms"]) == {"p50", "p99"}
        assert set(t["per_replica"]) == {"r0", "r1"}
        assert t["per_replica"]["r0"]["probe"]["suspect"] is False

    def test_transport_flap_alert_on_reconnect_storm(self,
                                                     params_cfg):
        router = _router(params_cfg, n=1, serving={"fleet": {
            "transport": {"flap_window_steps": 50,
                          "flap_alert_reconnects": 3}}})
        for s in (5, 9, 13):
            router._note_reconnect(s)
        kinds = [a.kind for a in router.alerts]
        assert kinds.count("transport_flap") == 1   # debounced
        router._note_reconnect(20)                  # still in window
        assert [a.kind for a in router.alerts].count(
            "transport_flap") == 1

    def test_prober_ledger_units(self):
        p = HealthProber()
        assert not p.suspect
        assert p.fail() == 1 and p.suspect
        assert p.ok(0.001) is True                  # a reconnect
        assert not p.suspect and p.reconnects == 1
        assert p.as_dict()["reconnects"] == 1

    def test_partition_verdict_and_degraded_placement(self,
                                                      params_cfg):
        """A replica whose peer becomes unreachable (the channel
        breaks under it — no fault injector, a REAL dead transport):
        first failed probe marks it suspect, so new placements prefer
        the survivor (degraded mode); the streak past
        ``probe_fail_threshold`` is the PARTITION verdict through the
        standard supervisor ladder; the respawn builds a fresh channel
        and the evacuated work replays bitwise."""
        refs = _single_frontend_refs(
            params_cfg, {4: SYS[1] + [77], 5: SYS[2] + [78]}, 6)
        # heartbeat/progress deadlines parked high: the PROBE ladder
        # must be the detector under test, not step silence
        router = _router(params_cfg, n=2, serving={"fleet": {
            "heartbeat_timeout_steps": 10,
            "progress_timeout_steps": 20}})
        r4 = router.submit(SYS[1] + [77], uid=4, max_new_tokens=6)
        home = router._entries[4].slot
        router.step()
        # the partition: the victim's underlying channel dies (every
        # send raises), while the replica object is still "alive"
        router._replicas[home].channel.inner.close()
        router.step()                     # probe fail 1 -> suspect
        assert router._replicas[home].prober.suspect
        r5 = router.submit(SYS[2] + [78], uid=5, max_new_tokens=6)
        assert router._entries[5].slot == 1 - home   # degraded mode
        router.step()                     # probe fail 2
        router.step()                     # streak 3 -> the verdict
        rec = router.get_fleet_report()["recovery"]
        assert rec["deaths"] == 1
        ev = rec["events"][0]
        assert ev["slot"] == home and ev["mode"] == "partition"
        assert "probe failures" in ev["reason"]
        router.drain()
        assert r4.state == RequestState.FINISHED
        assert r5.state == RequestState.FINISHED
        assert r4.tokens == refs[4] and r5.tokens == refs[5]
        assert router.replay_mismatches == 0
        assert sorted(router.pooled_replicas) == [0, 1]  # respawned
