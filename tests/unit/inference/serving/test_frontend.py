"""ServingFrontend — open-world continuous batching over the v2
ragged engine: request lifecycle, mid-flight join/leave, streaming
delivery, SLO/deadline admission, and the ISSUE acceptance e2e
(staggered shared-prefix requests through serve() with a join + a
cancellation, zero recompiles in the steady window, prefix hits, and
streams bitwise-identical to serve-alone generate_batch)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.sampling import SamplingParams
from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceEngineConfig,
                                        RequestState, ServingFrontend)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.resilience.errors import (InjectedFault, ServingError,
                                             ServingOverloadError,
                                             TerminalRequestError,
                                             UnknownRequestError)
from deepspeed_tpu.resilience.fault_injector import fault_injector

SYS = list(range(1, 17))                 # 2 full 8-token shared blocks
TAILS = {0: [31, 32, 33], 1: [41, 42], 2: [51], 3: [61, 62]}


@pytest.fixture(scope="module")
def params_cfg():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return params, cfg


def _engine(params_cfg, **kw):
    params, cfg = params_cfg
    eng_kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=32, kv_block_size=8,
                  max_blocks_per_seq=8, kv_dtype="float32")
    eng_kw.update(kw)
    return InferenceEngineV2(params, cfg,
                             RaggedInferenceEngineConfig(**eng_kw))


@pytest.fixture(scope="module")
def engine(params_cfg):
    return _engine(params_cfg)


def _clean(engine):
    cached = (engine.prefix_cache.stats()["cached_blocks"]
              if engine.prefix_cache else 0)
    assert not engine._state_manager.tracked_sequences
    assert engine.free_blocks == engine._config.n_kv_blocks - cached


class TestAcceptanceE2E:

    def test_staggered_shared_prefix_requests_stream_bitwise(
            self, params_cfg):
        """The ISSUE acceptance test: N staggered requests with a
        shared system prompt through serve() — (a) a mid-flight join
        and a cancellation, (b) zero recompiles in the steady window,
        (c) prefix-hit-rate > 0, (d) every greedy stream bitwise
        identical to the same request served alone."""
        # serve-alone references: one closed-world generate_batch per
        # request on a cache-less engine of the same config
        ref_eng = _engine(params_cfg)
        refs = {k: ref_eng.generate_batch(
                    {900 + k: SYS + TAILS[k]}, max_new_tokens=6
                )[900 + k] for k in TAILS}

        eng = _engine(params_cfg)          # fresh: recompile count 1
        fe = ServingFrontend(eng)
        reqs = {}
        cancelled = {}

        def poll(f, step):
            # staggered arrivals -> requests JOIN the in-flight batch
            # while earlier ones are mid-decode
            if step in (0, 2, 4, 6):
                k = step // 2
                reqs[k] = f.submit(SYS + TAILS[k], uid=900 + k,
                                   max_new_tokens=6)
            if step == 8 and not cancelled:
                # cancel request 3 mid-flight
                assert not reqs[3].done
                cancelled[3] = list(reqs[3].tokens)
                assert f.cancel(reqs[3].uid)
            return step < 9

        fe.serve(poll=poll)
        # (a) joins were mid-flight: the run overlapped request
        # lifetimes (request 1 submitted while 0 decoded, etc.)
        assert all(reqs[k].state == RequestState.FINISHED
                   for k in (0, 1, 2))
        assert reqs[3].state == RequestState.CANCELLED
        rep = fe.get_serving_report()
        # (b) one compile at the first dispatch, then ZERO recompiles:
        # joins/leaves never change the executable signature
        assert rep["recompiles"] == 1
        assert rep["steady_steps"] > 0
        assert rep["steady_blocking_syncs"] == 0
        # (c) prefix reuse engaged across the shared system prompt
        assert rep["prefix"]["hit_rate"] > 0
        assert rep["prefix"]["tokens_reused"] >= 16
        # (d) bitwise identity vs serve-alone, cancelled included
        # (its delivered tokens are a prefix of its alone-stream)
        for k in (0, 1, 2):
            assert reqs[k].tokens == refs[k], k
        got3 = reqs[3].tokens
        assert got3 == refs[3][:len(got3)]
        # leave-without-draining: the engine is empty afterwards
        _clean(eng)
        assert rep["requests"]["finished"] == 3
        assert rep["requests"]["cancelled"] == 1


class TestLifecycleAndStreaming:

    def test_stream_iterator_pumps_to_completion(self, engine):
        fe = ServingFrontend(engine)
        ref = engine.generate_batch({700: SYS + [91, 92]},
                                    max_new_tokens=5)
        # generate_batch replaced the metrics; the front-end re-owns
        fe = ServingFrontend(engine)
        r = fe.submit(SYS + [91, 92], max_new_tokens=5)
        assert r.state == RequestState.QUEUED
        toks = list(fe.stream(r.uid))
        assert toks == ref[700]
        assert r.state == RequestState.FINISHED
        assert r.ttft_ms is not None and r.latency_ms >= r.ttft_ms
        _clean(engine)

    def test_on_token_callback_ordered(self, engine):
        fe = ServingFrontend(engine)
        seen = []
        r = fe.submit(SYS + [93], max_new_tokens=4,
                      on_token=seen.append)
        fe.drain()
        assert seen == r.tokens and len(seen) == 4
        _clean(engine)

    def test_cancel_mid_prefill_frees_blocks_immediately(
            self, params_cfg):
        """A prompt spread over several SplitFuse chunks, cancelled
        between its chunks: KV blocks and the slot free NOW."""
        eng = _engine(params_cfg, token_budget=8,
                      max_ragged_sequence_count=2)
        fe = ServingFrontend(eng)
        free0 = eng.free_blocks
        r = fe.submit(list(range(1, 21)), max_new_tokens=4)
        fe.step()                       # chunk 1 of the prompt staged
        assert r.state == RequestState.PREFILL
        assert eng.free_blocks < free0
        assert fe.cancel(r.uid)
        assert r.state == RequestState.CANCELLED
        cached = eng.prefix_cache.stats()["cached_blocks"]
        assert eng.free_blocks == free0 - cached
        assert not eng._state_manager.tracked_sequences
        # the front-end keeps serving afterwards
        r2 = fe.submit(list(range(1, 9)), max_new_tokens=2)
        fe.drain()
        assert r2.state == RequestState.FINISHED

    def test_queued_cancel_and_unknown_uid(self, engine):
        """The typed cancel/stream contract (fleet satellite): unknown
        uids raise UnknownRequestError ("never placed"), terminal uids
        raise TerminalRequestError carrying the state ("finished while
        routing") — never a bare KeyError / silent False."""
        fe = ServingFrontend(engine)
        r = fe.submit(SYS, max_new_tokens=2)
        assert fe.cancel(r.uid) is True      # still QUEUED
        assert r.state == RequestState.CANCELLED
        with pytest.raises(TerminalRequestError) as ei:
            fe.cancel(r.uid)                 # already terminal
        assert ei.value.uid == r.uid and ei.value.state == "CANCELLED"
        assert isinstance(ei.value, ServingError)
        with pytest.raises(UnknownRequestError) as ei:
            fe.cancel(12345)
        assert ei.value.uid == 12345
        with pytest.raises(UnknownRequestError):
            fe.stream(12345)
        with pytest.raises(UnknownRequestError):
            fe.result(12345)
        # a terminal-but-retained request still streams its buffer
        assert list(fe.stream(r.uid)) == r.tokens
        _clean(engine)

    def test_cancel_finished_request_is_typed_terminal(self, engine):
        """'finished while routing': a FINISHED request's cancel raises
        TerminalRequestError with state FINISHED (distinguishable from
        never-placed) and its tokens stay readable."""
        fe = ServingFrontend(engine)
        r = fe.submit(SYS + [71], max_new_tokens=3)
        fe.drain()
        assert r.state == RequestState.FINISHED
        with pytest.raises(TerminalRequestError) as ei:
            fe.cancel(r.uid)
        assert ei.value.state == "FINISHED"
        assert fe.result(r.uid) == r.tokens and len(r.tokens) == 3
        _clean(engine)

    def test_mixed_greedy_and_sampled_requests(self, engine):
        fe = ServingFrontend(engine)
        g = fe.submit(SYS + [94], max_new_tokens=4)
        s = fe.submit(SYS + [95], max_new_tokens=4,
                      sampling=SamplingParams(temperature=1.3,
                                              seed=7))
        fe.drain()
        assert len(g.tokens) == 4 and len(s.tokens) == 4
        # conflicting per-request seeds are rejected at submit
        with pytest.raises(ValueError, match="conflicts"):
            fe.submit(SYS, sampling=SamplingParams(temperature=1.0,
                                                   seed=8))
        _clean(engine)

    def test_sampled_stream_bitwise_matches_generate_batch(
            self, params_cfg):
        """Draws are (seed, uid, position)-keyed, so a sampled request
        through the open-world front-end matches the same request in a
        closed-world run — INCLUDING its first token (regression: the
        sampling dict was once built after the final prompt chunk left
        the pending table, so the first token sampled greedily)."""
        sp = SamplingParams(temperature=1.3, top_k=16, seed=11)
        eng = _engine(params_cfg, prefix_cache=False)
        ref = eng.generate_batch({41: SYS + [42]}, max_new_tokens=5,
                                 sampling={41: sp})
        fe = ServingFrontend(eng, {"prefix": {"enabled": False}})
        r = fe.submit(SYS + [42], uid=41, max_new_tokens=5,
                      sampling=sp)
        fe.drain()
        assert r.tokens == ref[41]
        # the greedy stream must differ (proves sampling engaged)
        greedy = eng.generate_batch({43: SYS + [42]}, max_new_tokens=5)
        assert r.tokens != greedy[43]

    def test_greedy_pinned_rejects_sampled_submit(self, engine):
        fe = ServingFrontend(engine, {"executable": "greedy"})
        with pytest.raises(ValueError, match="pinned"):
            fe.submit(SYS, sampling=SamplingParams(temperature=1.0))
        _clean(engine)


class TestAdmissionAndSLO:

    def test_queue_bound_sheds_or_raises_at_submit(self, engine):
        fe = ServingFrontend(engine, {"max_queue_depth": 1})
        fe.submit(SYS, max_new_tokens=2)
        with pytest.raises(ServingOverloadError):
            fe.submit(SYS + [1], max_new_tokens=2)
        fe.drain()
        fe2 = ServingFrontend(engine, {"max_queue_depth": 1,
                                       "on_overload": "shed"})
        fe2.submit(SYS, max_new_tokens=2)
        shed = fe2.submit(SYS + [1], max_new_tokens=2)
        assert shed.state == RequestState.SHED
        fe2.drain()
        _clean(engine)
        # engine admission knob restored for the module engine
        engine._config.max_queue_depth = 0

    def test_slo_breach_sheds_unprioritized_and_alerts(self, engine):
        """With a sub-microsecond TTFT SLO, the first served request
        puts the live histogram in breach: later priority<=0 arrivals
        shed (with a typed TelemetryAlert), priority>0 rides through."""
        fe = ServingFrontend(engine, {"ttft_slo_ms": 1e-6})
        r1 = fe.submit(SYS + [96], max_new_tokens=3)
        fe.drain()                       # r1 serves (no data -> no gate)
        assert r1.state == RequestState.FINISHED
        low = fe.submit(SYS + [97], max_new_tokens=3)
        high = fe.submit(SYS + [98], max_new_tokens=3, priority=1)
        fe.drain()
        assert low.state == RequestState.SHED
        assert "SLO" in low.shed_reason
        assert high.state == RequestState.FINISHED
        kinds = {a.kind for a in fe.alerts}
        assert kinds == {"slo_breach"}
        rep = fe.get_serving_report()
        assert rep["gate"]["slo_sheds"] == 1
        assert rep["gate"]["slo_breaches"] >= 1
        _clean(engine)

    def test_expired_deadline_shed_with_fake_clock(self, engine):
        t = [0.0]
        fe = ServingFrontend(engine, clock=lambda: t[0])
        ok = fe.submit(SYS + [99], max_new_tokens=2, deadline_ms=50.0)
        late = fe.submit(SYS + [90], max_new_tokens=2,
                         deadline_ms=5.0)
        t[0] += 0.010                    # 10ms in queue
        fe.drain()
        assert ok.state == RequestState.FINISHED
        assert late.state == RequestState.SHED
        assert "deadline" in late.shed_reason
        assert any(a.metric == "serving/deadline_ms"
                   for a in fe.alerts)
        _clean(engine)

    def test_telemetry_hub_receives_gate_alerts(self, engine, tmp_path):
        from deepspeed_tpu.telemetry.hub import JsonlSink, TelemetryHub
        sink = JsonlSink(str(tmp_path / "t.jsonl"))
        hub = TelemetryHub(sink=sink)
        fe = ServingFrontend(engine, {"ttft_slo_ms": 1e-6})
        fe.attach_telemetry(hub)
        fe.submit(SYS + [89], max_new_tokens=2)
        fe.drain()
        shed = fe.submit(SYS + [88], max_new_tokens=2)
        fe.drain()
        assert shed.state == RequestState.SHED
        assert hub.alert_counts().get("slo_breach", 0) >= 1
        recs = sink.read_records()
        assert any(r.get("kind") == "alert" for r in recs)
        # the serving namespace reaches the hub's flat stream
        flat = hub.sample(1)
        assert any(k.startswith("serving/") for k in flat)
        _clean(engine)


class TestFaultDrill:

    def test_shed_request_never_leaks_blocks_or_slots(self, engine):
        """The satellite drill: injected faults at the serving.admit
        and frontend.join sites shed exactly the struck request —
        engine pool and sequence table end clean, the surviving
        request streams normally."""
        free0 = engine.free_blocks
        tracked0 = len(engine._state_manager.tracked_sequences)
        fe = ServingFrontend(engine)
        with fault_injector.inject("serving.admit:error"):
            victim = fe.submit(SYS + [87], max_new_tokens=3)
            survivor = fe.submit(SYS + [86], max_new_tokens=3)
            fe.drain()
        assert victim.state == RequestState.SHED
        assert "admission fault" in victim.shed_reason
        assert survivor.state == RequestState.FINISHED
        assert len(engine._state_manager.tracked_sequences) == tracked0
        cached = engine.prefix_cache.stats()["cached_blocks"]
        assert engine.free_blocks == \
            engine._config.n_kv_blocks - cached

        # join-site fault fires AFTER prefix adoption: the handler
        # must flush the just-created sequence
        with fault_injector.inject("frontend.join:error"):
            victim2 = fe.submit(SYS + [85], max_new_tokens=3)
            survivor2 = fe.submit(SYS + [84], max_new_tokens=3)
            fe.drain()
        assert victim2.state == RequestState.SHED
        assert "join fault" in victim2.shed_reason
        assert isinstance(InjectedFault("x"), Exception)
        assert survivor2.state == RequestState.FINISHED
        _clean(engine)
        rep = fe.get_serving_report()
        assert rep["requests"]["shed"] == 2
        assert rep["requests"]["finished"] == 2

    def test_stuck_frontend_raises_typed_overload(self, params_cfg):
        """Requests waiting, nothing schedulable, nothing in flight:
        step() surfaces the typed saturation error instead of
        spinning."""
        eng = _engine(params_cfg, n_kv_blocks=2, max_blocks_per_seq=8,
                      prefix_cache=False)
        fe = ServingFrontend(eng, {"prefix": {"enabled": False}})
        fe.submit(list(range(1, 30)), max_new_tokens=2)  # needs 4 blocks
        with pytest.raises(ServingOverloadError, match="stuck"):
            fe.drain()
