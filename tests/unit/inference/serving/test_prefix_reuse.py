"""Prefix-aware KV block reuse — the device-facing contract:

* greedy streams are BITWISE identical with the cache on vs off
  (including through the EOS-overshoot rollback path): shared blocks
  hold the same KV values a private prefill would have written, and
  the device reads them through the same fixed-shape block tables;
* refcount conservation under serve/flush churn through the real
  engine (`generate_batch` runs, not synthetic descriptors);
* scheduler pressure reclaims cache-only blocks instead of failing.
"""

import jax
import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import (InferenceEngineV2,
                                        RaggedInferenceEngineConfig)
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

# 2 full 8-token blocks of shared head + unique tails
SYS = list(range(1, 17))
PROMPTS_A = {10: SYS + [31, 32, 33], 11: SYS + [41, 42]}
PROMPTS_B = {20: SYS + [51], 21: SYS + [61, 62, 63, 64]}


@pytest.fixture(scope="module")
def params_cfg():
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return params, cfg


def _engine(params_cfg, prefix_cache, n_blocks=32, **kw):
    params, cfg = params_cfg
    return InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(
            token_budget=32, max_ragged_sequence_count=4,
            n_kv_blocks=n_blocks, kv_block_size=8,
            max_blocks_per_seq=8, kv_dtype="float32",
            prefix_cache=prefix_cache, **kw))


def _clean(engine, cached=0):
    assert not engine._state_manager.tracked_sequences
    assert engine.free_blocks == engine._config.n_kv_blocks - cached


class TestBitwiseReuse:

    def test_streams_identical_with_reuse_on_vs_off(self, params_cfg):
        off = _engine(params_cfg, False)
        ref_a = off.generate_batch(dict(PROMPTS_A), max_new_tokens=6)
        _clean(off)
        ref_b = off.generate_batch(dict(PROMPTS_B), max_new_tokens=6)
        _clean(off)

        on = _engine(params_cfg, True)
        # run 1: cold cache (intra-batch arrivals register, later
        # requests in the SAME batch may already hit)
        got_a = on.generate_batch(dict(PROMPTS_A), max_new_tokens=6)
        assert got_a == ref_a
        st = on.prefix_cache.stats()
        assert st["cached_blocks"] == 2
        # run 2: warm cache — both requests adopt the 16-token head
        got_b = on.generate_batch(dict(PROMPTS_B), max_new_tokens=6)
        assert got_b == ref_b
        st = on.prefix_cache.stats()
        assert st["hits"] >= 2
        assert st["tokens_reused"] >= 32
        _clean(on, cached=st["cached_blocks"])

    def test_eos_overshoot_rollback_path_with_reuse(self, params_cfg):
        """EOS discovered one step late on an ADOPTED sequence: the
        speculative row's rollback frees only private blocks, streams
        still match the cache-off run bitwise."""
        off = _engine(params_cfg, False)
        probe = off.generate_batch(dict(PROMPTS_A), max_new_tokens=6)
        _clean(off)
        eos = probe[10][2]          # mid-stream token -> late EOS
        ref = off.generate_batch(dict(PROMPTS_A), max_new_tokens=6,
                                 eos_token_id=eos)
        _clean(off)

        on = _engine(params_cfg, True)
        on.generate_batch(dict(PROMPTS_A), max_new_tokens=2)  # seed
        got = on.generate_batch(dict(PROMPTS_A), max_new_tokens=6,
                                eos_token_id=eos)
        assert got == ref
        assert len(got[10]) == 3 and got[10][-1] == eos
        rep = on.get_serving_report()
        assert rep["cancelled_speculative_steps"] >= 1
        assert rep["prefix"]["hits"] >= 2
        _clean(on, cached=on.prefix_cache.stats()["cached_blocks"])

    def test_sampled_streams_identical_with_reuse(self, params_cfg):
        from deepspeed_tpu.inference.sampling import SamplingParams
        sp = SamplingParams(temperature=1.3, top_k=16, seed=11)
        off = _engine(params_cfg, False)
        ref = off.generate_batch(dict(PROMPTS_A), max_new_tokens=5,
                                 sampling=sp)
        _clean(off)
        on = _engine(params_cfg, True)
        on.generate_batch(dict(PROMPTS_A), max_new_tokens=2,
                          sampling=sp)          # seed the cache
        got = on.generate_batch(dict(PROMPTS_A), max_new_tokens=5,
                                sampling=sp)
        # draws are (seed, uid, position)-keyed: adoption shifts WHICH
        # positions run, never the key of a sampled position
        assert got == ref


class TestRefcountChurn:

    def test_serve_flush_churn_conserves_every_block(self, params_cfg):
        eng = _engine(params_cfg, True)
        for r in range(4):
            prompts = {100 * r + k: SYS + [70 + 10 * r + k]
                       for k in range(3)}
            out = eng.generate_batch(prompts, max_new_tokens=3)
            assert all(len(v) == 3 for v in out.values())
            _clean(eng, cached=eng.prefix_cache.stats()["cached_blocks"])
        st = eng.prefix_cache.stats()
        assert st["hits"] >= 9        # rounds 2-4 all hit (3 each)
        # cache pins exactly its entries; clearing restores the pool
        assert eng.prefix_cache.clear() == st["cached_blocks"]
        assert eng.free_blocks == eng._config.n_kv_blocks
        assert eng._state_manager.kv.allocator.live_blocks == 0

    def test_scheduler_reclaims_cache_blocks_under_pressure(
            self, params_cfg):
        """A pool mostly pinned by the cache must serve new work: the
        scheduler evicts cache-only blocks instead of raising
        OutOfKVBlocks."""
        eng = _engine(params_cfg, True, n_blocks=8)
        long_head = list(range(1, 41))           # 5 blocks cached
        eng.generate_batch({1: long_head + [99]}, max_new_tokens=2)
        assert eng.prefix_cache.stats()["cached_blocks"] == 5
        assert eng.free_blocks == 3
        # an unrelated prompt needing 5 blocks forces reclaim
        out = eng.generate_batch(
            {2: [200 + i for i in range(33)]}, max_new_tokens=2)
        assert len(out[2]) == 2
        assert eng.prefix_cache.stats()["evicted_blocks"] >= 2
        _clean(eng, cached=eng.prefix_cache.stats()["cached_blocks"])
