"""Async tiered-store I/O for the serving cache (PR 18): demotions
kicked after step dispatch and finalized on a later poll (write-behind
via the shared IoWorker), ring-prefetched promotion staged ahead of
prefill, the PR 16 contracts (crash-leaves-entry-hot, one tier at a
time, walk guard, degrade-to-recompute) held across the new async
window — and the acceptance gate: greedy streams bitwise identical
with async on/off, including under seeded chaos."""

import threading

import numpy as np
import pytest

from deepspeed_tpu.inference.v2 import RequestState, ServingFrontend
from deepspeed_tpu.inference.v2.ragged_manager import BlockedAllocator
from deepspeed_tpu.inference.v2.serving.prefix import chain_digests
from deepspeed_tpu.inference.v2.serving.tiered import TieredPrefixCache
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime.store import AsyncSpillQueue, HostBlockStore

from .test_tiered_cache import (BS, FakeKV, _chain, _engine, _requests,
                                _tiers_cfg, params_cfg)  # noqa: F401

pytestmark = pytest.mark.fault


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def _async_tiered(n_blocks=16, max_blocks=0, dram_bytes=0,
                  queue_bytes=64 << 20, **kw):
    a = BlockedAllocator(n_blocks)
    kv = FakeKV()
    dram = AsyncSpillQueue(HostBlockStore(dram_bytes),
                           max_pending_bytes=queue_bytes)
    pc = TieredPrefixCache(BS, a, max_blocks=max_blocks, kv_io=kv,
                           dram_store=dram, disk_store=None,
                           async_io=True, **kw)
    assert pc.async_io
    return pc, a, kv


def _settle(pc, timeout=10.0):
    """Deterministic finalize: drain the spill worker, then poll."""
    assert pc.dram.drain(timeout=timeout)
    return pc.poll_demotions()


class TestAsyncDemotion:

    def test_insert_defers_and_kick_finalizes_with_overlap(self):
        pc, a, kv = _async_tiered(max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        # async mode: the size bound did NOT demote inside insert()
        assert pc.cached_blocks == 3 and pc.demoted_blocks == 0
        assert pc.kick_demotions() == 1
        d1 = chain_digests(p1, BS)[0]
        assert d1 in pc._demote_inflight
        assert pc.resident_tier(d1) == "hbm"   # hot until finalized
        assert _settle(pc) == 1
        assert pc.resident_tier(d1) == "dram"  # one tier at a time
        assert pc.cached_blocks == 2 and pc.demoted_blocks == 1
        assert a.free_blocks == 16 - 2
        st = pc.stats()
        assert st["cache_demote_exposed_ms"] > 0.0
        assert st["cache_demote_overlapped_ms"] > 0.0
        assert st["demote_inflight"] == 0
        # the spilled payload promotes back bitwise
        blocks, n = pc.match(p1)
        assert n == BS
        assert np.array_equal(kv.data[blocks[0]],
                              np.full((2, 2, BS, 2), 0, np.float32))

    def test_killed_flush_leaves_entry_hot(self):
        """THE drill: a kill on the background flush drops the spill
        — nothing torn, the entry simply stays in HBM and the next
        kick retries."""
        pc, a, kv = _async_tiered(max_blocks=2)
        p1, b1 = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        with fault_injector.inject("store.flush:kill"):
            pc.kick_demotions()
            _settle(pc)
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "hbm"
        assert pc.demote_failures == 1 and pc.demoted_blocks == 0
        assert len(pc.dram) == 0
        assert np.array_equal(kv.data[b1[0]],
                              np.full((2, 2, BS, 2), 0, np.float32))
        pc.kick_demotions()                    # fault cleared: retried
        _settle(pc)
        assert pc.demoted_blocks == 1

    def test_kill_at_the_kick_never_reaches_the_queue(self):
        pc, a, kv = _async_tiered(max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        with fault_injector.inject("cache.demote:kill@0xinf"):
            assert pc.kick_demotions() == 0
        assert pc.demote_failures >= 1
        assert not pc._demote_inflight
        assert pc.resident_tier(chain_digests(p1, BS)[0]) == "hbm"

    def test_backpressure_skips_the_demotion_not_the_step(self):
        pc, a, kv = _async_tiered(max_blocks=2, queue_bytes=1)
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        assert pc.kick_demotions() == 0        # valve: skipped, typed
        assert pc.spill_backpressure >= 1
        assert pc.resident_tier(chain_digests(p1, BS)[0]) == "hbm"
        assert not pc._demote_inflight

    def test_readopted_entry_aborts_its_inflight_demotion(self):
        """The coherence hazard the tick check closes: the entry got
        HOT again while its gathered payload was in flight — the
        finalize must abort and delete the spilled copy, never leave
        the digest in two tiers (or demote a block someone adopted)."""
        pc, a, kv = _async_tiered(max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        pc.kick_demotions()
        assert pc.dram.drain(timeout=10.0)     # flush landed...
        assert pc.match(p1)[1] == BS           # ...but p1 re-adopted
        assert pc.poll_demotions() == 0
        assert pc.demote_aborts == 1
        d1 = chain_digests(p1, BS)[0]
        assert pc.resident_tier(d1) == "hbm"   # stayed hot
        assert len(pc.dram) == 0               # spilled copy retired

    def test_sync_reclaim_never_steals_an_inflight_digest(self):
        """need_free stays synchronous in async mode, and must route
        AROUND digests with a pending flush — a sync demote of the
        same digest would race its own background copy."""
        pc, a, kv = _async_tiered(max_blocks=2)
        p1, _ = _chain(pc, a, kv, 0)
        p2, _ = _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        gate = threading.Event()
        pc.dram.worker.submit(gate.wait)       # park the flush
        pc.kick_demotions()
        d1, d2 = (chain_digests(p, BS)[0] for p in (p1, p2))
        assert d1 in pc._demote_inflight
        assert pc.reclaim(1) == 1              # sync valve, d1 shielded
        assert pc.resident_tier(d2) == "dram"  # the NEXT leaf went
        gate.set()
        _settle(pc)
        assert pc.resident_tier(d1) == "dram"  # flush finalized clean
        assert pc.demote_aborts == 0

    def test_clear_with_inflight_flush_retires_the_orphan(self):
        pc, a, kv = _async_tiered(max_blocks=2)
        _chain(pc, a, kv, 0)
        _chain(pc, a, kv, 100)
        _chain(pc, a, kv, 200)
        gate = threading.Event()
        pc.dram.worker.submit(gate.wait)
        pc.kick_demotions()
        pc.clear()
        assert pc.cached_blocks == 0
        gate.set()
        assert pc.dram.drain(timeout=10.0)     # orphan payload landed
        pc.poll_demotions()                    # entry gone -> abort
        assert pc.demote_aborts == 1
        assert len(pc.dram) == 0


class TestPromotePrefetch:

    def _spilled_chain(self, n_blocks=3, **kw):
        pc, a, kv = _async_tiered(**kw)
        prompt, _ = _chain(pc, a, kv, 0, n_blocks=n_blocks)
        pc.reclaim(n_blocks)                   # whole chain to dram
        assert pc.spilled_blocks == n_blocks
        return pc, a, kv, prompt

    def test_hint_stages_and_match_consumes_overlapped(self):
        pc, a, kv, prompt = self._spilled_chain(prefetch_depth=4)
        assert pc.hint_adoptions(prompt) == 3
        assert pc.dram.drain(timeout=10.0)     # staging off-thread
        blocks, n = pc.match(prompt)
        assert n == 3 * BS
        st = pc.stats()
        assert st["prefetch_kicks"] == 3 and st["prefetch_hits"] == 3
        assert st["prefetch_misses"] == 0
        assert st["cache_promote_overlapped_ms"] > 0.0
        for i, b in enumerate(blocks):         # bitwise payloads
            assert np.array_equal(
                kv.data[b], np.full((2, 2, BS, 2), i, np.float32))
        assert not pc._prefetch_stage          # stages consumed

    def test_windowed_ring_advances_behind_the_walk(self):
        """prefetch_depth=1 over a 3-block spilled span: only the
        first block stages at hint time; each consumed stage advances
        the ring, so the whole chain still arrives prefetched."""
        pc, a, kv, prompt = self._spilled_chain(prefetch_depth=1)
        assert pc.hint_adoptions(prompt) == 1
        assert pc.match(prompt)[1] == 3 * BS
        st = pc.stats()
        assert st["prefetch_kicks"] == 3 and st["prefetch_hits"] == 3

    def test_prefetch_fault_is_advisory_never_degrades(self):
        pc, a, kv, prompt = self._spilled_chain()
        with fault_injector.inject("cache.prefetch:ioerror@0xinf"):
            pc.hint_adoptions(prompt)
            assert pc.dram.drain(timeout=10.0)
            blocks, n = pc.match(prompt)       # sync fallback reads
        assert n == 3 * BS and pc.degraded == 0
        assert pc.prefetch_errors >= 1
        assert pc.stats()["cache_promote_exposed_ms"] > 0.0

    def test_unhinted_match_counts_misses_and_still_serves(self):
        pc, a, kv, prompt = self._spilled_chain()
        assert pc.match(prompt)[1] == 3 * BS
        st = pc.stats()
        assert st["prefetch_misses"] == 3 and st["prefetch_hits"] == 0

    def test_fresh_insert_invalidates_the_stale_stage(self):
        """A prefill re-inserting a spilled digest retires the spilled
        copy AND its parked stage — the stage must never outlive the
        tier residency it was read from."""
        pc, a, kv, prompt = self._spilled_chain(n_blocks=1)
        pc.hint_adoptions(prompt)
        assert pc.dram.drain(timeout=10.0)
        assert len(pc._prefetch_stage) == 1
        _chain(pc, a, kv, 0)                   # fresh prefill, same chain
        assert not pc._prefetch_stage
        assert pc.match(prompt)[1] == BS       # served from HBM
        assert pc.stats()["prefetch_hits"] == 0

    def test_hint_stops_at_the_quarantine_exactly_like_the_walk(self):
        pc, a, kv, prompt = self._spilled_chain()
        d1 = chain_digests(prompt, BS)[0]
        pc._quarantine[d1] = True
        assert pc.hint_adoptions(prompt) == 0  # walk would stop too


class TestServingAsyncBitwiseGate:

    def _serve_settled(self, fe, requests, max_new_tokens=6):
        """Serial serve that deterministically finalizes the async
        demotions between requests (drain the spill worker + poll),
        so tier crossings actually happen before the next submit's
        hint/match — same schedule, no timing dependence."""
        pc = fe.engine.prefix_cache
        out = {}
        for uid, prompt in requests.items():
            r = fe.submit(prompt, uid=uid,
                          max_new_tokens=max_new_tokens)
            fe.drain()
            assert r.state == RequestState.FINISHED
            out[uid] = list(r.tokens)
            if getattr(pc, "async_io", False):
                assert pc.dram.drain(timeout=30.0)
                pc.poll_demotions()
        return out

    def _async_cfg(self):
        cfg = _tiers_cfg()
        cfg["prefix"]["tiers"]["async_io"] = True
        return cfg

    def test_streams_identical_async_on_off_with_real_crossings(
            self, params_cfg):
        """THE acceptance gate: same greedy schedule, sync tiers vs
        async tiers — bitwise-identical streams, with real async
        demotions, staged promotions AND zero added blocking syncs
        (`blocking_sync` counts only the no-dispatch drain steps,
        exactly like the sync run)."""
        reqs = _requests()
        fe_sync = ServingFrontend(_engine(params_cfg), _tiers_cfg())
        try:
            refs = self._serve_settled(fe_sync, reqs)
        finally:
            fe_sync.close()

        fe = ServingFrontend(_engine(params_cfg), self._async_cfg())
        try:
            got = self._serve_settled(fe, reqs)
            assert got == refs, "stream diverged with async tiers"
            pc = fe.engine.prefix_cache
            st = pc.stats()
            assert st["async_io"] == 1
            assert st["demoted_blocks"] > 0      # write-behind spills
            assert st["promoted_blocks"] > 0
            assert st["prefetch_hits"] > 0       # promote-ahead landed
            assert st["degraded"] == 0
            assert st["cache_demote_overlapped_ms"] > 0.0
            assert st["cache_promote_overlapped_ms"] > 0.0
            # the serving report carries the async counter schema
            rep = fe.engine.get_serving_report()["prefix"]
            for k in ("spill_backlog", "demote_aborts",
                      "cache_demote_exposed_ms", "prefetch_kicks"):
                assert k in rep
        finally:
            fe.close()

    @pytest.mark.slow
    def test_chaos_matrix_streams_stay_bitwise(self, params_cfg):
        """Seeded chaos across every async crossing: killed flushes,
        slow flushes, failed prefetches, killed demote kicks — the
        streams never move (degrade-to-recompute + entry-stays-hot do
        the absorbing) and nothing crashes."""
        reqs = _requests()
        fe_sync = ServingFrontend(_engine(params_cfg), _tiers_cfg())
        try:
            refs = self._serve_settled(fe_sync, reqs)
        finally:
            fe_sync.close()
        for spec in ("store.flush:kill",
                     "store.flush:slow@0xinf~0.005",
                     "cache.prefetch:ioerror@0xinf",
                     "cache.demote:kill",
                     "cache.promote:kill"):
            fe = ServingFrontend(_engine(params_cfg),
                                 self._async_cfg())
            try:
                with fault_injector.inject(spec):
                    got = self._serve_settled(fe, reqs)
                assert got == refs, f"stream diverged under {spec}"
                assert fe.engine.prefix_cache.stats()[
                    "spilled_blocks"] >= 0   # internals stayed sane
            finally:
                fe.close()
