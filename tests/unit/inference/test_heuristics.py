"""v2 module-implementation selection (reference
v2/modules/heuristics.py:186): config picks/pins implementations, bad
combinations fail loudly, and the pinned attention implementation
actually reaches the kernel dispatch."""

import numpy as np
import pytest

import jax

from deepspeed_tpu.inference.v2 import InferenceEngineV2
from deepspeed_tpu.inference.v2.engine_v2 import RaggedInferenceEngineConfig
from deepspeed_tpu.inference.v2.heuristics import (instantiate_attention,
                                                   instantiate_linear,
                                                   instantiate_moe)


def test_attention_selection():
    assert instantiate_attention("auto") == {}
    assert instantiate_attention("pallas") == {"force_pallas": True}
    assert instantiate_attention("reference") == \
        {"force_reference": True}
    with pytest.raises(ValueError, match="attention"):
        instantiate_attention("triton")


def test_linear_selection():
    assert instantiate_linear("dense") == "dense"
    assert instantiate_linear("woq_kernel", quantized=True) == \
        "woq_kernel"
    with pytest.raises(ValueError, match="quantized"):
        instantiate_linear("woq_kernel", quantized=False)
    # auto on CPU -> dense even for quantized trees
    assert instantiate_linear("auto", quantized=True) in \
        ("dense", "woq_kernel")


def test_moe_selection():
    assert instantiate_moe("auto", ep_size=1) == "replicated"
    assert instantiate_moe("auto", ep_size=4) == "expert_parallel"
    with pytest.raises(ValueError, match="ep_size"):
        instantiate_moe("expert_parallel", ep_size=1)
    with pytest.raises(ValueError, match="conflicts"):
        instantiate_moe("replicated", ep_size=4)


def test_engine_serves_with_pinned_reference_attention(eight_devices):
    """The config knob reaches the dispatch: decode with
    attn_impl='reference' produces the same tokens as 'auto' (on the
    CPU test platform both resolve to the reference math, so this is a
    wiring check, not a numerics one)."""
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    outs = {}
    for impl in ("auto", "reference"):
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        eng = InferenceEngineV2(
            params, cfg, RaggedInferenceEngineConfig(
                token_budget=32, max_ragged_sequence_count=4,
                n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
                kv_dtype="float32", attn_impl=impl))
        outs[impl] = eng.generate_batch({1: [3, 1, 4, 1, 5]},
                                        max_new_tokens=5)
    assert outs["auto"] == outs["reference"]


def test_woq_kernel_linear_serves_same_tokens(eight_devices):
    """linear_impl='woq_kernel': the forward consumes the quantized
    tree through _linear (no whole-tree dequant) and decodes the same
    tokens as the dequantize path."""
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    outs = {}
    for impl in ("dense", "woq_kernel"):
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        eng = InferenceEngineV2(
            params, cfg, RaggedInferenceEngineConfig(
                token_budget=32, max_ragged_sequence_count=4,
                n_kv_blocks=32, kv_block_size=8, max_blocks_per_seq=8,
                kv_dtype="float32", weight_dtype="int8",
                quantization_min_size=16, linear_impl=impl))
        assert eng.linear_impl == impl
        outs[impl] = eng.generate_batch({1: [3, 1, 4, 1, 5]},
                                        max_new_tokens=5)
    assert outs["dense"] == outs["woq_kernel"]


def test_bad_engine_config_fails_at_construction(eight_devices):
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    with pytest.raises(ValueError, match="attention"):
        InferenceEngineV2(params, cfg, RaggedInferenceEngineConfig(
            attn_impl="cuda"))
