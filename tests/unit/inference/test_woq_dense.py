"""WOQDense routing: the dense branch must be bit-identical to
flax nn.Dense (training / unquantized serving), and a quantized param
tree must take the woq_matmul branch — for plain dicts AND FrozenDict
trees (flax.core.freeze)."""

import numpy as np

import flax
import flax.linen as nn
import jax
import jax.numpy as jnp

from deepspeed_tpu.inference.quantization import quantize_weight
from deepspeed_tpu.models.woq_dense import WOQDense
from deepspeed_tpu.ops.pallas_kernels.woq_matmul import woq_matmul_reference


def _trees(rng, use_bias=True):
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    dense = nn.Dense(128, use_bias=use_bias)
    woq = WOQDense(128, use_bias=use_bias)
    params = dense.init(jax.random.PRNGKey(0), x)
    return x, dense, woq, params


def test_dense_branch_bit_identical_to_nn_dense(rng):
    for use_bias in (True, False):
        x, dense, woq, params = _trees(rng, use_bias)
        ref = dense.apply(params, x)
        got = woq.apply(params, x)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_init_structure_matches_nn_dense(rng):
    x, dense, woq, _ = _trees(rng)
    pd = dense.init(jax.random.PRNGKey(1), x)["params"]
    pw = woq.init(jax.random.PRNGKey(1), x)["params"]
    assert set(pd) == set(pw) == {"kernel", "bias"}
    for k in pd:
        assert pd[k].shape == pw[k].shape


def test_quantized_tree_routes_to_woq_matmul(rng):
    x, dense, woq, params = _trees(rng)
    kernel = params["params"]["kernel"]
    leaf = quantize_weight(kernel, 8, 64)
    qparams = {"params": {"kernel": leaf,
                          "bias": params["params"]["bias"]}}
    got = woq.apply(qparams, x.astype(jnp.bfloat16))
    expect = woq_matmul_reference(
        x.astype(jnp.bfloat16), leaf["woq_q"], leaf["woq_scales"]) \
        + params["params"]["bias"].astype(jnp.bfloat16)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(expect, np.float32),
                               atol=3e-2, rtol=3e-2)
    # and the quantized output differs from dense only by quant noise
    ref = dense.apply(params, x)
    err = np.abs(np.asarray(got, np.float32) - np.asarray(ref))
    assert 0 < err.max() < 0.2


def test_frozen_dict_tree_also_routes(rng):
    """flax.core.freeze trees are Mappings, not dicts — the woq branch
    must still fire (a dict-only isinstance check silently falls into
    the dense path and crashes on the subtree)."""
    x, dense, woq, params = _trees(rng)
    leaf = quantize_weight(params["params"]["kernel"], 8, 64)
    qparams = flax.core.freeze(
        {"params": {"kernel": jax.tree_util.tree_map(lambda a: a, leaf),
                    "bias": params["params"]["bias"]}})
    got = woq.apply(qparams, x.astype(jnp.bfloat16))
    assert got.shape == (4, 128)
    assert np.isfinite(np.asarray(got, np.float32)).all()


def test_llama_is_woq_native(rng):
    from deepspeed_tpu.models.llama import LlamaForCausalLM
    assert getattr(LlamaForCausalLM, "woq_native", False)
