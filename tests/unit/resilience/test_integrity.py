"""Checkpoint integrity: manifest verification, atomic shard writes,
bounded retry, and the previous-good-tag fallback — every corruption
mode must end in the previous good state or a typed error, never
garbage."""

import json
import os
import pickle
import time

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.checkpoint.engine import (_npz_load, _npz_save,
                                             load_checkpoint,
                                             save_checkpoint)
from deepspeed_tpu.resilience import (CheckpointCorruptionError,
                                      CheckpointLoadError, retry_io,
                                      verify_manifest, write_manifest)

pytestmark = pytest.mark.fault


def _state():
    return {"w": jnp.arange(8.0), "b": jnp.ones((3, 2)) * 2.0}


def _save_two_tags(d):
    save_checkpoint(str(d), "t1", _state(), client_state={"global_steps": 1})
    time.sleep(0.01)  # distinct state mtimes order the fallback scan
    save_checkpoint(str(d), "t2", _state(), client_state={"global_steps": 2})


def _corrupt_largest_payload(state_dir, how="truncate"):
    man = json.load(open(os.path.join(state_dir, "manifest.json")))
    rel = max(man["files"], key=lambda r: man["files"][r]["size"])
    p = os.path.join(state_dir, rel)
    if how == "truncate":
        with open(p, "r+b") as f:
            f.truncate(max(0, os.path.getsize(p) - 7))
    else:  # same-size bit flip: only the checksum can catch it
        with open(p, "r+b") as f:
            f.seek(os.path.getsize(p) // 2)
            byte = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([byte[0] ^ 0xFF]))
    return rel


def test_manifest_roundtrip(tmp_path):
    sd = tmp_path / "state"
    sd.mkdir()
    (sd / "a.bin").write_bytes(b"payload-a")
    (sd / "b.bin").write_bytes(b"payload-b" * 100)
    man = write_manifest(str(sd))
    assert set(man["files"]) == {"a.bin", "b.bin"}
    assert verify_manifest(str(sd)) is not None


@pytest.mark.parametrize("how", ["truncate", "bitflip"])
def test_manifest_detects_corruption(tmp_path, how):
    save_checkpoint(str(tmp_path), "t", _state())
    sd = os.path.join(str(tmp_path), "t", "state")
    _corrupt_largest_payload(sd, how)
    with pytest.raises(CheckpointCorruptionError,
                       match="mismatch|size"):
        verify_manifest(sd)


def test_manifest_detects_missing_file(tmp_path):
    save_checkpoint(str(tmp_path), "t", _state())
    sd = os.path.join(str(tmp_path), "t", "state")
    man = json.load(open(os.path.join(sd, "manifest.json")))
    os.unlink(os.path.join(sd, next(iter(man["files"]))))
    with pytest.raises(CheckpointCorruptionError, match="missing"):
        verify_manifest(sd)


def test_missing_manifest_is_legacy_not_corrupt(tmp_path):
    """Pre-integrity checkpoints (no manifest) still load; strict mode
    upgrades the absence to corruption."""
    save_checkpoint(str(tmp_path), "t", _state())
    sd = os.path.join(str(tmp_path), "t", "state")
    os.unlink(os.path.join(sd, "manifest.json"))
    assert verify_manifest(sd) is None
    with pytest.raises(CheckpointCorruptionError, match="manifest"):
        verify_manifest(sd, strict=True)
    state, _ = load_checkpoint(str(tmp_path), "t", _state())
    np.testing.assert_allclose(np.asarray(state["w"]),
                               np.arange(8.0))


def test_corrupt_tag_falls_back_to_previous_good(tmp_path):
    _save_two_tags(tmp_path)
    _corrupt_largest_payload(
        os.path.join(str(tmp_path), "t2", "state"))
    state, cs = load_checkpoint(str(tmp_path), None, _state())
    assert cs["global_steps"] == 1          # t1, the previous good tag
    np.testing.assert_allclose(np.asarray(state["w"]), np.arange(8.0))
    # latest repointed at what was actually loaded
    assert (tmp_path / "latest").read_text().strip() == "t1"


def test_stale_latest_falls_back(tmp_path):
    """``latest`` naming a deleted tag must recover through the scan,
    not crash or return garbage."""
    _save_two_tags(tmp_path)
    import shutil
    shutil.rmtree(tmp_path / "t2")
    (tmp_path / "latest").write_text("t2")
    state, cs = load_checkpoint(str(tmp_path), None, _state())
    assert cs["global_steps"] == 1


def test_explicit_tag_never_silently_substitutes(tmp_path):
    """An explicitly requested tag that is corrupt must RAISE — the
    caller asked for specific weights; handing back a different tag's
    would be worse than failing."""
    _save_two_tags(tmp_path)
    _corrupt_largest_payload(
        os.path.join(str(tmp_path), "t2", "state"))
    with pytest.raises(CheckpointLoadError):
        load_checkpoint(str(tmp_path), "t2", _state())
    # latest-resolved load still falls back
    state, cs = load_checkpoint(str(tmp_path), None, _state())
    assert cs["global_steps"] == 1


def test_persistent_transient_io_error_raises_not_falls_back(tmp_path):
    """An FS brownout that outlives the retry budget is NOT corruption:
    the same-tag retry runs, then the OSError propagates — falling
    back (and repointing ``latest``) would permanently discard progress
    from an intact checkpoint."""
    from deepspeed_tpu.resilience import fault_injector
    _save_two_tags(tmp_path)
    with fault_injector.inject("checkpoint.load:ioerror@0xinf"):
        with pytest.raises(OSError):
            load_checkpoint(str(tmp_path), None, _state(),
                            io_retries=1)
    # latest still names the newest tag — nothing was repointed
    assert (tmp_path / "latest").read_text().strip() == "t2"


def test_no_good_tag_raises_typed_error(tmp_path):
    _save_two_tags(tmp_path)
    for t in ("t1", "t2"):
        _corrupt_largest_payload(
            os.path.join(str(tmp_path), t, "state"))
    with pytest.raises(CheckpointLoadError, match="no loadable"):
        load_checkpoint(str(tmp_path), None, _state())


def test_npz_shard_writes_are_atomic(tmp_path, monkeypatch):
    """A writer that dies mid-payload must leave either the previous
    complete shard or no file — never truncated bytes under the real
    name (satellite: _npz_save through tmp+fsync+rename)."""
    sd = str(tmp_path / "state")
    state = _state()
    _npz_save(sd, state)
    good = open(os.path.join(sd, "leaves.npz"), "rb").read()

    def dying_savez(f, **arrays):
        f.write(good[: len(good) // 2])
        raise OSError("disk died mid-write")

    monkeypatch.setattr(np, "savez", dying_savez)
    with pytest.raises(OSError, match="disk died"):
        _npz_save(sd, state)
    # the previous complete shard survived byte-for-byte; no tmp litter
    assert open(os.path.join(sd, "leaves.npz"), "rb").read() == good
    assert not [n for n in os.listdir(sd) if ".tmp." in n]
    monkeypatch.undo()
    loaded = _npz_load(sd, state)
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.arange(8.0))


def test_truncated_npz_without_manifest_still_falls_back(tmp_path,
                                                         monkeypatch):
    """Defense in depth: even with the manifest gone (legacy dir), a
    truncated shard must fail the tag — the deserializer error routes
    to the fallback scan, not to garbage state."""
    import deepspeed_tpu.checkpoint.engine as ce
    monkeypatch.setattr(ce, "_try_orbax", lambda: None)  # force npz
    _save_two_tags(tmp_path)
    sd = os.path.join(str(tmp_path), "t2", "state")
    os.unlink(os.path.join(sd, "manifest.json"))
    p = os.path.join(sd, "leaves.npz")
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    state, cs = load_checkpoint(str(tmp_path), None, _state())
    assert cs["global_steps"] == 1


KILL_WORKER = """
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import deepspeed_tpu.checkpoint.engine as ce
ce._try_orbax = lambda: None          # force the npz shard path
d = sys.argv[1]
state = {"w": np.arange(40000, dtype=np.float32),
         "b": np.ones((400, 400), dtype=np.float32)}
i = 0
while True:
    ce.save_checkpoint(d, f"t{i}", state,
                       client_state={"global_steps": i})
    i += 1
"""


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_kill_between_shard_writes_never_leaves_corrupt_tag(tmp_path):
    """SIGKILL an npz checkpoint writer mid-loop: whatever instant the
    kill lands (between payload writes, before the manifest, before
    ``latest``), the tag named by ``latest`` must verify and load."""
    import signal
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    script = tmp_path / "worker.py"
    script.write_text(KILL_WORKER)
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, str(script), str(ckpt)],
                            env=env)
    try:
        deadline = time.monotonic() + 120
        latest = ckpt / "latest"
        # let at least one commit land, then kill mid-flight
        while time.monotonic() < deadline and not latest.exists():
            time.sleep(0.02)
        assert latest.exists(), "worker never committed a checkpoint"
        time.sleep(0.15)
    finally:
        proc.send_signal(signal.SIGKILL)
        proc.wait()

    tag = latest.read_text().strip()
    state_dir = os.path.join(str(ckpt), tag, "state")
    # the committed tag's payload verifies bit-for-bit...
    assert verify_manifest(state_dir) is not None
    # ...and no half-written file ever sits under a real shard name
    assert not [n for n in os.listdir(state_dir) if ".tmp." in n]
    template = {"w": np.arange(40000, dtype=np.float32),
                "b": np.ones((400, 400), dtype=np.float32)}
    state, cs = load_checkpoint(str(ckpt), None, template)
    np.testing.assert_allclose(np.asarray(state["w"]), template["w"])
    assert cs["global_steps"] == int(tag[1:])


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_offload_host_state_follows_fallback_tag(eight_devices,
                                                 tmp_path):
    """When the integrity fallback picks an older tag, the ZeRO-Offload
    host optimizer state must load from that SAME tag — never mix one
    tag's model state with another's Adam moments."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    def build():
        from deepspeed_tpu.parallel.mesh import mesh_manager
        mesh_manager.reset()
        model = GPT2LMHeadModel(GPT2Config.tiny())
        engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": 1,
                "offload_optimizer": {"device": "cpu", "ratio": 1.0}},
            "steps_per_print": 0})
        return engine

    engine = build()
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path), tag="good")
    good_masters = [a.copy() for a in engine._offload.host_adam.master]
    engine.train_batch(batch=batch)
    time.sleep(0.01)
    engine.save_checkpoint(str(tmp_path), tag="bad")

    _corrupt_largest_payload(
        os.path.join(str(tmp_path), "bad", "state"))
    engine2 = build()
    engine2.init_params(batch)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == 1          # fell back to "good"
    # the host Adam masters came from "good" too, not from "bad"
    for a, b in zip(good_masters, engine2._offload.host_adam.master):
        np.testing.assert_array_equal(a, b)


def test_retry_io_bounded_backoff():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    t0 = time.monotonic()
    assert retry_io(flaky, retries=3, backoff_seconds=0.01,
                    max_backoff_seconds=0.05) == "ok"
    assert len(calls) == 3
    assert time.monotonic() - t0 < 1.0

    calls.clear()
    with pytest.raises(OSError):
        retry_io(flaky, retries=1, backoff_seconds=0.001)
    assert len(calls) == 2          # initial attempt + 1 retry

    # corruption is not retryable by default
    def corrupt():
        calls.append(1)
        raise CheckpointCorruptionError("bad checksum")

    calls.clear()
    with pytest.raises(CheckpointCorruptionError):
        retry_io(corrupt, retries=5, backoff_seconds=0.001)
    assert len(calls) == 1
