"""Collective watchdog: deadlines fire as typed CollectiveTimeout; the
comm facade's eager paths are actually wired through it."""

import time

import numpy as np
import pytest

from deepspeed_tpu.resilience import (CollectiveTimeout,
                                      collective_watchdog,
                                      fault_injector)
from deepspeed_tpu.resilience.watchdog import CollectiveWatchdog

pytestmark = pytest.mark.fault


def test_fast_op_passes_through():
    wd = CollectiveWatchdog(timeout_seconds=5.0)
    assert wd.run("fast", lambda: 42) == 42
    assert wd.timeouts == 0


def test_hung_op_times_out_typed():
    wd = CollectiveWatchdog(timeout_seconds=0.2)
    with pytest.raises(CollectiveTimeout) as ei:
        wd.run("stuck_allreduce", lambda: time.sleep(10))
    assert ei.value.op == "stuck_allreduce"
    assert wd.timeouts == 1
    # a later op after recovery is served by a fresh worker thread
    assert wd.run("next", lambda: "ok") == "ok"


def test_disabled_watchdog_is_passthrough():
    wd = CollectiveWatchdog(timeout_seconds=None)
    assert not wd.enabled
    assert wd.run("anything", lambda: 7) == 7


def test_env_configures_deadline(monkeypatch):
    from deepspeed_tpu.resilience.watchdog import ENV_TIMEOUT
    monkeypatch.setenv(ENV_TIMEOUT, "12.5")
    assert CollectiveWatchdog().timeout_seconds == 12.5


def test_eager_collective_hang_detected(eight_devices):
    """End-to-end: an injected hang inside eager all_reduce dispatch
    trips the armed watchdog with a typed error."""
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
    mesh_manager.init(MeshConfig(data=-1))
    x = np.arange(8.0, dtype=np.float32)
    # sanity: clean path works
    out = dist.all_reduce(x, group="data")
    assert np.isfinite(np.asarray(out)).all()

    collective_watchdog.configure(0.3)
    with fault_injector.inject("collective:hang~5"):
        with pytest.raises(CollectiveTimeout):
            dist.all_reduce(x, group="data")
    collective_watchdog.configure(None)
    # recovered: the next collective is clean
    out = dist.all_reduce(x, group="data")
    assert np.asarray(out).shape == (8,)


class TestHeartbeatMonitor:
    """Job-level liveness ledger (the supervisor's detector half):
    per-worker heartbeat/progress deadlines in logical steps."""

    def _mk(self, **kw):
        from deepspeed_tpu.resilience.watchdog import HeartbeatMonitor
        return HeartbeatMonitor(4, **kw)

    def test_fresh_beats_are_clean(self):
        m = self._mk(heartbeat_timeout_steps=0)
        for s in range(3):
            for r in range(4):
                m.beat(r, s)
            assert m.check(s) == []

    def test_silence_past_deadline_is_hang(self):
        m = self._mk(heartbeat_timeout_steps=1)
        for r in range(4):
            m.beat(r, 0)
        for s in (1, 2):
            for r in (0, 1, 3):   # worker 2 goes silent after step 0
                m.beat(r, s)
        bad = m.check(2)
        assert [(r, mode) for r, mode, _ in bad] == [(2, "hang")]

    def test_heartbeat_without_progress_is_slow(self):
        m = self._mk(heartbeat_timeout_steps=0,
                     progress_timeout_steps=1)
        for s in range(3):
            for r in range(4):
                m.beat(r, s, progressed=(r != 1))
        bad = m.check(2)
        assert [(r, mode) for r, mode, _ in bad] == [(1, "slow")]

    def test_retire_and_restore(self):
        m = self._mk(heartbeat_timeout_steps=0)
        for r in range(4):
            m.beat(r, 0)
        m.retire(3)
        m.beat(3, 5)          # retired workers' beats are ignored
        # everyone else is silent since step 0; the retired worker is
        # no longer watched
        assert sorted(r for r, _, _ in m.check(5)) == [0, 1, 2]
        m.restore(0, 5)
        assert sorted(r for r, _, _ in m.check(5)) == [1, 2]

    def test_wall_deadline(self, monkeypatch):
        m = self._mk(heartbeat_timeout_steps=100,
                     wall_timeout_seconds=0.05)
        for r in range(4):
            m.beat(r, 0)
        time.sleep(0.08)
        m.beat(0, 0)          # one fresh wall beat
        bad = m.check(0)
        assert sorted(r for r, _, _ in bad) == [1, 2, 3]
        assert all(mode == "hang" for _, mode, _ in bad)
