"""Fault-injection coverage for the lifecycle/durability sites added
by the long-run durability PR: ``lifecycle.evict`` (bounded-cache
eviction), ``serving.admit`` (admission control), and
``serving.dispatch`` (the serving loop's watchdog-guarded forward
dispatch — a ``hang`` spec here is exactly how a wedged runtime is
simulated). Tier-1, ``fault``-marked, alongside the existing site
suite."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.resilience import fault_injector
from deepspeed_tpu.resilience.errors import (CollectiveTimeout,
                                             InjectedFault,
                                             InjectedIOError,
                                             ServingOverloadError)
from deepspeed_tpu.runtime.lifecycle import BoundedCache

pytestmark = pytest.mark.fault


def _v2_engine(**cfg_kwargs):
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.engine_v2 import \
        RaggedInferenceEngineConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    return InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(token_budget=32,
                                    max_ragged_sequence_count=4,
                                    n_kv_blocks=16, kv_block_size=8,
                                    max_blocks_per_seq=8,
                                    kv_dtype="float32", **cfg_kwargs))


class TestLifecycleEvictSite:

    def test_eviction_fault_leaves_cache_consistent(self):
        """The site fires BEFORE any state changes, and room is made
        BEFORE the new entry lands: an injected eviction fault
        surfaces to the caller with every old entry intact, the new
        entry absent, and the size still within the bound."""
        c = BoundedCache("t_fault_evict", max_entries=2)
        c.put("a", 1)
        c.put("b", 2)
        with fault_injector.inject("lifecycle.evict:error"):
            with pytest.raises(InjectedFault):
                c.put("c", 3)
            assert fault_injector.fired == ["lifecycle.evict:error@0"]
        # nothing was dropped mid-flight, nothing landed over-bound
        assert c.get("a") == 1 and c.get("b") == 2
        assert "c" not in c and len(c) == 2
        # disarmed, the same insert evicts cleanly
        c.put("c", 3)
        assert len(c) == 2 and "c" in c

    def test_invalidate_does_not_fire_evict_site(self):
        """Explicit invalidation is a lifecycle boundary, not an LRU
        eviction — restore paths must not trip eviction faults."""
        c = BoundedCache("t_fault_inval", max_entries=2)
        c.put("a", 1)
        with fault_injector.inject("lifecycle.evict:error"):
            assert c.invalidate("restore") == 1
            assert fault_injector.fired == []


class TestServingAdmitSite:

    def test_admission_fault_is_typed_and_state_clean(self):
        eng = _v2_engine()
        with fault_injector.inject("serving.admit:ioerror"):
            with pytest.raises(InjectedIOError):
                eng.generate_batch({1: [1, 2, 3]}, max_new_tokens=2)
        # admission rejected before any engine state moved
        assert not eng._state_manager.tracked_sequences
        assert eng.free_blocks == eng._config.n_kv_blocks
        # engine serves normally once disarmed
        out = eng.generate_batch({2: [1, 2, 3]}, max_new_tokens=2)
        assert len(out[2]) == 2

    def test_admit_fires_once_per_request(self):
        eng = _v2_engine()
        with fault_injector.inject("serving.admit:ioerror@2"):
            # fault on the THIRD considered request (per-uid ordinals)
            with pytest.raises(InjectedIOError):
                eng.generate_batch({1: [1], 2: [2], 3: [3]},
                                   max_new_tokens=1)
            assert fault_injector.call_count("serving.admit") == 3


class TestServingDispatchSite:

    def test_watchdog_fires_on_hung_dispatch(self):
        """The acceptance-criteria hang test: a wedged dispatch raises
        a typed CollectiveTimeout within the configured deadline — the
        lookahead loop never wedges."""
        import time
        eng = _v2_engine(dispatch_timeout_seconds=0.5)
        assert eng._dispatch_watchdog.enabled
        with fault_injector.inject("serving.dispatch:hang~30"):
            t0 = time.perf_counter()
            with pytest.raises(CollectiveTimeout, match="serving.dispatch"):
                eng.generate_batch({1: [1, 2, 3]}, max_new_tokens=2)
            assert time.perf_counter() - t0 < 5.0   # not the 30s hang
        assert eng._dispatch_watchdog.timeouts == 1
        # the abandoned worker thread may still mutate engine state, so
        # the engine is POISONED: further runs refuse with the typed
        # overload error instead of racing the zombie dispatch
        with pytest.raises(ServingOverloadError, match="poisoned"):
            eng.generate_batch({2: [1, 2, 3]}, max_new_tokens=1)

    def test_dispatch_error_propagates_without_watchdog(self):
        eng = _v2_engine()
        assert not eng._dispatch_watchdog.enabled
        with fault_injector.inject("serving.dispatch:error"):
            with pytest.raises(InjectedFault):
                eng.generate_batch({1: [1, 2, 3]}, max_new_tokens=2)

    def test_watchdog_disarmed_under_model_parallel_config(self):
        """tp>1 would dispatch a multi-device program from the watchdog
        worker thread — the XLA collective-rendezvous deadlock the
        transfer-engine PR documented — so the engine refuses to arm."""
        devs = jax.devices()
        if len(devs) < 2:
            pytest.skip("needs >= 2 virtual devices")
        eng = _v2_engine(dispatch_timeout_seconds=1.0, tp_size=2)
        assert not eng._dispatch_watchdog.enabled


class TestOverloadTyping:

    def test_out_of_kv_blocks_is_typed_overload(self):
        """A workload whose working set cannot fit the KV pool fails
        with the typed ServingOverloadError (carrying saturation
        numbers), not a raw OutOfKVBlocks scheduling string."""
        eng = _v2_engine()
        # 4 sequences x long prompts exhaust 16 blocks x 8 tokens
        prompts = {uid: list(range(30)) for uid in range(4)}
        with pytest.raises(ServingOverloadError) as ei:
            eng.generate_batch(prompts, max_new_tokens=40)
        assert ei.value.free_blocks >= 0
        assert 0.0 <= ei.value.kv_util <= 1.0
