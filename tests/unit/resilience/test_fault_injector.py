"""FaultInjector: spec grammar, deterministic firing, scoping."""

import time

import pytest

from deepspeed_tpu.resilience import (FaultSpec, InjectedFault,
                                      InjectedIOError, fault_injector)

pytestmark = pytest.mark.fault


def test_spec_grammar():
    s = FaultSpec.parse("checkpoint.save:ioerror")
    assert (s.site, s.kind, s.after, s.count) == \
        ("checkpoint.save", "ioerror", 0, 1)
    s = FaultSpec.parse("collective:hang@2~30")
    assert (s.site, s.kind, s.after, s.arg) == \
        ("collective", "hang", 2, 30.0)
    s = FaultSpec.parse("data.fetch:error@1x3")
    assert (s.site, s.kind, s.after, s.count) == \
        ("data.fetch", "error", 1, 3)
    s = FaultSpec.parse("data.fetch:ioerror@0xinf")
    assert s.count == float("inf")
    with pytest.raises(ValueError, match="kind"):
        FaultSpec.parse("data.fetch:explode")
    with pytest.raises(ValueError, match="fault spec"):
        FaultSpec.parse("justasite")


def test_fire_is_deterministic_by_ordinal():
    with fault_injector.inject("data.fetch:ioerror@1x2"):
        fault_injector.fire("data.fetch")          # call 0: clean
        with pytest.raises(InjectedIOError):
            fault_injector.fire("data.fetch")      # call 1: faults
        with pytest.raises(InjectedIOError):
            fault_injector.fire("data.fetch")      # call 2: faults
        fault_injector.fire("data.fetch")          # call 3: clean again
        assert fault_injector.fired == ["data.fetch:ioerror@1",
                                        "data.fetch:ioerror@2"]
    # scope exit disarms and clears counters
    assert not fault_injector.enabled
    fault_injector.fire("data.fetch")


def test_sites_are_independent():
    with fault_injector.inject("collective:error"):
        fault_injector.fire("data.fetch")          # other site: clean
        with pytest.raises(InjectedFault):
            fault_injector.fire("collective")


def test_injected_ioerror_is_oserror():
    """Injected transient faults must flow through the same except
    clauses real disk faults hit."""
    assert issubclass(InjectedIOError, OSError)
    assert issubclass(InjectedIOError, InjectedFault)


def test_hang_kind_sleeps():
    with fault_injector.inject("collective:hang~0.2"):
        t0 = time.monotonic()
        fault_injector.fire("collective")
        assert time.monotonic() - t0 >= 0.2


def test_env_spec_arms_on_construction(monkeypatch):
    from deepspeed_tpu.resilience.fault_injector import (ENV_SPEC,
                                                         FaultInjector)
    monkeypatch.setenv(ENV_SPEC, "checkpoint.load:ioerror")
    inj = FaultInjector()
    assert inj.enabled
    with pytest.raises(InjectedIOError):
        inj.fire("checkpoint.load")


# -- per-target specs (site@target:...) --------------------------------

def test_target_spec_grammar():
    s = FaultSpec.parse("transport.send@replica1:drop~0.2")
    assert (s.site, s.target, s.kind) == \
        ("transport.send", "replica1", "drop")
    assert s.count == float("inf")      # rate spec: applies forever
    assert s.arg == 0.2
    s = FaultSpec.parse("transport.send@replica0:error@1x2")
    assert (s.target, s.after, s.count) == ("replica0", 1, 2)
    assert FaultSpec.parse("transport.send:drop~0.2").target is None


def test_target_spec_matches_only_its_detail():
    with fault_injector.inject("transport.send@replica1:error"):
        fault_injector.fire("transport.send", detail="replica0")
        fault_injector.fire("transport.send", detail="replica2")
        with pytest.raises(InjectedFault):
            fault_injector.fire("transport.send", detail="replica1")
        # the audit log names the target and the TARGET's ordinal
        assert fault_injector.fired == \
            ["transport.send@replica1:error@0"]


def test_target_window_counts_targets_calls_alone():
    # @after=2 means "replica1's third send", however much other
    # replicas' traffic interleaves — the global ordinal would need
    # the drill to reverse-engineer the interleaving.
    with fault_injector.inject("transport.send@replica1:error@2"):
        for _ in range(5):
            fault_injector.fire("transport.send", detail="replica0")
        fault_injector.fire("transport.send", detail="replica1")  # m=0
        fault_injector.fire("transport.send", detail="replica1")  # m=1
        with pytest.raises(InjectedFault):
            fault_injector.fire("transport.send", detail="replica1")
        # the global per-site counter still saw every call
        assert fault_injector.call_count("transport.send") == 8


def test_targeted_and_global_specs_coexist():
    with fault_injector.inject("transport.send@replica1:error@0x1,"
                               "transport.send:ioerror@2x1"):
        fault_injector.fire("transport.send", detail="replica0")  # n=0
        with pytest.raises(InjectedFault):                        # m=0
            fault_injector.fire("transport.send", detail="replica1")
        with pytest.raises(InjectedIOError):                      # n=2
            fault_injector.fire("transport.send", detail="replica0")


def test_targeted_consume_returns_target_ordinal():
    with fault_injector.inject("transport.recv@replica1:drop~0.5"):
        spec, m = fault_injector.consume("transport.recv",
                                         detail="replica0",
                                         with_ordinal=True)
        assert spec is None
        for want in range(3):
            spec, m = fault_injector.consume("transport.recv",
                                             detail="replica1",
                                             with_ordinal=True)
            assert spec is not None and spec.target == "replica1"
            assert m == want    # the TARGET's own counter
