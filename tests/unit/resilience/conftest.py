import pytest


@pytest.fixture(autouse=True)
def _disarm_resilience():
    """Fault specs and watchdog deadlines are process-global; every
    test starts and ends disarmed so injections cannot leak."""
    from deepspeed_tpu.resilience import (collective_watchdog,
                                          fault_injector)
    fault_injector.reset()
    collective_watchdog.configure(None)
    yield
    fault_injector.reset()
    collective_watchdog.configure(None)
