"""Fast CPU fault-injection smoke: one injected failure per site
class — checkpoint save, checkpoint load, collective, host-offload
transfer (d2h and h2d), data fetch — each detected and recovered
within its configured retry/rollback budget. Runs inside tier-1.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.resilience import (InjectedFault, fault_injector)

pytestmark = pytest.mark.fault


def test_checkpoint_save_site_recovers_via_retry(tmp_path):
    """Injected transient write fault: the bounded retry absorbs it and
    the committed tag verifies + loads."""
    import jax.numpy as jnp
    from deepspeed_tpu.checkpoint.engine import (load_checkpoint,
                                                 save_checkpoint)
    state = {"w": jnp.arange(6.0)}
    with fault_injector.inject("checkpoint.save:ioerror"):
        save_checkpoint(str(tmp_path), "t", state)
        assert fault_injector.fired == ["checkpoint.save:ioerror@0"]
    loaded, _ = load_checkpoint(str(tmp_path), None, state)
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.arange(6.0))


def test_checkpoint_load_site_recovers_via_retry(tmp_path):
    import jax.numpy as jnp
    from deepspeed_tpu.checkpoint.engine import (load_checkpoint,
                                                 save_checkpoint)
    state = {"w": jnp.arange(6.0)}
    save_checkpoint(str(tmp_path), "t", state)
    with fault_injector.inject("checkpoint.load:ioerror"):
        loaded, _ = load_checkpoint(str(tmp_path), None, state)
        assert fault_injector.fired == ["checkpoint.load:ioerror@0"]
    np.testing.assert_allclose(np.asarray(loaded["w"]), np.arange(6.0))


def test_collective_site_fault_is_detected_typed(eight_devices):
    """Collectives have NO in-place retry (replaying a collective is
    not generally safe): the contract is typed detection, recovery is
    the caller's rollback/respawn path."""
    import deepspeed_tpu.comm as dist
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
    mesh_manager.init(MeshConfig(data=-1))
    x = np.ones(8, dtype=np.float32)
    with fault_injector.inject("collective:error"):
        with pytest.raises(InjectedFault):
            dist.all_reduce(x, group="data")
    # the facade is healthy again once the fault passes
    out = dist.all_reduce(x, group="data")
    assert float(np.asarray(out)[0]) == 8.0


def test_data_fetch_site_recovers_via_retry(eight_devices):
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader
    data = [{"x": np.full((4,), i, np.float32)} for i in range(32)]
    loader = DeepSpeedDataLoader(data, batch_size=8)
    with fault_injector.inject("data.fetch:ioerror@1"):
        batches = list(loader)
        assert fault_injector.fired == ["data.fetch:ioerror@1"]
    assert len(batches) == 4
    np.testing.assert_allclose(batches[1]["x"][:, 0],
                               np.arange(8, 16, dtype=np.float32))


@pytest.mark.parametrize("site", [
    # tier-1 diet (PR 17): the bucketed transfer.d2h drill
    # (test_offload_bucketed) keeps a d2h fault-retry path tier-1
    pytest.param("offload.d2h",
                 marks=pytest.mark.slow),
    pytest.param("offload.h2d",
                 marks=pytest.mark.slow)])  # tier-1 diet (PR 5)
def test_offload_transfer_site_recovers_via_retry(
        site, rng, eight_devices):
    """One train step with ZeRO-Offload while the named transfer leg
    faults once: the bounded retry recovers and the host Adam update
    still lands."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 2,
            "offload_optimizer": {"device": "cpu", "ratio": 1.0}},
        "steps_per_print": 0,
    })
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    l0 = float(engine.train_batch(batch=batch))     # compiles cleanly
    with fault_injector.inject(f"{site}:ioerror"):
        l1 = float(engine.train_batch(batch=batch))
        assert fault_injector.fired == [f"{site}:ioerror@0"]
    assert np.isfinite(l1)
    # the step under injection still optimized (host update applied)
    l2 = float(engine.train_batch(batch=batch))
    assert l2 < l0


def test_engine_config_arms_injection(rng, eight_devices):
    """The config block drives injection end to end: an armed
    data.fetch fault fires during engine-driven batch fetch and the
    loader's retry budget recovers it."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    data = [{"input_ids": np.zeros(16, np.int32),
             "labels": np.zeros(16, np.int32)} for _ in range(16)]
    model = GPT2LMHeadModel(GPT2Config.tiny())
    try:
        engine, _, _, loader = deepspeed_tpu.initialize(
            model=model, training_data=data, config={
                "train_batch_size": 8,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "steps_per_print": 0,
                "resilience": {"fault_injection": "data.fetch:ioerror"},
            })
        assert fault_injector.enabled
        loss = engine.train_batch()
        assert np.isfinite(float(loss))
        assert fault_injector.fired == ["data.fetch:ioerror@0"]
    finally:
        fault_injector.reset()
