"""Train-loop sentinel: NaN/spike detection state machine, and the
engine-level auto-rollback — a poisoned state must be restored from the
last verified checkpoint within the configured budget."""

import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.resilience import TrainingDivergenceError
from deepspeed_tpu.resilience.sentinel import (OK, ROLLBACK, SKIP,
                                               TrainSentinel)

pytestmark = pytest.mark.fault


def test_nan_budget_escalates_to_rollback():
    s = TrainSentinel(failure_budget=3)
    assert s.observe(1.0) == OK
    assert s.observe(float("nan")) == SKIP
    assert s.observe(float("inf")) == SKIP
    assert s.observe(float("nan")) == ROLLBACK
    s.note_rollback()
    assert s.rollbacks == 1
    assert s.observe(1.0) == OK           # re-armed, counters fresh
    assert s.consecutive_failures == 0


def test_healthy_step_resets_consecutive_count():
    s = TrainSentinel(failure_budget=2)
    assert s.observe(float("nan")) == SKIP
    assert s.observe(0.9) == OK
    assert s.observe(float("nan")) == SKIP    # count restarted
    assert s.observe(float("nan")) == ROLLBACK


def test_spike_detection_arms_after_warmup():
    s = TrainSentinel(loss_spike_factor=5.0, window=4,
                      failure_budget=1)
    # warm-up: even a big jump is tolerated before `window` good steps
    assert s.observe(100.0) == OK
    for _ in range(4):
        assert s.observe(1.0) == OK
    assert s.observe(2.0) == OK               # 2x: not a spike
    assert s.observe(1000.0) == ROLLBACK      # >5x EMA after warm-up


def test_overflow_graced_by_default():
    """Scaler warm-up legitimately overflows several steps in a row
    (the in-step rollback already handles it): by default that never
    escalates, and the garbage overflow-step loss never taints the
    EMA."""
    s = TrainSentinel(failure_budget=2, loss_spike_factor=5.0,
                      window=1)
    for _ in range(10):
        assert s.observe(float("inf"), overflow=True) == SKIP
    assert s.consecutive_failures == 0
    assert s.ema is None


def test_overflow_counts_when_opted_in():
    s = TrainSentinel(failure_budget=2, count_overflow=True)
    assert s.observe(1.0, overflow=True) == SKIP
    assert s.observe(1.0, overflow=True) == ROLLBACK


def test_spike_detection_off_by_default():
    s = TrainSentinel(failure_budget=1, window=1)
    s.observe(1.0)
    s.observe(1.0)
    assert s.observe(1e9) == OK               # factor 0 = disabled


def _nan_poison(tree):
    return jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, tree)


@pytest.mark.slow  # tier-1 diet (ISSUE 7): the supervisor suite keeps rollback e2e in tier-1
def test_engine_auto_rollback_restores_verified_checkpoint(
        rng, eight_devices, tmp_path):
    """End to end: train, checkpoint, poison the state to NaN; the
    sentinel skips through its budget then restores the checkpoint and
    training resumes with finite losses from the saved step."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    ckpt = str(tmp_path / "ckpt")
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
        "resilience": {"sentinel": {
            "enabled": True, "failure_budget": 2, "max_rollbacks": 1,
            "ckpt_dir": ckpt}},
    })
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    for _ in range(2):
        engine.train_batch(batch=batch)
    engine.save_checkpoint(ckpt)
    assert engine.global_steps == 2

    # poison: every float leaf of the master params becomes NaN — the
    # next steps produce NaN losses no skip can fix
    engine.state = engine.state._replace(
        master_params=_nan_poison(engine.state.master_params))

    l1 = float(engine.train_batch(batch=batch))   # failure 1: skip
    assert math.isnan(l1)
    assert engine.skipped_steps == 1
    assert engine.global_steps == 2               # schedules frozen
    engine.train_batch(batch=batch)               # failure 2: rollback
    assert engine._sentinel.rollbacks == 1
    assert engine.global_steps == 2               # restored step count

    # recovered: finite loss, steps advance again
    l = float(engine.train_batch(batch=batch))
    assert math.isfinite(l)
    assert engine.global_steps == 3


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_engine_rollback_budget_escalates(rng, eight_devices, tmp_path):
    """Past max_rollbacks the engine raises the typed divergence error
    (the elastic agent layer handles it as a worker failure)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    ckpt = str(tmp_path / "ckpt")
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        "resilience": {"sentinel": {
            "enabled": True, "failure_budget": 1, "max_rollbacks": 0,
            "ckpt_dir": ckpt}},
    })
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.train_batch(batch=batch)
    engine.save_checkpoint(ckpt)
    engine.state = engine.state._replace(
        master_params=_nan_poison(engine.state.master_params))
    with pytest.raises(TrainingDivergenceError, match="diverged"):
        engine.train_batch(batch=batch)


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_engine_rollback_without_checkpoint_is_typed(
        rng, eight_devices, tmp_path):
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
        "resilience": {"sentinel": {
            "enabled": True, "failure_budget": 1,
            "ckpt_dir": str(tmp_path / "empty")}},
    })
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.train_batch(batch=batch)
    engine.state = engine.state._replace(
        master_params=_nan_poison(engine.state.master_params))
    with pytest.raises(TrainingDivergenceError, match="no committed"):
        engine.train_batch(batch=batch)
