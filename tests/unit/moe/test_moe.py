"""MoE tests (reference shape: tests/unit/moe/test_moe.py — gating
invariants, layer correctness, EP-sharded parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.moe import (CapacityBins, Experts, MoE, MOELayer,
                               TopKGate, top1gating, top2gating)
from deepspeed_tpu.moe.experts import ExpertMLP
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


def _logits(rng, S=64, E=4):
    return jnp.asarray(rng.standard_normal((S, E)).astype(np.float32))


class TestTop1Gating:

    def test_capacity_respected(self, rng):
        logits = _logits(rng)
        l_aux, combine, dispatch, counts = top1gating(
            logits, capacity_factor=1.0, min_capacity=4)
        S, E = logits.shape
        C = combine.shape[-1]
        assert C == max(4, S // E)
        # each (expert, slot) holds at most one token
        per_slot = np.asarray(dispatch).sum(axis=0)
        assert per_slot.max() <= 1
        # combine weight of a routed token equals its softmax gate
        gates = np.asarray(jax.nn.softmax(logits, axis=1))
        cw = np.asarray(combine).sum(axis=2)
        routed = cw > 0
        np.testing.assert_allclose(cw[routed],
                                   gates[routed], rtol=1e-6)

    def test_aux_loss_formula(self, rng):
        logits = _logits(rng, S=128, E=8)
        l_aux, *_ = top1gating(logits, 1.0, 4)
        gates = np.asarray(jax.nn.softmax(logits, axis=1))
        mask = np.eye(8)[gates.argmax(1)]
        expected = (gates.mean(0) * mask.mean(0)).sum() * 8
        np.testing.assert_allclose(float(l_aux), expected, rtol=1e-6)

    def test_drop_tokens_false_keeps_everything(self, rng):
        logits = _logits(rng, S=32, E=4)
        _, combine, dispatch, counts = top1gating(logits, 1.0, 4,
                                                  drop_tokens=False)
        # capacity == S: every token routed
        assert np.asarray(dispatch).astype(np.int32).sum() == 32

    def test_rts_changes_selection_under_pressure(self, rng):
        logits = _logits(rng, S=64, E=2)
        key = jax.random.PRNGKey(0)
        _, _, d1, _ = top1gating(logits, 0.25, 4, use_rts=True, rng=key)
        _, _, d2, _ = top1gating(logits, 0.25, 4, use_rts=False)
        # same budget of dispatched tokens...
        assert np.asarray(d1).sum() == np.asarray(d2).sum()
        # ...but randomized priority must pick a different set than FIFO
        assert (np.asarray(d1) != np.asarray(d2)).any()


class TestTop2Gating:

    def test_two_experts_per_token(self, rng):
        logits = _logits(rng, S=64, E=8)
        l_aux, combine, dispatch, counts = top2gating(
            logits, capacity_factor=2.0, min_capacity=4,
            top2_2nd_expert_sampling=False)
        # with ample capacity every token reaches 2 experts
        per_token = np.asarray(dispatch).astype(np.int32).sum(axis=(1, 2))
        assert (per_token == 2).all()
        # normalized top-2 weights sum to 1
        w = np.asarray(combine).sum(axis=(1, 2))
        np.testing.assert_allclose(w, np.ones_like(w), rtol=1e-5)

    def test_capacity_drops(self, rng):
        logits = _logits(rng, S=64, E=2)
        _, _, dispatch, _ = top2gating(logits, 0.25, 4,
                                       top2_2nd_expert_sampling=False)
        C = dispatch.shape[-1]
        assert np.asarray(dispatch).astype(np.int32).sum() <= 2 * C


class TestMoELayer:

    def test_single_expert_equals_dense(self, rng):
        """num_experts=1, cf big enough: MoE == plain expert MLP."""
        x = jnp.asarray(rng.standard_normal((2, 8, 16)).astype(np.float32))
        moe = MoE(hidden_size=16, num_experts=1, k=1, capacity_factor=1.0,
                  min_capacity=16, expert_kwargs={"d_ff": 32})
        params = moe.init(jax.random.PRNGKey(0), x)
        out, l_aux, counts = moe.apply(params, x)
        assert out.shape == x.shape
        assert int(counts[0]) == 16

        dense = ExpertMLP(d_model=16, d_ff=32)
        expert_params = jax.tree_util.tree_map(
            lambda p: p[0],
            params["params"]["deepspeed_experts"]["experts"])
        ref = dense.apply({"params": expert_params}, x)
        # combine weights scale by the gate prob (=1.0 with one expert)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.slow  # tier-1 diet (PR 17): expert-parallel + single-expert-dense smokes stay
    def test_residual_moe(self, rng):
        x = jnp.asarray(rng.standard_normal((4, 16)).astype(np.float32))
        moe = MoE(hidden_size=16, num_experts=2, use_residual=True,
                  min_capacity=8, expert_kwargs={"d_ff": 32})
        params = moe.init(jax.random.PRNGKey(0), x)
        out, _, _ = moe.apply(params, x)
        assert out.shape == x.shape
        assert "residual_mlp" in params["params"]
        assert "coefficient" in params["params"]

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_grad_flows_through_gate(self, rng):
        x = jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32))
        moe = MoE(hidden_size=16, num_experts=4, min_capacity=8,
                  expert_kwargs={"d_ff": 32})
        params = moe.init(jax.random.PRNGKey(0), x)

        def loss(p):
            out, l_aux, _ = moe.apply(p, x)
            return jnp.sum(out ** 2) + 0.01 * l_aux

        grads = jax.grad(loss)(params)
        g_wg = grads["params"]["gate"]["wg"]
        assert float(jnp.abs(g_wg).sum()) > 0

    def test_expert_parallel_matches_single_device(self, eight_devices, rng):
        """EP over 8 experts on an 8-way expert axis == unsharded run."""
        x = jnp.asarray(rng.standard_normal((4, 8, 16)).astype(np.float32))
        moe = MoE(hidden_size=16, num_experts=8, min_capacity=8,
                  expert_kwargs={"d_ff": 32})

        mesh_manager.reset()
        params = moe.init(jax.random.PRNGKey(0), x)
        ref, ref_aux, _ = moe.apply(params, x)

        mesh_manager.init(MeshConfig(data=1, expert=8), devices=eight_devices)
        out, l_aux, _ = jax.jit(
            lambda p, t: moe.apply(p, t))(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(float(l_aux), float(ref_aux), rtol=1e-5)


class TestCapacityBins:

    def test_bin_selection_and_stats(self):
        bins = CapacityBins(num_bins=4, min_bin=8, max_bin=64)
        assert bins.get_binned_capacity(10) == 32  # bins: [8, 32, 48, 64]
        assert bins.get_binned_capacity(64) == 64
        # above the top bin: extend rather than silently under-size
        assert bins.get_binned_capacity(1000) == 1000
        stats = bins.get_stats()
        assert sum(stats["usage"]) == 3

    def test_static_capacity_override_in_gating(self, rng):
        logits = _logits(rng, S=64, E=4)
        bins = CapacityBins(num_bins=4, min_bin=8, max_bin=64)
        cap = bins.get_binned_capacity(20)
        _, combine, _, _ = top1gating(logits, 1.0, 4, capacity=cap)
        assert combine.shape[-1] == cap


class TestMoEEngineSharding:

    def test_engine_shards_expert_bank(self, eight_devices, rng):
        """Engine-trained MoE model must place stacked expert params on
        the expert mesh axis (the moe_tensor_rules composition — without
        it the [E, ...] banks replicate at E-times memory)."""
        import flax.linen as nn

        import deepspeed_tpu
        from deepspeed_tpu.parallel.mesh import EXPERT_AXIS

        class TinyMoEModel(nn.Module):
            @nn.compact
            def __call__(self, batch_x, labels=None):
                out, l_aux, _ = MoE(hidden_size=16, num_experts=8,
                                    min_capacity=8,
                                    expert_kwargs={"d_ff": 32})(batch_x)
                loss = jnp.mean((out - batch_x) ** 2) + 0.01 * l_aux
                return loss, out

        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=1, expert=8),
                          devices=eight_devices)
        config = {
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 0,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=TinyMoEModel(), config=config)
        x = jnp.asarray(rng.standard_normal((4, 8, 16)).astype(np.float32))
        loss = engine.train_batch(batch={"batch_x": x})
        assert np.isfinite(float(loss))

        from deepspeed_tpu.utils.tree import flatten_with_names
        names, leaves, _ = flatten_with_names(engine.state.master_params)
        expert_leaves = [(n, l) for n, l in zip(names, leaves)
                         if "experts" in n.split(".") and hasattr(l, "sharding")]
        assert expert_leaves, "no expert params found"
        for n, l in expert_leaves:
            spec = l.sharding.spec
            assert spec and spec[0] == EXPERT_AXIS, \
                f"{n} not sharded on expert axis: {spec}"
