"""Torch-free TensorBoard event writer (round-3 verdict weak item 7:
the monitor must not silently lose TB logging on a torch-free VM).

Cross-validated against the REAL tensorboard proto parser when the
package is importable — the on-disk bytes, not just our own decoder.
"""

import numpy as np
import pytest

from deepspeed_tpu.monitor.tb_writer import (EventFileWriter, crc32c,
                                             read_scalar_events)


def test_crc32c_known_vectors():
    # RFC 3720 test vectors
    assert crc32c(b"") == 0x0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes([0] * 32)) == 0x8A9136AA


def test_roundtrip_and_framing(tmp_path):
    w = EventFileWriter(str(tmp_path))
    vals = [("loss", 5.0, 0), ("loss", 4.5, 1), ("lr", 1e-3, 1)]
    for tag, v, s in vals:
        w.add_scalar(tag, v, s)
    w.flush()
    got = read_scalar_events(w.path)
    assert [(t, round(v, 6), s) for t, v, s in got] == \
        [(t, round(v, 6), s) for t, v, s in vals]
    w.close()


def test_real_tensorboard_parses_our_bytes(tmp_path):
    """The authoritative check: tensorboard's own protobuf classes
    decode our records (EventFileLoader's data-compat layer rewrites
    simple_value into tensor form, so parse the raw records)."""
    pytest.importorskip("tensorboard")
    from tensorboard.compat.proto.event_pb2 import Event

    import struct

    w = EventFileWriter(str(tmp_path))
    w.add_scalar("train/loss", 3.25, 7)
    w.flush()
    w.close()

    events = []
    with open(w.path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            f.read(4)
            data = f.read(length)
            f.read(4)
            e = Event()
            e.ParseFromString(data)
            events.append(e)
    assert events[0].file_version == "brain.Event:2"
    scalar = events[1]
    assert scalar.step == 7
    v = scalar.summary.value[0]
    assert v.tag == "train/loss"
    assert abs(v.simple_value - 3.25) < 1e-6


def test_monitor_uses_torchfree_writer(tmp_path):
    from deepspeed_tpu.monitor.monitor import TensorBoardMonitor

    class Cfg:
        enabled = True
        output_path = str(tmp_path)
        job_name = "job"

    mon = TensorBoardMonitor(Cfg())
    assert mon.enabled
    mon.write_events([("Train/loss", 1.5, 10)])
    got = read_scalar_events(mon.summary_writer.path)
    assert got == [("Train/loss", 1.5, 10)]
