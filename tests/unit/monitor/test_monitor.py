"""Monitor backend tests (reference shape:
tests/unit/monitor/test_monitor.py — writer construction + event
round-trips)."""

import csv
import os

import numpy as np
import pytest

import deepspeed_tpu


def test_csv_monitor_writes_events(tmp_path):
    from deepspeed_tpu.monitor.monitor import csvMonitor as CSVMonitor

    class Cfg:
        enabled = True
        output_path = str(tmp_path)
        job_name = "job"

    m = CSVMonitor(Cfg())
    m.write_events([("Train/loss", 1.5, 10), ("Train/lr", 1e-3, 10)])
    m.write_events([("Train/loss", 1.2, 20)])
    files = [f for root, _, fs in os.walk(tmp_path) for f in fs
             if f.endswith(".csv")]
    assert files, "no csv written"
    rows = []
    for root, _, fs in os.walk(tmp_path):
        for f in fs:
            if f.endswith(".csv"):
                with open(os.path.join(root, f)) as fh:
                    rows += list(csv.reader(fh))
    flat = [r for r in rows if r]
    assert any("1.5" in c for r in flat for c in r)


def test_monitor_master_fans_out(tmp_path):
    from deepspeed_tpu.monitor.monitor import MonitorMaster

    class CSVCfg:
        enabled = True
        output_path = str(tmp_path)
        job_name = "job"

    class Off:
        enabled = False
        output_path = ""
        job_name = ""

    class MC:
        tensorboard_config = Off()
        wandb_config = Off()
        csv_config = CSVCfg()

    mm = MonitorMaster(MC())
    assert mm.enabled
    mm.write_events([("Train/Samples/train_loss", 3.14, 1)])
    files = [f for root, _, fs in os.walk(tmp_path) for f in fs
             if f.endswith(".csv")]
    assert files


@pytest.mark.slow  # tier-1 diet (ISSUE 7): tb_writer/monitor unit tests stay
def test_engine_writes_monitor_events(tmp_path):
    """Engine train_batch emits Train/Samples/* events through the
    configured monitor (reference: engine.py:2303-2333)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "steps_per_print": 0,
                "csv_monitor": {"enabled": True,
                                "output_path": str(tmp_path),
                                "job_name": "run"}})
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    engine.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    files = [f for root, _, fs in os.walk(tmp_path) for f in fs
             if f.endswith(".csv")]
    assert files, "engine produced no monitor output"
