"""ZeRO × engine-feature composition invariants (reference pattern:
tests/unit/runtime/zero/test_zero.py — the stage grid crossed with
gradient accumulation, clipping, and precision; plus runtime/utils math
tests from tests/unit/runtime/test_runtime_utils.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager
from deepspeed_tpu.runtime.utils import (clip_grad_norm_, global_norm,
                                         partition_balanced,
                                         partition_uniform)


def _engine(overrides, seed=3):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "steps_per_print": 0,
    }
    cfg.update(overrides)
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, rng=jax.random.PRNGKey(seed))
    return engine


def _batch(rng, n=16, seq=16, vocab=256):
    ids = rng.integers(0, vocab, size=(n, seq), dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


@pytest.mark.slow  # tier-1 diet (ISSUE 7): heaviest composition case; clipping/grad-norm smokes stay
def test_gas_split_does_not_change_math(rng, eight_devices):
    """Same global batch through gas=1 vs gas=4 must give the same
    averaged gradient, hence the same loss trajectory (the reference's
    gradient-accumulation invariant)."""
    batch = _batch(rng, n=32)
    losses = {}
    for gas in (1, 4):
        mesh_manager.reset()
        engine = _engine({"train_batch_size": 32,
                          "gradient_accumulation_steps": gas,
                          "zero_optimization": {"stage": 2}})
        losses[gas] = [float(engine.train_batch(batch=batch))
                       for _ in range(4)]
    np.testing.assert_allclose(losses[1], losses[4], rtol=2e-4)


@pytest.mark.parametrize("stage", [
    pytest.param(1, marks=pytest.mark.slow),
    pytest.param(2, marks=pytest.mark.slow)])  # tier-1 diet (ISSUE 7): grad-norm smoke stays
def test_clipping_parity_across_stages(stage, rng, eight_devices):
    """Sharding must not change the clipped trajectory: stage N with
    clipping == stage 0 with clipping, step for step. A tiny max_norm
    makes every step clip, so any norm-computation divergence across
    shardings would show immediately."""
    batch = _batch(rng)
    losses = {}
    for s in (0, stage):
        mesh_manager.reset()
        engine = _engine({"zero_optimization": {"stage": s},
                          "gradient_clipping": 1e-3,
                          "optimizer": {"type": "Adam",
                                        "params": {"lr": 1e-2}}})
        losses[s] = [float(engine.train_batch(batch=batch))
                     for _ in range(4)]
    np.testing.assert_allclose(losses[0], losses[stage], rtol=2e-3)


def test_grad_norm_metric_is_preclip_and_positive(rng, eight_devices):
    engine = _engine({"gradient_clipping": 1e-4})
    engine.train_batch(batch=_batch(rng))
    gn = engine.get_global_grad_norm()
    # the reported norm is the TRUE (pre-clip) global norm, far above
    # the clip bound at init on random data
    assert gn is not None and float(gn) > 1e-4


@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_bf16_zero3_composes_with_gas_and_clipping(rng, eight_devices):
    engine = _engine({"bf16": {"enabled": True},
                      "train_batch_size": 32,
                      "zero_optimization": {"stage": 3},
                      "gradient_accumulation_steps": 4,
                      "gradient_clipping": 1.0})
    batch = _batch(rng, n=32)
    losses = [float(engine.train_batch(batch=batch)) for _ in range(6)]
    assert losses[-1] < losses[0]
    assert engine.global_steps == 6


# ---------------- pure math helpers ----------------

def test_clip_grad_norm_scales_to_bound():
    g = {"w": jnp.full((10,), 3.0), "b": jnp.full((6,), 4.0)}
    norm = float(global_norm(g))
    assert norm == pytest.approx(np.sqrt(10 * 9 + 6 * 16))
    clipped, total = clip_grad_norm_(g, max_norm=1.0)
    assert float(total) == pytest.approx(norm)
    assert float(global_norm(clipped)) == pytest.approx(1.0, rel=1e-3)
    # under the bound: untouched
    small = {"w": jnp.full((4,), 1e-4)}
    same, _ = clip_grad_norm_(small, max_norm=1.0)
    np.testing.assert_allclose(np.asarray(same["w"]),
                               np.asarray(small["w"]), rtol=1e-5)


def test_global_norm_inf_ord_and_empty():
    g = {"a": jnp.array([1.0, -5.0]), "b": jnp.array([2.0])}
    assert float(global_norm(g, ord=float("inf"))) == 5.0
    assert float(global_norm({})) == 0.0


def test_partition_uniform_spreads_residual():
    assert partition_uniform(10, 3) == [0, 4, 7, 10]
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(3, 5)[-1] == 3


def test_partition_balanced_minimizes_bottleneck():
    # one heavy item must sit alone
    parts = partition_balanced([10, 1, 1, 1, 1], 2)
    assert parts[0] == 0 and parts[-1] == 5
    bounds = list(zip(parts[:-1], parts[1:]))
    weights = [10, 1, 1, 1, 1]
    loads = [sum(weights[a:b]) for a, b in bounds]
    assert max(loads) == 10
    # uniform weights -> near-uniform split
    parts = partition_balanced([1] * 8, 4)
    loads = [b - a for a, b in zip(parts[:-1], parts[1:])]
    assert max(loads) == 2
