"""int4 gradient DOWNLOAD wire for ZeRO-Offload (round-5 link-volume
step: ~0.52 B/param device->host, half the int8 wire) with a
DEVICE-resident error-feedback residual — the upload leg's telescoping
trick (offload.py _delta_payload) run in the download direction.

Reference roles: swap_tensor/pipelined_optimizer_swapper.py grad
streaming + the OffloadPP reduced host wire (blogs/deepspeed-offloadpp).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import deepspeed_tpu
from deepspeed_tpu.comm.compressed import (_block_dequantize4,
                                           _block_quantize4)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager


def _config(grad_dtype="bf16", **offload_extra):
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW",
                         "params": {"lr": 1e-3, "weight_decay": 0.01}},
           "bf16": {"enabled": True},
           "zero_optimization": {
               "stage": 2,
               "offload_optimizer": {"device": "cpu",
                                     "grad_dtype": grad_dtype,
                                     **offload_extra}},
           "gradient_clipping": 1.0,
           "steps_per_print": 0}
    return cfg


def _train(config, steps=10, seed=0):
    mesh_manager.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()), config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    return engine, [float(engine.train_batch(batch=batch))
                    for _ in range(steps)]


def test_quantize4_roundtrip_matches_host_decode(rng):
    """The device pack and the coordinator's host unpack are exact
    inverses of the same nibble convention (element 2k low, 2k+1 high)."""
    from deepspeed_tpu.runtime.zero.offload import OffloadCoordinator
    x = rng.standard_normal(1000).astype(np.float32)
    q4, sc = _block_quantize4(jnp.asarray(x))
    assert np.asarray(q4).dtype == np.uint8
    assert q4.shape == (4, 128)          # 1000 -> 4 blocks, packed half
    dev = np.asarray(_block_dequantize4(q4, sc, 1000, jnp.float32))

    co = OffloadCoordinator.__new__(OffloadCoordinator)
    co._int8_grads = True
    co._grad_bits = 4
    co._shapes = [(1000,)]
    host = co._decode_grads([np.asarray(q4), np.asarray(sc)])
    np.testing.assert_array_equal(host[0], dev)
    # quantization error bounded by half a step (per-block amax / 7)
    g = np.pad(x, (0, 24)).reshape(4, 256)
    amax = np.abs(g).max(axis=1, keepdims=True)
    bound = (amax / 7.0) * 0.5 + 1e-7
    err = np.abs(np.pad(dev, (0, 24)).reshape(4, 256) - g)
    assert (err <= bound).all()


def test_error_feedback_telescopes(rng):
    """sum of dequantized payloads == sum of true grads - final
    residual: the host stream loses NOTHING over steps except the one
    in-flight residual (the invariant that makes a 4-bit wire safe)."""
    g_sum = np.zeros(777, np.float32)
    deq_sum = np.zeros(777, np.float32)
    r = jnp.zeros(777, jnp.float32)
    for _ in range(12):
        g = rng.standard_normal(777).astype(np.float32) * 1e-2
        c = jnp.asarray(g) + r
        q4, sc = _block_quantize4(c)
        deq = _block_dequantize4(q4, sc, 777, jnp.float32)
        r = c - deq
        g_sum += g
        deq_sum += np.asarray(deq)
    np.testing.assert_allclose(deq_sum, g_sum - np.asarray(r),
                               atol=1e-5)
    # the residual itself stays bounded by one quantization step
    assert float(jnp.abs(r).max()) < 0.05


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_int4_grads_parity_with_bf16_wire(eight_devices):
    """Error feedback keeps the int4 grad wire's trajectory on the
    uncompressed wire's curve to rounding noise."""
    _, ref = _train(_config("bf16"), steps=10)
    _, got = _train(_config("int4"), steps=10)
    # coarser than the int8 wire's 5e-3: the EF stream preserves the
    # grad SUM exactly, but Adam is nonlinear in the per-step grads,
    # so 4-bit rounding shows up as a small trajectory wobble
    np.testing.assert_allclose(got, ref, atol=2e-2)
    assert got[-1] < got[0]


@pytest.mark.slow  # tier-1 diet (ISSUE 7): int4 mirror/byte-count smokes stay
def test_wire_payload_is_packed_nibbles(eight_devices):
    """The device->host stream actually carries uint8 nibble pairs of
    ~half the int8 volume (plus one fp32 scale per 256-block)."""
    engine, _ = _train(_config("int4"), steps=1)
    captured = {}
    orig = engine._offload.apply_grads

    def spy(state_master, off_grads, lr, skip=False):
        captured["wire"] = [np.asarray(x) for x in off_grads]
        return orig(state_master, off_grads, lr=lr, skip=skip)

    engine._offload.apply_grads = spy
    ids = np.zeros((engine.train_batch_size(), 16), np.int32)
    engine.train_batch(batch={"input_ids": ids, "labels": ids})
    wire = captured["wire"]
    assert wire and len(wire) == 2 * len(engine._offload.off_idx)
    total_bytes = sum(a.nbytes for a in wire)
    n_off = sum(int(np.prod(s)) for s in engine._offload._shapes)
    for q4, sc in zip(wire[0::2], wire[1::2]):
        assert q4.dtype == np.uint8
        assert sc.dtype == np.float32
        assert q4.shape[1] == 128        # 256-block packed in half
    # ~0.52 B/param incl. scales; block padding adds a little
    assert total_bytes < 0.6 * n_off


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_residual_lives_on_device_and_moves(eight_devices):
    engine, _ = _train(_config("int4"), steps=3)
    res = engine._offload_grad_residual
    assert len(res) == len(engine._offload.off_idx)
    flat = jax.tree_util.tree_leaves(engine.state.master_params)
    for r, i in zip(res, engine._offload.off_idx):
        assert isinstance(r, jax.Array)
        assert r.shape == flat[i].shape and r.dtype == jnp.float32
    # after real steps the residual carries live rounding error
    assert any(float(jnp.abs(r).max()) > 0 for r in res)


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_checkpoint_roundtrips_residual(eight_devices, tmp_path):
    """The residual is optimizer state: a resume must restore it
    bit-for-bit, or the stream would replay/lose one step's rounding."""
    engine, _ = _train(_config("int4"), steps=4)
    saved = [np.asarray(r) for r in engine._offload_grad_residual]
    engine.save_checkpoint(str(tmp_path))
    # keep training so the live residual moves past the checkpoint
    ids = np.zeros((engine.train_batch_size(), 16), np.int32)
    b = {"input_ids": ids, "labels": ids}
    engine.train_batch(batch=b)
    assert any(not np.array_equal(np.asarray(r), s) for r, s in
               zip(engine._offload_grad_residual, saved))
    engine.load_checkpoint(str(tmp_path))
    for r, s in zip(engine._offload_grad_residual, saved):
        np.testing.assert_array_equal(np.asarray(r), s)
    losses = [float(engine.train_batch(batch=b)) for _ in range(3)]
    assert np.isfinite(losses).all()


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_int4_composes_with_delta_upload_and_dpu(eight_devices):
    """The full config-4 wire: int4 grads down + int4 deltas up +
    delayed update still converges on the bf16 trajectory."""
    _, ref = _train(_config("bf16"), steps=10)
    _, got = _train(_config("int4", upload_dtype="int4_delta",
                            delayed_update=True), steps=10)
    # DPU trails one step; compare the settled tail loosely
    np.testing.assert_allclose(got[3:], ref[3:], rtol=0.15)
    assert got[-1] < got[0]


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_fp16_overflow_protects_residual(eight_devices):
    """On an fp16 overflow the host skips the payload AND the device
    residual must carry the OLD value forward — absorbing the inf/nan
    wavefront would poison every later step's error feedback."""
    mesh_manager.reset()
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           # huge initial scale -> guaranteed overflow on step 1
           "fp16": {"enabled": True, "initial_scale_power": 18,
                    "loss_scale_window": 2},
           "zero_optimization": {
               "stage": 2,
               "offload_optimizer": {"device": "cpu",
                                     "grad_dtype": "int4"}},
           "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()), config=cfg)
    ids = np.zeros((engine.train_batch_size(), 16), np.int32)
    b = {"input_ids": ids, "labels": ids}
    engine.train_batch(batch=b)
    assert engine.skipped_steps >= 1          # the overflow happened
    assert engine._offload.host_adam.step_count == 0   # host skipped
    for r in engine._offload_grad_residual:
        arr = np.asarray(r)
        assert np.isfinite(arr).all()
        np.testing.assert_array_equal(arr, 0.0)   # old (zero) carried
    # once the scale backs off, training proceeds and the residual
    # starts carrying real rounding error
    for _ in range(8):
        engine.train_batch(batch=b)
    assert engine._offload.host_adam.step_count >= 1
    assert all(np.isfinite(np.asarray(r)).all()
               for r in engine._offload_grad_residual)


def test_unknown_grad_dtype_rejected(eight_devices):
    mesh_manager.reset()
    with pytest.raises(ValueError, match="grad_dtype"):
        deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(GPT2Config.tiny()),
            config=_config("int2"))
