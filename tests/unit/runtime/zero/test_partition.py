"""ZeRO sharding-rule unit tests (reference:
tests/unit/runtime/zero/test_zero.py partitioning assertions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
from deepspeed_tpu.runtime.zero.partition import (ZeroShardingRules,
                                                  shard_leaf_spec)


@pytest.fixture
def mesh(eight_devices):
    return mesh_manager.init(MeshConfig(data=1, fsdp=8))


def test_shard_leaf_spec_picks_divisible_dim(mesh):
    spec = shard_leaf_spec((16, 24), mesh, "fsdp")
    assert spec == P(None, "fsdp")
    spec = shard_leaf_spec((64, 24), mesh, "fsdp")
    assert spec == P("fsdp", None)


def test_shard_leaf_spec_small_stays_replicated(mesh):
    spec = shard_leaf_spec((4,), mesh, "fsdp")
    assert spec == P()
    spec = shard_leaf_spec((64,), mesh, "fsdp", min_size=1000)
    assert spec == P()


def test_shard_respects_base_spec(mesh):
    base = P(None, "tensor")
    spec = shard_leaf_spec((64, 32), mesh, "fsdp", base_spec=base)
    assert spec == P("fsdp", "tensor")


def test_stage_semantics(mesh):
    params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}

    r0 = ZeroShardingRules(mesh=mesh, stage=0)
    assert r0.param_spec("w", params["w"]) == P()
    assert r0.opt_spec("w", params["w"]) == P()
    assert r0.grad_spec("w", params["w"]) == P()

    r1 = ZeroShardingRules(mesh=mesh, stage=1)
    assert r1.param_spec("w", params["w"]) == P()
    assert r1.opt_spec("w", params["w"]) == P("fsdp", None)
    assert r1.grad_spec("w", params["w"]) == P()

    r2 = ZeroShardingRules(mesh=mesh, stage=2)
    assert r2.grad_spec("w", params["w"]) == P("fsdp", None)
    assert r2.param_spec("w", params["w"]) == P()

    r3 = ZeroShardingRules(mesh=mesh, stage=3)
    assert r3.param_spec("w", params["w"]) == P("fsdp", None)


def test_persistence_threshold(mesh):
    r3 = ZeroShardingRules(mesh=mesh, stage=3, param_persistence_threshold=10_000)
    small = jnp.zeros((64,))
    big = jnp.zeros((256, 256))
    assert r3.param_spec("s", small) == P()
    assert r3.param_spec("b", big) == P("fsdp", None)
    # optimizer states shard regardless of persistence threshold
    assert r3.opt_spec("s", small) == P("fsdp")


class TestShardedAtBirthInit:
    """zero.Init / sharded init parity (reference:
    partition_parameters.py:299 — no rank holds the full model)."""

    def test_engine_init_params_born_sharded(self, eight_devices):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        from deepspeed_tpu.parallel.mesh import FSDP_AXIS, mesh_manager
        mesh_manager.reset()
        config = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "steps_per_print": 0,
        }
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(GPT2Config.tiny()), config=config)
        ids = np.zeros((engine.train_batch_size(), 16), np.int32)
        engine.init_params({"input_ids": ids, "labels": ids})
        wte = engine.state.master_params["params"]["wte"]
        assert FSDP_AXIS in tuple(wte.sharding.spec)

    def test_zero_init_context_and_sharded_init(self, eight_devices):
        import deepspeed_tpu
        from deepspeed_tpu import zero
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        from deepspeed_tpu.parallel.mesh import mesh_manager
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=1, fsdp=-1))
        model = GPT2LMHeadModel(GPT2Config.tiny())
        ids = np.zeros((1, 8), np.int32)
        with zero.Init():
            assert zero.init_is_active()
            params = zero.sharded_init(model.init, jax.random.PRNGKey(0),
                                       ids)
        assert not zero.init_is_active()
        leaves = jax.tree_util.tree_leaves(params)
        assert any(ax is not None
                   for l in leaves if l.ndim >= 2
                   for ax in tuple(l.sharding.spec))

        abstract = zero.abstract_init(model.init, jax.random.PRNGKey(0),
                                      ids)
        assert all(isinstance(l, jax.ShapeDtypeStruct)
                   for l in jax.tree_util.tree_leaves(abstract))


class TestPersistenceThresholdSpecs:
    """param_persistence_threshold boundary semantics, asserted on the
    emitted PartitionSpecs directly (ISSUE 3 satellite): strictly-below
    stays replicated, at/above shards; hybrid data+fsdp meshes carry
    states over fsdp only."""

    def test_threshold_boundaries(self, mesh):
        from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules
        import jax.numpy as jnp
        r3 = ZeroShardingRules(mesh=mesh, stage=3,
                               param_persistence_threshold=4096)
        below = jnp.zeros((32, 64))     # 2048 < 4096 -> persists
        at = jnp.zeros((64, 64))        # 4096 == threshold -> sharded
        above = jnp.zeros((128, 64))    # 8192 > threshold -> sharded
        assert r3.param_spec("below", below) == P()
        assert r3.param_spec("at", at) == P("fsdp", None)
        assert r3.param_spec("above", above) == P("fsdp", None)
        # persistence gates PARAM placement only: grads/opt states of a
        # persistent leaf still shard (they are consumed sharded);
        # the largest dim (64) carries the axis
        assert r3.grad_spec("below", below) == P(None, "fsdp")
        assert r3.opt_spec("below", below) == P(None, "fsdp")

    def test_hybrid_data_fsdp_mesh(self, eight_devices):
        """data=2 x fsdp=4: states shard over fsdp ONLY (replicated
        across data — the MiCS / hpZ hybrid semantics); divisibility is
        judged against the fsdp axis size, not the device count."""
        import jax.numpy as jnp
        from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules
        mesh = mesh_manager.init(MeshConfig(data=2, fsdp=4))
        r3 = ZeroShardingRules(mesh=mesh, stage=3,
                               param_persistence_threshold=0)
        # 12 divides by 4 but not 8: only the fsdp axis size matters
        assert r3.param_spec("w", jnp.zeros((12, 6))) == P("fsdp", None)
        # largest divisible dim wins; dim 0 indivisible -> dim 1
        assert r3.param_spec("w2", jnp.zeros((6, 12))) == P(None, "fsdp")
        # nothing divisible -> replicated, never padded (spec may be
        # spelled P() or P(None, None); both mean fully replicated)
        assert all(ax is None
                   for ax in tuple(r3.param_spec("w3", jnp.zeros((6, 6)))))
        # 1-d states shard over fsdp alone; DATA_AXIS never appears
        assert r3.opt_spec("b", jnp.zeros((8,))) == P("fsdp")
        for spec in (r3.param_spec("w", jnp.zeros((12, 6))),
                     r3.grad_spec("w", jnp.zeros((12, 6))),
                     r3.opt_spec("w", jnp.zeros((12, 6)))):
            assert "data" not in tuple(spec)

    def test_hybrid_mesh_with_tensor_base_spec(self, eight_devices):
        """A tensor-parallel base spec keeps its axis; fsdp lands on
        the largest UNSHARDED divisible dim."""
        import jax.numpy as jnp
        from deepspeed_tpu.runtime.zero.partition import ZeroShardingRules
        mesh = mesh_manager.init(MeshConfig(data=2, fsdp=2, tensor=2))
        rules = ZeroShardingRules(
            mesh=mesh, stage=3, param_persistence_threshold=0,
            tensor_rules=lambda name, shape: P(None, "tensor")
            if name.endswith("kernel") else None)
        assert rules.param_spec("q.kernel", jnp.zeros((8, 8))) == \
            P("fsdp", "tensor")
        assert rules.param_spec("bias", jnp.zeros((8,))) == P("fsdp")
