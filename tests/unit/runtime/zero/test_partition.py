"""ZeRO sharding-rule unit tests (reference:
tests/unit/runtime/zero/test_zero.py partitioning assertions)."""

import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
from deepspeed_tpu.runtime.zero.partition import (ZeroShardingRules,
                                                  shard_leaf_spec)


@pytest.fixture
def mesh(eight_devices):
    return mesh_manager.init(MeshConfig(data=1, fsdp=8))


def test_shard_leaf_spec_picks_divisible_dim(mesh):
    spec = shard_leaf_spec((16, 24), mesh, "fsdp")
    assert spec == P(None, "fsdp")
    spec = shard_leaf_spec((64, 24), mesh, "fsdp")
    assert spec == P("fsdp", None)


def test_shard_leaf_spec_small_stays_replicated(mesh):
    spec = shard_leaf_spec((4,), mesh, "fsdp")
    assert spec == P()
    spec = shard_leaf_spec((64,), mesh, "fsdp", min_size=1000)
    assert spec == P()


def test_shard_respects_base_spec(mesh):
    base = P(None, "tensor")
    spec = shard_leaf_spec((64, 32), mesh, "fsdp", base_spec=base)
    assert spec == P("fsdp", "tensor")


def test_stage_semantics(mesh):
    params = {"w": jnp.zeros((64, 64)), "b": jnp.zeros((64,))}

    r0 = ZeroShardingRules(mesh=mesh, stage=0)
    assert r0.param_spec("w", params["w"]) == P()
    assert r0.opt_spec("w", params["w"]) == P()
    assert r0.grad_spec("w", params["w"]) == P()

    r1 = ZeroShardingRules(mesh=mesh, stage=1)
    assert r1.param_spec("w", params["w"]) == P()
    assert r1.opt_spec("w", params["w"]) == P("fsdp", None)
    assert r1.grad_spec("w", params["w"]) == P()

    r2 = ZeroShardingRules(mesh=mesh, stage=2)
    assert r2.grad_spec("w", params["w"]) == P("fsdp", None)
    assert r2.param_spec("w", params["w"]) == P()

    r3 = ZeroShardingRules(mesh=mesh, stage=3)
    assert r3.param_spec("w", params["w"]) == P("fsdp", None)


def test_persistence_threshold(mesh):
    r3 = ZeroShardingRules(mesh=mesh, stage=3, param_persistence_threshold=10_000)
    small = jnp.zeros((64,))
    big = jnp.zeros((256, 256))
    assert r3.param_spec("s", small) == P()
    assert r3.param_spec("b", big) == P("fsdp", None)
    # optimizer states shard regardless of persistence threshold
    assert r3.opt_spec("s", small) == P("fsdp")
