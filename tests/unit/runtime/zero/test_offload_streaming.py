"""Streaming grad wire (runtime/transfer/streaming.py + the streamed
host step in runtime/zero/offload.py): bit-exactness vs the bucketed
and per-leaf wires across grad/upload codecs, the per-layer group
schedule + kick window, the d2h exposed/overlapped attribution, the
trace evidence that copies start before the step's device wall ends,
and fault recovery on the streamed waits."""

import math

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager
from deepspeed_tpu.resilience import fault_injector
from deepspeed_tpu.runtime.transfer.streaming import (StreamSchedule,
                                                      WireClock,
                                                      build_wire_groups)
from deepspeed_tpu.runtime.zero.schedule import (layer_index_of,
                                                 offload_wire_groups)


def _config(streaming=True, window=0, enabled=True, bucket_mb=1 / 64,
            grad_dtype="bf16", upload_dtype="bf16", delayed=False,
            bf16=True):
    return {"train_micro_batch_size_per_gpu": 4,
            "gradient_accumulation_steps": 1,
            "optimizer": {"type": "AdamW",
                          "params": {"lr": 1e-3, "weight_decay": 0.01}},
            "bf16": {"enabled": bf16},
            "zero_optimization": {
                "stage": 2,
                "offload_optimizer": {
                    "device": "cpu", "delayed_update": delayed,
                    "grad_dtype": grad_dtype,
                    "upload_dtype": upload_dtype,
                    "transfer": {"enabled": enabled,
                                 "bucket_mb": bucket_mb,
                                 "streaming": streaming,
                                 "window": window}}},
            "gradient_clipping": 1.0,
            "steps_per_print": 0}


def _train(config, steps=2, seed=0, gas=None):
    mesh_manager.reset()
    if gas:
        config = dict(config)
        config["gradient_accumulation_steps"] = gas
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    return engine, [float(engine.train_batch(batch=batch))
                    for _ in range(steps)]


def _assert_same_offload_state(e0, e1):
    for a, b in zip(e0._offload.host_adam.master,
                    e1._offload.host_adam.master):
        np.testing.assert_array_equal(a, b)
    for m0, m1, v0, v1 in zip(e0._offload.host_adam.m,
                              e1._offload.host_adam.m,
                              e0._offload.host_adam.v,
                              e1._offload.host_adam.v):
        np.testing.assert_array_equal(m0, m1)
        np.testing.assert_array_equal(v0, v1)
    f0 = jax.tree_util.tree_leaves(e0.state.master_params)
    f1 = jax.tree_util.tree_leaves(e1.state.master_params)
    for i in e0._offload.off_idx:
        np.testing.assert_array_equal(np.asarray(f0[i]),
                                      np.asarray(f1[i]))


# ---------------------------------------------------------------------------
# pure planning units (no engine, free)
# ---------------------------------------------------------------------------

class TestWirePlanning:
    def test_layer_index_parsing(self):
        assert layer_index_of("params.h_3.attn.c_attn.kernel") == 3
        assert layer_index_of("params.layers_12.mlp.up_proj.kernel") == 12
        assert layer_index_of("params.blocks_0.fc.bias") == 0
        assert layer_index_of("params.wte") is None
        assert layer_index_of("params.ln_f.scale") is None
        assert layer_index_of("params.lm_head") is None
        # 'h' must be a separated token, not a substring
        assert layer_index_of("params.head_7x.w") is None

    def test_groups_backward_order_rest_trails(self):
        names = ["params.wte", "params.h_0.a", "params.h_1.a",
                 "params.h_1.b", "params.ln_f.scale"]
        groups = offload_wire_groups(names, [0, 1, 2, 3, 4], per_leaf=1)
        assert [g.label for g in groups] == ["layer1", "layer0", "rest"]
        assert groups[0].slots == [2, 3]     # last layer first
        assert groups[1].slots == [1]
        assert groups[2].slots == [0, 4]     # embed + final norm trail

    def test_groups_per_leaf_entries(self):
        # int8/int4 wire: 2 wire tensors (q, scales) per slot
        names = ["params.h_0.a", "params.h_1.a"]
        groups = offload_wire_groups(names, [0, 1], per_leaf=2)
        assert groups[0].label == "layer1"
        assert groups[0].entries == [2, 3]
        assert groups[1].entries == [0, 1]

    def test_groups_fallback_per_slot_reversed(self):
        # no layer tokens anywhere: per-slot groups in reverse flatten
        # order (flatten ~ forward, so reverse ~ backward completion)
        groups = offload_wire_groups(["params.a", "params.b"], [0, 1],
                                     per_leaf=1)
        assert [g.slots for g in groups] == [[1], [0]]

    def test_stream_schedule_windowing(self):
        groups = build_wire_groups([2, 1, 0], per_leaf=1)
        s = StreamSchedule(groups, window=0)
        assert s.take_initial() == groups        # kick-all
        assert s.take_next() == []
        s = StreamSchedule(groups, window=2)
        assert s.take_initial() == groups[:2]
        assert s.take_next() == [groups[2]]      # released by arrival
        assert s.take_next() == []               # nothing left
        with pytest.raises(ValueError, match="window"):
            StreamSchedule(groups, window=-1)

    def test_wire_clock_split(self):
        c = WireClock()
        c.kick()
        c.t_kick, c.t_done = 10.0, 10.5   # device busy 500 ms post-kick
        c.note_wait(10.0, 10.6)           # 100 ms exposed, 500 hidden
        c.note_wait(10.7, 10.9)           # 200 ms exposed (post-done)
        out = c.split()
        # exposed: wait wall after t_done = 0.1 + 0.2 s
        assert out["d2h_exposed_ms"] == pytest.approx(300.0)
        # window 10.0 -> 10.9 minus exposed
        assert out["d2h_overlapped_ms"] == pytest.approx(600.0)
        # no waits recorded -> zeros, never a crash
        assert WireClock().split() == {"d2h_exposed_ms": 0.0,
                                       "d2h_overlapped_ms": 0.0}

    def test_window_config_validated(self):
        from deepspeed_tpu.runtime.zero.config import (
            DeepSpeedZeroOffloadTransferConfig)
        with pytest.raises(ValueError, match="window"):
            DeepSpeedZeroOffloadTransferConfig.from_dict(
                {"streaming": True, "window": -2})


# ---------------------------------------------------------------------------
# engine-level: bit-identity, attribution, overlap evidence
# ---------------------------------------------------------------------------

# tier-1 keeps the default-wire smoke; compressed wires + the window
# sweep ride the slow tier (tier-1 budget rule)
@pytest.mark.perf
@pytest.mark.parametrize("grad_dtype,upload_dtype,delayed", [
    ("bf16", "bf16", False),
    pytest.param("int8", "int8_delta", False, marks=pytest.mark.slow),
    pytest.param("int4", "int4_delta", True, marks=pytest.mark.slow),
])
def test_streamed_bit_identical_to_bucketed(eight_devices, grad_dtype,
                                            upload_dtype, delayed):
    """THE acceptance invariant: the streamed wire only reorders WHEN
    bytes move and when each slot's host Adam runs — losses, host
    Adam state and device leaves stay bitwise equal to the bucketed
    wire (itself asserted == per-leaf in test_offload_bucketed) for
    every codec, including the delta-upload error-feedback stream
    across steps."""
    steps = 4 if delayed else 2
    e0, l0 = _train(_config(streaming=False, grad_dtype=grad_dtype,
                            upload_dtype=upload_dtype, delayed=delayed),
                    steps=steps)
    e1, l1 = _train(_config(streaming=True, grad_dtype=grad_dtype,
                            upload_dtype=upload_dtype, delayed=delayed),
                    steps=steps)
    assert e1._offload.streaming and not e0._offload.streaming
    assert l0 == l1
    # DPU: join the in-flight host step before comparing state (the
    # worker mutates host Adam arrays until merged)
    e0._merge_offload_future()
    e1._merge_offload_future()
    _assert_same_offload_state(e0, e1)


@pytest.mark.slow
def test_streamed_window_bit_identical_and_bounded(eight_devices):
    """A depth-2 kick window changes in-flight bookkeeping only: the
    update stays bitwise equal to the unwindowed stream."""
    e0, l0 = _train(_config(streaming=True, window=0), steps=3)
    e1, l1 = _train(_config(streaming=True, window=2), steps=3)
    assert l0 == l1
    _assert_same_offload_state(e0, e1)
    assert e1._offload._stream_window == 2


@pytest.mark.perf
@pytest.mark.slow  # tier-1 diet (PR 17): telemetry e2e keeps per-bucket d2h tracing tier-1; param_stream pins the overlap keys
def test_streamed_overlap_attribution_and_trace(eight_devices):
    """ISSUE acceptance (tests satellite): (a) the breakdown carries
    the exposed/overlapped split with exposed <= the blocking wall
    and a real overlapped share, and (b) a traced step shows the d2h
    copies STARTING before the step's device wall ends — the kick
    instant and the first transfer.d2h wait both precede the
    transfer.device_done mark (async dispatch: the device is still
    chewing the gas-8 step while the host kicks and waits)."""
    from deepspeed_tpu.telemetry.trace import tracer
    tracer.configure(enabled=True, capacity=16384)
    try:
        # gas=8 stretches the device wall well past the host's
        # dispatch->kick->first-wait latency (microseconds)
        engine, losses = _train(_config(streaming=True), steps=2, gas=8)
        bd = engine.get_offload_breakdown()
        for k in ("grad_d2h_ms", "host_adam_ms", "param_h2d_ms",
                  "d2h_exposed_ms", "d2h_overlapped_ms", "d2h_groups",
                  "h2d_buckets", "overlap_residue_ms"):
            assert k in bd, bd
        assert bd["d2h_groups"] >= 2          # per-layer groups, not one
        assert bd["d2h_exposed_ms"] <= bd["grad_d2h_ms"] + 1e-6
        assert bd["d2h_overlapped_ms"] > 0.0  # some wire wall hid
        spans = tracer.snapshot()
        kicks = [r for r in spans if r.name == "transfer.d2h_kick"]
        dones = [r for r in spans if r.name == "transfer.device_done"]
        waits = [r for r in spans if r.name == "transfer.d2h"]
        assert kicks and dones and waits
        # pair each step's kick with the done that follows it
        k0 = kicks[0].t0_ns
        done_after = min(d.t0_ns for d in dones if d.t0_ns >= k0)
        assert k0 < done_after, "copies kicked after the device wall"
        first_wait = min(w.t0_ns for w in waits if w.t0_ns >= k0)
        assert first_wait < done_after, \
            "no transfer.d2h span started before the device wall ended"
    finally:
        tracer.disable()
        tracer.clear()


@pytest.mark.perf
@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_offload_train_step_donations_clean(eight_devices):
    """Donation audit satellite: the offload train step's donation
    annotations are clean — XLA aliases every donated buffer (state +
    int4 grad residual), so the audit reports zero refusals. A future
    annotation regression (a donated arg XLA must copy) fails here
    instead of silently doubling HBM."""
    engine, _ = _train(_config(streaming=True, grad_dtype="int4",
                               upload_dtype="int4_delta"), steps=2)
    rep = engine._scheduled_steps["train_step"].schedule_report()
    assert rep["donation_refused"] == {"count": 0, "bytes": 0}


@pytest.mark.slow
def test_streamed_dpu_pipeline_and_checkpoint_flush(eight_devices,
                                                    tmp_path):
    """DPU + streamed wire: one-step-stale pipeline fill holds, the
    curve falls, and a checkpoint save flushes the in-flight host
    step (host Adam fully caught up)."""
    engine, losses = _train(_config(streaming=True, delayed=True),
                            steps=7)
    assert losses[0] == losses[1]        # pipeline fill
    assert losses[-1] < losses[2] < losses[0], losses
    engine.save_checkpoint(str(tmp_path))
    assert engine._offload_future is None
    assert engine._offload.host_adam.step_count == 7


@pytest.mark.fault
def test_streamed_d2h_fault_recovers_via_retry(rng, eight_devices):
    """A transient fault on one streamed group wait is absorbed by the
    bounded retry — re-reading the still-live wire tensors is
    idempotent (the stream token holds their refs)."""
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=_config(streaming=True, bucket_mb=64))
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    l0 = float(engine.train_batch(batch=batch))
    with fault_injector.inject("transfer.d2h:ioerror"):
        l1 = float(engine.train_batch(batch=batch))
        assert fault_injector.fired == ["transfer.d2h:ioerror@0"]
    assert np.isfinite(l1)
    l2 = float(engine.train_batch(batch=batch))
    assert l2 < l0


def test_streaming_requires_bucketed_engine(eight_devices):
    """streaming with transfer.enabled=false falls back (warn) to the
    per-leaf wire — never a half-configured stream."""
    engine, losses = _train(_config(streaming=True, enabled=False),
                            steps=2)
    off = engine._offload
    assert not off.streaming and off._transfer is None
    assert losses[-1] < losses[0]
    bd = engine.get_offload_breakdown()
    assert "d2h_groups" not in bd
