"""int4 delta upload wire for ZeRO-Offload (the round-5 link-volume
step past int8: 0.625 B/param host->device; same error-feedback mirror
invariant, coarser per-step rounding)."""

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager
from deepspeed_tpu.runtime.zero.offload import _apply_delta4


def _config(upload_dtype="bf16"):
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW",
                         "params": {"lr": 1e-3, "weight_decay": 0.01}},
           "bf16": {"enabled": True},
           "zero_optimization": {
               "stage": 2,
               "offload_optimizer": {"device": "cpu",
                                     "grad_dtype": "int8",
                                     "upload_dtype": upload_dtype}},
           "gradient_clipping": 1.0,
           "steps_per_print": 0}
    return cfg


def _train(config, steps=10, seed=0):
    mesh_manager.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()), config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    return engine, [float(engine.train_batch(batch=batch))
                    for _ in range(steps)]


def test_nibble_pack_unpack_roundtrip(rng):
    vals = rng.integers(-8, 8, size=(3, 256)).astype(np.int8)
    u = (vals.astype(np.int16) & 0xF).astype(np.uint8)
    packed = (u[:, 0::2] | (u[:, 1::2] << 4)).astype(np.uint8)
    assert packed.shape == (3, 128)        # half the bytes
    scales = np.ones(3, np.float32)
    leaf = jnp.zeros((3 * 256,), jnp.float32)
    out = np.asarray(_apply_delta4(leaf, jnp.asarray(packed),
                                   jnp.asarray(scales)))
    np.testing.assert_array_equal(out, vals.reshape(-1).astype(np.float32))


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_int4_delta_parity_with_bf16_wire(eight_devices):
    """The int4 wire tracks the uncompressed wire to rounding noise —
    the mirror's error feedback carries the coarser residual forward."""
    _, ref = _train(_config("bf16"), steps=10)
    _, got = _train(_config("int4_delta"), steps=10)
    np.testing.assert_allclose(got, ref, atol=8e-3)
    assert got[-1] < got[0]


def test_int4_payload_is_half_the_int8_bytes(eight_devices):
    engine, _ = _train(_config("int4_delta"), steps=1)
    off = engine._offload
    assert off._delta_bits == 4
    sh = off._leaf_shardings(engine.state.master_params)
    payload = off._delta_payload(0, sh[0])
    assert "q4" in payload
    n = int(np.prod(off._shapes[0]))
    q4 = np.asarray(payload["q4"])
    assert q4.dtype == np.uint8
    # <= because of block padding; ~0.5 B/param plus one scale per block
    assert q4.size <= (n + 255) // 256 * 128
    assert q4.size >= n // 2


def test_int4_mirror_matches_device_leaves(eight_devices):
    """Mirror invariant (same contract as int8): after steps, the host
    mirror equals the actual device compute-dtype leaves bit-for-bit."""
    import jax

    engine, _ = _train(_config("int4_delta"), steps=4)
    off = engine._offload
    leaves = jax.tree_util.tree_leaves(engine.state.master_params)
    one_ulp = 2.0 ** -7     # same tolerance contract as the int8 test:
    # XLA's fused add+cast can break a rounding tie differently than
    # the host once in ~1e5 element-steps; error feedback folds that
    # ULP into the next delta so it never compounds
    for slot, i in enumerate(off.off_idx):
        dev = np.asarray(leaves[i], np.float32).reshape(-1)
        mir = off._mirror[slot].reshape(-1)
        diff = np.abs(dev - mir)
        denom = np.maximum(np.abs(dev), 1e-30)
        assert float((diff / denom).max()) <= one_ulp, slot
        assert (diff == 0).mean() > 0.999


def test_unknown_upload_dtype_rejected(eight_devices):
    cfg = _config("int2_delta")
    with pytest.raises(ValueError, match="upload_dtype"):
        deepspeed_tpu.initialize(model=GPT2LMHeadModel(GPT2Config.tiny()),
                                 config=cfg)
