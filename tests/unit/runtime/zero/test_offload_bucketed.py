"""Bucketed transfer engine under ZeRO-Offload: bit-exactness vs the
per-leaf wire (fp32, int8 and int4 wire modes, including delta
uploads), pipeline correctness under delayed_update + sentinel
rollback, and fault injection at the transfer.d2h/transfer.h2d
sites."""

import math

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager
from deepspeed_tpu.resilience import fault_injector


def _config(enabled=True, bucket_mb=1 / 64, grad_dtype="bf16",
            upload_dtype="bf16", delayed=False, bf16=True,
            sentinel=None):
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW",
                         "params": {"lr": 1e-3, "weight_decay": 0.01}},
           "bf16": {"enabled": bf16},
           "zero_optimization": {
               "stage": 2,
               "offload_optimizer": {
                   "device": "cpu", "delayed_update": delayed,
                   "grad_dtype": grad_dtype,
                   "upload_dtype": upload_dtype,
                   # fractional-MB buckets force a real multi-bucket
                   # schedule on the tiny test model (~16 buckets for
                   # the ~250KB bf16 wire) while the pack/unpack jits
                   # stay cheap to compile
                   "transfer": {"enabled": enabled,
                                "bucket_mb": bucket_mb}}},
           "gradient_clipping": 1.0,
           "steps_per_print": 0}
    if sentinel:
        cfg["resilience"] = {"sentinel": sentinel}
    return cfg


def _train(config, steps=2, seed=0):
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    return engine, [float(engine.train_batch(batch=batch))
                    for _ in range(steps)]


# tier-1 diet (PR 5): the compressed wires ride the slow tier.
# tier-1 diet (PR 17): the fp32 wire too — streamed-vs-bucketed parity
# (test_offload_streaming) keeps the wire bit-identity smoke tier-1.
@pytest.mark.parametrize("grad_dtype,upload_dtype,bf16", [
    pytest.param("bf16", "bf16", False,  # fp32 wire (fp32 compute)
                 marks=pytest.mark.slow),
    pytest.param("int8", "int8_delta", True,
                 marks=pytest.mark.slow),
    pytest.param("int4", "int4_delta", True,
                 marks=pytest.mark.slow),
])
def test_bucketed_bit_identical_to_per_leaf(eight_devices, grad_dtype,
                                            upload_dtype, bf16):
    """THE acceptance invariant: the bucketed path only regroups bytes,
    so losses, host masters and device leaves are bitwise equal to the
    per-leaf path across every wire mode. Two steps: step 2 consumes
    step 1's error-feedback state (grad residual / delta mirror), so
    cross-step feedback is covered too."""
    e0, l0 = _train(_config(enabled=False, grad_dtype=grad_dtype,
                            upload_dtype=upload_dtype, bf16=bf16))
    e1, l1 = _train(_config(enabled=True, grad_dtype=grad_dtype,
                            upload_dtype=upload_dtype, bf16=bf16))
    assert e1._offload._transfer is not None
    assert e0._offload._transfer is None
    assert l0 == l1
    for a, b in zip(e0._offload.host_adam.master,
                    e1._offload.host_adam.master):
        np.testing.assert_array_equal(a, b)
    for m0, m1, v0, v1 in zip(e0._offload.host_adam.m,
                              e1._offload.host_adam.m,
                              e0._offload.host_adam.v,
                              e1._offload.host_adam.v):
        np.testing.assert_array_equal(m0, m1)
        np.testing.assert_array_equal(v0, v1)
    f0 = jax.tree_util.tree_leaves(e0.state.master_params)
    f1 = jax.tree_util.tree_leaves(e1.state.master_params)
    for i in e0._offload.off_idx:
        np.testing.assert_array_equal(np.asarray(f0[i]),
                                      np.asarray(f1[i]))


@pytest.mark.slow  # tier-1 diet (ISSUE 16): bit-identical parity smoke stays
def test_bucket_counters_reported_and_bounded(eight_devices):
    """The decomposition carries the per-bucket counters, the schedule
    respects the ceil(stream_bytes/bucket) bound, and fuses many
    leaves into fewer transfers."""
    engine, _ = _train(_config(), steps=2)
    bd = engine.get_offload_breakdown()
    for k in ("grad_d2h_ms", "host_adam_ms", "param_h2d_ms",
              "overlap_residue_ms", "d2h_buckets", "h2d_buckets"):
        assert k in bd, bd
    off = engine._offload
    assert bd["d2h_buckets"] == off._d2h_plan.n_transfers
    bucket = off._transfer.bucket_bytes
    for plan, key in ((off._d2h_plan, "d2h_buckets"),
                      (off._h2d_plan, "h2d_buckets")):
        bound = sum(math.ceil(sp.nbytes / bucket) for sp in plan.streams)
        assert 1 <= bd[key] <= bound
    # many small leaves ride FEWER fused transfers than leaf count
    assert len(off.off_idx) > bd["d2h_buckets"]


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_delayed_update_bucketed_pipeline(eight_devices, tmp_path):
    """DPU + bucketed wire: the one-step-stale pipeline fill holds, the
    curve falls, and a checkpoint save flushes the in-flight host
    step (host Adam fully caught up)."""
    engine, losses = _train(_config(delayed=True), steps=7)
    assert losses[0] == losses[1]        # pipeline fill
    assert losses[-1] < losses[2] < losses[0], losses
    engine.save_checkpoint(str(tmp_path))
    assert engine._offload_future is None
    assert engine._offload.host_adam.step_count == 7


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_delayed_update_bucketed_sentinel_rollback(eight_devices, rng,
                                                   tmp_path):
    """Divergence under the bucketed DPU pipeline: the sentinel's
    rollback restores the checkpoint (device AND host-offload state)
    and training resumes finite — the in-flight bucketed host step
    must not leak poisoned leaves past the restore."""
    ckpt = str(tmp_path / "ckpt")
    cfg = _config(delayed=True, sentinel={
        "enabled": True, "failure_budget": 2, "max_rollbacks": 1,
        "ckpt_dir": ckpt})
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    for _ in range(3):
        engine.train_batch(batch=batch)
    engine.save_checkpoint(ckpt)
    assert engine.global_steps == 3

    import jax.numpy as jnp
    poisoned = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, jnp.nan)
        if jnp.issubdtype(x.dtype, jnp.floating) else x,
        engine.state.master_params)
    engine.state = engine.state._replace(master_params=poisoned)

    l1 = float(engine.train_batch(batch=batch))      # failure 1: skip
    assert math.isnan(l1)
    engine.train_batch(batch=batch)                  # failure 2: rollback
    assert engine._sentinel.rollbacks == 1
    assert engine.global_steps == 3
    losses = [float(engine.train_batch(batch=batch)) for _ in range(3)]
    assert np.isfinite(losses).all(), losses
    assert engine.global_steps == 6


@pytest.mark.fault
@pytest.mark.parametrize("site", [
    "transfer.d2h",
    pytest.param("transfer.h2d",
                 marks=pytest.mark.slow)])  # tier-1 diet (PR 5)
def test_transfer_site_fault_recovers_via_retry(site, rng,
                                                eight_devices):
    """A transient fault on one fused-bucket transfer is absorbed by
    the bounded retry and the host update still lands."""
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=_config(bucket_mb=64))
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    l0 = float(engine.train_batch(batch=batch))     # compiles cleanly
    with fault_injector.inject(f"{site}:ioerror"):
        l1 = float(engine.train_batch(batch=batch))
        assert fault_injector.fired == [f"{site}:ioerror@0"]
    assert np.isfinite(l1)
    l2 = float(engine.train_batch(batch=batch))
    assert l2 < l0


@pytest.mark.fault
@pytest.mark.slow  # tier-1 diet (PR 5)
def test_transfer_h2d_fault_retries_delta_upload(rng, eight_devices):
    """Delta uploads are retryable UNDER BUCKETING (unlike the per-leaf
    wire): the staged q/scales are immutable once written, so replaying
    a failed device_put never re-advances the error-feedback mirror —
    the mirror still tracks the device leaves after the fault."""
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=_config(grad_dtype="int8",
                                    upload_dtype="int8_delta"))
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    float(engine.train_batch(batch=batch))
    with fault_injector.inject("transfer.h2d:ioerror"):
        l1 = float(engine.train_batch(batch=batch))
        assert fault_injector.fired == ["transfer.h2d:ioerror@0"]
    assert np.isfinite(l1)
    off = engine._offload
    flat = jax.tree_util.tree_leaves(engine.state.master_params)
    one_ulp = 2.0 ** -7
    for slot, i in enumerate(off.off_idx):
        dev = np.asarray(flat[i], dtype=np.float32)
        mir = off._mirror[slot].reshape(dev.shape)
        diff = np.abs(dev - mir)
        denom = np.maximum(np.abs(dev), 1e-30)
        assert float((diff / denom).max()) <= one_ulp


def test_transfer_disabled_keeps_per_leaf_path(eight_devices):
    engine, losses = _train(_config(enabled=False), steps=2)
    assert engine._offload._transfer is None
    bd = engine.get_offload_breakdown()
    assert "d2h_buckets" not in bd
    assert losses[-1] < losses[0]


def test_bad_bucket_mb_rejected():
    from deepspeed_tpu.runtime.zero.config import (
        DeepSpeedZeroOffloadTransferConfig)
    with pytest.raises(ValueError, match="bucket_mb"):
        DeepSpeedZeroOffloadTransferConfig.from_dict({"bucket_mb": 0})
