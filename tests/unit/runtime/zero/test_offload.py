"""ZeRO-Offload tests: mask selection, loss parity vs on-device
optimizer, partial ratio, checkpoint round-trip."""

import os

import jax
import numpy as np

from deepspeed_tpu.utils.jax_compat import host_memory_kind
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime.zero.offload import select_offload_mask


def _config(offload=False, ratio=1.0, stage=1, delayed=False):
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW",
                         "params": {"lr": 1e-3, "weight_decay": 0.01}},
           "bf16": {"enabled": True},
           "zero_optimization": {"stage": stage},
           "gradient_clipping": 1.0,
           "steps_per_print": 0}
    if offload:
        cfg["zero_optimization"]["offload_optimizer"] = {
            "device": "cpu", "ratio": ratio, "delayed_update": delayed}
    return cfg


def _train(config, steps=5, seed=0):
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, 256, size=(gbs, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    return engine, [float(engine.train_batch(batch=batch))
                    for _ in range(steps)]


def test_select_offload_mask_ratio():
    params = [np.zeros(100), np.zeros(50), np.zeros(850)]
    assert select_offload_mask(params, 1.0) == [True, True, True]
    # 0.5: largest leaf (850 = 85%) alone crosses the ratio
    assert select_offload_mask(params, 0.5) == [False, False, True]
    assert select_offload_mask(params, 0.0) == [False, False, False]


@pytest.mark.slow  # tier-1 diet (ISSUE 7): the equivalence suite rides the slow tier; partial-ratio + wire smokes stay
def test_offload_matches_device_training(eight_devices):
    _, ref_losses = _train(_config(offload=False))
    engine, off_losses = _train(_config(offload=True))
    assert engine._offload is not None
    assert len(engine._offload.off_idx) > 0
    # identical seeds/init: host fp32 Adam mirrors the fused device path
    # up to bf16 push-back rounding
    np.testing.assert_allclose(off_losses, ref_losses, rtol=2e-2)
    assert off_losses[-1] < off_losses[0]


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_delayed_update_converges_and_flushes(eight_devices, tmp_path):
    """DPU (delayed_update): offloaded leaves trail by one step, so the
    trajectory is NOT bitwise-equal to the synchronous path, but the
    model must still converge on the same batch, and a checkpoint save
    must flush the in-flight host update (host Adam fully caught up)."""
    engine, losses = _train(_config(offload=True, delayed=True), steps=10)
    assert engine._offload_cfg.delayed_update
    # losses[0] == losses[1] is the expected pipeline fill (the first
    # host update merges one step late); after that the curve falls
    assert losses[0] == losses[1]
    assert losses[-1] < losses[2] < losses[0], losses
    # sync path for comparison: same trend, close trajectory
    _, sync_losses = _train(_config(offload=True), steps=10)
    np.testing.assert_allclose(losses[3:], sync_losses[3:], rtol=0.15)

    engine.save_checkpoint(str(tmp_path))
    assert engine._offload_future is None  # flushed
    # 10 train_batches, one in flight at each boundary: after the flush
    # the host Adam has consumed every step's grads
    assert engine._offload.host_adam.step_count == 10


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_partial_offload_ratio(eight_devices):
    engine, losses = _train(_config(offload=True, ratio=0.5))
    n_leaves = len(jax.tree_util.tree_leaves(engine.state.master_params))
    assert 0 < len(engine._offload.off_idx) < n_leaves
    assert losses[-1] < losses[0]


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_offload_checkpoint_roundtrip(eight_devices, tmp_path):
    engine, losses = _train(_config(offload=True), steps=3)
    engine.save_checkpoint(str(tmp_path))
    assert os.path.exists(os.path.join(
        tmp_path, "latest"))
    tag = open(os.path.join(tmp_path, "latest")).read().strip()
    assert os.path.exists(os.path.join(
        tmp_path, tag, "zero_offload_host_state.npz"))

    engine2, _ = _train(_config(offload=True), steps=1)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == 3
    assert engine2._offload.host_adam.step_count == \
        engine._offload.host_adam.step_count
    for a, b in zip(engine._offload.host_adam.master,
                    engine2._offload.host_adam.master):
        np.testing.assert_array_equal(a, b)


def test_offload_rejects_client_optimizer(eight_devices):
    import optax
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, optimizer=optax.adam(1e-3), config=_config(offload=True))
    ids = np.zeros((engine.train_batch_size(), 8), dtype=np.int32)
    with pytest.raises(ValueError, match="config-defined"):
        engine.init_params({"input_ids": ids, "labels": ids})


class TestParamOffloadHost:
    """ZeRO-Infinity parameter offload: master params + optimizer state
    live in pinned_host memory; the step streams them through HBM and
    writes updates back to host."""

    def _engine(self, stage=2):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        config = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {
                "stage": stage,
                "offload_param": {"device": "cpu"},
            },
            "steps_per_print": 0,
        }
        model = GPT2LMHeadModel(GPT2Config.tiny())
        engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                   config=config)
        return engine

    def test_state_lives_on_host_and_trains(self):
        import jax
        engine = self._engine()
        ids = np.random.default_rng(0).integers(
            0, 256, size=(engine.train_batch_size(), 32), dtype=np.int32)
        batch = {"input_ids": ids, "labels": ids.copy()}
        l0 = float(engine.train_batch(batch=batch))
        for _ in range(4):
            l1 = float(engine.train_batch(batch=batch))
        assert np.isfinite(l0) and l1 < l0

        kinds = {leaf.sharding.memory_kind
                 for leaf in jax.tree_util.tree_leaves(
                     engine.state.master_params)
                 if hasattr(leaf, "sharding")}
        assert kinds == {host_memory_kind()}, kinds
        kinds = {leaf.sharding.memory_kind
                 for leaf in jax.tree_util.tree_leaves(
                     engine.state.opt_state)
                 if hasattr(leaf, "sharding")}
        assert kinds == {host_memory_kind()}, kinds

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_loss_parity_vs_device_resident(self):
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        ids = np.random.default_rng(0).integers(0, 256, size=(16, 32),
                                                dtype=np.int32)
        batch = {"input_ids": ids, "labels": ids.copy()}

        losses = {}
        for offload in (False, True):
            zero = {"stage": 2}
            if offload:
                zero["offload_param"] = {"device": "cpu"}
            config = {
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": zero,
                "steps_per_print": 0,
            }
            model = GPT2LMHeadModel(GPT2Config.tiny())
            engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                       config=config)
            ls = [float(engine.train_batch(batch=batch))
                  for _ in range(3)]
            losses[offload] = ls
        np.testing.assert_allclose(losses[False], losses[True],
                                   rtol=2e-2)

    @pytest.mark.slow  # tier-1 diet (PR 17): state_lives_on_host smoke stays; eval rides test_eval_batch
    def test_eager_triple_and_eval_with_param_offload(self):
        """eval_batch and the eager forward/backward/step triple must
        swap host state through the device too (review finding: only
        train_batch swapped)."""
        engine = self._engine(stage=1)
        ids = np.random.default_rng(0).integers(
            0, 256, size=(engine.train_batch_size(), 32), dtype=np.int32)
        batch = {"input_ids": ids, "labels": ids.copy()}
        engine.init_params(batch)
        ev = float(engine.eval_batch(batch=batch))
        assert np.isfinite(ev)
        engine.backward(batch=batch)
        engine.step()
        import jax
        kinds = {x.sharding.memory_kind
                 for x in jax.tree_util.tree_leaves(
                     engine.state.master_params)}
        assert kinds == {host_memory_kind()}


class TestCompressedWire:
    """Round-4 link-volume attack (VERDICT item 1): int8 gradient
    stream down, block-int8 DELTA param refresh up (error-feedback
    mirror), and the audited step decomposition."""

    def _cfg(self, grad_dtype="bf16", upload_dtype="bf16"):
        cfg = _config(offload=True, stage=2)
        cfg["zero_optimization"]["offload_optimizer"].update(
            grad_dtype=grad_dtype, upload_dtype=upload_dtype)
        return cfg

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_int8_grads_and_delta_upload_parity(self, eight_devices):
        """The compressed wire tracks the bf16 wire to rounding noise
        over 10 steps (the delta's error feedback keeps device params
        equal to the host master within one int8 rounding)."""
        _, ref = _train(self._cfg(), steps=10)
        _, got = _train(self._cfg(grad_dtype="int8",
                                  upload_dtype="int8_delta"), steps=10)
        np.testing.assert_allclose(got, ref, atol=5e-3)

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_mirror_tracks_device_leaves(self, eight_devices):
        """After delta uploads the host mirror tracks the device
        leaves to within ONE bf16 ULP (XLA's fused add+cast can break
        a rounding tie differently than the host once in ~1e5 element-
        steps; the error feedback folds that ULP into the next delta,
        so it never compounds — drift beyond 1 ULP would)."""
        cfg = self._cfg(grad_dtype="int8", upload_dtype="int8_delta")
        engine, _ = _train(cfg, steps=6)
        off = engine._offload
        flat = jax.tree_util.tree_leaves(engine.state.master_params)
        one_ulp = 2.0 ** -7          # bf16 max relative spacing
        for slot, i in enumerate(off.off_idx):
            dev = np.asarray(flat[i], dtype=np.float32)
            mir = off._mirror[slot].reshape(dev.shape)
            diff = np.abs(dev - mir)
            denom = np.maximum(np.abs(dev), 1e-30)
            assert float((diff / denom).max()) <= one_ulp, \
                (slot, float(diff.max()))
            # overwhelmingly bitwise-equal (ties are rare)
            assert (diff == 0).mean() > 0.999

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_breakdown_reported(self, eight_devices):
        engine, _ = _train(self._cfg(), steps=3)
        bd = engine.get_offload_breakdown()
        for k in ("grad_d2h_ms", "host_adam_ms", "param_h2d_ms",
                  "overlap_residue_ms"):
            assert k in bd and bd[k] >= 0.0, bd

    # UN-QUARANTINED (was slow-tier since PR 5): the post-restore
    # XLA-CPU abort/NaN that used to strike here in LONG full-suite
    # processes was root-caused by the lifecycle PR (writeup: README
    # "Long-run durability"; mechanism note in runtime/lifecycle.py).
    # Two layers: (1) dead engines' cyclic object graphs accumulate
    # between gen-2 GC passes, keeping the heap hot and fragmented;
    # (2) the restore stack (orbax/TensorStore) returns state leaves
    # whose buffers jax does not exclusively own, and this test's
    # post-restore train_batch DONATES them into the AOT step
    # executable — latent on a young heap (hence passing standalone),
    # abort-or-NaN on a ~550-test heap. Fixes: load_checkpoint now
    # REBUFFERS restored state into fresh XLA-owned allocations and
    # invalidates the AOT step caches (asserted below), and the suite
    # sweeps dead engines per test module (tests/conftest.py
    # _lifecycle_sweep).
    @pytest.mark.slow  # tier-1 diet (PR 17): param_stream's over-budget checkpoint round-trip keeps restore -> wire-resync tier-1
    def test_mirror_resynced_after_checkpoint_restore(
            self, eight_devices, tmp_path):
        """After load_checkpoint the mirror must equal the RESTORED
        device leaves — deltas against the pre-restore mirror would
        silently shift every offloaded param (review finding)."""
        cfg = self._cfg(grad_dtype="int8", upload_dtype="int8_delta")
        engine, _ = _train(cfg, steps=4)
        engine.save_checkpoint(str(tmp_path))
        # keep training so the live mirror moves past the checkpoint
        ids = np.zeros((engine.train_batch_size(), 16), np.int32)
        engine.train_batch(batch={"input_ids": ids, "labels": ids})
        engine.load_checkpoint(str(tmp_path))
        # the post-restore-abort regression gate: restore must have
        # dropped every cached AOT executable, so the train_batch below
        # compiles against the restored buffers instead of re-entering
        # a stale program that donates them
        assert engine._scheduled_steps["train_step"].cache_size == 0
        off = engine._offload
        flat = jax.tree_util.tree_leaves(engine.state.master_params)
        for slot, i in enumerate(off.off_idx):
            dev = np.asarray(flat[i], dtype=np.float32)
            np.testing.assert_array_equal(
                dev, off._mirror[slot].reshape(dev.shape))
        # and training continues without divergence. The post-restore
        # corruption guard (lifecycle.verify_steps_after_restore,
        # offload.verify_and_repair) is armed for these steps: on the
        # long-process heaps where the device copy of a leaf came back
        # poisoned (the NaN variant of the old abort), it re-uploads
        # the host master and training stays finite.
        b = {"input_ids": ids, "labels": ids}
        losses = [float(engine.train_batch(batch=b)) for _ in range(3)]
        assert np.isfinite(losses).all(), (
            losses, engine.get_offload_breakdown())

    def test_bad_dtypes_rejected(self, eight_devices):
        from deepspeed_tpu.parallel.mesh import mesh_manager
        for key, val in (("grad_dtype", "fp8"),
                         ("upload_dtype", "int4")):
            mesh_manager.reset()
            model = GPT2LMHeadModel(GPT2Config.tiny())
            cfg = self._cfg(**{key: val})
            with pytest.raises(ValueError, match=key):
                eng, _, _, _ = deepspeed_tpu.initialize(model=model,
                                                        config=cfg)
                ids = np.zeros((eng.train_batch_size(), 16), np.int32)
                eng.init_params({"input_ids": ids, "labels": ids})
