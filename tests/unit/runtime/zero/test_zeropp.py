"""ZeRO++ end-to-end: qwZ/qgZ consumed by the compiled train step.

Reference: deepspeed/runtime/zero/partition_parameters.py:989 (quantized
weight all-gather), runtime/comm/coalesced_collectives.py (qgZ quantized
reduce), docs/_tutorials/zeropp.md (qwZ halves all-gather volume; qgZ
int8 all-to-all gradient reduction).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


def _train(stage, mesh_cfg, steps=6, **zero_extra):
    mesh_manager.reset()
    mesh_manager.init(mesh_cfg)
    cfg = GPT2Config.tiny()
    model = GPT2LMHeadModel(cfg)
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage, **zero_extra},
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    gb = engine.train_batch_size()
    rng = np.random.default_rng(0)
    # fixed batch: overfitting gives a strong, comparable loss trajectory
    ids = rng.integers(0, cfg.vocab_size, size=(gb, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch))
              for _ in range(steps)]
    return engine, losses


def _lowered_text(engine):
    """Optimized (post-SPMD-partitioning) HLO of the compiled train step
    — the text where collective ops and their payload dtypes appear."""
    import jax
    gb = engine.train_batch_size()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 256, size=(gb, 16), dtype=np.int32)
    b = engine._split_microbatches({"input_ids": ids, "labels": ids})
    b = engine._shard_batch(b, leading_gas=True)
    return engine._jit_train_step.lower(
        engine.state, b, jax.random.PRNGKey(0)).compile().as_text()


class TestZeroPlusPlus:

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_qgz_loss_parity_stage2(self, eight_devices):
        """dp2 x fsdp4 ZeRO-2: int8 grad reduce-scatter tracks the
        uncompressed run within int8 tolerance, loss still falls."""
        mesh = MeshConfig(data=2, fsdp=4)
        _, base = _train(2, mesh)
        _, qgz = _train(2, mesh, zero_quantized_gradients=True)
        assert qgz[-1] < qgz[0], qgz          # still learning
        for a, b in zip(base, qgz):
            assert abs(a - b) / abs(a) < 0.05, (base, qgz)

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_qwz_loss_parity_stage3(self, eight_devices):
        """fsdp8 ZeRO-3: int8 param all-gather tracks the uncompressed
        run within int8 tolerance."""
        mesh = MeshConfig(data=1, fsdp=8)
        _, base = _train(3, mesh, stage3_param_persistence_threshold=0)
        _, qwz = _train(3, mesh, zero_quantized_weights=True,
                        stage3_param_persistence_threshold=0)
        assert qwz[-1] < qwz[0], qwz
        for a, b in zip(base, qwz):
            assert abs(a - b) / abs(a) < 0.05, (base, qwz)

    @pytest.mark.slow  # tier-1 diet (ISSUE 7): heaviest zeropp wire; cheaper qwz/qgz tests stay
    def test_qwz_qgz_compose(self, eight_devices):
        """qwZ (stage 3) is ignored-with-warning at stage 2 and qgZ at
        stage 3 — but each works in its regime; stage-2 run with both
        knobs on still trains (qgZ active, qwZ warned off)."""
        mesh = MeshConfig(data=2, fsdp=4)
        _, losses = _train(2, mesh, zero_quantized_gradients=True,
                           zero_quantized_weights=True)
        assert losses[-1] < losses[0]

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_qwz_changes_collective_payload_in_hlo(self, eight_devices):
        """The compiled HLO must actually move int8 over the wire for
        the param gather when qwZ is on, and no s8 collectives when
        off (the byte-volume assertion from the reference's 'qwZ halves
        all-gather volume' claim)."""
        mesh = MeshConfig(data=1, fsdp=8)
        eng_off, _ = _train(3, mesh, steps=1,
                            stage3_param_persistence_threshold=0)
        eng_on, _ = _train(3, mesh, steps=1, zero_quantized_weights=True,
                           stage3_param_persistence_threshold=0)
        txt_off = _lowered_text(eng_off)
        txt_on = _lowered_text(eng_on)

        def s8_collectives(txt):
            return [l for l in txt.splitlines()
                    if ("all-gather" in l or "all_gather" in l)
                    and "s8[" in l]

        assert s8_collectives(txt_on), "qwZ HLO has no int8 all-gather"
        assert not s8_collectives(txt_off)

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_qgz_changes_collective_payload_in_hlo(self, eight_devices):
        mesh = MeshConfig(data=2, fsdp=4)
        eng_off, _ = _train(2, mesh, steps=1)
        eng_on, _ = _train(2, mesh, steps=1,
                           zero_quantized_gradients=True)
        txt_off = _lowered_text(eng_off)
        txt_on = _lowered_text(eng_on)

        def s8_a2a(txt):
            return [l for l in txt.splitlines()
                    if ("all-to-all" in l or "all_to_all" in l)
                    and "s8[" in l]

        assert s8_a2a(txt_on), "qgZ HLO has no int8 all-to-all"
        assert not s8_a2a(txt_off)
