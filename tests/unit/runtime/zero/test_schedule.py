"""Latency-hiding schedule layer (runtime/zero/schedule.py): the XLA
options translator, the compiled-step cache, the layer-scan step's
numerics contract, the schedule report, and the [compat] knob audit.

Numerics contract asserted here (see schedule.py module docstring):
the model decomposition (embed/layer/head) and the prefetch ring are
BIT-EXACT; the one tolerated difference vs the flat step is XLA's
``lax.scan`` loop transpose, which reassociates backward-reduction
fusion at the float32-ulp level — the flat-vs-scan trajectory test
bounds it tightly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.schedule import (ScheduledStep,
                                                 build_layer_scan_loss,
                                                 compile_with_options,
                                                 derive_prefetch_depth,
                                                 xla_compiler_options)
from deepspeed_tpu.utils.tree import named_leaves


def _zc(d=None):
    return DeepSpeedZeroConfig.from_dict(dict({"stage": 3}, **(d or {})))


def _llama_batches(cfg, n, global_bs, seq=16, seed=0):
    r = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = r.integers(0, cfg.vocab_size, size=(global_bs, seq),
                         dtype=np.int32)
        out.append({"input_ids": ids, "labels": ids.copy()})
    return out


def _llama_engine(layer_schedule=None, zero_extra=None, gas=2):
    cfg = LlamaConfig.tiny()
    zo = {"stage": 3}
    if layer_schedule is not None:
        zo["layer_schedule"] = layer_schedule
    zo.update(zero_extra or {})
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=LlamaForCausalLM(cfg),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": gas,
                "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
                "zero_optimization": zo,
                "gradient_clipping": 1.0,
                "steps_per_print": 0})
    return engine, cfg


# ---------------------------------------------------------------------------
# pillar 1: the options translator
# ---------------------------------------------------------------------------

class TestOptionsTranslator:

    def test_knob_mapping_thresholds(self):
        zc = _zc({"reduce_bucket_size": 123_456,
                  "prefetch_bucket_size": 654_321})
        opts = xla_compiler_options(zc, backend="cpu")
        assert opts["xla_gpu_all_reduce_combine_threshold_bytes"] == 123_456
        assert opts["xla_gpu_reduce_scatter_combine_threshold_bytes"] == 123_456
        assert opts["xla_gpu_all_gather_combine_threshold_bytes"] == 654_321

    def test_tpu_backend_gets_overlap_flags(self):
        opts = xla_compiler_options(_zc(), backend="tpu")
        assert opts.get("xla_tpu_enable_latency_hiding_scheduler") is True
        assert "xla_tpu_all_gather_combine_threshold_bytes" in opts

    def test_overlap_comm_false_drops_overlap_flags(self):
        opts = xla_compiler_options(_zc({"overlap_comm": False}),
                                    backend="tpu")
        assert "xla_tpu_enable_latency_hiding_scheduler" not in opts
        # combiner thresholds stay — bucketing is orthogonal to overlap
        assert "xla_tpu_all_reduce_combine_threshold_bytes" in opts

    def test_translator_disabled(self):
        assert xla_compiler_options(_zc({"xla_scheduling": False})) == {}

    def test_compile_drops_unknown_options(self, eight_devices):
        lowered = jax.jit(lambda x: x * 2).lower(jnp.ones((4,)))
        compiled, applied, dropped = compile_with_options(
            lowered,
            {"xla_definitely_not_a_flag": True,
             "xla_gpu_all_gather_combine_threshold_bytes": 1 << 20},
            label="test")
        assert "xla_definitely_not_a_flag" in dropped
        assert "xla_gpu_all_gather_combine_threshold_bytes" in applied
        np.testing.assert_array_equal(
            np.asarray(compiled(jnp.ones((4,)))), 2 * np.ones((4,)))


# ---------------------------------------------------------------------------
# the compiled-step cache
# ---------------------------------------------------------------------------

class TestScheduledStep:

    def test_shape_keyed_cache(self, eight_devices):
        calls = []

        def f(x, y):
            calls.append(None)
            return x + y

        step = ScheduledStep(jax.jit(f), label="s")
        a = jnp.ones((4,))
        assert float(step(a, a)[0]) == 2.0
        assert float(step(a + 1, a)[0]) == 3.0
        assert step.cache_size == 1          # same signature reused
        b = jnp.ones((8,))
        step(b, b)
        assert step.cache_size == 2          # new shape, new executable
        rep = step.schedule_report()
        assert "collective_count" in rep

    def test_static_args_in_key(self, eight_devices):
        step = ScheduledStep(jax.jit(lambda x, n: x * n,
                                     static_argnums=(1,)),
                             label="s", static_argnums=(1,))
        a = jnp.ones((4,))
        assert float(step(a, 3)[0]) == 3.0
        assert float(step(a, 5)[0]) == 5.0   # static change recompiles
        assert step.cache_size == 2
        assert float(step(a, 3)[0]) == 3.0   # cached entry still valid
        assert step.cache_size == 2

    def test_key_extras_invalidate(self, eight_devices):
        jitted = jax.jit(lambda x: x + 1)
        s1 = ScheduledStep(jitted, label="s", key_extras=(2,))
        s2 = ScheduledStep(jitted, label="s", key_extras=(4,))
        a = jnp.ones((4,))
        k1 = s1._key((a,))
        k2 = s2._key((a,))
        assert k1 != k2                      # gas folds into the key

    def test_report_lazy_and_memoized(self, eight_devices):
        step = ScheduledStep(jax.jit(lambda x: x * 2), label="train_step")
        assert step.schedule_report() == {}   # nothing compiled yet
        step(jnp.ones((4,)))
        rep = step.schedule_report()
        assert 0.0 <= rep["overlap_estimate"] <= 1.0
        assert step.schedule_report() is rep  # memoized per program

    def test_donation_audit_reports_refused(self, eight_devices):
        """A donated arg XLA cannot alias to any output (consumed, but
        no same-shaped output) is counted with its byte size in the
        schedule report — the warn-once audit the bench decomposition
        surfaces."""

        def f(a, b):
            return (a * 2.0).sum() + b   # 'a' has no aliasable output

        step = ScheduledStep(jax.jit(f, donate_argnums=(0,)),
                             label="audit")
        step(jnp.ones((64, 32), jnp.float32), jnp.ones((8,), jnp.float32))
        rep = step.schedule_report()
        assert rep["donation_refused"]["count"] == 1
        assert rep["donation_refused"]["bytes"] == 64 * 32 * 4

    def test_donation_audit_clean_when_aliasable(self, eight_devices):
        step = ScheduledStep(jax.jit(lambda a: a + 1.0,
                                     donate_argnums=(0,)),
                             label="audit_ok")
        step(jnp.ones((16, 16), jnp.float32))
        rep = step.schedule_report()
        assert rep["donation_refused"] == {"count": 0, "bytes": 0}

    def test_donation_parse_helper(self):
        from deepspeed_tpu.runtime.zero.schedule import (
            parse_refused_donations)
        # both message dialects: the AOT path's ShapedArray(...) and
        # the eager-dispatch plain dtype[shape] list (bench r04)
        out = parse_refused_donations([
            "Some donated buffers were not usable: "
            "ShapedArray(float32[64,32]).\nSee an explanation at "
            "https://jax.readthedocs.io/faq",
            "Some donated buffers were not usable: "
            "bfloat16[16,576,32,128], bfloat16[16,576,32,128].",
        ])
        assert out["count"] == 3
        assert out["bytes"] == 64 * 32 * 4 + 2 * 2 * 16 * 576 * 32 * 128
        assert parse_refused_donations(["unrelated warning"]) == \
            {"count": 0, "bytes": 0}


# ---------------------------------------------------------------------------
# pillar 2: the layer-scan step
# ---------------------------------------------------------------------------

class TestLayerScan:

    def _setup(self, eight):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        mesh = mesh_manager.init(MeshConfig(data=1, fsdp=8))
        ids = np.random.default_rng(0).integers(
            0, cfg.vocab_size, size=(8, 16), dtype=np.int32)
        batch = {"input_ids": jnp.asarray(ids), "labels": jnp.asarray(ids)}
        params = model.init(jax.random.PRNGKey(0), ids)
        return cfg, model, mesh, batch, params

    @pytest.mark.slow  # tier-1 diet (PR 17): prefetch-ring + scan-forward bit-exact smokes stay
    def test_spec_decomposition_bit_exact(self, eight_devices):
        """The model's embed/layer/head functions, unrolled in a plain
        Python loop, reproduce the flat forward AND backward bitwise —
        the decomposition itself introduces zero numerical change."""
        cfg, model, mesh, batch, params = self._setup(eight_devices)
        spec = model.layer_scan_spec()

        def flat_loss(p):
            return model.apply(p, **batch)[0]

        def unrolled_loss(p):
            rest, layers = spec.split(p)
            x, aux = spec.embed(rest, batch, None)
            for lp in layers:
                x = spec.layer(lp, x, aux)
            return spec.head(rest, x, batch)[0]

        lf, gf = jax.jit(jax.value_and_grad(flat_loss))(params)
        lu, gu = jax.jit(jax.value_and_grad(unrolled_loss))(params)
        assert float(lf) == float(lu)
        for (n, a), (_, b) in zip(named_leaves(gf), named_leaves(gu)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=n)

    def test_prefetch_ring_bit_exact(self, eight_devices):
        """Depth-k prefetch (the software-pipelined ring) is bitwise
        identical to depth-0 (gather in-iteration): the ring's
        stack/slice/concat plumbing is value-preserving."""
        cfg, model, mesh, batch, params = self._setup(eight_devices)
        spec = model.layer_scan_spec()

        def grads_at(prefetch):
            zc = _zc({"layer_schedule": {"enabled": True,
                                         "prefetch": prefetch}})
            fn = build_layer_scan_loss(spec, mesh=mesh, zero_cfg=zc)
            return jax.jit(jax.value_and_grad(
                lambda p: fn(p, batch, None)[0]))(params)

        l0, g0 = grads_at(0)
        l1, g1 = grads_at(1)
        assert float(l0) == float(l1)
        for (n, a), (_, b) in zip(named_leaves(g0), named_leaves(g1)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                          err_msg=n)

    def test_scan_forward_loss_bit_identical_to_flat(self, eight_devices):
        cfg, model, mesh, batch, params = self._setup(eight_devices)
        fn = build_layer_scan_loss(model.layer_scan_spec(), mesh=mesh,
                                   zero_cfg=_zc({"layer_schedule":
                                                 {"enabled": True}}))
        lf = jax.jit(lambda p: model.apply(p, **batch)[0])(params)
        ls = jax.jit(lambda p: fn(p, batch, None)[0])(params)
        assert float(lf) == float(ls)

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_engine_10step_trajectories(self, rng, eight_devices):
        """Fixed-seed 10-step runs through the full engine:

        * prefetch=0 vs prefetch=1 layer-scan trajectories are BITWISE
          equal (the ring is exact — the bit-identity acceptance,
          asserted where XLA guarantees it);
        * layer-scan vs flat: first-step loss bit-equal, trajectory
          within float32 ulps (the lax.scan transpose reassociates
          backward-reduction fusion — measured ~1e-9 relative on
          grads; anything past 1e-5 would mean a real defect, not
          reassociation)."""
        cfg = LlamaConfig.tiny()
        batches = _llama_batches(cfg, 10, 16)

        def run(layer_schedule):
            mesh_manager.reset()
            engine, _ = _llama_engine(layer_schedule)
            return [float(engine.train_batch(batch=b)) for b in batches]

        flat = run(None)
        scan0 = run({"enabled": True, "prefetch": 0})
        scan1 = run({"enabled": True, "prefetch": 1})
        assert scan0 == scan1                 # ring bitwise-exact
        assert flat[0] == scan1[0]
        np.testing.assert_allclose(scan1, flat, rtol=1e-5, atol=0)
        assert all(np.isfinite(flat)) and all(np.isfinite(scan1))

    def test_custom_positions_honored(self, eight_devices):
        """batch['positions'] must reach RoPE exactly like the flat
        path (packed/shifted sequences) — regression for the embed
        recomputing arange positions unconditionally."""
        cfg, model, mesh, batch, params = self._setup(eight_devices)
        r = np.random.default_rng(1)
        batch = dict(batch, positions=jnp.asarray(
            r.integers(0, 64, size=batch["input_ids"].shape,
                       dtype=np.int32)))
        fn = build_layer_scan_loss(model.layer_scan_spec(), mesh=mesh,
                                   zero_cfg=_zc({"layer_schedule":
                                                 {"enabled": True}}))
        lf = jax.jit(lambda p: model.apply(p, **batch)[0])(params)
        ls = jax.jit(lambda p: fn(p, batch, None)[0])(params)
        assert float(lf) == float(ls)

    def test_derive_prefetch_depth(self):
        # window = max_live // per_layer - 1, clamped to [0, L-1]
        assert derive_prefetch_depth(300, 100, 8) == 2
        assert derive_prefetch_depth(100, 100, 8) == 0
        assert derive_prefetch_depth(10**9, 100, 8) == 7   # clamp high
        assert derive_prefetch_depth(0, 100, 8) == 0       # clamp low
        assert derive_prefetch_depth(300, 100, 8, override=5) == 5
        assert derive_prefetch_depth(300, 100, 8, override=-1) == 2

    def test_layer_schedule_requires_model_spec(self, eight_devices):
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        with pytest.raises(ValueError, match="layer_scan_spec"):
            deepspeed_tpu.initialize(
                model=GPT2LMHeadModel(GPT2Config.tiny()),
                config={"train_micro_batch_size_per_gpu": 1,
                        "optimizer": {"type": "Adam",
                                      "params": {"lr": 1e-3}},
                        "zero_optimization": {
                            "stage": 3,
                            "layer_schedule": {"enabled": True}},
                        "steps_per_print": 0})

    def test_bad_remat_policy_rejected(self):
        with pytest.raises(ValueError, match="remat"):
            _zc({"layer_schedule": {"enabled": True, "remat": "bogus"}})


# ---------------------------------------------------------------------------
# [compat] knob audit (satellite)
# ---------------------------------------------------------------------------

class _RecordingLogger:
    def __init__(self):
        self.warnings = []

    def warning(self, msg, *a, **kw):
        self.warnings.append(str(msg))

    def __getattr__(self, name):          # info/debug/... pass-through
        return lambda *a, **kw: None


class TestKnobAudit:

    def test_compat_field_warns_once(self, monkeypatch):
        from deepspeed_tpu.runtime import config_utils
        rec = _RecordingLogger()
        monkeypatch.setattr(config_utils, "logger", rec)
        config_utils._COMPAT_WARNED.clear()
        DeepSpeedZeroConfig.from_dict({"stage": 3,
                                       "round_robin_gradients": True})
        hits = [w for w in rec.warnings
                if "parsed but inert on TPU" in w
                and "round_robin_gradients" in w]
        assert len(hits) == 1
        # warn-ONCE: a second config with the same knob stays silent
        DeepSpeedZeroConfig.from_dict({"stage": 3,
                                       "round_robin_gradients": True})
        hits = [w for w in rec.warnings
                if "round_robin_gradients" in w]
        assert len(hits) == 1

    def test_activated_knobs_do_not_warn(self, monkeypatch):
        from deepspeed_tpu.runtime import config_utils
        rec = _RecordingLogger()
        monkeypatch.setattr(config_utils, "logger", rec)
        config_utils._COMPAT_WARNED.clear()
        DeepSpeedZeroConfig.from_dict({
            "stage": 3,
            "reduce_bucket_size": 1,
            "prefetch_bucket_size": 2,
            "overlap_comm": False,
            "max_live_parameters": 3,
        })
        assert not [w for w in rec.warnings
                    if "parsed but inert" in w]

    def test_default_values_do_not_warn(self, monkeypatch):
        from deepspeed_tpu.runtime import config_utils
        rec = _RecordingLogger()
        monkeypatch.setattr(config_utils, "logger", rec)
        config_utils._COMPAT_WARNED.clear()
        DeepSpeedZeroConfig.from_dict({"stage": 2})
        assert not [w for w in rec.warnings
                    if "parsed but inert" in w]


# ---------------------------------------------------------------------------
# CI perf smoke (satellite): translator A/B + schedule report audit
# ---------------------------------------------------------------------------

@pytest.mark.perf
class TestScheduleSmoke:

    @pytest.mark.slow  # tier-1 diet (ISSUE 7): layer-scan bit-exact + options smokes stay
    def test_zero3_translator_ab_and_report(self, rng, eight_devices):
        """Compile a tiny ZeRO-3 step with and without the options
        translator: (a) bitwise-identical losses (the options steer
        scheduling, never math), (b) the schedule report is populated
        and its all-gather bytes match the stage-3 param gather volume
        to within tolerance."""
        cfg = LlamaConfig.tiny()
        batches = _llama_batches(cfg, 2, 16)

        def run(xla_scheduling):
            mesh_manager.reset()
            engine, _ = _llama_engine(
                zero_extra={"xla_scheduling": xla_scheduling})
            losses = [float(engine.train_batch(batch=b)) for b in batches]
            return engine, losses

        engine_on, on = run(True)
        _, off = run(False)
        assert on == off                     # (a) identical outputs

        rep = engine_on.get_schedule_report()
        assert rep, "schedule report missing"
        assert rep["collective_count"] > 0
        assert rep["bytes_moved"] > 0
        assert 0.0 <= rep["overlap_estimate"] <= 1.0
        # CPU accepts the gpu-spelled combiner thresholds: the
        # translator plumbing ran end-to-end, not vacuously
        assert rep["options_applied"]

        # (b) bytes audit: at stage 3 the compute view gathers every
        # (opt-sharded) master leaf once per step program — all-gather
        # bytes ~= the full floating-param footprint in compute dtype.
        # Band is loose upward for scheduler-inserted regathers.
        param_bytes = sum(
            int(np.prod(l.shape)) * 4        # fp32 compute dtype
            for _, l in named_leaves(engine_on.state.master_params)
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype,
                                                      jnp.floating))
        ag = rep["collectives"].get("all-gather", {"bytes": 0.0})
        assert ag["bytes"] >= 0.9 * param_bytes, (ag, param_bytes)
        assert ag["bytes"] <= 4.0 * param_bytes, (ag, param_bytes)
