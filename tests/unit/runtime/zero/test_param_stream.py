"""Parameter-residency wire (runtime/zero/param_stream.py): bitwise
streamed-vs-resident training with zero extra recompiles, the
prefetch-ring overlap attribution, over-budget training + checkpoint
round-trip, the serving cold-start weight stream, seeded fault drills
on the param.fetch/param.h2d envelopes, and the open/stream/close
lifecycle (flat fd table + RSS)."""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager
from deepspeed_tpu.resilience import fault_injector
from deepspeed_tpu.resilience.errors import ParamStreamError
from deepspeed_tpu.runtime.transfer.streaming import (WireClock,
                                                      build_wire_groups)
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroOffloadParamConfig
from deepspeed_tpu.runtime.zero.param_stream import (ParamStoreSource,
                                                     ParamStreamCoordinator,
                                                     open_param_store,
                                                     residency_gauges,
                                                     save_params_to_store)
from deepspeed_tpu.utils.tree import flatten_with_names


def _config(stream=True, tier="dram", prefetch=0, bucket_mb=0.25,
            codec="none", nvme_path=None, hbm_budget_mb=0.0,
            async_io=False):
    c = {"train_micro_batch_size_per_gpu": 4,
         "gradient_accumulation_steps": 1,
         "optimizer": {"type": "AdamW",
                       "params": {"lr": 1e-3, "weight_decay": 0.01}},
         "bf16": {"enabled": True},
         "zero_optimization": {"stage": 2},
         "gradient_clipping": 1.0,
         "steps_per_print": 0}
    if stream:
        op = {"enabled": True, "tier": tier, "prefetch": prefetch,
              "bucket_mb": bucket_mb, "codec": codec,
              "hbm_budget_mb": hbm_budget_mb, "async_io": async_io}
        if nvme_path is not None:
            op["nvme_path"] = str(nvme_path)
        c["zero_optimization"]["offload_param"] = op
    return c


def _engine(config):
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    return engine


def _batch(engine, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


def _train(config, steps=3):
    engine = _engine(config)
    batch = _batch(engine)
    return engine, [float(engine.train_batch(batch=batch))
                    for _ in range(steps)]


def _toy_tree():
    import jax.numpy as jnp
    return {"embed": {"w": jnp.arange(12., dtype=jnp.float32).reshape(3, 4)},
            "layers": [{"w": jnp.ones((4, 4), jnp.float32) * (i + 1),
                        "b": jnp.arange(4., dtype=jnp.float32) * i}
                       for i in range(3)],
            "head": {"w": jnp.full((4, 3), 2.0, jnp.float32)}}


def _coordinator(tree, **over):
    names, leaves, _ = flatten_with_names(tree)
    kw = dict({"enabled": True, "tier": "dram", "prefetch": 0,
               "bucket_mb": 0.25, "codec": "none"}, **over)
    cfg = DeepSpeedZeroOffloadParamConfig.from_dict(kw)
    return ParamStreamCoordinator(names, leaves, cfg), names, leaves


def _n_fds():
    return len(os.listdir("/proc/self/fd"))


def _rss_kb():
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1])
    raise RuntimeError("no VmRSS")


# ---------------------------------------------------------------------------
# pure planning / unit pieces (no engine, free)
# ---------------------------------------------------------------------------
class TestForwardWireGroups:

    def test_forward_order_rest_leads_layers_ascend(self):
        # slots: [h.0.w, h.2.w, embed, h.1.w, head]
        layers = [0, 2, None, 1, None]
        gs = build_wire_groups(layers, per_leaf=1, forward=True)
        assert [g.label for g in gs] == ["rest", "layer0", "layer1",
                                         "layer2"]
        assert gs[0].slots == [2, 4]       # embeddings lead the forward
        assert gs[1].slots == [0]
        # backward mode unchanged: layers descend, rest trails
        bs = build_wire_groups(layers, per_leaf=1)
        assert [g.label for g in bs] == ["layer2", "layer1", "layer0",
                                         "rest"]

    def test_forward_toy_fallback_keeps_flatten_order(self):
        gs = build_wire_groups([None, None, None], per_leaf=1,
                               forward=True)
        assert [g.slots for g in gs] == [[0], [1], [2]]
        bs = build_wire_groups([None, None, None], per_leaf=1)
        assert [g.slots for g in bs] == [[2], [1], [0]]

    def test_wire_clock_split_prefix(self):
        c = WireClock()
        c.kick()
        c.t_done = c.t_kick
        c.note_wait(c.t_kick + 0.01, c.t_kick + 0.02)
        out = c.split(prefix="param_d2h")
        assert set(out) == {"param_d2h_exposed_ms",
                            "param_d2h_overlapped_ms"}
        assert out["param_d2h_exposed_ms"] > 0


class TestCoordinatorUnits:

    def test_cycle_gather_round_trip_bitwise(self):
        tree = _toy_tree()
        c, _, leaves = _coordinator(tree)
        assert [g.label for g in c.groups] == ["rest", "layer0",
                                               "layer1", "layer2"]
        mirrored = c.cycle(tree)
        # mirrors are real correct-valued arrays (checkpoint save /
        # profiling / sentinel read the state directly between steps)
        for a, b in zip(leaves, jax.tree_util.tree_leaves(mirrored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        gathered = c.gather(mirrored)
        for a, b in zip(leaves, jax.tree_util.tree_leaves(gathered)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert c.gather(gathered) is None   # already resident
        bd = c.last_breakdown
        assert set(bd) == {"param_d2h_exposed_ms",
                           "param_d2h_overlapped_ms",
                           "param_h2d_exposed_ms",
                           "param_h2d_overlapped_ms", "param_fetch_ms",
                           "param_drop_exposed_ms",
                           "param_drop_overlapped_ms"}
        c.close()

    def test_quantized_codec_skips_small_leaves(self):
        # int8 planes need >= 2 trailing axes: 0/1-d leaves (biases)
        # stay exact while matrices compress
        tree = _toy_tree()
        c, names, leaves = _coordinator(tree, codec="int8")
        mirrored = c.cycle(tree)
        flat = jax.tree_util.tree_leaves(mirrored)
        for n, a, b in zip(names, leaves, flat):
            if np.asarray(a).ndim < 2:
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
            else:
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=0.05, atol=0.05)
        c.close()

    def test_prefetch_window_bounds_inflight_bytes(self):
        tree = _toy_tree()
        c, _, _ = _coordinator(tree, prefetch=1)
        c.cycle(tree)
        kicked = [g for g in c.groups if c._gstate[g.label].kicked]
        assert len(kicked) == 1             # the window, not everything
        assert c.window_bytes == c._gstate[kicked[0].label].nbytes
        assert c.window_bytes < c.total_bytes
        c.gather(tree)                      # late groups fetch exposed
        c.close()

    def test_residency_gauges_track_the_cycle(self):
        tree = _toy_tree()
        c, _, _ = _coordinator(tree)
        g0 = residency_gauges()
        # armed non-resident: the whole window is already in flight,
        # and no host mirrors are bound until the first cycle
        assert g0["param_device_bytes"] == c.total_bytes
        assert g0["param_mirror_bytes"] == 0
        m = c.cycle(tree)
        g1 = residency_gauges()
        assert g1["param_mirror_bytes"] == c.total_bytes   # dropped
        assert g1["param_store_bytes"] > 0
        c.gather(m)
        assert residency_gauges()["param_device_bytes"] == c.total_bytes
        c.close()
        assert residency_gauges()["param_store_bytes"] == 0

    def test_manifest_round_trip_rebuilds_lists_and_dicts(self):
        tree = _toy_tree()
        store = open_param_store("dram")
        save_params_to_store(tree, store)
        src = ParamStoreSource(store)
        out = src.load_tree()
        fa, ta = jax.tree_util.tree_flatten(tree)
        fb, tb = jax.tree_util.tree_flatten(out)
        assert ta == tb                     # lists stayed lists
        for a, b in zip(fa, fb):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert src.report["cold_leaves"] == len(fa)
        src.close()


# ---------------------------------------------------------------------------
# async drop overlap (PR 18): drop-phase store writes on the IoWorker
# ---------------------------------------------------------------------------
class TestAsyncDropOverlap:

    def test_async_cycle_gather_bitwise_with_drop_overlap(self):
        tree = _toy_tree()
        c, _, leaves = _coordinator(tree, async_io=True)
        m = c.cycle(tree)
        # cycle returned with drop flushes still in flight — gather's
        # read-through serves the pending bytes identically
        g = c.gather(m)
        for a, b in zip(leaves, jax.tree_util.tree_leaves(g)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert c._store.drain(timeout=10.0)
        c.cycle(g)
        bd = c.last_breakdown
        # the overlapped half reports with a one-cycle lag: cycle 2
        # publishes cycle 1's background flush wall
        assert bd["param_drop_overlapped_ms"] > 0.0
        rep = c.report()
        assert rep["async_io"] is True
        assert rep["spill_flushed"] > 0
        assert rep["drop_backpressure"] == 0
        c.close()

    def test_async_backpressure_falls_back_to_sync_put(self):
        tree = _toy_tree()
        c, _, leaves = _coordinator(tree, async_io=True,
                                    spill_queue_mb=1e-6)
        m = c.cycle(tree)            # every leaf over the 1-byte bound
        g = c.gather(m)
        for a, b in zip(leaves, jax.tree_util.tree_leaves(g)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert c.report()["drop_backpressure"] > 0
        c.close()

    @pytest.mark.fault
    def test_async_flush_error_latches_and_raises_typed(self):
        tree = _toy_tree()
        c, _, _ = _coordinator(tree, async_io=True)
        with fault_injector.inject("store.flush:ioerror@0xinf"):
            c.cycle(tree)
            assert c._store.drain(timeout=10.0)
        # a background flush failure must not vanish on the worker:
        # the NEXT cycle surfaces it as the wire's typed error
        with pytest.raises(ParamStreamError):
            c.cycle(tree)
        c.close()


# ---------------------------------------------------------------------------
# seeded fault drills (coordinator level: milliseconds per drill)
# ---------------------------------------------------------------------------
@pytest.mark.fault
class TestFaultDrills:

    def test_fetch_transient_retries_inside_the_envelope(self):
        tree = _toy_tree()
        c, _, leaves = _coordinator(tree)
        with fault_injector.inject("param.fetch:ioerror"):
            m = c.cycle(tree)               # prefetch kicks fetch here
            assert fault_injector.fired == ["param.fetch:ioerror@0"]
        g = c.gather(m)
        for a, b in zip(leaves, jax.tree_util.tree_leaves(g)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        c.close()

    def test_fetch_persistent_raises_typed(self):
        tree = _toy_tree()
        c, _, _ = _coordinator(tree)
        with fault_injector.inject("param.fetch:ioerror@0xinf"):
            with pytest.raises(ParamStreamError, match="unfetchable"):
                c.cycle(tree)
        c.close()

    def test_h2d_transient_retries_persistent_raises(self):
        tree = _toy_tree()
        c, _, leaves = _coordinator(tree)
        with fault_injector.inject("param.h2d:ioerror"):
            m = c.cycle(tree)
        g = c.gather(m)
        for a, b in zip(leaves, jax.tree_util.tree_leaves(g)):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        with fault_injector.inject("param.h2d:ioerror@0xinf"):
            with pytest.raises(ParamStreamError, match="h2d bucket"):
                c.cycle(g)
        c.close()

    def test_missing_leaf_raises_typed_not_silent(self):
        # prefetch=1: only "rest" kicks at cycle time; punch the hole
        # AFTER the cycle (a cycle re-puts every leaf) so the gather's
        # late fetch of layer2 hits it
        tree = _toy_tree()
        c, _, _ = _coordinator(tree, prefetch=1)
        m = c.cycle(tree)
        c.store.delete(b"param/layers.2.w")
        with pytest.raises(ParamStreamError, match="unfetchable"):
            c.gather(m)
        c.close()


# ---------------------------------------------------------------------------
# lifecycle: open/stream/close soak (coordinator) + engine smoke
# ---------------------------------------------------------------------------
class TestLifecycle:

    def test_soak_20_cycles_flat_fds_and_rss(self, tmp_path):
        tree = _toy_tree()
        # warm allocator/caches once so the measured window is steady
        c, _, _ = _coordinator(tree, tier="nvme",
                               nvme_path=str(tmp_path / "warm"))
        c.gather(c.cycle(tree))
        c.close()
        fd0, rss0 = _n_fds(), _rss_kb()
        for i in range(20):
            c, _, _ = _coordinator(tree, tier="nvme",
                                   nvme_path=str(tmp_path / f"c{i}"))
            assert _n_fds() == fd0 + 1      # the held journal fd
            m = c.cycle(tree)
            c.gather(m)
            c.close()
            c.close()                       # idempotent
            assert _n_fds() == fd0, f"fd leak at cycle {i}"
        assert _rss_kb() - rss0 < 20 * 1024, "RSS grew over the soak"
        assert residency_gauges()["param_store_bytes"] == 0

    @pytest.mark.slow  # tier-1 diet: the coordinator soak above is
    # the tier-1 fd/RSS gate; every engine test also closes clean
    def test_engine_open_stream_close_smoke(self, tmp_path):
        # warm one engine first: lazily-opened process fds (compile
        # cache, plugin loads) must not count against the cycles
        engine, _ = _train(_config(tier="nvme",
                                   nvme_path=tmp_path / "warm"), steps=1)
        engine.close()
        fd0 = _n_fds()
        for i in range(3):
            engine, losses = _train(
                _config(tier="nvme", nvme_path=tmp_path / f"e{i}"),
                steps=1)
            assert np.isfinite(losses[0])
            engine.close()
            assert engine._param_stream is None
            assert _n_fds() <= fd0, f"fd leak at engine cycle {i}"


# ---------------------------------------------------------------------------
# engine-level: the acceptance contracts
# ---------------------------------------------------------------------------
class TestEngineStreaming:

    def test_streamed_bitwise_resident_single_compile_overlap(self):
        """The headline contract: streaming only changes WHERE params
        live between steps — losses are bitwise equal to the resident
        run, streaming adds ZERO compiled signatures over the resident
        baseline (the wire gathers through the canonicalizing unpack
        before the first dispatch, so every step presents the same
        shardings), and the h2d window is overlapped, not exposed."""
        e0, l0 = _train(_config(stream=False), steps=3)
        e1, l1 = _train(_config(stream=True), steps=3)
        assert l0 == l1                     # bitwise, not allclose
        s0 = e0._scheduled_steps.get("train_step")
        s1 = e1._scheduled_steps.get("train_step")
        if s0 is not None and s1 is not None:
            # both modes share the engine's one-time init->steady-state
            # warmup signature; streaming must not add any of its own
            assert s1.cache_size <= s0.cache_size
        bd = e1.get_offload_breakdown()
        assert bd["param_h2d_overlapped_ms"] > bd["param_h2d_exposed_ms"]
        rep = e1.get_schedule_report()["param_stream"]
        assert rep["enabled"] and rep["steps"] == 3
        assert rep["store_used_bytes"] == rep["total_param_bytes"]
        # the wire's gauges reach the shared memory snapshot
        from deepspeed_tpu.telemetry.hub import memory_snapshot
        assert memory_snapshot()["param_store_gb"] > 0
        e0.close()
        e1.close()

    def test_over_budget_trains_and_checkpoint_round_trips(self, tmp_path):
        """A param footprint over the (simulated) HBM budget still
        trains — loss falls — and the checkpoint round-trips through
        a fresh streamed engine bitwise. Runs on the NVMe tier, so
        this is also the tier-1 engine-level disk-store smoke."""
        cfg = _config(hbm_budget_mb=0.1, prefetch=1, tier="nvme",
                      nvme_path=tmp_path / "m0")
        e0 = _engine(cfg)
        batch = _batch(e0)
        losses = [float(e0.train_batch(batch=batch)) for _ in range(3)]
        assert losses[-1] < losses[0]
        rep = e0.get_schedule_report()["param_stream"]
        assert rep["over_budget"]
        assert rep["window_bytes"] < rep["total_param_bytes"]
        assert rep["store_disk_bytes"] == rep["total_param_bytes"]
        assert (tmp_path / "m0" / "param_store").is_dir()
        ck = tmp_path / "ckpt"
        e0.save_checkpoint(str(ck), tag="s3")
        l0 = float(e0.train_batch(batch=batch))
        # fresh engine (own store dir): one step to initialize params,
        # then restore (load_checkpoint needs an initialized state
        # tree to rebuffer); resync() reseeds the new store
        e1, _ = _train(_config(hbm_budget_mb=0.1, prefetch=1,
                               tier="nvme",
                               nvme_path=tmp_path / "m1"), steps=1)
        e1.load_checkpoint(str(ck), tag="s3")
        l1 = float(e1.train_batch(batch=batch))
        assert l0 == l1                     # restored stream, bitwise
        e0.close()
        e1.close()

    def test_streamed_losses_bitwise_async_drop(self):
        """The train-side PR 18 overlap smoke: with async_io the
        drop-phase store writes ride the IoWorker behind the next
        step's compute — losses stay bitwise, and the breakdown's
        drop split shows hidden (overlapped) wall."""
        _, ref = _train(_config(), steps=3)
        e, got = _train(_config(async_io=True), steps=3)
        assert got == ref                   # bitwise, not allclose
        bd = e.get_offload_breakdown()
        assert bd["param_drop_overlapped_ms"] > 0.0
        rep = e.get_schedule_report()["param_stream"]
        assert rep["async_io"] and rep["spill_flushed"] > 0
        e.close()

    @pytest.mark.slow
    def test_async_tier_codec_matrix_bitwise_or_sane(self, tmp_path):
        """async x tier x codec: codec none stays bitwise with the
        sync reference on both tiers; lossy codecs stay finite and
        training still converges (same bar as the sync codec A/B)."""
        _, ref = _train(_config(), steps=3)
        for i, kw in enumerate([dict(tier="dram"),
                                dict(tier="nvme"),
                                dict(tier="nvme", prefetch=1)]):
            if kw.get("tier") == "nvme":
                kw["nvme_path"] = tmp_path / f"a{i}"
            e, ls = _train(_config(async_io=True, **kw), steps=3)
            assert ls == ref, kw
            e.close()
        for codec in ("int8", "int4"):
            e, ls = _train(_config(async_io=True, codec=codec), steps=3)
            assert np.isfinite(ls).all()
            assert ls[-1] < ls[0] * 1.05, (codec, ls)
            e.close()

    @pytest.mark.fault
    @pytest.mark.slow
    def test_engine_persistent_fetch_fault_raises_typed(self):
        engine, _ = _train(_config(), steps=1)
        batch = _batch(engine)
        with fault_injector.inject("param.fetch:ioerror@0xinf"):
            with pytest.raises(ParamStreamError):
                engine.train_batch(batch=batch)
        engine.close()

    @pytest.mark.slow
    def test_nvme_tier_and_prefetch_matrix_bitwise(self, tmp_path):
        _, ref = _train(_config(stream=False), steps=3)
        for i, kw in enumerate([dict(tier="nvme"),
                                dict(prefetch=1),
                                dict(tier="nvme", prefetch=2)]):
            if "nvme" in kw.get("tier", ""):
                kw["nvme_path"] = tmp_path / f"m{i}"
            e, ls = _train(_config(**kw), steps=3)
            assert ls == ref, kw
            e.close()

    @pytest.mark.slow
    def test_codec_ab_trains_close_to_exact(self):
        _, exact = _train(_config(), steps=3)
        for codec in ("int8", "int4"):
            e, ls = _train(_config(codec=codec), steps=3)
            assert np.isfinite(ls).all()
            assert ls[-1] < ls[0] * 1.05, (codec, ls)
            # lossy but sane: first-step loss within a few percent
            assert abs(ls[0] - exact[0]) / exact[0] < 0.05, (codec, ls)
            e.close()

    @pytest.mark.slow  # tier-1 diet: the over-budget acceptance test
    # runs on the nvme tier, and the coordinator soak cycles nvme fds
    def test_nvme_smoke(self, tmp_path):
        engine, losses = _train(_config(tier="nvme",
                                        nvme_path=tmp_path), steps=2)
        assert losses[-1] < losses[0]
        store_dir = tmp_path / "param_store"
        assert store_dir.is_dir() and any(store_dir.iterdir())
        rep = engine.get_schedule_report()["param_stream"]
        assert rep["tier"] == "nvme"
        assert rep["store_disk_bytes"] == rep["total_param_bytes"]
        engine.close()


# ---------------------------------------------------------------------------
# serving cold start
# ---------------------------------------------------------------------------
class TestColdServe:

    def test_cold_started_engine_streams_bitwise(self, tmp_path):
        """Direct-params engine vs store-cold-started engine emit
        identical greedy streams (codec none = byte-exact wire)."""
        from deepspeed_tpu.inference.v2 import InferenceEngineV2
        from deepspeed_tpu.inference.v2.engine_v2 import \
            RaggedInferenceEngineConfig
        from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 8), np.int32))
        kw = dict(token_budget=32, max_ragged_sequence_count=4,
                  n_kv_blocks=16, kv_block_size=8, max_blocks_per_seq=8,
                  kv_dtype="float32")
        prompts = {1: [3, 1, 4, 1, 5], 2: [2, 7]}
        direct = InferenceEngineV2(params, cfg,
                                   RaggedInferenceEngineConfig(**kw))
        want = direct.generate_batch(prompts, max_new_tokens=6)
        direct.close()
        store = open_param_store("nvme", nvme_path=str(tmp_path))
        save_params_to_store(params, store)
        fd_held = _n_fds()
        cold = InferenceEngineV2(ParamStoreSource(store), cfg,
                                 RaggedInferenceEngineConfig(**kw))
        assert cold._param_source.report["cold_leaves"] > 0
        got = cold.generate_batch(prompts, max_new_tokens=6)
        assert got == want
        cold.close()
        assert _n_fds() < fd_held           # the journal fd is gone
        cold.close()                        # idempotent
