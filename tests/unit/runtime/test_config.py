"""Config parsing + batch reconciliation (reference:
tests/unit/runtime/test_ds_config_dict.py)."""

import json

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig


def test_basic_config():
    cfg = DeepSpeedConfig({
        "train_batch_size": 16,
        "optimizer": {"type": "Adam", "params": {"lr": 0.001}},
        "fp16": {"enabled": False},
        "zero_optimization": {"stage": 2},
        "gradient_clipping": 1.0,
    })
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.train_batch_size == 16
    assert cfg.train_micro_batch_size_per_gpu == 4
    assert cfg.gradient_accumulation_steps == 1
    assert cfg.zero_config.stage == 2
    assert cfg.optimizer_config.type == "Adam"
    assert cfg.gradient_clipping == 1.0


def test_batch_reconciliation_two_given():
    cfg = DeepSpeedConfig({"train_batch_size": 32,
                           "train_micro_batch_size_per_gpu": 2})
    cfg.resolve_batch_sizes(dp_world_size=4)
    assert cfg.gradient_accumulation_steps == 4


def test_batch_mismatch_raises():
    cfg = DeepSpeedConfig({"train_batch_size": 10,
                           "train_micro_batch_size_per_gpu": 2,
                           "gradient_accumulation_steps": 2})
    with pytest.raises(ValueError):
        cfg.resolve_batch_sizes(dp_world_size=4)


def test_fp16_bf16_conflict():
    with pytest.raises(ValueError):
        DeepSpeedConfig({"fp16": {"enabled": True}, "bf16": {"enabled": True}})


def test_zero_deprecated_alias():
    cfg = DeepSpeedConfig({"zero_optimization": {
        "stage": 3, "stage3_max_live_parameters": 123}})
    assert cfg.zero_config.max_live_parameters == 123


def test_config_from_file(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(json.dumps({"train_batch_size": 8,
                             "bf16": {"enabled": True}}))
    cfg = DeepSpeedConfig(str(p))
    assert cfg.bf16_config.enabled
    import jax.numpy as jnp
    assert cfg.precision_dtype == jnp.bfloat16


def test_duplicate_keys_rejected(tmp_path):
    p = tmp_path / "dup.json"
    p.write_text('{"train_batch_size": 8, "train_batch_size": 16}')
    with pytest.raises(ValueError):
        DeepSpeedConfig(str(p))


def test_mesh_section():
    cfg = DeepSpeedConfig({"mesh": {"data": 2, "fsdp": 4}})
    assert cfg.mesh_config.data == 2
    assert cfg.mesh_config.fsdp == 4


def test_scheduler_section():
    cfg = DeepSpeedConfig({"scheduler": {"type": "WarmupLR", "params": {
        "warmup_min_lr": 0, "warmup_max_lr": 0.001, "warmup_num_steps": 1000}}})
    assert cfg.scheduler_config.type == "WarmupLR"


def test_serving_section():
    cfg = DeepSpeedConfig({
        "serving": {
            "max_queue_depth": 64,
            "ttft_slo_ms": 350.0,
            "executable": "greedy",
            "prefix": {"enabled": False, "max_blocks": 128},
        },
    })
    sc = cfg.serving_config
    assert sc.max_queue_depth == 64
    assert sc.ttft_slo_ms == 350.0
    assert sc.executable == "greedy"
    assert sc.prefix.enabled is False
    assert sc.prefix.max_blocks == 128
    # defaults: admission overrides unset (keep the engine's), shed
    # policy on, prefix reuse on
    d = DeepSpeedConfig({}).serving_config
    assert d.max_queue_depth is None
    assert d.admission_kv_util_threshold is None
    assert d.slo_shed is True and d.prefix.enabled is True
    assert d.on_overload == "raise"


def test_serving_fleet_section():
    cfg = DeepSpeedConfig({
        "serving": {
            "fleet": {
                "n_replicas": 3,
                "policy": "round_robin",
                "affinity_weight": 2.5,
                "heartbeat_timeout_steps": 1,
                "respawn": False,
                "imbalance_alert_spread": 8,
            },
        },
    })
    fc = cfg.serving_config.fleet
    assert fc.n_replicas == 3
    assert fc.policy == "round_robin"
    assert fc.affinity_weight == 2.5
    assert fc.heartbeat_timeout_steps == 1
    assert fc.respawn is False
    assert fc.imbalance_alert_spread == 8
    # defaults: affinity policy, respawn on, bounded affinity map
    d = DeepSpeedConfig({}).serving_config.fleet
    assert d.n_replicas == 2 and d.policy == "affinity"
    assert d.respawn is True and d.affinity_map_entries > 0
    assert d.max_requeues_per_request >= 1
