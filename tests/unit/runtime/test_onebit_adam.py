"""1-bit Adam: error-feedback compressed optimizer in the engine step.

Reference: deepspeed/runtime/fp16/onebit/adam.py (warmup -> frozen
variance + compressed momentum allreduce), runtime/comm/nccl.py:52
(compressed_allreduce with error compensation), tests/onebit/.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


def _train(opt_type, steps, freeze_step=10, lr=1e-3, seed=0):
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    params = {"lr": lr}
    if opt_type == "OneBitAdam":
        params["freeze_step"] = freeze_step
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": opt_type, "params": params},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch))
              for _ in range(steps)]
    return engine, losses


class TestOnebitAdam:

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_warmup_matches_plain_adam(self, eight_devices):
        """Before freeze_step the math is standard Adam with full-
        precision averaging: trajectories must coincide."""
        _, ref = _train("Adam", steps=6)
        _, ob = _train("OneBitAdam", steps=6, freeze_step=100)
        np.testing.assert_allclose(ob, ref, rtol=1e-4)

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_convergence_parity_over_50_steps(self, eight_devices):
        """The compressed stage (error feedback, 1-bit momentum wire)
        tracks uncompressed Adam over >= 50 steps on the virtual mesh:
        same overfitting trajectory within compression tolerance."""
        _, ref = _train("Adam", steps=55)
        engine, ob = _train("OneBitAdam", steps=55, freeze_step=5)
        assert ob[-1] < ob[0] * 0.5, ob[-1]        # converged hard
        # parity = comparable convergence quality, not identical curves:
        # the sign-compressed momentum takes a different (here slightly
        # steeper) trajectory, exactly like the reference's published
        # curves track but don't overlay fp32 Adam
        assert ob[-1] <= ref[-1] * 1.3, (ref[-1], ob[-1])
        # steadily decreasing after the freeze transition
        assert ob[20] > ob[35] > ob[-1]

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_error_feedback_accumulates(self, eight_devices):
        """Past freeze_step the per-shard error buffers must be nonzero
        (compression is really happening) and differ across shards."""
        engine, _ = _train("OneBitAdam", steps=12, freeze_step=3)
        errs = [np.asarray(e) for e in
                __import__("jax").tree_util.tree_leaves(
                    engine.state.opt_state.error)
                if e.ndim > 1]
        assert any(np.abs(e).max() > 0 for e in errs)
        big = next(e for e in errs if np.abs(e).max() > 0)
        assert big.shape[0] == 8               # one slice per shard
        # shards hold different residuals (local grads differ)
        assert np.abs(big[0] - big[1]).max() > 0

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_wire_payload_is_one_bit(self, eight_devices):
        """The compiled step must move packed uint8 sign words over the
        wire (not fp32 momentum)."""
        import jax
        engine, _ = _train("OneBitAdam", steps=1, freeze_step=1)
        ids = np.zeros((engine.train_batch_size(), 16), np.int32)
        b = engine._split_microbatches({"input_ids": ids, "labels": ids})
        b = engine._shard_batch(b, leading_gas=True)
        txt = engine._jit_train_step.lower(
            engine.state, b, jax.random.PRNGKey(0)).compile().as_text()
        u8 = [l for l in txt.splitlines()
              if "all-gather" in l and "u8[" in l]
        assert u8, "no uint8 all-gather in the compiled onebit step"

    def test_guards(self, eight_devices):
        """fp16 and ZeRO>=2 are rejected with actionable errors."""
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        model = GPT2LMHeadModel(GPT2Config.tiny())
        # stage 1 is supported (chunk-sharded frozen variance,
        # test_onebit_family.py); stage 2+ still rejected
        with pytest.raises(ValueError, match="stage 0 or 1"):
            deepspeed_tpu.initialize(model=model, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "OneBitAdam", "params": {}},
                "zero_optimization": {"stage": 2}})
