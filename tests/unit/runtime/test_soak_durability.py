"""Long-run durability soaks (the ``soak`` marker, slow tier): drive
many save/restore/train and serve cycles through one process and
assert the lifecycle gauges stay BOUNDED — non-monotonic host RSS,
live-executable count, and live-array footprint. This is the
leak-detector harness ROADMAP item 5 asked for: the post-restore
XLA-CPU abort was process-lifetime growth (see runtime/lifecycle.py),
and these soaks are the regression net that keeps it dead.

Tier-1 keeps a cheap smoke (test_lifecycle.py asserts eviction fires
and gauges populate); everything here is ``soak + slow``."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime.lifecycle import LeakCheck

pytestmark = [pytest.mark.soak, pytest.mark.slow]


def _engine():
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "bf16": {"enabled": True},
           "zero_optimization": {"stage": 2, "offload_optimizer": {
               "device": "cpu", "grad_dtype": "int8",
               "upload_dtype": "int8_delta"}},
           "gradient_clipping": 1.0, "steps_per_print": 0}
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    return engine, {"input_ids": ids, "labels": ids.copy()}


def test_restore_train_cycles_bounded(tmp_path):
    """>= 20 save/restore/train cycles through ONE engine: the exact
    sequence that used to abort XLA CPU in long processes. Executable
    count, device-array footprint, and host RSS must all plateau —
    every restore drops the stale AOT programs and the recompile
    replaces (not accumulates) them."""
    engine, batch = _engine()
    for _ in range(2):                      # settle compiles
        engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path))

    lc = LeakCheck()
    for _ in range(20):
        engine.train_batch(batch=batch)
        engine.load_checkpoint(str(tmp_path))
        loss = float(engine.train_batch(batch=batch))
        assert np.isfinite(loss)
        lc.snapshot()
    lc.assert_bounded("live_executables", slack_abs=0)
    lc.assert_bounded("live_arrays", slack_abs=0)
    lc.assert_bounded("live_array_bytes", slack_abs=0)
    # RSS plateaus but jitters (allocator pools, npz temp buffers):
    # 5% + 32 MB of slack still catches the ~16 MB/cycle leak class
    lc.assert_bounded("host_rss_gb", slack_frac=0.05,
                      slack_abs=32 / 1024)
    engine.close()


def test_engine_lifecycle_cycles_bounded(tmp_path):
    """>= 20 engine build/train/close cycles: the full-suite pattern
    that accumulated ~41 dead device arrays per engine before close()
    + sweep existed. With deterministic teardown the retained set must
    stay flat."""
    lc = LeakCheck()
    for i in range(20):
        engine, batch = _engine()
        assert np.isfinite(float(engine.train_batch(batch=batch)))
        engine.close()
        del engine
        lc.snapshot()
    lc.assert_bounded("live_executables", slack_abs=0)
    lc.assert_bounded("live_arrays", slack_abs=0)
    lc.assert_bounded("host_rss_gb", slack_frac=0.05,
                      slack_abs=48 / 1024)


def test_serve_cycles_bounded():
    """>= 20 generate_batch runs on one v2 engine (lookahead mode):
    KV pools are donated through every step and the dispatch-signature
    set is bounded, so serving forever must not grow executables,
    arrays, or RSS."""
    import jax
    from deepspeed_tpu.inference.v2 import InferenceEngineV2
    from deepspeed_tpu.inference.v2.engine_v2 import \
        RaggedInferenceEngineConfig
    from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8), np.int32))
    eng = InferenceEngineV2(
        params, cfg,
        RaggedInferenceEngineConfig(token_budget=32,
                                    max_ragged_sequence_count=4,
                                    n_kv_blocks=16, kv_block_size=8,
                                    max_blocks_per_seq=8,
                                    kv_dtype="float32"))
    prompts = {10: [3, 1, 4, 1, 5], 11: [2, 7, 1], 12: [9, 9]}
    eng.generate_batch(dict(prompts), max_new_tokens=4)  # compile

    lc = LeakCheck()
    for i in range(20):
        out = eng.generate_batch(
            {uid + 100 * i: list(p) for uid, p in prompts.items()},
            max_new_tokens=4)
        assert all(len(v) == 4 for v in out.values())
        assert not eng._state_manager.tracked_sequences
        lc.snapshot()
    lc.assert_bounded("live_arrays", slack_abs=0)
    lc.assert_bounded("live_array_bytes", slack_abs=0)
    lc.assert_bounded("host_rss_gb", slack_frac=0.05,
                      slack_abs=32 / 1024)
    rep = eng.get_serving_report()
    # the recompile counter's backing set stayed bounded
    assert len(eng._seen_signatures) <= \
        eng._config.max_dispatch_signatures
    assert rep["recompiles"] == 0       # steady serving recompiles nothing
