"""Tensor-fragment API tests (reference shape:
tests/unit/runtime/zero/test_zero_tensor_fragment.py)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.utils.tensor_fragment import (
    engine_param_names, safe_get_full_fp32_param, safe_get_full_grad,
    safe_get_full_optimizer_state, safe_set_full_fp32_param,
    safe_set_full_optimizer_state)


# tier-1 diet (PR 17): stage-1 keeps the fragment API tier-1; the
# stage-3 (gathered full-param) pass rides the slow tier
@pytest.fixture(scope="module",
                params=[1, pytest.param(3, marks=pytest.mark.slow)])
def engine(request):
    model = GPT2LMHeadModel(GPT2Config.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": request.param},
        "steps_per_print": 0,
    }
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(eng.train_batch_size(), 32), dtype=np.int32)
    eng.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    return eng


def test_get_full_param_all_names(engine):
    names = engine_param_names(engine)
    assert names
    for name in names[:5]:
        v = safe_get_full_fp32_param(engine, name)
        assert v is not None and v.dtype == np.float32
    assert safe_get_full_fp32_param(engine, "no.such.param") is None


def test_set_full_param_roundtrip(engine):
    name = engine_param_names(engine)[0]
    orig = safe_get_full_fp32_param(engine, name)
    new = orig + 1.5
    assert safe_set_full_fp32_param(engine, name, new)
    got = safe_get_full_fp32_param(engine, name)
    np.testing.assert_allclose(got, new, rtol=1e-6)
    safe_set_full_fp32_param(engine, name, orig)  # restore
    with pytest.raises(ValueError):
        safe_set_full_fp32_param(engine, name, np.zeros((3,)))


def test_optimizer_state_access(engine):
    name = engine_param_names(engine)[0]
    m = safe_get_full_optimizer_state(engine, name, "exp_avg")
    v = safe_get_full_optimizer_state(engine, name, "exp_avg_sq")
    assert m is not None and v is not None
    assert m.shape == safe_get_full_fp32_param(engine, name).shape
    # after one Adam step some moment entries must be non-zero
    assert np.abs(m).sum() > 0

    new = np.zeros_like(m)
    assert safe_set_full_optimizer_state(engine, name, "exp_avg", new)
    got = safe_get_full_optimizer_state(engine, name, "exp_avg")
    np.testing.assert_allclose(got, 0.0)


def test_grad_access_on_eager_path():
    model = GPT2LMHeadModel(GPT2Config.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 0,
    }
    eng, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(eng.train_batch_size(), 32), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    eng.init_params(batch)
    name = engine_param_names(eng)[0]
    assert safe_get_full_grad(eng, name) is None  # before backward
    eng.backward(batch=batch)
    g = safe_get_full_grad(eng, name)
    assert g is not None and np.abs(g).sum() > 0
    eng.step()
