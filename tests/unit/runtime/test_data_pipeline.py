"""Data-efficiency tests (reference shape:
tests/unit/runtime/test_data_efficiency.py — curriculum schedules,
random-LTD)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.data_pipeline import (CurriculumDataSampler,
                                                 CurriculumScheduler,
                                                 RandomLTDScheduler,
                                                 random_ltd_layer,
                                                 truncate_to_difficulty)


class TestCurriculumScheduler:

    def test_fixed_linear(self):
        s = CurriculumScheduler({
            "minimum_difficulty": 8, "maximum_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(50) == 32  # 8 + 0.5*56 = 36 -> floor to 32
        assert s.get_difficulty(100) == 64
        assert s.get_difficulty(10_000) == 64

    def test_fixed_root(self):
        s = CurriculumScheduler({
            "minimum_difficulty": 8, "maximum_difficulty": 64,
            "schedule_type": "fixed_root",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8, "root_degree": 2}})
        # sqrt schedule front-loads difficulty vs linear
        assert s.get_difficulty(25) >= 32

    def test_fixed_discrete(self):
        s = CurriculumScheduler({
            "minimum_difficulty": 1, "maximum_difficulty": 3,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [1, 2, 3],
                                "max_step": [5, 10]}})
        assert s.get_difficulty(3) == 1
        assert s.get_difficulty(7) == 2
        assert s.get_difficulty(11) == 3

    def test_bad_config_raises(self):
        with pytest.raises(ValueError):
            CurriculumScheduler({"schedule_type": "fixed_linear"})
        with pytest.raises(ValueError):
            CurriculumScheduler({
                "minimum_difficulty": 1, "maximum_difficulty": 2,
                "schedule_type": "nope"})


def test_engine_curriculum_changes_seqlen():
    """The curriculum schedule changes the fed sequence length over
    steps (VERDICT done-criterion)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    model = GPT2LMHeadModel(GPT2Config.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 0,
        "curriculum_learning": {
            "enabled": True,
            "minimum_difficulty": 8,
            "maximum_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 8},
        },
    }
    rng = np.random.default_rng(0)
    data = [{"input_ids": (ids := rng.integers(0, 256, size=(32,),
                                               dtype=np.int32)),
             "labels": ids.copy()} for _ in range(64)]
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=model, config=config, training_data=data)
    assert isinstance(loader, CurriculumDataSampler)

    seen = []
    for _ in range(6):
        batch = next(engine.data_iterator)
        seen.append(batch["input_ids"].shape[1])
        engine.train_batch(batch=batch)
    assert seen[0] == 8
    assert seen[-1] == 32
    assert len(set(seen)) > 1, f"difficulty never changed: {seen}"


def test_truncate_transform():
    b = {"input_ids": np.ones((2, 16), np.int32),
         "labels": np.ones((2, 16), np.int32), "other": 3}
    out = truncate_to_difficulty(b, 4)
    assert out["input_ids"].shape == (2, 4)
    assert out["other"] == 3


class TestRandomLTD:

    def test_layer_keeps_subset_and_passthrough(self):
        B, T, C, keep = 2, 16, 4, 6
        x = jnp.asarray(np.random.default_rng(0).standard_normal((B, T, C)),
                        jnp.float32)
        marker = lambda t: t + 100.0
        out = random_ltd_layer(marker, x, keep, jax.random.PRNGKey(0))
        changed = np.isclose(np.asarray(out - x), 100.0).all(axis=-1)
        assert (changed.sum(axis=1) == keep).all()

    def test_keep_all_is_identity_wrap(self):
        x = jnp.ones((1, 4, 2))
        out = random_ltd_layer(lambda t: t * 2, x, 4, jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_scheduler_anneals(self):
        s = RandomLTDScheduler(min_value=128, max_value=512,
                               total_ltd_step=100, difficulty_step=16)
        assert s.get_current_seq(0) == 128
        assert s.get_current_seq(100) == 512
        assert s.get_current_seq(50) in range(128, 513, 16)
