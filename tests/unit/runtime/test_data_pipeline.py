"""Data-efficiency tests (reference shape:
tests/unit/runtime/test_data_efficiency.py — curriculum schedules,
random-LTD)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.data_pipeline import (CurriculumDataSampler,
                                                 CurriculumScheduler,
                                                 RandomLTDScheduler,
                                                 random_ltd_layer,
                                                 truncate_to_difficulty)


class TestCurriculumScheduler:

    def test_fixed_linear(self):
        s = CurriculumScheduler({
            "minimum_difficulty": 8, "maximum_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8}})
        assert s.get_difficulty(0) == 8
        assert s.get_difficulty(50) == 32  # 8 + 0.5*56 = 36 -> floor to 32
        assert s.get_difficulty(100) == 64
        assert s.get_difficulty(10_000) == 64

    def test_fixed_root(self):
        s = CurriculumScheduler({
            "minimum_difficulty": 8, "maximum_difficulty": 64,
            "schedule_type": "fixed_root",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8, "root_degree": 2}})
        # sqrt schedule front-loads difficulty vs linear
        assert s.get_difficulty(25) >= 32

    def test_fixed_discrete(self):
        s = CurriculumScheduler({
            "minimum_difficulty": 1, "maximum_difficulty": 3,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [1, 2, 3],
                                "max_step": [5, 10]}})
        assert s.get_difficulty(3) == 1
        assert s.get_difficulty(7) == 2
        assert s.get_difficulty(11) == 3

    def test_bad_config_raises(self):
        with pytest.raises(ValueError):
            CurriculumScheduler({"schedule_type": "fixed_linear"})
        with pytest.raises(ValueError):
            CurriculumScheduler({
                "minimum_difficulty": 1, "maximum_difficulty": 2,
                "schedule_type": "nope"})


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_engine_curriculum_changes_seqlen():
    """The curriculum schedule changes the fed sequence length over
    steps (VERDICT done-criterion)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

    model = GPT2LMHeadModel(GPT2Config.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "steps_per_print": 0,
        "curriculum_learning": {
            "enabled": True,
            "minimum_difficulty": 8,
            "maximum_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 4,
                                "difficulty_step": 8},
        },
    }
    rng = np.random.default_rng(0)
    data = [{"input_ids": (ids := rng.integers(0, 256, size=(32,),
                                               dtype=np.int32)),
             "labels": ids.copy()} for _ in range(64)]
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=model, config=config, training_data=data)
    assert isinstance(loader, CurriculumDataSampler)

    seen = []
    for _ in range(6):
        batch = next(engine.data_iterator)
        seen.append(batch["input_ids"].shape[1])
        engine.train_batch(batch=batch)
    assert seen[0] == 8
    assert seen[-1] == 32
    assert len(set(seen)) > 1, f"difficulty never changed: {seen}"


def test_truncate_transform():
    b = {"input_ids": np.ones((2, 16), np.int32),
         "labels": np.ones((2, 16), np.int32), "other": 3}
    out = truncate_to_difficulty(b, 4)
    assert out["input_ids"].shape == (2, 4)
    assert out["other"] == 3


class TestRandomLTD:

    def test_layer_keeps_subset_and_passthrough(self):
        B, T, C, keep = 2, 16, 4, 6
        x = jnp.asarray(np.random.default_rng(0).standard_normal((B, T, C)),
                        jnp.float32)
        marker = lambda t: t + 100.0
        out = random_ltd_layer(marker, x, keep, jax.random.PRNGKey(0))
        changed = np.isclose(np.asarray(out - x), 100.0).all(axis=-1)
        assert (changed.sum(axis=1) == keep).all()

    def test_keep_all_is_identity_wrap(self):
        x = jnp.ones((1, 4, 2))
        out = random_ltd_layer(lambda t: t * 2, x, 4, jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(out), 2.0)

    def test_scheduler_anneals(self):
        s = RandomLTDScheduler(min_value=128, max_value=512,
                               total_ltd_step=100, difficulty_step=16)
        assert s.get_current_seq(0) == 128
        assert s.get_current_seq(100) == 512
        assert s.get_current_seq(50) in range(128, 513, 16)


class TestProgressiveLayerDrop:
    """PLD schedule + stochastic layer skip (reference:
    runtime/progressive_layer_drop.py)."""

    def test_theta_schedule(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import (
            ProgressiveLayerDrop)
        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.001)
        assert pld.get_theta() == 1.0
        pld.update_state(0)
        assert abs(pld.get_theta() - 1.0) < 1e-9
        pld.update_state(10_000)
        assert 0.5 < pld.get_theta() < 0.51
        # deeper layers drop more
        pld.update_state(5000)
        p0 = pld.layer_keep_prob(0, 12)
        p11 = pld.layer_keep_prob(11, 12)
        assert p0 > p11

    def test_maybe_drop_layer_expectation(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import (
            maybe_drop_layer)
        x = jnp.ones((4, 8))
        layer = lambda t: t + 1.0
        # keep_prob 1 or eval: exact layer output
        np.testing.assert_allclose(
            np.asarray(maybe_drop_layer(layer, x, 1.0,
                                        jax.random.PRNGKey(0))), 2.0)
        np.testing.assert_allclose(
            np.asarray(maybe_drop_layer(layer, x, 0.3,
                                        jax.random.PRNGKey(0),
                                        train=False)), 2.0)
        # expectation over many draws ~= layer output
        outs = [np.asarray(maybe_drop_layer(layer, x, 0.7,
                                            jax.random.PRNGKey(i)))[0, 0]
                for i in range(400)]
        assert abs(np.mean(outs) - 2.0) < 0.1


def test_eigenvalue_power_iteration():
    """Top Hessian eigenvalue of a known quadratic (reference:
    runtime/eigenvalue.py role for MoQ curvature)."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
    evals = np.array([5.0, 2.0, 0.5], np.float32)
    A = jnp.diag(jnp.asarray(evals))

    def loss(x):
        return 0.5 * x @ A @ x

    x0 = jnp.asarray([1.0, 1.0, 1.0], jnp.float32)
    est = Eigenvalue(max_iter=200, tol=1e-5).compute_eigenvalue(loss, x0)
    assert abs(est - 5.0) < 1e-2

    # pytree params work too
    def loss_tree(p):
        return 0.5 * (3.0 * jnp.sum(p["a"] ** 2) + jnp.sum(p["b"] ** 2))

    est = Eigenvalue(max_iter=200, tol=1e-5).compute_eigenvalue(
        loss_tree, {"a": jnp.ones((4,)), "b": jnp.ones((2, 2))})
    assert abs(est - 3.0) < 1e-2


@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_engine_pld_config_wiring():
    """PLD config section drives an engine-held scheduler stepped each
    global step (review finding: modules existed but were unreachable
    from the config)."""
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 0},
                "steps_per_print": 0,
                "progressive_layer_drop": {"enabled": True,
                                           "theta": 0.5, "gamma": 0.1},
                "eigenvalue": {"enabled": True, "max_iter": 5}})
    assert engine.progressive_layer_drop is not None
    assert engine.eigenvalue is not None
    assert engine.get_pld_theta() == 1.0
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 16), dtype=np.int32)
    for _ in range(3):
        engine.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    assert engine.get_pld_theta() < 1.0


def test_eigenvalue_bf16_params():
    """HVP tangents must match bf16 primal dtypes (review finding)."""
    from deepspeed_tpu.runtime.eigenvalue import Eigenvalue
    def loss(p):
        return 0.5 * jnp.sum(p.astype(jnp.float32) ** 2) * 4.0
    est = Eigenvalue(max_iter=50, tol=1e-4).compute_eigenvalue(
        loss, jnp.ones((8,), jnp.bfloat16))
    assert abs(est - 4.0) < 0.1
