"""Runtime batch-size mutation (reference: engine.py:423
set_train_batch_size — gas changes, micro stays; :441
set_train_micro_batch_size — micro changes, gas stays)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel


def _engine():
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "steps_per_print": 0})
    return engine


def _batch(rng, n, seq=16):
    ids = rng.integers(0, 256, size=(n, seq), dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_set_train_batch_size_changes_gas(rng, eight_devices):
    engine = _engine()
    assert engine.train_batch_size() == 16      # 1 micro * 2 gas * 8 dp
    loss0 = float(engine.train_batch(batch=_batch(rng, 16)))

    engine.set_train_batch_size(32)             # gas 2 -> 4
    assert engine.gradient_accumulation_steps() == 4
    assert engine.train_micro_batch_size_per_gpu() == 1
    assert engine.train_batch_size() == 32
    # training continues at the new accumulation depth
    loss1 = float(engine.train_batch(batch=_batch(rng, 32)))
    assert np.isfinite(loss0) and np.isfinite(loss1)
    assert engine.global_steps == 2


def test_set_train_batch_size_divisibility(rng, eight_devices):
    engine = _engine()
    with pytest.raises(ValueError, match="divisible"):
        engine.set_train_batch_size(20)         # not divisible by 1*8


def test_set_train_batch_size_rebuilds_engine_loader(rng, eight_devices):
    """With the engine-owned dataloader (train_batch() without batch=),
    a batch-size change must rebuild the loader to the new GLOBAL size
    and keep the curriculum scheduler's runtime state."""
    class DS:
        def __init__(self):
            r = np.random.default_rng(0)
            self.ids = r.integers(0, 256, size=(128, 16), dtype=np.int32)

        def __len__(self):
            return len(self.ids)

        def __getitem__(self, i):
            return {"input_ids": self.ids[i], "labels": self.ids[i]}

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()),
        training_data=DS(),
        config={"train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "curriculum_learning": {
                    "enabled": True, "curriculum_type": "seqlen",
                    "minimum_difficulty": 4, "maximum_difficulty": 16,
                    "schedule_type": "custom", "schedule_config": {}},
                "steps_per_print": 0})
    engine.set_custom_curriculum_learning_schedule(lambda step: 8)
    float(engine.train_batch())
    steps_before = engine.curriculum_sampler.global_steps
    engine.set_train_batch_size(32)
    # the rebuilt sampler must NOT replay the schedule warm-up
    assert engine.curriculum_sampler.global_steps == steps_before
    loss = float(engine.train_batch())          # loader now yields 32
    assert np.isfinite(loss)
    # the custom schedule survived the dataloader rebuild
    assert engine.curriculum_scheduler.get_difficulty(99) == 8


@pytest.mark.slow  # tier-1 diet (ISSUE 7): micro-change reset smoke stays
def test_set_train_micro_batch_size_keeps_gas(rng, eight_devices):
    engine = _engine()
    engine.train_batch(batch=_batch(rng, 16))
    engine.set_train_micro_batch_size(2)
    assert engine.gradient_accumulation_steps() == 2
    assert engine.train_batch_size() == 32      # 2 * 2 * 8
    loss = float(engine.train_batch(batch=_batch(rng, 32)))
    assert np.isfinite(loss)


@pytest.mark.slow  # tier-1 diet (PR 17): micro_change_resets_compiled_steps pins the same all-steps reset contract
def test_gas_change_resets_all_compiled_steps(rng, eight_devices):
    """A gas change must reset EVERY compiled step together — the old
    behavior reset only _jit_train_step, leaving the gas-keyed siblings
    (and their cached executables) compiled for the old accumulation
    count (ISSUE 3 satellite)."""
    engine = _engine()
    float(engine.train_batch(batch=_batch(rng, 16)))
    engine.eval_batch(batch=_batch(rng, 8))
    assert engine._jit_train_step is not None
    assert engine._jit_eval_step is not None

    engine.set_train_batch_size(32)
    assert engine._jit_train_step is None
    assert engine._jit_eval_step is None
    assert engine._jit_grad_step is None
    assert engine._jit_apply_grads is None

    # everything rebuilds lazily and trains at the new depth
    loss = float(engine.train_batch(batch=_batch(rng, 32)))
    assert np.isfinite(loss)


def test_micro_change_resets_compiled_steps(rng, eight_devices):
    # no training here — the reset + batch math is the contract; the
    # recompile-and-train path is covered by the gas-change test above
    engine = _engine()
    engine._jit_train_step = object()       # stand-in compiled step
    engine._jit_eval_step = object()
    engine.set_train_micro_batch_size(2)
    assert engine._jit_train_step is None
    assert engine._jit_eval_step is None
    assert engine.train_batch_size() == 32
