"""Activation checkpointing API tests (reference shape:
tests/unit/runtime/activation_checkpointing/test_activation_checkpointing.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.activation_checkpointing import (
    CheckpointFunction, checkpoint, configure, is_configured, remat, reset)


@pytest.fixture(autouse=True)
def clean_config():
    reset()
    yield
    reset()


def _f(x):
    return jnp.tanh(x @ x.T).sum()


def test_checkpoint_matches_plain(rng):
    x = jnp.asarray(rng.standard_normal((16, 16)).astype(np.float32))
    assert np.allclose(float(checkpoint(_f, x)), float(_f(x)))
    g1 = jax.grad(lambda x: checkpoint(_f, x))(x)
    g2 = jax.grad(_f)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-5)


def test_configure_and_policies(rng):
    assert not is_configured()
    configure(deepspeed_config={
        "activation_checkpointing": {"partition_activations": True}})
    assert is_configured()
    x = jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))
    g = jax.grad(lambda x: checkpoint(_f, x))(x)
    np.testing.assert_allclose(np.asarray(g),
                               np.asarray(jax.grad(_f)(x)), rtol=1e-5)


def test_checkpoint_function_shim(rng):
    x = jnp.asarray(rng.standard_normal((8, 8)).astype(np.float32))
    assert np.allclose(float(CheckpointFunction.apply(_f, x)),
                       float(_f(x)))


def test_remat_decorator(rng):
    @remat
    def f(x):
        return jnp.sum(jnp.sin(x) ** 2)

    x = jnp.asarray(rng.standard_normal((32,)).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(jax.grad(f)(x)),
        np.asarray(jax.grad(lambda x: jnp.sum(jnp.sin(x) ** 2))(x)),
        rtol=1e-5)


def test_remat_reduces_saved_residuals(rng):
    """Remat's purpose: fewer saved residuals between fwd and bwd."""
    from jax._src.ad_checkpoint import saved_residuals

    def deep(x):
        for _ in range(4):
            x = jnp.tanh(x @ x.T)
        return x.sum()

    x = jnp.asarray(rng.standard_normal((32, 32)).astype(np.float32))
    plain = saved_residuals(deep, x)
    rematted = saved_residuals(jax.checkpoint(deep), x)
    assert len(rematted) < len(plain)
