"""runtime/transfer/ring.py — the shared prefetch/demotion ring (PR
18): the windowed kick state machine both the param wire and the
tiered cache drive, the kick→collect overlap clock behind every
``*_exposed_ms``/``*_overlapped_ms`` split, and the IoWorker daemon
that executes write-behind spills and prefetch staging."""

import threading

import pytest

from deepspeed_tpu.runtime.transfer.ring import (IoWorker, OverlapClock,
                                                 PrefetchRing)


class TestPrefetchRing:

    def _ring(self, labels, window=0, nbytes=None):
        kicks = []
        ring = PrefetchRing(labels, kick=kicks.append, nbytes=nbytes)
        ring.rearm(window)
        return ring, kicks

    def test_rearm_zero_kicks_everything_in_order(self):
        ring, kicks = self._ring(["a", "b", "c"])
        assert kicks == ["a", "b", "c"]
        assert all(ring.kicked(x) for x in "abc")

    def test_rearm_window_kicks_prefix_only(self):
        ring, kicks = self._ring(["a", "b", "c", "d"], window=2)
        assert kicks == ["a", "b"]
        assert not ring.kicked("c")

    def test_rearm_returns_kicked_bytes(self):
        sizes = {"a": 10, "b": 20, "c": 40}
        ring, _ = self._ring(["a", "b", "c"], window=2,
                             nbytes=sizes.__getitem__)
        assert ring.rearm(2) == 30
        assert ring.rearm(0) == 70

    def test_ensure_late_kicks_exactly_once(self):
        ring, kicks = self._ring(["a", "b", "c"], window=1)
        assert ring.ensure("b") is True      # the exposed path
        assert ring.ensure("b") is False     # already in flight
        assert ring.ensure("a") is False     # rearm kicked it
        assert kicks == ["a", "b"]

    def test_advance_releases_next_unkicked(self):
        ring, kicks = self._ring(["a", "b", "c"], window=1)
        assert ring.advance() == "b"
        assert ring.advance() == "c"
        assert ring.advance() is None        # pass exhausted
        assert kicks == ["a", "b", "c"]

    def test_advance_skips_late_kicked_items(self):
        ring, kicks = self._ring(["a", "b", "c"], window=1)
        ring.ensure("b")
        assert ring.advance() == "c"
        assert kicks == ["a", "b", "c"]

    def test_rearm_resets_the_pass(self):
        ring, kicks = self._ring(["a", "b"], window=0)
        ring.rearm(0)
        assert kicks == ["a", "b", "a", "b"]

    def test_bytes_labels_survive_the_kick_span(self):
        # cache rings use digest (bytes) labels; the ring.kick span
        # must hexlify them for the JSON trace sink, not crash
        ring, kicks = self._ring([b"\x01\x02", b"\x03\x04"])
        assert kicks == [b"\x01\x02", b"\x03\x04"]

    def test_kick_failure_propagates_and_item_stays_unkicked(self):
        def boom(label):
            raise OSError("kick died")

        ring = PrefetchRing(["a"], kick=boom)
        with pytest.raises(OSError):
            ring.rearm(0)
        assert not ring.kicked("a")          # retryable via ensure


class TestOverlapClock:

    def test_split_attributes_exposed_vs_overlapped(self):
        c = OverlapClock()
        c.mark_kick()
        t = c.t_kick
        c.note_block(t + 0.010, t + 0.020)   # 10ms blocked
        c.note_block(t + 0.030, t + 0.050)   # 20ms blocked, last=50ms
        out = c.split("param_h2d")
        assert out["param_h2d_exposed_ms"] == pytest.approx(30.0)
        assert out["param_h2d_overlapped_ms"] == pytest.approx(20.0)

    def test_zero_length_wait_is_not_recorded(self):
        c = OverlapClock()
        c.mark_kick()
        t = c.t_kick
        c.note_block(t + 0.010, t + 0.010)
        out = c.split("x")
        assert out["x_exposed_ms"] == 0.0
        assert out["x_overlapped_ms"] == pytest.approx(10.0)

    def test_mark_kick_resets_prior_window(self):
        c = OverlapClock()
        c.mark_kick()
        c.note_block(c.t_kick, c.t_kick + 1.0)
        c.mark_kick()
        out = c.split("x")
        assert out["x_exposed_ms"] == 0.0
        assert out["x_overlapped_ms"] == 0.0


class TestIoWorker:

    def test_jobs_run_fifo_and_drain_waits(self):
        w = IoWorker("t-fifo")
        got = []
        for i in range(8):
            w.submit(lambda i=i: got.append(i))
        assert w.drain(timeout=10.0)
        assert got == list(range(8))
        assert w.backlog == 0

    def test_a_raising_job_does_not_kill_the_drain_thread(self):
        w = IoWorker("t-err")
        got = []
        w.submit(lambda: (_ for _ in ()).throw(OSError("boom")))
        w.submit(lambda: got.append("alive"))
        assert w.drain(timeout=10.0)
        assert got == ["alive"] and w.errors == 1

    def test_drain_timeout_returns_false(self):
        w = IoWorker("t-slow")
        gate = threading.Event()
        w.submit(gate.wait)
        assert w.drain(timeout=0.05) is False
        assert w.backlog == 1
        gate.set()
        assert w.drain(timeout=10.0)

    def test_thread_is_lazy_and_restarts_after_death(self):
        w = IoWorker("t-lazy")
        assert w._thread is None             # nothing until a submit
        w.submit(lambda: None)
        assert w.drain(timeout=10.0)
        assert w._thread is not None and w._thread.daemon
