"""Transfer-engine tests: bucket planning invariants, the perf-marked
scheduler smoke (transfer count ≤ ceil(total_bytes/bucket)), pack →
device_get → views round trips, and the upload staging/fill pipeline —
all byte-exact by construction."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.transfer import (BucketPlan, StagingPair,
                                            TransferEngine, bucket_ranges)


def test_bucket_ranges_cover_and_are_fixed_size():
    rs = bucket_ranges(1000, 256)
    assert rs[0] == (0, 256) and rs[-1] == (768, 1000)
    assert sum(t - s for s, t in rs) == 1000
    assert all(t - s == 256 for s, t in rs[:-1])


@pytest.mark.perf
def test_bucketed_scheduler_transfer_bound():
    """Tier-1-safe CPU microbenchmark smoke: a synthetic
    many-small-leaves tree (512 x 2048 fp32 = 4 MiB) must schedule
    ≤ ceil(total_bytes/bucket) fused transfers — versus 512 per-leaf
    copies. The single-dtype bound is exact."""
    specs = [((2048,), np.float32)] * 512
    bucket = 1 << 20
    plan = BucketPlan(specs, bucket)
    total_bytes = sum(int(np.prod(s)) * np.dtype(d).itemsize
                      for s, d in specs)
    assert plan.n_transfers <= math.ceil(total_bytes / bucket)
    assert plan.n_transfers == 4  # vs 512 per-leaf dispatches


@pytest.mark.perf
def test_mixed_dtype_scheduler_bound_is_per_stream():
    """Mixed wire (int8 payload + fp32 scales): the bound is
    ceil(stream_bytes/bucket) per dtype stream, and the tiny scales
    stream is ordered FIRST so bulk buckets release leaves
    incrementally."""
    specs = []
    for _ in range(64):
        specs.append(((4, 256), np.int8))
        specs.append(((4,), np.float32))
    plan = BucketPlan(specs, 16 << 10)
    per_stream = [math.ceil(sp.nbytes / (16 << 10))
                  for sp in plan.streams]
    assert plan.n_transfers == sum(per_stream)
    assert plan.streams[0].dtype == np.float32  # smallest bytes first
    assert plan.streams[0].nbytes < plan.streams[1].nbytes


def test_plan_views_are_zero_copy_and_ordered():
    specs = [((3, 5), np.float32), ((7,), np.int8), ((2, 2), np.float32)]
    plan = BucketPlan(specs, 1 << 20)
    staging = plan.alloc_staging()
    views = plan.views(staging)
    assert [v.shape for v in views] == [(3, 5), (7,), (2, 2)]
    assert [v.dtype for v in views] == [np.float32, np.int8, np.float32]
    views[0][...] = 1.5
    views[2][...] = -2.0
    # both fp32 views alias ONE staging buffer back to back
    f32 = next(s for s in staging if s.dtype == np.float32)
    assert f32[:15].tolist() == [1.5] * 15
    assert f32[15:19].tolist() == [-2.0] * 4


def test_arrival_tracker_releases_on_last_covering_bucket():
    # one stream, 10-elem buckets; member 1 spans buckets 0-2
    specs = [((4,), np.float32), ((20,), np.float32),
             ((6,), np.float32)]
    plan = BucketPlan(specs, 10 * 4)
    (sp,) = plan.streams
    assert len(sp.buckets) == 3
    tr = plan.arrival_tracker()
    assert tr.mark(0, 0) == [0]          # member 0 complete
    assert tr.mark(0, 1) == []           # member 1 still spans bucket 2
    assert set(tr.mark(0, 2)) == {1, 2}


def test_fill_tracker_releases_bucket_when_last_member_staged():
    specs = [((4,), np.float32), ((20,), np.float32),
             ((6,), np.float32)]
    plan = BucketPlan(specs, 10 * 4)
    fl = plan.fill_tracker()
    # member 1 alone covers bucket 1 -> it releases at once; buckets 0
    # and 2 still wait on members 0 and 2 respectively
    assert fl.fill(1) == [(0, 1)]
    assert fl.fill(0) == [(0, 0)]
    assert fl.fill(2) == [(0, 2)]


def test_plan_check_rejects_layout_drift():
    plan = BucketPlan([((4,), np.float32)], 1 << 20)
    with pytest.raises(ValueError, match="mismatch"):
        plan.check([np.zeros((5,), np.float32)])
    with pytest.raises(ValueError, match="covers 1"):
        plan.check([np.zeros((4,), np.float32)] * 2)


@pytest.mark.parametrize("bucket_bytes", [64, 1 << 20])
def test_pack_device_get_roundtrip_bitexact(bucket_bytes, rng):
    """pack -> async D2H -> staging views returns the exact bytes of
    every leaf, across dtypes and bucket sizes (including buckets far
    smaller than a leaf)."""
    eng = TransferEngine(bucket_bytes=bucket_bytes)
    arrays = [
        jnp.asarray(rng.normal(size=(33, 7)).astype(np.float32)),
        jnp.asarray(rng.integers(-128, 127, size=(40, 16)).astype(np.int8)),
        jnp.asarray(rng.normal(size=(257,)).astype(np.float32)),
        jnp.asarray(rng.normal(size=(5,)).astype(np.float32)
                    .astype(jnp.bfloat16)),
    ]
    plan = eng.plan(arrays)
    views = eng.device_get(plan, arrays)
    for a, v in zip(arrays, views):
        np.testing.assert_array_equal(np.asarray(a), v)


def test_pack_unpack_device_roundtrip(rng):
    """Device->device through fused buckets: pack then unpack is the
    identity on every leaf (the scatter-back used by the H2D leg)."""
    eng = TransferEngine(bucket_bytes=300)
    arrays = [jnp.asarray(rng.normal(size=s).astype(np.float32))
              for s in [(11, 3), (40,), (2, 2, 2)]]
    plan = eng.plan(arrays)
    buckets = eng.pack(plan, arrays)
    out = eng.unpack(plan, buckets)
    for a, o in zip(arrays, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(o))


def test_staging_pair_rotates_two_buffer_sets():
    pair = StagingPair("pmv", 8)
    assert pair[0] is not pair[1]
    assert pair[0] is pair[2] and pair[1] is pair[3]
    assert set(pair[0]) == {"p", "m", "v"}
    pair[0]["p"][:] = 1.0
    assert pair[1]["p"][0] != 1.0 or True  # distinct memory
    assert not np.shares_memory(pair[0]["p"], pair[1]["p"])
