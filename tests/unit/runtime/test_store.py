"""runtime/store.py — the tiered block store under the tiered prefix
cache: KV spill codecs (bitwise ``none``, approximate int8/int4), the
DRAM tier's LRU byte budget, the disk tier's write-ahead index journal
with crash-window recovery (torn tail, journal-without-payload),
integrity verification on every read, the retry/deadline I/O envelope
around the ``store.write``/``store.read`` fault sites, and close()
releasing the held journal fd."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.resilience.errors import (InjectedIOError,
                                             StoreCorruptionError)
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime.store import (KV_CODECS, DiskBlockStore,
                                         HostBlockStore, decode_kv,
                                         encode_kv)


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def _arr(seed=0, shape=(2, 2, 8, 4), dtype=np.float32):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(shape).astype(dtype)


class TestCodecs:

    def test_none_roundtrip_is_bitwise(self):
        a = _arr(1)
        payload, meta = encode_kv(a, "none")
        b = decode_kv(payload, meta)
        assert b.dtype == a.dtype and b.shape == a.shape
        assert np.array_equal(
            a.view(np.uint8), b.view(np.uint8))  # bitwise, not approx

    def test_none_roundtrip_bfloat16(self):
        """The serving KV dtype path: bfloat16 has no stdlib numpy
        name — decode resolves it through ml_dtypes."""
        import ml_dtypes
        a = _arr(2).astype(ml_dtypes.bfloat16)
        payload, meta = encode_kv(a, "none")
        b = decode_kv(payload, meta)
        assert b.dtype == a.dtype
        assert np.array_equal(a.view(np.uint8), b.view(np.uint8))

    @pytest.mark.parametrize("codec,bound", [("int8", 0.05),
                                             ("int4", 0.5)])
    def test_quantized_roundtrip_is_close(self, codec, bound):
        a = _arr(3)
        payload, meta = encode_kv(a, codec)
        assert len(payload) < a.nbytes          # it actually compresses
        b = decode_kv(payload, meta)
        err = np.abs(a - b.astype(np.float32)).max() / \
            np.abs(a).max()
        assert err < bound

    def test_int4_odd_element_count_pads(self):
        a = _arr(4, shape=(1, 3, 3)).astype(np.float32)  # 9 elements
        payload, meta = encode_kv(a, "int4")
        assert meta.get("pad") == 1
        b = decode_kv(payload, meta)
        assert b.shape == a.shape

    def test_zero_plane_stays_zero(self):
        a = np.zeros((1, 4, 4), np.float32)
        for codec in KV_CODECS:
            payload, meta = encode_kv(a, codec)
            assert np.array_equal(decode_kv(payload, meta), a)

    def test_unknown_codec_rejected(self):
        with pytest.raises(ValueError, match="unknown KV codec"):
            encode_kv(_arr(), "zstd")


class TestHostBlockStore:

    def test_roundtrip_and_lru_touch(self):
        s = HostBlockStore(1 << 20)
        s.put(b"a", b"payload-a", {"m": 1})
        s.put(b"b", b"payload-b", {"m": 2})
        assert b"a" in s and len(s) == 2
        payload, meta = s.get(b"a")           # touches a -> b is LRU
        assert payload == b"payload-a" and meta == {"m": 1}
        key, payload, _ = s.pop_lru()
        assert key == b"b" and payload == b"payload-b"

    def test_byte_budget_and_delete(self):
        s = HostBlockStore(10)
        s.put(b"a", b"x" * 8, {})
        assert not s.over_budget
        s.put(b"b", b"y" * 8, {})
        assert s.over_budget and s.used_bytes == 16
        s.delete(b"a")
        assert not s.over_budget and s.used_bytes == 8
        s.delete(b"a")                         # idempotent
        assert s.used_bytes == 8

    def test_overwrite_replaces_bytes_not_leaks(self):
        s = HostBlockStore(0)
        s.put(b"a", b"x" * 100, {})
        s.put(b"a", b"y" * 4, {})
        assert s.used_bytes == 4
        assert s.get(b"a")[0] == b"y" * 4

    def test_missing_key_raises_keyerror(self):
        with pytest.raises(KeyError):
            HostBlockStore(0).get(b"nope")

    def test_host_corruption_detected(self):
        """A flipped bit in host memory must degrade, not serve: the
        payload is verified against its put-time blake2b on get."""
        s = HostBlockStore(0)
        s.put(b"a", b"payload", {})
        payload, b2, meta = s._entries[b"a"]
        s._entries[b"a"] = (b"pAyload", b2, meta)
        with pytest.raises(StoreCorruptionError, match="checksum"):
            s.get(b"a")

    def test_close_clears(self):
        s = HostBlockStore(0)
        s.put(b"a", b"x", {})
        s.close()
        assert len(s) == 0 and s.used_bytes == 0


class TestDiskBlockStore:

    def test_roundtrip_delete_and_stats(self, tmp_path):
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"payload-1", {"shape": [2]})
        payload, meta = s.get(b"\x01")
        assert payload == b"payload-1" and meta == {"shape": [2]}
        assert s.as_dict()["entries"] == 1
        s.delete(b"\x01")
        assert b"\x01" not in s and s.used_bytes == 0
        s.close()

    def test_reopen_recovers_live_entries(self, tmp_path):
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"one", {})
        s.put(b"\x02", b"two", {})
        s.delete(b"\x01")
        s.close()
        r = DiskBlockStore(str(tmp_path))
        assert r.recovery.recovered_entries == 1
        assert r.recovery.corrupt_records == 0
        assert b"\x01" not in r                # the del replayed
        assert r.get(b"\x02")[0] == b"two"
        r.close()

    def test_torn_journal_tail_is_counted_not_fatal(self, tmp_path):
        """The journal's author may have CRASHED mid-append: a torn
        tail is the expected case, replayed tolerantly as a counted
        typed error."""
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"one", {})
        s.close()
        with open(s.index_path, "ab") as f:  # atomic-ok: test simulates a torn journal tail
            f.write(b'{"rec": "put", "k": "02", "si')
        r = DiskBlockStore(str(tmp_path))
        assert r.recovery.recovered_entries == 1
        assert r.recovery.corrupt_records == 1
        assert all(isinstance(e, StoreCorruptionError)
                   for e in r.recovery.errors)
        assert r.get(b"\x01")[0] == b"one"
        r.close()

    def test_journal_without_payload_is_dropped(self, tmp_path):
        """The crash window the write protocol leaves open BY DESIGN
        (journal first, payload second): a put record whose file never
        landed is dropped with a counted error — never served."""
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"one", {})
        s._journal_append({"rec": "put", "k": "02", "size": 3,
                           "b2": "00" * 16, "meta": {}})
        s.close()                              # crashed before payload
        r = DiskBlockStore(str(tmp_path))
        assert r.recovery.recovered_entries == 1
        assert r.recovery.dropped_entries == 1
        assert b"\x02" not in r
        r.close()

    def test_payload_size_mismatch_dropped_on_recovery(self, tmp_path):
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"payload-full", {})
        path = s._block_path(b"\x01")
        s.close()
        with open(path, "wb") as f:  # atomic-ok: test simulates a truncated payload file
            f.write(b"pay")
        r = DiskBlockStore(str(tmp_path))
        assert r.recovery.dropped_entries == 1
        assert b"\x01" not in r
        r.close()

    def test_corrupt_payload_raises_typed_error_on_get(self, tmp_path):
        """Same-size bit rot passes the recovery size check but MUST
        fail the blake2b verification on read."""
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"payload-full", {})
        with open(s._block_path(b"\x01"), "wb") as f:  # atomic-ok: test plants same-size corruption
            f.write(b"pAyload-full")
        with pytest.raises(StoreCorruptionError, match="integrity"):
            s.get(b"\x01")
        s.close()

    def test_budget_and_pop_lru(self, tmp_path):
        s = DiskBlockStore(str(tmp_path), max_bytes=10)
        s.put(b"\x01", b"x" * 8, {})
        s.put(b"\x02", b"y" * 8, {})
        assert s.over_budget
        key, payload, _ = s.pop_lru()
        assert key == b"\x01" and payload == b"x" * 8
        assert not s.over_budget
        s.close()

    def test_close_is_idempotent_and_fences_writes(self, tmp_path):
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\x01", b"one", {})
        assert not s.closed
        s.close()
        s.close()                              # idempotent
        assert s.closed
        with pytest.raises(StoreCorruptionError, match="closed"):
            s.put(b"\x02", b"two", {})

    def test_close_releases_the_journal_fd(self, tmp_path):
        n0 = len(os.listdir("/proc/self/fd"))
        s = DiskBlockStore(str(tmp_path))
        assert len(os.listdir("/proc/self/fd")) == n0 + 1
        s.put(b"\x01", b"one", {})
        s.close()
        assert len(os.listdir("/proc/self/fd")) == n0

    def test_journal_records_are_one_json_per_line(self, tmp_path):
        s = DiskBlockStore(str(tmp_path), fsync_every=1)
        s.put(b"\x01", b"one", {"codec": "none"})
        s.delete(b"\x01")
        s.close()
        with open(s.index_path) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        assert [r["rec"] for r in recs] == ["put", "del"]
        assert recs[0]["size"] == 3 and recs[0]["meta"] == \
            {"codec": "none"}

    def test_journal_compacts_past_dead_record_threshold(
            self, tmp_path):
        """Churn (put/del pairs) grows an append-only journal without
        bound and slows every future ``recover()`` replay; once dead
        records dominate, the journal is atomically rewritten as live
        entries only — and the compacted journal replays identically."""
        s = DiskBlockStore(str(tmp_path))
        s.put(b"\xaa", b"keeper", {"m": 1})
        n_appends = 1
        for i in range(DiskBlockStore.COMPACT_MIN_RECORDS + 100):
            s.put(b"\x01", b"x" * 8, {})
            s.delete(b"\x01")
            n_appends += 2
        assert s.compactions >= 1
        s.close()
        with open(s.index_path) as f:
            n_lines = sum(1 for line in f if line.strip())
        assert n_lines < n_appends // 2     # bounded by churn, not ops
        r = DiskBlockStore(str(tmp_path))
        assert r.recovery.corrupt_records == 0
        assert r.recovery.recovered_entries == 1
        assert r.get(b"\xaa")[0] == b"keeper" and b"\x01" not in r
        r.close()


@pytest.mark.fault
class TestIoEnvelope:

    def test_transient_ioerror_is_retried(self, tmp_path):
        """One injected I/O error inside the retry budget: the write
        succeeds on the re-attempt, nothing propagates."""
        s = DiskBlockStore(str(tmp_path), backoff_seconds=0.0)
        with fault_injector.inject("store.write:ioerror"):
            s.put(b"\x01", b"one", {})
        assert s.get(b"\x01")[0] == b"one"
        s.close()

    def test_retried_put_appends_one_journal_record(self, tmp_path):
        """The write-ahead record lands OUTSIDE the retry envelope:
        two failed attempts before the success must not leave three
        identical put records bloating the journal."""
        s = DiskBlockStore(str(tmp_path), backoff_seconds=0.0,
                           fsync_every=1)
        with fault_injector.inject("store.write:ioerror@0x2"):
            s.put(b"\x01", b"one", {})
        assert s.get(b"\x01")[0] == b"one"
        s.close()
        with open(s.index_path) as f:
            recs = [json.loads(line) for line in f if line.strip()]
        assert [r["rec"] for r in recs] == ["put"]

    def test_persistent_ioerror_exhausts_retries(self, tmp_path):
        s = DiskBlockStore(str(tmp_path), retries=2,
                           backoff_seconds=0.0)
        with fault_injector.inject("store.write:ioerror@0xinf"):
            with pytest.raises(InjectedIOError):
                s.put(b"\x01", b"one", {})
        # the failed put left no entry (journal-first is recover-safe,
        # the in-memory index only commits after both writes)
        assert b"\x01" not in s
        s.close()

    def test_deadline_exhaustion_is_typed_non_retryable(self, tmp_path):
        """A wall-clock deadline crossing between attempts surfaces as
        StoreCorruptionError — NOT an OSError, so the retry loop stops
        instead of spinning on a dead tier."""
        s = DiskBlockStore(str(tmp_path), retries=50,
                           backoff_seconds=0.05,
                           deadline_seconds=0.01)
        with fault_injector.inject("store.read:ioerror@0xinf"):
            s.put(b"\x01", b"one", {})  # write path unaffacted by spec
            with pytest.raises(StoreCorruptionError, match="deadline"):
                s.get(b"\x01")
        s.close()

    def test_targeted_spec_hits_only_the_named_tier(self, tmp_path):
        """The drills aim at one tier: ``store.write@disk:...`` must
        not trip the DRAM store's writes (fired with detail='dram')."""
        disk = DiskBlockStore(str(tmp_path), retries=0,
                              backoff_seconds=0.0)
        dram = HostBlockStore(0, retries=0)
        with fault_injector.inject("store.write@disk:ioerror"):
            dram.put(b"\x01", b"one", {})      # unaffected
            with pytest.raises(OSError):
                disk.put(b"\x01", b"one", {})
        assert b"\x01" in dram and b"\x01" not in disk
        disk.close()
