"""DataAnalyzer map-reduce indexing + difficulty-based curriculum
sampling (reference: data_sampling/data_analyzer.py + data_sampler.py).
"""

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline import (CurriculumScheduler,
                                                 DataAnalyzer,
                                                 DifficultyBasedSampler,
                                                 DifficultyIndex,
                                                 seqlen_metric)


def _dataset(n=64, seed=0):
    """Variable-length samples padded to 32: difficulty = token count."""
    rng = np.random.default_rng(seed)
    data = []
    for i in range(n):
        ln = int(rng.integers(4, 33))
        ids = np.zeros(32, np.int32)
        ids[:ln] = rng.integers(1, 100, ln)
        data.append({"input_ids": ids})
    return data


class TestDataAnalyzer:

    def test_map_reduce_single_worker(self, tmp_path):
        data = _dataset()
        an = DataAnalyzer(data, save_path=str(tmp_path))
        paths = an.run_map_reduce()
        idx = DifficultyIndex(paths["seqlen"])
        expect = np.asarray([seqlen_metric(s) for s in data])
        np.testing.assert_array_equal(idx.sample_to_metric, expect)
        # metric_to_sample: every sample within the max difficulty
        assert len(idx.samples_within(32)) == len(data)
        within8 = idx.samples_within(8)
        assert set(within8) == {i for i, v in enumerate(expect) if v <= 8}

    def test_map_reduce_multi_worker_matches_single(self, tmp_path):
        data = _dataset()
        for w in range(4):
            DataAnalyzer(data, num_workers=4, worker_id=w,
                         save_path=str(tmp_path / "multi")).run_map()
        paths = DataAnalyzer(data, num_workers=4,
                             save_path=str(tmp_path / "multi")).run_reduce()
        single = DataAnalyzer(data,
                              save_path=str(tmp_path / "single"))
        spaths = single.run_map_reduce()
        a = DifficultyIndex(paths["seqlen"])
        b = DifficultyIndex(spaths["seqlen"])
        np.testing.assert_array_equal(a.sample_to_metric,
                                      b.sample_to_metric)

    def test_reduce_without_map_fails_clean(self, tmp_path):
        with pytest.raises(FileNotFoundError, match="map shards"):
            DataAnalyzer(_dataset(),
                         save_path=str(tmp_path)).run_reduce()


class TestDifficultySampler:

    def test_sampler_respects_and_expands_difficulty(self, tmp_path):
        data = _dataset()
        paths = DataAnalyzer(data, save_path=str(tmp_path)).run_map_reduce()
        idx = DifficultyIndex(paths["seqlen"])
        sched = CurriculumScheduler({
            "minimum_difficulty": 8, "maximum_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 4}})
        sampler = DifficultyBasedSampler(idx, sched, batch_size=4)
        metric = idx.sample_to_metric
        it = iter(sampler)
        batch = next(it)
        assert (metric[batch] <= 8).all()
        for step in range(1, 11):
            sampler.step()
        assert sched.current_difficulty == 32
        seen = set()
        for _ in range(30):
            b = next(it)
            assert (metric[b] <= 32).all()
            seen.update(int(x) for x in b)
        # the expanded pool is actually drawn from (hard samples appear)
        assert max(metric[list(seen)]) > 8

    def test_sampler_errors_when_pool_too_small(self, tmp_path):
        data = _dataset()
        paths = DataAnalyzer(data, save_path=str(tmp_path)).run_map_reduce()
        idx = DifficultyIndex(paths["seqlen"])
        sched = CurriculumScheduler({
            "minimum_difficulty": 1, "maximum_difficulty": 32,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 10,
                                "difficulty_step": 1}})
        sampler = DifficultyBasedSampler(idx, sched, batch_size=64)
        with pytest.raises(ValueError, match="difficulty"):
            next(iter(sampler))
        # with drop_last=False an empty pool must still raise (not spin
        # yielding zero-size batches forever)
        sampler_nodrop = DifficultyBasedSampler(idx, sched, batch_size=64,
                                                drop_last=False)
        with pytest.raises(ValueError, match="raise minimum_difficulty"):
            next(iter(sampler_nodrop))
