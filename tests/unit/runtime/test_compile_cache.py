"""Persistent XLA compilation cache config (the reference's
CUDA-graph/kernel-JIT caching analog — see CompileCacheConfig)."""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime.config import CompileCacheConfig, DeepSpeedConfig


def test_config_defaults_disabled():
    cfg = DeepSpeedConfig({"train_batch_size": 8})
    assert cfg.compile_cache_config.enabled is False


def test_config_parses_section():
    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "compile_cache": {"enabled": True,
                                             "dir": "/tmp/x",
                                             "min_compile_time_secs": 0}})
    cc = cfg.compile_cache_config
    assert cc.enabled and cc.dir == "/tmp/x"
    assert cc.min_compile_time_secs == 0


@pytest.mark.slow  # tier-1 diet (PR 17): config-section smokes stay; the populate integration rides the slow tier
def test_engine_populates_cache_dir(tmp_path, rng, eight_devices):
    cache_dir = tmp_path / "xla_cache"
    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        engine, _, _, _ = deepspeed_tpu.initialize(
            model=GPT2LMHeadModel(GPT2Config.tiny()),
            config={"train_batch_size": 8,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "compile_cache": {"enabled": True,
                                      "dir": str(cache_dir),
                                      "min_compile_time_secs": 0},
                    "steps_per_print": 0})
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
        assert cache_dir.is_dir()
        ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
        engine.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
        # the compiled train step must have been persisted
        assert len(os.listdir(cache_dir)) > 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)
