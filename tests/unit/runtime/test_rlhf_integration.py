"""DeepSpeed-Chat-shaped RLHF integration: the actor loop the hybrid
engine exists for (reference: blogs/deepspeed-chat — actor generates
rollouts through the inference path, a reward scores them, the policy
updates, the NEXT rollout reflects the update; hybrid_engine.py:30).

This is the integration seam test: hybrid engine + LoRA adapters +
TP mesh + reward-weighted policy step in ONE loop. The "PPO-lite"
objective (reward-weighted log-likelihood on self-generated tokens) is
deliberately simple — the framework seams, not RL math, are under
test."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models.llama import LlamaConfig
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine


class _ActorLM:
    """Llama wrapped with a weighted-CE loss head: batches carry
    per-sequence reward weights (the PPO-lite objective)."""

    def __init__(self, cfg):
        from deepspeed_tpu.models.llama import LlamaForCausalLM
        self.inner = LlamaForCausalLM(cfg)
        self.config = cfg
        # forward the native TP rules so tp2 exercises the same
        # sharding path a real Llama actor uses (not the AutoTP
        # fallback the wrapper would otherwise trigger)
        rules = getattr(self.inner, "tensor_sharding_rules", None)
        if rules is not None:
            self.tensor_sharding_rules = rules

    def init(self, rng, input_ids, labels=None, weights=None, **kw):
        return self.inner.init(rng, np.asarray(input_ids))

    def apply(self, params, input_ids, labels=None, weights=None,
              rngs=None, **kw):
        if labels is None:
            return self.inner.apply(params, input_ids, **kw)
        logits = self.inner.apply(params, input_ids, **kw)
        logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32),
                                  axis=-1)
        tgt = labels[:, 1:]
        ll = jnp.take_along_axis(logp, tgt[..., None],
                                 axis=-1)[..., 0]
        w = weights if weights is not None else \
            jnp.ones((input_ids.shape[0],), jnp.float32)
        # reward-weighted likelihood: positive reward pushes the
        # policy toward its own rollout, negative away
        return -jnp.mean(w[:, None] * ll)

    def init_cache(self, *a, **kw):
        return self.inner.init_cache(*a, **kw)


def _toy_reward(tokens: np.ndarray, target_token: int) -> np.ndarray:
    """Reward: sequences containing the target id are pushed up, the
    rest mildly down — a verifiable training signal."""
    frac = (tokens == target_token).mean(axis=1)
    return np.where(frac > 0, 1.0 + 4.0 * frac, -0.1).astype(np.float32)


@pytest.mark.parametrize("tensor", [
    1, pytest.param(2, marks=pytest.mark.slow)],  # tier-1 diet
    ids=["tp1", "tp2"])
@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_generate_score_update_loop(eight_devices, tensor):
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1, tensor=tensor))
    cfg = dataclasses.replace(LlamaConfig.tiny(), vocab_size=64)
    actor = _ActorLM(cfg)
    engine = DeepSpeedHybridEngine(
        model=actor,
        config={
            "train_micro_batch_size_per_gpu": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 5e-2}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 0,
        },
        inference_config={"dtype": "float32", "tp_size": tensor},
        lora={"r": 4, "alpha": 8.0})
    B = engine.train_batch_size()
    prompts = np.tile(np.array([[1, 2, 3]], np.int32), (B, 1))
    engine.init_params({"input_ids": prompts, "labels": prompts})

    target = 7

    def p_target():
        """Policy probability of the rewarded token after the prompt —
        measured through the INFERENCE path (so it also asserts each
        rollout engine refresh saw the newest adapters)."""
        logits = np.asarray(engine.infer_forward(prompts[:1]),
                            np.float32)[0, -1]
        return float(jax.nn.softmax(jnp.asarray(logits))[target])

    p0 = p_target()
    probs = [p0]
    for it in range(8):
        # rollout through the inference path (fused LoRA weights),
        # sampled so the policy can explore
        out = engine.generate(prompts, max_new_tokens=8,
                              temperature=1.0,
                              rng=jax.random.PRNGKey(it))
        gen = np.asarray(out)[:, prompts.shape[1]:]
        rewards = _toy_reward(gen, target)
        # policy step on the rollout, reward-weighted
        batch = {"input_ids": np.asarray(out, np.int32),
                 "labels": np.asarray(out, np.int32),
                 "weights": rewards}
        engine.train_batch(batch=batch)
        probs.append(p_target())
    # the policy's probability of the rewarded token rose, and every
    # refresh exposed the newest adapters to the rollout engine (the
    # weight-sharing contract) — sampled-token fractions are too noisy
    # at this scale, the probability is the low-variance readout
    assert probs[-1] > p0 * 1.2, probs

    # only the (small) adapter tree trained — the frozen-base VALUE
    # invariant is pinned by test_hybrid_engine.py TestLora; here we
    # assert the state size shows LoRA economics
    n_adapter = sum(x.size for x in jax.tree_util.tree_leaves(
        engine.state.master_params))
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(
        engine._lora_base))
    assert n_adapter < n_base / 5
