"""Lifecycle/durability subsystem (runtime/lifecycle.py): bounded
caches, memory gauges, the checkpoint-restore executable invalidation
(the post-restore-abort regression gates), and deterministic engine
teardown. The tier-1 smokes here assert eviction fires and gauges are
populated; the ≥20-cycle leak soaks live in test_soak_durability.py."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime.lifecycle import (BoundedCache, LeakCheck,
                                             memory_gauges, registry,
                                             sweep)


def _config(extra_zero=None, lifecycle=None):
    cfg = {"train_micro_batch_size_per_gpu": 4,
           "gradient_accumulation_steps": 1,
           "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
           "bf16": {"enabled": True},
           "zero_optimization": {"stage": 2, **(extra_zero or {})},
           "gradient_clipping": 1.0,
           "steps_per_print": 0}
    if lifecycle is not None:
        cfg["lifecycle"] = lifecycle
    return cfg


def _train(config, steps=2, seed=0):
    from deepspeed_tpu.parallel.mesh import mesh_manager
    mesh_manager.reset()
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(steps)]
    return engine, batch, losses


class TestBoundedCache:

    def test_lru_eviction_at_cap(self):
        evicted = []
        c = BoundedCache("t_lru", max_entries=2,
                         on_evict=lambda k, v: evicted.append(k))
        c.put("a", 1)
        c.put("b", 2)
        assert c.get("a") == 1          # refresh: "b" is now LRU
        c.put("c", 3)
        assert evicted == ["b"]
        assert "a" in c and "c" in c and "b" not in c
        assert c.stats.evictions == 1

    def test_stats_and_invalidate(self):
        c = BoundedCache("t_stats", max_entries=4)
        c.put("x", 1)
        assert c.get("x") == 1
        assert c.get("missing") is None
        assert (c.stats.hits, c.stats.misses) == (1, 1)
        assert c.invalidate("test") == 1
        assert len(c) == 0
        assert c.stats.invalidations == 1

    def test_rejects_nonpositive_cap(self):
        with pytest.raises(ValueError, match="max_entries"):
            BoundedCache("t_bad", max_entries=0)

    def test_registered_in_registry_and_gauges(self):
        c = BoundedCache("t_registered", max_entries=3,
                         kind="executable")
        c.put("k", object())
        rep = registry.report()
        name = next(n for n in rep if n.startswith("t_registered"))
        assert rep[name]["size"] == 1
        assert rep[name]["kind"] == "executable"
        g = memory_gauges()
        assert g["live_executables"] >= 1
        assert g["host_rss_gb"] > 0
        assert g["live_arrays"] >= 0


class TestMemoryGauges:

    def test_schema(self):
        g = memory_gauges()
        for key in ("device_bytes_in_use", "device_peak_bytes",
                    "host_rss_gb", "live_executables", "live_arrays",
                    "live_array_bytes", "caches"):
            assert key in g, key
        assert isinstance(g["caches"], dict)

    def test_sweep_returns_gauges(self):
        g = sweep("unit test")
        assert g["host_rss_gb"] > 0

    def test_leakcheck_flags_monotonic_growth(self):
        lc = LeakCheck(include_arrays=False, collect=False)
        for v in (1.0, 1.0, 2.0, 3.0):
            lc.snapshots.append({"fake": v})
        with pytest.raises(AssertionError, match="unbounded growth"):
            lc.assert_bounded("fake")
        lc2 = LeakCheck(include_arrays=False, collect=False)
        for v in (3.0, 3.0, 3.0, 3.0):
            lc2.snapshots.append({"fake": v})
        lc2.assert_bounded("fake")      # flat passes

    def test_leakcheck_needs_four_snapshots(self):
        lc = LeakCheck(include_arrays=False, collect=False)
        lc.snapshots.append({"fake": 1.0})
        with pytest.raises(ValueError, match="4"):
            lc.assert_bounded("fake")


class TestEngineLifecycle:
    """The post-restore-abort regression gates (root cause: README
    "Long-run durability" / runtime/lifecycle.py docstring)."""

    @pytest.mark.slow  # tier-1 diet (ISSUE 14)
    def test_restore_invalidates_aot_executables(self, tmp_path):
        engine, batch, _ = _train(_config(), steps=3)
        engine.save_checkpoint(str(tmp_path))
        step = engine._scheduled_steps["train_step"]
        assert step.cache_size > 0
        engine.load_checkpoint(str(tmp_path))
        # every cached executable dropped: the next step compiles
        # against the freshly device_put state buffers it donates
        assert step.cache_size == 0
        loss = float(engine.train_batch(batch=batch))
        assert np.isfinite(loss)
        assert step.cache_size == 1

    @pytest.mark.slow  # tier-1 keeps the two regression gates below
    def test_restore_rebuffers_state_into_fresh_buffers(self, tmp_path):
        """Restored leaves must be XLA-owned copies, value-identical
        to what the checkpoint holds, with placement preserved — the
        other half of the post-restore-abort fix (the restore stack's
        buffers must never reach a donating step)."""
        import jax
        engine, batch, _ = _train(_config(), steps=2)
        engine.save_checkpoint(str(tmp_path))
        before = [np.asarray(x) for x in
                  jax.tree_util.tree_leaves(engine.state)
                  if isinstance(x, jax.Array)]
        shardings = [x.sharding for x in
                     jax.tree_util.tree_leaves(engine.state)
                     if isinstance(x, jax.Array)]
        engine.load_checkpoint(str(tmp_path))
        leaves = [x for x in jax.tree_util.tree_leaves(engine.state)
                  if isinstance(x, jax.Array)]
        for b, s, x in zip(before, shardings, leaves):
            np.testing.assert_array_equal(b, np.asarray(x))
            assert x.sharding.is_equivalent_to(s, x.ndim)
        assert np.isfinite(float(engine.train_batch(batch=batch)))

    @pytest.mark.slow  # escape-hatch behavior, not the regression gate
    def test_restore_invalidation_can_be_disabled(self, tmp_path):
        engine, batch, _ = _train(
            _config(lifecycle={"invalidate_on_restore": False}), steps=2)
        engine.save_checkpoint(str(tmp_path))
        step = engine._scheduled_steps["train_step"]
        n = step.cache_size
        assert n > 0
        engine.load_checkpoint(str(tmp_path))
        assert step.cache_size == n     # debugging escape hatch

    @pytest.mark.slow  # eviction firing is smoked cheaply in
    # TestBoundedCache; this one proves it on a real engine
    def test_step_executable_cache_bounded(self):
        engine, batch, _ = _train(
            _config(lifecycle={"max_step_executables": 1}), steps=2)
        step = engine._scheduled_steps["train_step"]
        assert step._cache.max_entries == 1
        # first-step vs steady-state signatures differ (the loss-scale
        # scalars change sharding after step 1), so with cap 1 the
        # steady-state compile must have EVICTED the first program
        assert step.cache_size == 1
        assert step._cache.stats.evictions >= 1
        # and the evicted signature recompiles rather than erroring
        assert np.isfinite(float(engine.train_batch(batch=batch)))

    @pytest.mark.slow  # tier-1 diet (ISSUE 7): restore-invalidation stays as the tier-1 abort-regression gate
    def test_post_restore_guard_repairs_poisoned_device_leaf(
            self, tmp_path):
        """Simulate the observed long-process failure deterministically:
        after a restore, poison one offloaded DEVICE leaf (the host
        authority stays sound) and train — the armed guard must detect
        the mirror-contract violation, re-upload the host master, and
        keep the losses finite."""
        import jax
        import jax.numpy as jnp
        engine, batch, _ = _train(
            _config(extra_zero={"offload_optimizer": {
                "device": "cpu", "grad_dtype": "int8",
                "upload_dtype": "int8_delta"}}), steps=3)
        engine.save_checkpoint(str(tmp_path))
        engine.load_checkpoint(str(tmp_path))
        assert engine._offload_verify_steps == 3
        # poison the device copy of one offloaded leaf with NaNs —
        # exactly the corruption the full-suite NaN strikes showed
        # (device copy bad BETWEEN steps; host master/mirror finite)
        off = engine._offload
        flat, treedef = jax.tree_util.tree_flatten(
            engine.state.master_params)
        i = off.off_idx[0]
        flat[i] = jnp.full_like(flat[i], jnp.nan)
        engine.state = engine.state._replace(
            master_params=jax.tree_util.tree_unflatten(treedef, flat))
        # the guard point (end of the step the corruption struck in):
        # detection + exact repair from the host master
        engine._verify_offload_if_armed()
        assert off.repairs == 1
        assert engine.get_offload_breakdown()["post_restore_repairs"] == 1
        leaf = np.asarray(
            jax.tree_util.tree_leaves(engine.state.master_params)[i],
            np.float32)
        assert np.isfinite(leaf).all()
        np.testing.assert_array_equal(
            leaf.reshape(-1),
            off._mirror[0].reshape(-1))       # mirror resynced to truth
        # training continues finite, and the guard disarms on budget
        losses = [float(engine.train_batch(batch=batch))
                  for _ in range(3)]
        assert np.isfinite(losses).all(), losses
        assert engine._offload_verify_steps == 0

    @pytest.mark.slow  # also exercised by the soak lifecycle cycles
    def test_close_releases_device_state_without_gc(self):
        import jax
        engine, _, _ = _train(_config(), steps=2)
        n_before = len(jax.live_arrays())
        engine.close()
        # close() breaks the reference cycles deterministically: the
        # state tree's buffers free by REFCOUNT, no gc.collect needed
        assert len(jax.live_arrays()) < n_before
        assert engine.state is None
        engine.close()                  # idempotent

    def test_schedule_report_carries_process_gauges(self):
        engine, _, _ = _train(_config(), steps=1)
        rep = engine.get_schedule_report()
        pm = rep["process_memory"]
        assert pm["host_rss_gb"] > 0
        assert pm["live_executables"] >= 1
        assert any(n.startswith("scheduled_step:train_step")
                   for n in pm["caches"])
