"""Small reference-API parity surface: get_batch_info, the
save_fp16_model alias, dataloader post-process hook, custom curriculum
schedule routing (reference: engine.py:407,452,456,3590)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint import load_16bit_state
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel


class _DS:
    def __init__(self, n=64, seq=16, vocab=256):
        rng = np.random.default_rng(0)
        self.ids = rng.integers(0, vocab, size=(n, seq), dtype=np.int32)

    def __len__(self):
        return len(self.ids)

    def __getitem__(self, i):
        return {"input_ids": self.ids[i], "labels": self.ids[i]}


def _engine(extra=None, training_data=None):
    cfg = {"train_batch_size": 16,
           "gradient_accumulation_steps": 2,
           "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
           "steps_per_print": 0}
    cfg.update(extra or {})
    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()), config=cfg,
        training_data=training_data)
    return engine, loader


def test_get_batch_info(eight_devices):
    engine, _ = _engine()
    assert engine.get_batch_info() == (16, 1, 2)   # 1 micro * 2 gas * 8 dp


@pytest.mark.slow  # tier-1 diet (PR 17): the exclude_frozen variant keeps save_fp16_model tier-1
def test_save_fp16_model_alias(tmp_path, rng, eight_devices):
    engine, _ = _engine()
    ids = rng.integers(0, 256, size=(16, 16), dtype=np.int32)
    engine.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    assert engine.save_fp16_model(str(tmp_path)) is True
    assert load_16bit_state(tmp_path / "model_16bit.npz")


def test_data_post_process_func_sees_batches(eight_devices):
    engine, loader = _engine(training_data=_DS())
    seen = []

    def post(batch, sampler_state):
        seen.append(dict(state=sampler_state))
        batch["labels"] = np.where(batch["labels"] == 0, 1, batch["labels"])
        return batch

    engine.set_data_post_process_func(post)
    loss = float(engine.train_batch())           # pulls from the loader
    assert np.isfinite(loss)
    # one call per global batch pulled
    assert len(seen) >= 1
    assert "epoch" in seen[0]["state"]


def test_custom_curriculum_schedule_routes(eight_devices):
    engine, _ = _engine(
        extra={"curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "minimum_difficulty": 4, "maximum_difficulty": 16,
            "schedule_type": "custom", "schedule_config": {}}},
        training_data=_DS())
    engine.set_custom_curriculum_learning_schedule(
        {"get_difficulty": lambda step: 8})
    assert engine.curriculum_scheduler.get_difficulty(123) == 8
    # bare-callable form also accepted
    engine.set_custom_curriculum_learning_schedule(lambda step: 12)
    assert engine.curriculum_scheduler.get_difficulty(0) == 12
    with pytest.raises(ValueError):
        engine.set_custom_curriculum_learning_schedule({})


def test_custom_schedule_before_dataloader_is_held(eight_devices):
    """Registering the schedule BEFORE any dataloader exists must not
    silently drop it — it applies when deepspeed_io builds the
    curriculum scheduler."""
    engine, _ = _engine(
        extra={"curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "minimum_difficulty": 4, "maximum_difficulty": 16,
            "schedule_type": "custom", "schedule_config": {}}})
    assert engine.curriculum_scheduler is None
    engine.set_custom_curriculum_learning_schedule(lambda step: 9)
    engine.training_dataloader = engine.deepspeed_io(_DS())
    assert engine.curriculum_scheduler.get_difficulty(1) == 9


@pytest.mark.slow  # tier-1 diet (PR 17): the two cheaper curriculum-hook smokes stay
def test_post_process_hook_gets_curriculum_state(eight_devices):
    """With curriculum enabled the hook must actually fire (the sampler
    wrapper delegates reads only) and receive the scheduler state."""
    engine, _ = _engine(
        extra={"curriculum_learning": {
            "enabled": True, "curriculum_type": "seqlen",
            "minimum_difficulty": 4, "maximum_difficulty": 16,
            "schedule_type": "custom", "schedule_config": {}}},
        training_data=_DS())
    engine.set_custom_curriculum_learning_schedule(lambda step: 8)
    states = []
    engine.set_data_post_process_func(
        lambda batch, state: (states.append(state), batch)[1])
    float(engine.train_batch())
    assert states, "post-process hook never fired under curriculum"
    assert "current_difficulty" in states[0]


def test_post_process_hook_before_dataloader_is_held(eight_devices):
    """A hook registered before any dataloader exists must apply when
    deepspeed_io builds one (same ordering contract as the curriculum
    schedule)."""
    engine, _ = _engine()
    seen = []
    engine.set_data_post_process_func(
        lambda batch, state: (seen.append(state), batch)[1])
    engine.training_dataloader = engine.deepspeed_io(_DS())
    for batch in engine.training_dataloader:
        break
    assert seen, "held post-process hook never installed"


def test_save_fp16_model_forwards_exclude_frozen(tmp_path, eight_devices):
    engine, _ = _engine()
    with pytest.raises(NotImplementedError):
        engine.save_fp16_model(str(tmp_path),
                               exclude_frozen_parameters=True)
