"""1-bit optimizer family: OnebitLamb, ZeroOneAdam, and stage-1
OneBitAdam.

Reference: deepspeed/runtime/fp16/onebit/lamb.py (frozen trust-ratio
EMA + factor-scaled compressed stage), zoadam.py (0/1 Adam interval
policies), tests/onebit/. The convergence-parity pattern follows
test_onebit_adam.py: trajectories track the uncompressed optimizer
rather than overlay it.
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager


def _train(opt_type, steps, params=None, stage=0, seed=0):
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    p = {"lr": 1e-3}
    p.update(params or {})
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": opt_type, "params": p},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), 16),
                       dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch))
              for _ in range(steps)]
    return engine, losses


class TestOnebitLamb:

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_warmup_matches_plain_lamb(self, eight_devices):
        """Before freeze_step the math is LAMB with full-precision
        averaging plus the coeff EMA bookkeeping: trajectories
        coincide (the EMA only feeds the compressed stage)."""
        _, ref = _train("Lamb", steps=5)
        _, ob = _train("OneBitLamb", steps=5,
                       params={"freeze_step": 100})
        # reference OnebitLamb carries no bias correction while our
        # plain LAMB does (optax.scale_by_adam) — early steps differ by
        # the correction factor, so compare the shape loosely
        assert ob[-1] < ob[0]
        assert ref[-1] < ref[0]

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_convergence_parity_compressed_stage(self, eight_devices):
        """The compressed stage (scaled momentum exchange, frozen
        trust ratio x variance-drift factor) keeps converging over 40
        steps. lr is LAMB-scale (trust ratio normalizes the update, so
        the working lr is ~100x Adam's — the reference tutorial tunes
        1-bit LAMB at comparable magnitudes)."""
        engine, ob = _train("OneBitLamb", steps=40,
                            params={"lr": 0.1, "freeze_step": 5})
        assert ob[-1] < ob[0] * 0.8, ob
        # still decreasing well inside the compressed stage
        assert ob[15] > ob[-1]
        assert min(ob[-5:]) < min(ob[:10])

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_scaling_coeff_set_at_transition(self, eight_devices):
        """scaling_coeff leaves move off their 1.0 init exactly when
        the compressed stage begins (lamb.py:171-182)."""
        import jax
        engine, _ = _train("OneBitLamb", steps=8,
                           params={"freeze_step": 4})
        sc = [float(s) for s in jax.tree_util.tree_leaves(
            engine.state.opt_state.scaling)]
        assert any(abs(s - 1.0) > 1e-6 for s in sc if s != 0.0)
        lf = [float(s) for s in jax.tree_util.tree_leaves(
            engine.state.opt_state.last_factor)]
        # factors stay inside the reference clamp band
        assert all(0.5 <= f <= 4.0 for f in lf if f != 0.0)

    @pytest.mark.slow  # tier-1 diet (ISSUE 7): onebit_adam keeps the wire-payload smoke
    def test_wire_payload_is_one_bit(self, eight_devices):
        import jax
        engine, _ = _train("OneBitLamb", steps=1,
                           params={"freeze_step": 1})
        ids = np.zeros((engine.train_batch_size(), 16), np.int32)
        b = engine._split_microbatches({"input_ids": ids, "labels": ids})
        b = engine._shard_batch(b, leading_gas=True)
        txt = engine._jit_train_step.lower(
            engine.state, b, jax.random.PRNGKey(0)).compile().as_text()
        u8 = [l for l in txt.splitlines()
              if "all-gather" in l and "u8[" in l]
        assert u8, "no uint8 all-gather in the compiled onebit-lamb step"


class TestZeroOneAdam:

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_variance_phase_tracks_adam(self, eight_devices):
        """With var_interval=1 (every step a full step) phase 1 IS
        Adam without bias correction — close trajectory, and loss
        falls."""
        _, ref = _train("Adam", steps=6)
        _, zo = _train("ZeroOneAdam", steps=6,
                       params={"var_freeze_step": 1000,
                               "var_update_scaler": 1000})
        assert zo[-1] < zo[0]
        assert zo[-1] <= ref[-1] * 1.6

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_convergence_with_intervals_and_local_steps(
            self, eight_devices):
        """Full 0/1 schedule: growing variance intervals, then frozen
        variance with local steps + interval sync — still converges.
        beta2 is matched to the test's tiny var_freeze_step: the
        algorithm (like the reference, which has no bias correction)
        assumes the variance has converged by the freeze, which at
        beta2=0.999 takes thousands of steps."""
        engine, zo = _train("ZeroOneAdam", steps=45,
                            params={"betas": [0.9, 0.9],
                                    "var_freeze_step": 20,
                                    "var_update_scaler": 4,
                                    "local_step_scaler": 8,
                                    "local_step_clipper": 4})
        # local-step phases are noisy step-to-step (synchronization
        # every k steps); judge the trend, not single points
        assert min(zo[-5:]) < zo[0] * 0.65, zo
        assert zo[10] > zo[25] > min(zo[-5:])
        st = engine.state.opt_state
        # schedules actually advanced
        assert int(st.var_interval) > 1
        assert int(st.local_interval) > 1

    @pytest.mark.slow  # tier-1 diet (ISSUE 7)
    def test_interval_state_survives_checkpoint(self, eight_devices,
                                                tmp_path):
        """var/local interval counters resume from a checkpoint — a
        restart must not reset the communication schedule."""
        engine, _ = _train("ZeroOneAdam", steps=12,
                           params={"var_freeze_step": 4,
                                   "var_update_scaler": 1,
                                   "local_step_scaler": 4,
                                   "local_step_clipper": 8})
        st = engine.state.opt_state
        engine.save_checkpoint(str(tmp_path))

        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        model = GPT2LMHeadModel(GPT2Config.tiny())
        engine2, _, _, _ = deepspeed_tpu.initialize(
            model=model, config={
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "ZeroOneAdam",
                              "params": {"lr": 1e-3,
                                         "var_freeze_step": 4,
                                         "var_update_scaler": 1,
                                         "local_step_scaler": 4,
                                         "local_step_clipper": 8}},
                "zero_optimization": {"stage": 0},
                "steps_per_print": 0})
        ids = np.zeros((engine2.train_batch_size(), 16), np.int32)
        engine2.init_params({"input_ids": ids, "labels": ids})
        engine2.load_checkpoint(str(tmp_path))
        st2 = engine2.state.opt_state
        assert int(st2.var_interval) == int(st.var_interval)
        assert int(st2.local_interval) == int(st.local_interval)
        assert int(st2.count) == int(st.count)


class TestOnebitAdamStage1:

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_stage1_matches_stage0_losses(self, eight_devices):
        """The chunked-variance layout is a storage change, not a math
        change: stage-1 OneBitAdam reproduces stage-0 losses."""
        _, s0 = _train("OneBitAdam", steps=10,
                       params={"freeze_step": 4}, stage=0)
        _, s1 = _train("OneBitAdam", steps=10,
                       params={"freeze_step": 4}, stage=1)
        np.testing.assert_allclose(s1, s0, rtol=2e-3)

    @pytest.mark.slow  # tier-1 diet (ISSUE 7)
    def test_stage1_variance_is_sharded(self, eight_devices):
        """The variance leaves store [world, chunk] rows, sharded one
        per device over the batch axes."""
        import jax
        engine, _ = _train("OneBitAdam", steps=2,
                           params={"freeze_step": 1}, stage=1)
        v_leaves = [v for v in jax.tree_util.tree_leaves(
            engine.state.opt_state.v) if v.ndim == 2 and v.shape[0] == 8]
        assert v_leaves, "no chunked variance leaves"
        v = v_leaves[0]
        # 8 shards, each device holding one row
        assert len(v.sharding.device_set) == 8
        shard = next(iter(v.addressable_shards))
        assert shard.data.shape[0] == 1

    def test_stage2_still_rejected(self, eight_devices):
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1))
        model = GPT2LMHeadModel(GPT2Config.tiny())
        with pytest.raises(ValueError, match="stage 0 or 1"):
            deepspeed_tpu.initialize(model=model, config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "OneBitAdam", "params": {}},
                "zero_optimization": {"stage": 2}})
