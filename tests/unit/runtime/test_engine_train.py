"""End-to-end engine training on the simulated mesh — the "SimpleModel"
loss-goes-down tests (reference pattern: tests/unit/simple_model.py +
tests/unit/runtime/test_ds_initialize.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel


def _batch(rng, n=16, seq=16, vocab=256):
    ids = rng.integers(0, vocab, size=(n, seq), dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


def _make_engine(config_overrides=None, **kw):
    cfg = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0,
        "steps_per_print": 0,
    }
    cfg.update(config_overrides or {})
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=cfg, **kw)
    return engine


@pytest.mark.parametrize("stage", [
    0,
    pytest.param(1, marks=pytest.mark.slow),  # tier-1 diet (ISSUE 7)
    pytest.param(2, marks=pytest.mark.slow),  # tier-1 diet (ISSUE 7)
    # tier-1 diet (PR 17): stage-3 training rides the offload/param-stream
    # engine smokes, which train stage 3 every tier-1 run
    pytest.param(3, marks=pytest.mark.slow)])
def test_train_loss_decreases(stage, rng, eight_devices):
    engine = _make_engine({"zero_optimization": {"stage": stage}})
    losses = []
    batch = _batch(rng)  # overfit one batch
    for _ in range(10):
        loss = engine.train_batch(batch=batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"
    assert engine.global_steps == 10


@pytest.mark.slow  # tier-1 diet (ISSUE 7): stage-0 loss_decreases smoke stays
def test_zero_stages_match_replicated(rng, eight_devices):
    """ZeRO sharding must not change the math: stage 0 vs stage 3 losses
    must track step-for-step (reference invariant:
    tests/unit/runtime/zero/test_zero.py loss parity)."""
    batch = _batch(rng)
    losses = {}
    for stage in (0, 3):
        from deepspeed_tpu.parallel.mesh import mesh_manager
        mesh_manager.reset()
        engine = _make_engine({"zero_optimization": {"stage": stage}},
                              rng=jax.random.PRNGKey(7))
        losses[stage] = [float(engine.train_batch(batch=batch)) for _ in range(5)]
    np.testing.assert_allclose(losses[0], losses[3], rtol=2e-3)


@pytest.mark.slow  # tier-1 diet (PR 17): bf16 is the default dtype of nearly every engine tier-1 test
def test_bf16_training(rng, eight_devices):
    engine = _make_engine({"bf16": {"enabled": True},
                           "zero_optimization": {"stage": 2}})
    batch = _batch(rng)
    l0 = float(engine.train_batch(batch=batch))
    for _ in range(8):
        l = float(engine.train_batch(batch=batch))
    assert l < l0


def test_fp16_dynamic_loss_scale(rng, eight_devices):
    engine = _make_engine({"fp16": {"enabled": True, "initial_scale_power": 8}})
    batch = _batch(rng)
    for _ in range(3):
        engine.train_batch(batch=batch)
    assert engine.loss_scale > 0


@pytest.mark.slow  # tier-1 diet (PR 17): the eager triple keeps tier-1 smokes via test_tensor_fragment's eager-path test
def test_forward_backward_step_parity(rng, eight_devices):
    """Eager triple must produce the same optimization trajectory as
    train_batch."""
    batch = _batch(rng)
    from deepspeed_tpu.parallel.mesh import mesh_manager

    engine_a = _make_engine(rng=jax.random.PRNGKey(3))
    la = [float(engine_a.train_batch(batch=batch)) for _ in range(3)]

    mesh_manager.reset()
    engine_b = _make_engine(rng=jax.random.PRNGKey(3))
    lb = []
    gas = engine_b.gradient_accumulation_steps()
    micro = {k: v.reshape(gas, -1, *v.shape[1:]) for k, v in batch.items()}
    for _ in range(3):
        step_losses = []
        for g in range(gas):
            mb = {k: v[g] for k, v in micro.items()}
            loss = engine_b.backward(batch=mb)
            step_losses.append(float(loss))
        engine_b.step()
        lb.append(sum(step_losses) / len(step_losses))
    np.testing.assert_allclose(la, lb, rtol=1e-4)


@pytest.mark.slow  # tier-1 diet (ISSUE 7): lr_schedules unit suite stays
def test_lr_schedule_integration(rng, eight_devices):
    engine = _make_engine({"scheduler": {"type": "WarmupLR", "params": {
        "warmup_min_lr": 0.0, "warmup_max_lr": 1e-3, "warmup_num_steps": 100,
        "warmup_type": "linear"}}})
    batch = _batch(rng)
    engine.train_batch(batch=batch)
    lr1 = engine.get_lr()[0]
    engine.train_batch(batch=batch)
    lr2 = engine.get_lr()[0]
    assert lr2 > lr1


def test_eval_batch(rng, eight_devices):
    engine = _make_engine()
    batch = _batch(rng)
    loss = engine.eval_batch(batch=batch)
    assert np.isfinite(float(loss))


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_checkpoint_roundtrip(tmp_path, rng, eight_devices):
    """Save/load round trip (reference: tests/unit/checkpoint/)."""
    from deepspeed_tpu.parallel.mesh import mesh_manager
    batch = _batch(rng)
    engine = _make_engine(rng=jax.random.PRNGKey(5))
    for _ in range(3):
        engine.train_batch(batch=batch)
    loss_before = float(engine.train_batch(batch=batch))
    engine.save_checkpoint(str(tmp_path), tag="t1")

    mesh_manager.reset()
    engine2 = _make_engine(rng=jax.random.PRNGKey(99))
    engine2.train_batch(batch=batch)  # init params differently
    engine2.load_checkpoint(str(tmp_path), tag="t1")
    assert engine2.global_steps == 4
    # params identical -> same next loss
    mesh_manager_loss = float(engine2.train_batch(batch=batch))
    engine_loss = float(engine.train_batch(batch=batch))
    np.testing.assert_allclose(mesh_manager_loss, engine_loss, rtol=1e-5)
