"""Hybrid engine tests (reference shape: tests/hybrid_engine/)."""

import numpy as np
import pytest

from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine


@pytest.fixture
def hybrid():
    model = LlamaForCausalLM(LlamaConfig.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    eng = DeepSpeedHybridEngine(model=model, config=config,
                                inference_config={"dtype": "float32"})
    ids = np.random.default_rng(0).integers(
        0, 256, size=(eng.train_batch_size(), 16), dtype=np.int32)
    eng.init_params({"input_ids": ids, "labels": ids.copy()})
    return eng, ids


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_generate_then_train_then_generate(hybrid):
    """The rollout -> PPO-step -> rollout loop: generate sees updated
    weights after each train step (the weight-sharing contract,
    reference hybrid_engine.py:132)."""
    eng, ids = hybrid
    prompt = np.asarray([[1, 2, 3]], np.int32)
    out1 = eng.generate(prompt, max_new_tokens=4)
    assert out1.shape == (1, 7)

    logits_before = np.asarray(eng.infer_forward(prompt))
    for _ in range(3):
        eng.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    logits_after = np.asarray(eng.infer_forward(prompt))
    assert not np.allclose(logits_before, logits_after), \
        "inference path did not pick up trained weights"

    out2 = eng.generate(prompt, max_new_tokens=4)
    assert out2.shape == (1, 7)


class TestLora:
    """LoRA fuse/unfuse parity (reference: hybrid_engine.py:132-146 +
    the DeepSpeed-Chat actor recipe)."""

    def _make(self, tensor=1, r=4):
        from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
        mesh_manager.reset()
        mesh_manager.init(MeshConfig(data=-1, tensor=tensor))
        model = LlamaForCausalLM(LlamaConfig.tiny())
        config = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 0,
        }
        eng = DeepSpeedHybridEngine(
            model=model, config=config,
            inference_config={"dtype": "float32", "tp_size": tensor},
            lora={"r": r, "alpha": 8.0})
        ids = np.random.default_rng(0).integers(
            0, 256, size=(eng.train_batch_size(), 16), dtype=np.int32)
        eng.init_params({"input_ids": ids, "labels": ids.copy()})
        return eng, ids

    @pytest.mark.slow  # tier-1 diet (ISSUE 7)
    def test_trains_adapters_only_and_rollouts_see_them(self):
        import jax
        eng, ids = self._make()
        # the training state is the (small) adapter tree, not the model
        master_names = set()
        for leaf_path, _ in jax.tree_util.tree_flatten_with_path(
                eng.state.master_params)[0]:
            master_names.add(str(leaf_path[-1]))
        assert master_names <= {".key['a']", ".key['b']",
                                "DictKey(key='a')", "DictKey(key='b')"} \
            or all(s.endswith("'a']") or s.endswith("'b']")
                   for s in master_names), master_names
        base_before = jax.tree_util.tree_leaves(eng._lora_base)
        prompt = np.asarray([[1, 2, 3]], np.int32)
        logits_before = np.asarray(eng.infer_forward(prompt))
        for _ in range(3):
            eng.train_batch(batch={"input_ids": ids,
                                   "labels": ids.copy()})
        logits_after = np.asarray(eng.infer_forward(prompt))
        assert not np.allclose(logits_before, logits_after), \
            "rollout did not see updated adapters"
        # the base tree was never written (unfuse is structural)
        base_after = jax.tree_util.tree_leaves(eng._lora_base)
        for b0, b1 in zip(base_before, base_after):
            np.testing.assert_array_equal(np.asarray(b0),
                                          np.asarray(b1))
        out = eng.generate(prompt, max_new_tokens=4)
        assert out.shape == (1, 7)

    def test_zero_init_adapters_reproduce_base_model(self):
        """b=0 at init -> the fused model IS the base model before any
        training (delta starts at exactly zero)."""
        import jax
        eng, ids = self._make()
        fused = eng.merged_params()
        for (n0, b), (n1, f) in zip(
                __import__("deepspeed_tpu.utils.tree",
                           fromlist=["named_leaves"]).named_leaves(
                    eng._lora_base),
                __import__("deepspeed_tpu.utils.tree",
                           fromlist=["named_leaves"]).named_leaves(fused)):
            np.testing.assert_allclose(np.asarray(f), np.asarray(b),
                                       atol=1e-6)

    @pytest.mark.slow  # tier-1 diet (PR 5)
    def test_lora_under_tp2(self, eight_devices):
        """generate -> train -> generate with a tensor-parallel mesh:
        the fused push and the TP-sharded inference compose."""
        eng, ids = self._make(tensor=2)
        prompt = np.asarray([[1, 2, 3]], np.int32)
        logits_before = np.asarray(eng.infer_forward(prompt))
        for _ in range(2):
            eng.train_batch(batch={"input_ids": ids,
                                   "labels": ids.copy()})
        logits_after = np.asarray(eng.infer_forward(prompt))
        assert not np.allclose(logits_before, logits_after)
        out = eng.generate(prompt, max_new_tokens=3)
        assert out.shape == (1, 6)


@pytest.mark.slow  # tier-1 diet (PR 17): the zero-init LoRA smoke keeps the hybrid engine tier-1
def test_param_refresh_is_lazy(hybrid):
    eng, ids = hybrid
    prompt = np.asarray([[1, 2, 3]], np.int32)
    eng.generate(prompt, max_new_tokens=2)
    step0 = eng._inf_params_step
    eng.generate(prompt, max_new_tokens=2)
    assert eng._inf_params_step == step0  # no re-push without a step
    eng.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    eng.generate(prompt, max_new_tokens=2)
    assert eng._inf_params_step != step0
