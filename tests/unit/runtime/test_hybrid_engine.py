"""Hybrid engine tests (reference shape: tests/hybrid_engine/)."""

import numpy as np
import pytest

from deepspeed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine


@pytest.fixture
def hybrid():
    model = LlamaForCausalLM(LlamaConfig.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 5e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    eng = DeepSpeedHybridEngine(model=model, config=config,
                                inference_config={"dtype": "float32"})
    ids = np.random.default_rng(0).integers(
        0, 256, size=(eng.train_batch_size(), 16), dtype=np.int32)
    eng.init_params({"input_ids": ids, "labels": ids.copy()})
    return eng, ids


def test_generate_then_train_then_generate(hybrid):
    """The rollout -> PPO-step -> rollout loop: generate sees updated
    weights after each train step (the weight-sharing contract,
    reference hybrid_engine.py:132)."""
    eng, ids = hybrid
    prompt = np.asarray([[1, 2, 3]], np.int32)
    out1 = eng.generate(prompt, max_new_tokens=4)
    assert out1.shape == (1, 7)

    logits_before = np.asarray(eng.infer_forward(prompt))
    for _ in range(3):
        eng.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    logits_after = np.asarray(eng.infer_forward(prompt))
    assert not np.allclose(logits_before, logits_after), \
        "inference path did not pick up trained weights"

    out2 = eng.generate(prompt, max_new_tokens=4)
    assert out2.shape == (1, 7)


def test_param_refresh_is_lazy(hybrid):
    eng, ids = hybrid
    prompt = np.asarray([[1, 2, 3]], np.int32)
    eng.generate(prompt, max_new_tokens=2)
    step0 = eng._inf_params_step
    eng.generate(prompt, max_new_tokens=2)
    assert eng._inf_params_step == step0  # no re-push without a step
    eng.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    eng.generate(prompt, max_new_tokens=2)
    assert eng._inf_params_step != step0
