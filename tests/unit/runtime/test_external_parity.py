"""Loss-curve parity against an EXTERNAL baseline (torch + HF
transformers on CPU) — the reference's convergence-test pattern
(tests/model/Megatron_GPT2 run_sanity_check.py) in unit-test form.

Same weights (HF state dict converted), same data, same AdamW
hyperparameters -> the per-step losses must track the torch
implementation closely in fp32.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tiny_hf_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=256, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg)
    return cfg, model


def _torch_losses(model, ids_np, lr, steps):
    model = model.train()
    opt = torch.optim.AdamW(model.parameters(), lr=lr, betas=(0.9, 0.999),
                            eps=1e-8, weight_decay=0.0)
    ids = torch.tensor(ids_np, dtype=torch.long)
    losses = []
    for _ in range(steps):
        opt.zero_grad()
        out = model(input_ids=ids, labels=ids)
        out.loss.backward()
        opt.step()
        losses.append(float(out.loss))
    return losses


def test_gpt2_loss_curve_matches_torch(tiny_hf_gpt2):
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2LMHeadModel,
                                           from_hf_state_dict)
    from deepspeed_tpu.parallel.mesh import mesh_manager

    hf_cfg, hf_model = tiny_hf_gpt2
    lr, steps = 1e-3, 8
    rng = np.random.default_rng(0)
    B = 8
    ids = rng.integers(0, 256, size=(B, 32), dtype=np.int32)

    # snapshot BEFORE the torch run mutates the model in place
    init_sd = {k: v.detach().clone()
               for k, v in hf_model.state_dict().items()}
    ref_losses = _torch_losses(hf_model, ids, lr, steps)

    cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=32,
                     n_layer=2, n_head=4, dropout=0.0)
    params = from_hf_state_dict(init_sd, cfg)
    mesh_manager.reset()
    config = {
        "train_micro_batch_size_per_gpu": max(1, B // 8),
        "optimizer": {"type": "AdamW",
                      "params": {"lr": lr, "betas": (0.9, 0.999),
                                 "eps": 1e-8, "weight_decay": 0.0}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), config=config,
        model_parameters=params)
    batch = {"input_ids": ids, "labels": ids.copy()}
    ours = [float(engine.train_batch(batch=batch)) for _ in range(steps)]

    # fp32 vs fp32: initial loss identical to ~1e-4, curve tracks
    np.testing.assert_allclose(ours[0], ref_losses[0], rtol=1e-3)
    np.testing.assert_allclose(ours, ref_losses, rtol=2e-2)
    assert ours[-1] < ours[0]


@pytest.mark.slow  # tier-1 diet (ISSUE 7): the short loss-curve parity stays
def test_gpt2_long_horizon_bf16_zero3_tracks_torch(tiny_hf_gpt2):
    """The north-star recipe over a LONG horizon: 100 steps of bf16
    compute + sharded fp32 master under ZeRO-3 must stay inside the
    torch fp32 loss-curve envelope — bf16 rounding wobbles per step
    but must not drift (the reference's Megatron_GPT2
    run_sanity_check.py convergence pattern)."""
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import (GPT2Config, GPT2LMHeadModel,
                                           from_hf_state_dict)
    from deepspeed_tpu.parallel.mesh import mesh_manager

    hf_cfg, hf_model = tiny_hf_gpt2
    lr, steps = 1e-3, 100
    rng = np.random.default_rng(1)
    B = 8
    ids = rng.integers(0, 256, size=(B, 32), dtype=np.int32)

    init_sd = {k: v.detach().clone()
               for k, v in hf_model.state_dict().items()}
    ref_losses = _torch_losses(hf_model, ids, lr, steps)

    cfg = GPT2Config(vocab_size=256, n_positions=64, n_embd=32,
                     n_layer=2, n_head=4, dropout=0.0)
    params = from_hf_state_dict(init_sd, cfg)
    mesh_manager.reset()
    config = {
        "train_micro_batch_size_per_gpu": max(1, B // 8),
        "optimizer": {"type": "AdamW",
                      "params": {"lr": lr, "betas": (0.9, 0.999),
                                 "eps": 1e-8, "weight_decay": 0.0}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3},
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(cfg), config=config,
        model_parameters=params)
    batch = {"input_ids": ids, "labels": ids.copy()}
    ours = [float(engine.train_batch(batch=batch))
            for _ in range(steps)]

    ours = np.asarray(ours)
    ref = np.asarray(ref_losses)
    # start matched to bf16 forward rounding
    np.testing.assert_allclose(ours[0], ref[0], rtol=2e-2)
    # envelope: windowed means track torch over the whole horizon
    w = 10
    ours_w = ours.reshape(-1, w).mean(axis=1)
    ref_w = ref.reshape(-1, w).mean(axis=1)
    np.testing.assert_allclose(ours_w, ref_w, rtol=6e-2)
    # same endpoint, real convergence
    np.testing.assert_allclose(ours[-10:].mean(), ref[-10:].mean(),
                               rtol=0.1)
    assert ours[-10:].mean() < 0.5 * ours[:5].mean()
