"""Deterministic dataloader resume: the (epoch, batch) cursor + RNG
contract.

The PR-1..6 loader restarted every resumed run at batch 0 of epoch 0 —
a recovered run silently re-trained on the head of the epoch and never
saw its tail (the ISSUE-7 satellite bugfix). The cursor now rides the
checkpoint client_state; these tests pin the replay-identity contract
the chaos harness builds on: resume(cursor) continues the EXACT sample
stream the original run would have produced.
"""

import numpy as np
import pytest

from deepspeed_tpu.runtime.dataloader import (DeepSpeedDataLoader,
                                              RepeatingLoader)


def _ds(n=64):
    return [{"x": np.array([i], dtype=np.int32)} for i in range(n)]


def _stream(loader, k):
    """First k batches' x-columns from a fresh wrap of ``loader``."""
    rl = RepeatingLoader(loader)
    return [np.asarray(next(rl)["x"]).ravel().tolist()
            for _ in range(k)]


class TestCursor:

    def test_mid_epoch_resume_replays_identically(self):
        src = DeepSpeedDataLoader(_ds(), 8, shuffle=True, seed=3)
        whole = _stream(src, 8)  # one full epoch
        # consume 3 batches, checkpoint the cursor, resume elsewhere
        orig = DeepSpeedDataLoader(_ds(), 8, shuffle=True, seed=3)
        rl = RepeatingLoader(orig)
        for _ in range(3):
            next(rl)
        sd = rl.state_dict()
        assert sd == {"epoch": 0, "batch_cursor": 3}
        fresh = DeepSpeedDataLoader(_ds(), 8, shuffle=True, seed=3)
        frl = RepeatingLoader(fresh)
        frl.load_state_dict(sd)
        resumed = [np.asarray(next(frl)["x"]).ravel().tolist()
                   for _ in range(5)]
        assert resumed == whole[3:]   # the tail, not batch 0 again

    def test_epoch_advances_on_wrap_and_reshuffles(self):
        loader = DeepSpeedDataLoader(_ds(32), 8, shuffle=True, seed=0)
        rl = RepeatingLoader(loader)
        epoch0 = [np.asarray(next(rl)["x"]).ravel().tolist()
                  for _ in range(4)]
        epoch1 = [np.asarray(next(rl)["x"]).ravel().tolist()
                  for _ in range(4)]
        assert loader.epoch == 1
        assert sorted(sum(epoch0, [])) == sorted(sum(epoch1, []))
        assert epoch0 != epoch1   # per-epoch reshuffle
        # cursor across the wrap: epoch 1, batch 4 consumed... next is 0
        rl2 = RepeatingLoader(
            DeepSpeedDataLoader(_ds(32), 8, shuffle=True, seed=0))
        rl2.load_state_dict({"epoch": 1, "batch_cursor": 0})
        replay = [np.asarray(next(rl2)["x"]).ravel().tolist()
                  for _ in range(4)]
        assert replay == epoch1

    def test_cursor_counts_yielded_batches(self):
        loader = DeepSpeedDataLoader(_ds(32), 8)
        it = iter(loader)
        assert loader.batch_cursor == 0
        next(it)
        assert loader.batch_cursor == 1
        next(it)
        assert loader.state_dict()["batch_cursor"] == 2

    def test_unshuffled_resume(self):
        loader = DeepSpeedDataLoader(_ds(32), 8, shuffle=False)
        loader.load_state_dict({"epoch": 0, "batch_cursor": 2})
        first = next(iter(loader))
        assert first["x"].ravel().tolist() == list(range(16, 24))


class TestEngineReplayIdentity:

    # slow tier: post-restore train_batch sequences are where the
    # known XLA-CPU full-suite flake strikes (README "Long-run
    # durability"; observed once here mid-suite while the same test
    # passes standalone). Tier-1 keeps the replay-identity class via
    # the chaos smokes + supervisor kill test; the loader-level cursor
    # tests above stay tier-1.
    @pytest.mark.slow
    @pytest.mark.fault
    def test_checkpoint_resume_replays_the_sample_stream(
            self, tmp_path, eight_devices):
        """Engine-level: train THROUGH the dataloader, checkpoint
        mid-epoch, keep training; a restored engine replays the
        continuation BITWISE (cursor + device PRNG both ride the
        checkpoint). Before the fix the restored run restarted at
        batch 0 and the trajectories diverged."""
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import (GPT2Config,
                                               GPT2LMHeadModel)
        from deepspeed_tpu.parallel.mesh import (MeshConfig,
                                                 mesh_manager)
        rng = np.random.default_rng(1)
        data = [{"input_ids": row, "labels": row.copy()}
                for row in rng.integers(
                    0, 256, size=(96, 16)).astype(np.int32)]
        config = {
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 0,
        }

        def build():
            mesh_manager.reset()
            mesh_manager.init(MeshConfig(data=-1))
            model = GPT2LMHeadModel(GPT2Config.tiny())
            eng, _, _, _ = deepspeed_tpu.initialize(
                model=model, config=config, training_data=data)
            return eng

        eng = build()
        for _ in range(3):
            eng.train_batch()
        eng.save_checkpoint(str(tmp_path))
        cont = [float(eng.train_batch()) for _ in range(4)]

        eng2 = build()
        b0 = {"input_ids": np.stack([d["input_ids"] for d in data[:16]]),
              "labels": np.stack([d["labels"] for d in data[:16]])}
        eng2.init_params(b0)
        eng2.load_checkpoint(str(tmp_path))
        assert eng2.training_dataloader.state_dict() == \
            {"epoch": 0, "batch_cursor": 3}
        replay = [float(eng2.train_batch()) for _ in range(4)]
        assert replay == cont
