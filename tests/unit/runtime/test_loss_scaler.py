"""Dynamic loss-scaler state machine + engine fp16 overflow-skip
(reference pattern: tests/unit/runtime/half_precision/test_dynamic_loss_scale.py
— scale halves after overflow, grows every `scale_window` good steps,
skipped steps leave params untouched)."""

import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.runtime.fp16.loss_scaler import (
    CreateLossScaler, dynamic_loss_scale_state, has_inf_or_nan,
    static_loss_scale_state, update_scale)

T, F = jnp.bool_(True), jnp.bool_(False)


def test_overflow_consumes_hysteresis_then_halves():
    s = dynamic_loss_scale_state(initial_scale_power=10, hysteresis=2)
    # first overflow: hysteresis absorbs it, scale unchanged
    s = update_scale(s, T, scale_window=1000, max_hysteresis=2)
    assert float(s.loss_scale) == 2.0**10
    # second consecutive overflow: scale halves, hysteresis refills
    s = update_scale(s, T, scale_window=1000, max_hysteresis=2)
    assert float(s.loss_scale) == 2.0**9
    assert int(s.hysteresis) == 2
    assert int(s.good_steps) == 0


def test_scale_grows_at_window_boundary():
    s = dynamic_loss_scale_state(initial_scale_power=8)
    for _ in range(4):
        s = update_scale(s, F, scale_window=4)
    assert float(s.loss_scale) == 2.0**9
    assert int(s.good_steps) == 4
    # not again until the next full window
    s = update_scale(s, F, scale_window=4)
    assert float(s.loss_scale) == 2.0**9


def test_overflow_resets_good_step_count():
    s = dynamic_loss_scale_state(initial_scale_power=8, hysteresis=1)
    for _ in range(3):
        s = update_scale(s, F, scale_window=4)
    s = update_scale(s, T, scale_window=4, max_hysteresis=1)
    assert int(s.good_steps) == 0
    # the next good step must NOT trigger growth (window restarts)
    s = update_scale(s, F, scale_window=4)
    assert float(s.loss_scale) == 2.0**7


def test_min_scale_clamp():
    s = dynamic_loss_scale_state(initial_scale_power=1, hysteresis=1)
    for _ in range(8):
        s = update_scale(s, T, min_scale=1.0, max_hysteresis=1)
    assert float(s.loss_scale) == 1.0


def test_static_scaler_never_moves():
    s = static_loss_scale_state(128.0)
    s2 = update_scale(s, T, dynamic=False)
    assert float(s2.loss_scale) == 128.0


def test_consecutive_hysteresis_refills_on_good_step():
    s = dynamic_loss_scale_state(initial_scale_power=8, hysteresis=2)
    s = update_scale(s, T, max_hysteresis=2)          # hysteresis 2 -> 1
    s = update_scale(s, F, consecutive_hysteresis=True, max_hysteresis=2)
    # a good step refilled the budget: one more overflow is absorbed again
    s = update_scale(s, T, consecutive_hysteresis=True, max_hysteresis=2)
    assert float(s.loss_scale) == 2.0**8


def test_has_inf_or_nan_over_pytree():
    clean = {"a": jnp.ones((3,)), "b": {"c": jnp.zeros((2, 2))}}
    assert not bool(has_inf_or_nan(clean))
    assert bool(has_inf_or_nan({"a": jnp.array([1.0, np.inf])}))
    assert bool(has_inf_or_nan({"a": jnp.array([np.nan])}))
    assert not bool(has_inf_or_nan({}))


def test_factory_routes_by_dtype():
    dyn = CreateLossScaler(jnp.float16, 0.0, True,
                           {"initial_scale_power": 4})
    assert dyn.dynamic and dyn.loss_scale == 16.0
    stat = CreateLossScaler(jnp.float16, 64.0, False)
    assert not stat.dynamic and stat.loss_scale == 64.0
    bf16 = CreateLossScaler(jnp.bfloat16, 64.0, True)
    assert bf16.loss_scale == 1.0  # bf16 needs no scaling


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_engine_fp16_backs_off_huge_scale(rng, eight_devices):
    """With an absurd initial scale the scaled fp16 grads overflow; the
    engine must skip those steps (params untouched, scale halving) and
    recover to finite training — the reference's core fp16 invariant."""
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_batch_size": 8,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "fp16": {"enabled": True, "initial_scale_power": 28,
                 "hysteresis": 1, "loss_scale_window": 1000},
        "steps_per_print": 0,
    })
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    start_scale = engine.loss_scale
    losses = [float(engine.train_batch(batch=batch)) for _ in range(12)]
    assert engine.loss_scale < start_scale, \
        f"scale never backed off: {engine.loss_scale} vs {start_scale}"
    assert all(np.isfinite(l) for l in losses), losses
    # once the scaler settled, training makes progress
    assert losses[-1] < losses[0], losses
