"""runtime/store.py PR 18 surfaces: the write-behind AsyncSpillQueue
(background encode+put with coalescing, read-through, typed
backpressure, latched errors, drain-on-close) and the disk tier's
journal group commit (payload fsync folded into the batched cadence —
the syscall-count pin — plus the deadline valve and recovery re-run
against a torn group tail)."""

import os
import threading
import time

import numpy as np
import pytest

from deepspeed_tpu.resilience.errors import (InjectedIOError,
                                             StoreBackpressure)
from deepspeed_tpu.resilience.fault_injector import fault_injector
from deepspeed_tpu.runtime.store import (AsyncSpillQueue,
                                         DiskBlockStore,
                                         HostBlockStore, decode_kv,
                                         encode_kv)


@pytest.fixture(autouse=True)
def _clean_injector():
    fault_injector.reset()
    yield
    fault_injector.reset()


def _arr(seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((2, 2, 8, 4)).astype(np.float32)


def _blocked_queue(**kw):
    """Queue whose worker is parked on a gate, so pending state is
    observable deterministically before any flush runs."""
    q = AsyncSpillQueue(HostBlockStore(0), **kw)
    gate = threading.Event()
    q.worker.submit(gate.wait)
    return q, gate


class TestAsyncSpillQueue:

    def test_put_async_flushes_bitwise(self):
        q = AsyncSpillQueue(HostBlockStore(0))
        a = _arr(1)
        q.put_async(b"k", a)
        assert q.drain(timeout=10.0)
        payload, meta = q.get(b"k")
        assert np.array_equal(decode_kv(payload, meta), a)
        st = q.stats()
        assert st["queued"] == 1 and st["flushed"] == 1
        assert st["backlog"] == 0 and st["backlog_bytes"] == 0
        assert st["flush_ms"] > 0.0

    def test_read_through_serves_pending_bytes_identically(self):
        q, gate = _blocked_queue()
        a = _arr(2)
        q.put_async(b"k", a)
        assert b"k" in q and len(q) == 1     # visible before flush
        payload, meta = q.get(b"k")          # reader-thread encode
        assert q.stats()["read_through"] == 1
        gate.set()
        assert q.drain(timeout=10.0)
        flushed_payload, flushed_meta = q.get(b"k")
        # the write-behind window was never observable: read-through
        # bytes == the bytes the flush eventually stored
        assert payload == flushed_payload and meta == flushed_meta

    def test_coalescing_keeps_only_the_newest_value(self):
        q, gate = _blocked_queue()
        q.put_async(b"k", _arr(3))
        q.put_async(b"k", _arr(4))           # supersedes in place
        gate.set()
        assert q.drain(timeout=10.0)
        st = q.stats()
        assert st["coalesced"] == 1 and st["flushed"] == 1
        payload, meta = q.get(b"k")
        assert np.array_equal(decode_kv(payload, meta), _arr(4))

    def test_backpressure_is_typed_and_coalesce_exempt(self):
        a = _arr(5)
        q, gate = _blocked_queue(max_pending_bytes=a.nbytes)
        q.put_async(b"k1", a)
        with pytest.raises(StoreBackpressure):
            q.put_async(b"k2", a)            # new key over the bound
        q.put_async(b"k1", _arr(6))          # re-put coalesces fine
        assert q.stats()["backpressure_events"] == 1
        gate.set()
        assert q.drain(timeout=10.0)

    def test_sync_put_cancels_the_pending_flush(self):
        q, gate = _blocked_queue()
        q.put_async(b"k", _arr(7))
        direct = encode_kv(_arr(8), "none")
        q.put(b"k", *direct)                 # newer direct write
        gate.set()
        assert q.drain(timeout=10.0)
        # the stale background value never overwrote the direct one
        assert q.get(b"k")[0] == direct[0]
        assert q.stats()["flushed"] == 0

    def test_delete_cancels_the_pending_flush(self):
        q, gate = _blocked_queue()
        q.put_async(b"k", _arr(9))
        q.delete(b"k")      # pending cancelled; store never had it
        gate.set()
        assert q.drain(timeout=10.0)
        assert b"k" not in q and q.stats()["flushed"] == 0

    def test_flush_error_is_latched_not_lost(self):
        q = AsyncSpillQueue(HostBlockStore(0))
        with fault_injector.inject("store.flush:ioerror"):
            q.put_async(b"k", _arr(10))
            assert q.drain(timeout=10.0)
        assert q.stats()["flush_errors"] == 1
        assert isinstance(q.take_error(), InjectedIOError)
        assert q.take_error() is None        # drained
        assert b"k" not in q                 # pending retired too

    def test_on_done_callback_reports_success_and_failure(self):
        q = AsyncSpillQueue(HostBlockStore(0))
        done = []
        q.put_async(b"ok", _arr(11),
                    on_done=lambda e, s: done.append((e, s)))
        with fault_injector.inject("store.flush:ioerror"):
            q.put_async(b"bad", _arr(12),
                        on_done=lambda e, s: done.append((e, s)))
            assert q.drain(timeout=10.0)
        assert done[0][0] is None and done[0][1] > 0.0
        assert isinstance(done[1][0], InjectedIOError)
        assert q.take_error() is None        # on_done owns the error

    def test_close_drains_before_closing(self):
        q = AsyncSpillQueue(HostBlockStore(0))
        for i in range(4):
            q.put_async(bytes([i]), _arr(i))
        q.close()
        assert q.stats()["flushed"] == 4     # nothing lost on shutdown

    def test_shared_worker_serves_two_tiers(self, tmp_path):
        dram = AsyncSpillQueue(HostBlockStore(0))
        disk = AsyncSpillQueue(DiskBlockStore(str(tmp_path)),
                               worker=dram.worker)
        dram.put_async(b"a", _arr(13))
        disk.put_async(b"b", _arr(14))
        assert dram.drain(timeout=10.0)      # drains the SHARED worker
        assert b"a" in dram and b"b" in disk
        disk.close()

    def test_passthrough_contract_matches_the_store(self, tmp_path):
        q = AsyncSpillQueue(DiskBlockStore(str(tmp_path)))
        q.put(b"k", *encode_kv(_arr(15), "none"))
        assert q.tier == "disk"
        assert q.used_bytes > 0 and not q.over_budget
        assert q.keys() == [b"k"]
        assert q.pop_lru()[0] == b"k"
        assert q.as_dict()["entries"] == 0   # __getattr__ passthrough
        q.close()
        assert q.closed


class TestJournalGroupCommit:

    def _count_fsyncs(self, monkeypatch):
        calls = []
        real = os.fsync
        monkeypatch.setattr(os, "fsync",
                            lambda fd: (calls.append(fd), real(fd)))
        return calls

    def test_group_mode_batches_payload_and_journal_fsyncs(
            self, tmp_path, monkeypatch):
        """THE bugfix pin: with journal_fsync_every=8, 9 puts used to
        cost ~11 fsyncs (one per payload inside atomic_write_bytes +
        the batched journal ones). Folded into the group-commit
        cadence they cost exactly 2 (first-record commit + one full
        8-record group), a syscall count a regression can't dodge."""
        s = DiskBlockStore(str(tmp_path), fsync_every=8)
        calls = self._count_fsyncs(monkeypatch)
        for i in range(9):
            s.put(bytes([i]), b"x" * 32, {})
        assert len(calls) == 2               # zero payload fsyncs
        assert s.fsyncs == 2                 # record 1 + the full group

    def test_strict_mode_keeps_per_put_durability(self, tmp_path,
                                                  monkeypatch):
        s = DiskBlockStore(str(tmp_path), fsync_every=1)
        calls = self._count_fsyncs(monkeypatch)
        for i in range(4):
            s.put(bytes([i]), b"x" * 32, {})
        # journal fsync per append AND payload fsync per put
        assert len(calls) >= 8

    def test_deadline_forces_the_commit_between_groups(self, tmp_path):
        s = DiskBlockStore(str(tmp_path), fsync_every=1000,
                           fsync_deadline_seconds=0.01)
        s.put(b"\x01", b"x", {})             # first record commits
        assert s.fsyncs == 1
        s.put(b"\x02", b"x", {})             # group far from full
        assert s.fsyncs == 1
        time.sleep(0.02)                     # deadline elapses
        s.put(b"\x03", b"x", {})
        assert s.fsyncs == 2                 # committed by age, not fill

    def test_flush_is_the_explicit_commit_barrier(self, tmp_path):
        s = DiskBlockStore(str(tmp_path), fsync_every=1000)
        s.put(b"\x01", b"x", {})
        s.put(b"\x02", b"x", {})
        before = s.fsyncs
        s.flush()
        assert s.fsyncs == before + 1
        s.flush()                            # nothing unsynced: no-op
        assert s.fsyncs == before + 1

    def test_recovery_survives_a_torn_group_tail(self, tmp_path):
        """Crash inside the group-commit window: the journal's tail
        record is torn mid-line. The next open replays every intact
        record, counts the torn one as a typed error, verifies the
        surviving payloads, and never raises."""
        s = DiskBlockStore(str(tmp_path), fsync_every=64)
        for i in range(4):
            s.put(bytes([i]), bytes(16 + i), {})
        os.close(s._jfd)                     # crash: no flush/compact
        s._jfd = None
        with open(s.index_path, "rb") as f:
            raw = f.read()
        lines = raw.rstrip(b"\n").split(b"\n")
        torn = b"\n".join(lines[:-1]) + b"\n" + lines[-1][:9]
        with open(s.index_path, "wb") as f:  # atomic-ok: test plants the torn tail
            f.write(torn)
        s2 = DiskBlockStore(str(tmp_path), fsync_every=64)
        assert len(s2) == 3                  # intact group survives
        assert s2.recovery.corrupt_records == 1  # the torn line, counted
        for i in range(3):
            payload, _ = s2.get(bytes([i]))
            assert payload == bytes(16 + i)  # verified, not just listed
        assert bytes([3]) not in s2
        s2.close()

    def test_recovery_drops_group_entries_missing_their_payload(
            self, tmp_path):
        """The other crash interleaving inside a group: journal
        records landed (OS buffer) but a payload file didn't — each
        such entry is dropped and counted, the rest survive."""
        s = DiskBlockStore(str(tmp_path), fsync_every=64)
        for i in range(3):
            s.put(bytes([i]), b"p" * 24, {})
        os.unlink(s._block_path(bytes([1])))  # its payload never hit
        os.close(s._jfd)
        s._jfd = None
        s2 = DiskBlockStore(str(tmp_path), fsync_every=64)
        assert len(s2) == 2
        assert s2.recovery.dropped_entries == 1
        assert bytes([1]) not in s2
        s2.close()
