"""Pipeline-engine checkpoint continuity (reference pattern:
tests/unit/checkpoint/test_pipeline.py — save mid-training, resume in a
fresh engine, losses continue identically)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import mesh_manager
from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule

HIDDEN = 16
VOCAB = 64


class EmbedLayer(nn.Module):
    @nn.compact
    def __call__(self, ids):
        e = self.param("embedding", nn.initializers.normal(0.02),
                       (VOCAB, HIDDEN))
        return e[ids]


class Block(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.Dense(HIDDEN * 2)(x)
        return x + nn.Dense(HIDDEN)(nn.relu(h))


class Head(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(VOCAB)(x)


def ce_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return -jnp.mean(ll)


def _engine(seed):
    pm = PipelineModule(
        [LayerSpec(EmbedLayer)] + [LayerSpec(Block) for _ in range(4)] +
        [LayerSpec(Head)], num_stages=4, loss_fn=ce_loss)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
              "zero_optimization": {"stage": 1},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=pm, config=config, rng=jax.random.PRNGKey(seed))
    return engine


from tests.conftest import SKIP_OLD_XLA_PIPE as _SPMD_PIPE


@_SPMD_PIPE
def test_pipeline_checkpoint_resume_continues_loss_curve(
        tmp_path, rng, eight_devices):
    engine = _engine(seed=1)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}

    for _ in range(3):
        engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path), tag="pipe3")
    expect = [float(engine.train_batch(batch=batch)) for _ in range(3)]

    mesh_manager.reset()
    engine2 = _engine(seed=99)           # different init
    engine2.train_batch(batch=batch)     # materialize params
    engine2.load_checkpoint(str(tmp_path), tag="pipe3")
    assert engine2.global_steps == 3
    got = [float(engine2.train_batch(batch=batch)) for _ in range(3)]
    np.testing.assert_allclose(got, expect, rtol=1e-4)


@_SPMD_PIPE
def test_pipeline_checkpoint_latest_pointer(tmp_path, rng, eight_devices):
    engine = _engine(seed=2)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.train_batch(batch=batch)
    engine.save_checkpoint(str(tmp_path))      # default tag
    # tag=None load resolves through `latest`
    mesh_manager.reset()
    engine2 = _engine(seed=3)
    engine2.train_batch(batch=batch)
    engine2.load_checkpoint(str(tmp_path))
    assert engine2.global_steps == 1
