"""1F1B pipeline schedule (reference runtime/pipe/schedule.py:189
TrainSchedule): the interleaved forward/backward executor with manual
per-tick vjp must produce the SAME loss and gradients as the GPipe +
autodiff path — they compute the same math in a different order — while
keeping the saved-activation footprint O(stages), not O(microbatches).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
from deepspeed_tpu.runtime.pipe import LayerSpec, PipelineModule, TiedLayerSpec

from test_pipeline import (VOCAB, Block, EmbedLayer, Head, ce_loss,
                           _pipeline_module)


def _train(schedule, steps=6, rng_seed=0, stages=4, gas=4,
           n_blocks=4, extra_config=None):
    mesh_manager.reset()
    pm = _pipeline_module(n_blocks=n_blocks, num_stages=stages,
                          schedule=schedule)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": gas,
              "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
              "zero_optimization": {"stage": 1},
              "gradient_clipping": 1.0,
              "steps_per_print": 0}
    config.update(extra_config or {})
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    r = np.random.default_rng(rng_seed)
    ids = r.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch))
              for _ in range(steps)]
    return engine, losses


from tests.conftest import SKIP_OLD_XLA_PIPE as _SPMD_PIPE


@_SPMD_PIPE
def test_1f1b_matches_gpipe_trajectory(eight_devices):
    """Same init/seed/batch: the two schedules are the same math in a
    different execution order — loss curves agree to numeric noise."""
    _, ref = _train("gpipe")
    _, got = _train("1f1b")
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    assert got[-1] < got[0]


@_SPMD_PIPE
def test_1f1b_gradients_match_gpipe(eight_devices):
    """One-step gradient comparison, leaf by leaf."""
    e1, _ = _train("gpipe", steps=1)
    e2, _ = _train("1f1b", steps=1)
    f1 = jax.tree_util.tree_leaves(
        jax.device_get(e1.state.master_params))
    f2 = jax.tree_util.tree_leaves(
        jax.device_get(e2.state.master_params))
    for a, b in zip(f1, f2):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-4)


@_SPMD_PIPE
def test_1f1b_nonuniform_and_indivisible_stages(eight_devices):
    """3 blocks over 4 stages: idle slots + the pre/post gating still
    line up with the interleaved backward."""
    _, losses = _train("1f1b", n_blocks=3, steps=6)
    assert losses[-1] < losses[0], losses


@_SPMD_PIPE
def test_1f1b_deep_microbatches_converge(eight_devices):
    """M >> P exercises the steady 1F1B phase (every tick does one F
    and one B)."""
    _, losses = _train("1f1b", gas=12, steps=4)
    assert losses[-1] < losses[0], losses


@_SPMD_PIPE
def test_1f1b_tied_embedding_head(eight_devices):
    """TiedLayerSpec: embed (stage 0) and head (last stage) grads must
    MEET in the pipe-axis psum — the tied-weight allreduce. Beyond the
    smoke test in test_pipeline.py, this trains to convergence so a
    silently-dropped head cotangent would show."""
    from test_pipeline import TiedEmbed, _tied_head_fwd
    mesh_manager.reset()
    embed = TiedLayerSpec("emb", TiedEmbed)
    head = TiedLayerSpec("emb", TiedEmbed, forward_fn=_tied_head_fwd)
    pm = PipelineModule(
        [embed] + [LayerSpec(Block) for _ in range(4)] + [head],
        num_stages=4, loss_fn=ce_loss, schedule="1f1b")
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
              "zero_optimization": {"stage": 0},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    ids = np.random.default_rng(0).integers(0, VOCAB, size=(gbs, 8),
                                            dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(8)]
    assert losses[-1] < losses[0], losses
    params = engine.get_params()["params"]
    assert "tied_emb" in params


@_SPMD_PIPE
def test_1f1b_composes_with_fp16_loss_scaling(eight_devices):
    """fp16 under the 1F1B schedule: the engine's loss-scale rides the
    custom_vjp cotangent (grads are linear in it), overflow machinery
    included — training must converge WITH fp16 actually engaged."""
    engine, losses = _train(
        "1f1b", steps=8,
        extra_config={"fp16": {"enabled": True},
                      "zero_optimization": {"stage": 0}})
    assert engine.fp16_enabled
    # the dynamic scaler starts at 2**16 and stays >> 1 absent mass
    # overflows — a silent fp32 fallback (scale pinned to 1) fails here
    assert engine.loss_scale > 1
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(losses))


@_SPMD_PIPE
def test_1f1b_saved_activations_independent_of_microbatches(
        eight_devices):
    """THE 1F1B memory claim: the residuals the outer autodiff stores
    for the pipelined loss are the schedule's own grad outputs — their
    count does not grow with M (GPipe's scan-carry residuals do)."""
    from jax._src.ad_checkpoint import saved_residuals
    from deepspeed_tpu.runtime.pipe.engine import _PipelinedLM

    mesh_manager.reset()
    mesh_manager.init(MeshConfig(pipe=4, data=2))
    ids_small = np.random.default_rng(0).integers(
        0, VOCAB, size=(8, 8), dtype=np.int32)
    ids_big = np.random.default_rng(0).integers(
        0, VOCAB, size=(32, 8), dtype=np.int32)

    def res_bytes(schedule, M, ids):
        pm = _pipeline_module(n_blocks=4, num_stages=4,
                              schedule=schedule)
        w = _PipelinedLM(pm, num_stages=4, num_microbatches=M)
        params = w.init(jax.random.PRNGKey(0), ids)
        res = saved_residuals(
            lambda p: w.apply(p, ids, labels=ids), params)
        return sum(int(np.prod(aval.shape)) * aval.dtype.itemsize
                   for aval, _ in res)

    # 1f1b residuals = the schedule's grad outputs: bytes equal at
    # M=4 and M=16. gpipe's scan-carry residuals stack per tick: bytes
    # grow with M (count stays constant; the ARRAYS get longer).
    assert res_bytes("1f1b", 4, ids_small) == \
        res_bytes("1f1b", 16, ids_big)
    assert res_bytes("gpipe", 16, ids_big) > \
        res_bytes("gpipe", 4, ids_small)
