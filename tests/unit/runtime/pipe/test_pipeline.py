"""Pipeline-parallel tests (reference shape: tests/unit/ pipeline
tests — schedule correctness, loss parity vs sequential execution)."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import (MeshConfig, PIPE_AXIS,
                                         mesh_manager)
from deepspeed_tpu.runtime.pipe import (LayerSpec, PipelineEngine,
                                        PipelineModule, gpipe_spmd)

HIDDEN = 16
VOCAB = 64


class EmbedLayer(nn.Module):
    @nn.compact
    def __call__(self, ids):
        e = self.param("embedding", nn.initializers.normal(0.02),
                       (VOCAB, HIDDEN))
        return e[ids]


class Block(nn.Module):
    @nn.compact
    def __call__(self, x):
        h = nn.Dense(HIDDEN * 2)(x)
        return x + nn.Dense(HIDDEN)(nn.relu(h))


class Head(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(VOCAB)(x)


def ce_loss(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)
    return -jnp.mean(ll)


def _pipeline_module(n_blocks=4, num_stages=4, **kw):
    specs = ([LayerSpec(EmbedLayer)] +
             [LayerSpec(Block) for _ in range(n_blocks)] +
             [LayerSpec(Head)])
    return PipelineModule(specs, num_stages=num_stages, loss_fn=ce_loss,
                          **kw)


from tests.conftest import SKIP_OLD_XLA_PIPE as _SPMD_PIPE


@_SPMD_PIPE
def test_gpipe_spmd_matches_sequential(eight_devices, rng):
    """The raw schedule: y = f_3(f_2(f_1(f_0(x)))) per microbatch."""
    mesh = mesh_manager.init(MeshConfig(pipe=4, data=2),
                             devices=eight_devices)
    M, B, H = 6, 4, 8
    x = rng.standard_normal((M, B, H)).astype(np.float32)
    w = rng.standard_normal((4, H, H)).astype(np.float32) * 0.3

    def stage_fn(wi, a):
        return jnp.tanh(a @ wi)

    def body(w_sharded, mbs):
        wi = w_sharded[0]
        outs = gpipe_spmd(stage_fn, wi, mbs)
        nstages = jax.lax.axis_size(PIPE_AXIS)
        stage = jax.lax.axis_index(PIPE_AXIS)
        return jax.lax.psum(
            jnp.where(stage == nstages - 1, outs, 0.0), PIPE_AXIS)

    fn = shard_map(body, mesh=mesh, axis_names={PIPE_AXIS},
                   in_specs=(P(PIPE_AXIS), P()), out_specs=P(),
                   check_vma=False)
    out = jax.jit(fn)(w, x)

    ref = x
    for i in range(4):
        ref = np.tanh(ref @ w[i])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


@_SPMD_PIPE
def test_pipeline_engine_loss_parity(eight_devices, rng):
    """Pipelined eval loss == sequential (unpipelined) computation."""
    pm = _pipeline_module(n_blocks=4, num_stages=4)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 0},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    assert mesh_manager.pipe_parallel_world_size() == 4

    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.init_params(batch)
    pipe_loss = float(engine.eval_batch(batch=batch))

    # sequential reference with the SAME params
    params = jax.device_get(engine.get_params())["params"]
    h = EmbedLayer().apply({"params": params["pre_0"]}, ids)
    for lp in engine.module.unstack_blocks(params):
        h = Block().apply({"params": lp}, h)
    logits = Head().apply({"params": params["post_0"]}, h)
    ref_loss = float(ce_loss(logits, ids))
    np.testing.assert_allclose(pipe_loss, ref_loss, rtol=1e-4)


@_SPMD_PIPE
def test_pipeline_training_converges(eight_devices, rng):
    pm = _pipeline_module(n_blocks=4, num_stages=4)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 3e-3}},
              "zero_optimization": {"stage": 1},
              "gradient_clipping": 1.0,
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(10)]
    assert losses[-1] < losses[0], f"no convergence: {losses}"


def test_pipeline_module_partitioning():
    pm = _pipeline_module(n_blocks=8, num_stages=4)
    assert len(pm) == 10
    pm_uniform = PipelineModule([LayerSpec(Block) for _ in range(8)],
                                num_stages=4, loss_fn=ce_loss,
                                partition_method="uniform")
    assert pm_uniform.parts == [0, 2, 4, 6, 8]


@_SPMD_PIPE
def test_indivisible_blocks_supported(eight_devices, rng):
    """3 blocks over 4 stages: non-uniform masked execution (one stage
    passes activations through) still matches the sequential model."""
    pm = _pipeline_module(n_blocks=3, num_stages=4)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 0},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.init_params(batch)
    pipe_loss = float(engine.eval_batch(batch=batch))

    params = jax.device_get(engine.get_params())["params"]
    h = EmbedLayer().apply({"params": params["pre_0"]}, ids)
    layer_params = engine.module.unstack_blocks(params)
    assert len(layer_params) == 3
    for lp in layer_params:
        h = Block().apply({"params": lp}, h)
    logits = Head().apply({"params": params["post_0"]}, h)
    np.testing.assert_allclose(pipe_loss, float(ce_loss(logits, ids)),
                               rtol=1e-4)


@_SPMD_PIPE
def test_pipeline_inference_output_shape(eight_devices, rng):
    """forward (no labels) returns [Btot, ...] logits, not microbatched."""
    pm = _pipeline_module(n_blocks=4, num_stages=4)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    engine.init_params({"input_ids": ids, "labels": ids.copy()})
    wrapper = engine.module
    logits = wrapper.apply(jax.device_get(engine.get_params()),
                           input_ids=ids)
    assert logits.shape == (gbs, 8, VOCAB)


class TiedEmbed(nn.Module):
    @nn.compact
    def __call__(self, ids):
        e = self.param("embedding", nn.initializers.normal(0.02),
                       (VOCAB, HIDDEN))
        return e[ids]


def _tied_head_fwd(module, variables, h):
    # reuse the embedding matrix transposed as the LM head
    return h @ variables["params"]["embedding"].T


@_SPMD_PIPE
def test_tied_layer_spec_shares_params(eight_devices, rng):
    from deepspeed_tpu.runtime.pipe import TiedLayerSpec
    specs = ([TiedLayerSpec("embed", TiedEmbed)] +
             [LayerSpec(Block) for _ in range(4)] +
             [TiedLayerSpec("embed", TiedEmbed,
                            forward_fn=_tied_head_fwd)])
    pm = PipelineModule(specs, num_stages=4, loss_fn=ce_loss)
    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.init_params(batch)
    params = engine.state.master_params["params"]
    assert "tied_embed" in params          # ONE shared entry
    assert "post_0" not in params
    loss = float(engine.train_batch(batch=batch))
    assert np.isfinite(loss)
    assert engine.micro_steps == 4         # counts pipeline microbatches


@_SPMD_PIPE
def test_non_uniform_weighted_parts(eight_devices, rng):
    """Explicit layer_weights produce non-uniform stages (reference:
    pipe/module.py:387 param-count balancing) that train with loss
    parity against the sequential model."""
    from deepspeed_tpu.runtime.pipe.engine import _PipelinedLM
    specs = ([LayerSpec(EmbedLayer)] +
             [LayerSpec(Block) for _ in range(6)] +
             [LayerSpec(Head)])
    pm = PipelineModule(specs, num_stages=4, loss_fn=ce_loss,
                        layer_weights=[5, 1, 1, 1, 1, 1, 1, 5])
    wrapper = _PipelinedLM(pm, num_stages=4, num_microbatches=4)
    counts = wrapper.stage_block_counts
    assert sum(counts) == 6
    assert len(set(counts)) > 1, f"expected non-uniform, got {counts}"

    config = {"train_micro_batch_size_per_gpu": 2,
              "gradient_accumulation_steps": 4,
              "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
              "zero_optimization": {"stage": 0},
              "steps_per_print": 0}
    engine, _, _, _ = deepspeed_tpu.initialize(model=pm, config=config)
    gbs = engine.train_batch_size()
    ids = rng.integers(0, VOCAB, size=(gbs, 8), dtype=np.int32)
    batch = {"input_ids": ids, "labels": ids.copy()}
    engine.init_params(batch)
    pipe_loss = float(engine.eval_batch(batch=batch))

    params = jax.device_get(engine.get_params())["params"]
    h = EmbedLayer().apply({"params": params["pre_0"]}, ids)
    for lp in engine.module.unstack_blocks(params):
        h = Block().apply({"params": lp}, h)
    logits = Head().apply({"params": params["post_0"]}, h)
    np.testing.assert_allclose(pipe_loss, float(ce_loss(logits, ids)),
                               rtol=1e-4)

    loss = float(engine.train_batch(batch=batch))
    assert np.isfinite(loss)


@_SPMD_PIPE
def test_pipeline_remat_bounds_saved_activations(eight_devices, rng):
    """Memory-profile evidence for the GPIPE schedule: with remat on,
    the backward saves only the per-tick carry chain instead of every
    layer's internals — saved residuals shrink vs remat off. (The 1f1b
    schedule manages its own activations; see test_pipeline_1f1b.py.)"""
    from jax._src.ad_checkpoint import saved_residuals
    from deepspeed_tpu.runtime.pipe.engine import _PipelinedLM

    mesh_manager.reset()
    mesh_manager.init(MeshConfig(pipe=4, data=2), devices=eight_devices)
    ids = rng.integers(0, VOCAB, size=(8, 8), dtype=np.int32)

    def build(remat):
        pm = _pipeline_module(n_blocks=4, num_stages=4,
                              schedule="gpipe")
        w = _PipelinedLM(pm, num_stages=4, num_microbatches=4, remat=remat)
        params = w.init(jax.random.PRNGKey(0), ids)

        def loss_fn(params):
            return w.apply(params, ids, labels=ids)

        return loss_fn, params

    f_remat, p1 = build(True)
    f_plain, p2 = build(False)
    n_remat = len(saved_residuals(f_remat, p1))
    n_plain = len(saved_residuals(f_plain, p2))
    assert n_remat < n_plain, (n_remat, n_plain)
    # numerics unchanged
    np.testing.assert_allclose(float(f_remat(p1)), float(f_plain(p1)),
                               rtol=1e-5)
