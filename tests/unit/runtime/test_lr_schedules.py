"""LR schedule math (reference: tests/unit/runtime/test_lr_schedulers.py)."""

import math

import pytest

from deepspeed_tpu.runtime.lr_schedules import (LRScheduler, get_lr_schedule,
                                                one_cycle, warmup_cosine_lr,
                                                warmup_decay_lr, warmup_lr)


def test_warmup_lr():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10,
                  warmup_type="linear")
    assert s(0) == 0.0
    assert abs(s(5) - 0.05) < 1e-9
    assert s(10) == 0.1
    assert s(100) == 0.1


def test_warmup_log_rate():
    s = warmup_lr(warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=100,
                  warmup_type="log")
    assert s(0) == 0.0
    assert s(50) < 0.1
    assert s(100) == 0.1


def test_warmup_decay():
    s = warmup_decay_lr(total_num_steps=100, warmup_max_lr=0.1,
                        warmup_num_steps=10, warmup_type="linear")
    assert abs(s(10) - 0.1) < 1e-9
    assert abs(s(100)) < 1e-9
    assert s(55) == pytest.approx(0.05)


def test_warmup_cosine():
    s = warmup_cosine_lr(total_num_steps=100, warmup_num_steps=10, base_lr=1.0,
                         cos_min_ratio=0.0)
    assert s(10) == pytest.approx(1.0)
    assert s(100) == pytest.approx(0.0, abs=1e-6)
    assert s(55) == pytest.approx(0.5, abs=0.01)


def test_one_cycle():
    s = one_cycle(cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10)
    assert s(0) == pytest.approx(0.01)
    assert s(10) == pytest.approx(0.1)
    assert s(20) == pytest.approx(0.01)


def test_scheduler_object_api():
    sched = LRScheduler(get_lr_schedule("WarmupLR", {
        "warmup_min_lr": 0, "warmup_max_lr": 0.1, "warmup_num_steps": 10,
        "warmup_type": "linear"}))
    for _ in range(5):
        sched.step()
    assert sched.get_lr()[0] == pytest.approx(0.05)
    sd = sched.state_dict()
    sched2 = LRScheduler(get_lr_schedule("WarmupLR", {
        "warmup_max_lr": 0.1, "warmup_num_steps": 10, "warmup_type": "linear"}))
    sched2.load_state_dict(sd)
    assert sched2.get_lr() == sched.get_lr()


def test_unknown_schedule():
    with pytest.raises(ValueError):
        get_lr_schedule("Nope", {})
