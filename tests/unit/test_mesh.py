import jax
import numpy as np
import pytest

from deepspeed_tpu.parallel.mesh import (MESH_AXES, MeshConfig, build_mesh,
                                         mesh_manager)


def test_mesh_config_resolution():
    cfg = MeshConfig(data=-1).resolved(8)
    assert cfg.data == 8
    assert cfg.shape == (1, 8, 1, 1, 1, 1)

    cfg = MeshConfig(data=2, fsdp=-1).resolved(8)
    assert cfg.fsdp == 4

    with pytest.raises(ValueError):
        MeshConfig(data=3).resolved(8)
    with pytest.raises(ValueError):
        MeshConfig(data=-1, fsdp=-1).resolved(8)


def test_build_mesh_axes(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    assert mesh.axis_names == MESH_AXES
    assert mesh.shape["data"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tensor"] == 2
    assert mesh.size == 8


def test_mesh_manager_queries(eight_devices):
    mesh_manager.init(MeshConfig(data=2, fsdp=4))
    assert mesh_manager.world_size() == 8
    assert mesh_manager.data_parallel_world_size() == 8  # data * fsdp
    assert mesh_manager.model_parallel_world_size() == 1
    sh = mesh_manager.sharding("data")
    assert sh.mesh.size == 8
