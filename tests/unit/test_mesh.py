import jax
import numpy as np
import pytest

from deepspeed_tpu.parallel.mesh import (MESH_AXES, MeshConfig, build_mesh,
                                         mesh_manager)


def test_mesh_config_resolution():
    cfg = MeshConfig(data=-1).resolved(8)
    assert cfg.data == 8
    assert cfg.shape == (1, 8, 1, 1, 1, 1)

    cfg = MeshConfig(data=2, fsdp=-1).resolved(8)
    assert cfg.fsdp == 4

    with pytest.raises(ValueError):
        MeshConfig(data=3).resolved(8)
    with pytest.raises(ValueError):
        MeshConfig(data=-1, fsdp=-1).resolved(8)


def test_build_mesh_axes(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, fsdp=2, tensor=2))
    assert mesh.axis_names == MESH_AXES
    assert mesh.shape["data"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["tensor"] == 2
    assert mesh.size == 8


def test_mesh_manager_queries(eight_devices):
    mesh_manager.init(MeshConfig(data=2, fsdp=4))
    assert mesh_manager.world_size() == 8
    assert mesh_manager.data_parallel_world_size() == 8  # data * fsdp
    assert mesh_manager.model_parallel_world_size() == 1
    sh = mesh_manager.sharding("data")
    assert sh.mesh.size == 8


class TestMultiSlice:
    """ICI x DCN hybrid mesh (reference seam: SURVEY §2.3 DCN note +
    groups.py:572 intra/inter-node split, generalized to slices)."""

    def test_dcn_axis_strides_across_slices(self, eight_devices):
        """2 slices of 4 virtual chips, data across DCN: every non-data
        axis neighbourhood stays within one slice; moving along data
        crosses slices."""
        from deepspeed_tpu.parallel.mesh import (MeshConfig,
                                                 mesh_manager)
        mesh_manager.reset()
        mesh = mesh_manager.init(
            MeshConfig(data=2, fsdp=2, tensor=2, num_slices=2,
                       dcn_axes=("data",)),
            devices=eight_devices)
        assert mesh_manager.dcn_axis_names() == ("data",)
        assert mesh_manager.is_dcn_axis("data")
        assert not mesh_manager.is_dcn_axis("fsdp")
        assert mesh_manager.is_dcn_axis(("data", "fsdp"))

        # virtual fallback: slice i = devices[i*4:(i+1)*4]
        slice_of = {id(d): i // 4 for i, d in enumerate(eight_devices)}
        arr = mesh.devices  # [pipe,data,expert,fsdp,seq,tensor]
        squeezed = arr.reshape(2, 2, 2)  # data, fsdp, tensor
        for di in range(2):
            slices = {slice_of[id(d)]
                      for d in squeezed[di].reshape(-1)}
            assert len(slices) == 1, \
                f"fsdp/tensor block at data={di} spans slices {slices}"
        # the two data rows live on different slices
        s0 = slice_of[id(squeezed[0, 0, 0])]
        s1 = slice_of[id(squeezed[1, 0, 0])]
        assert s0 != s1

    def test_dcn_factor_validation(self):
        from deepspeed_tpu.parallel.mesh import MeshConfig
        import pytest as _pytest
        cfg = MeshConfig(data=3, fsdp=1, num_slices=2,
                         dcn_axes=("data",))
        with _pytest.raises(ValueError, match="divisible"):
            cfg.dcn_factors()
        cfg2 = MeshConfig(data=2, fsdp=2, num_slices=4,
                          dcn_axes=("data", "fsdp"))
        with _pytest.raises(ValueError, match="explicit factors"):
            cfg2.dcn_factors()
        cfg3 = MeshConfig(data=2, fsdp=2, num_slices=4,
                          dcn_axes={"data": 2, "fsdp": 2})
        assert cfg3.dcn_factors() == {"data": 2, "fsdp": 2}

    @pytest.mark.slow  # tier-1 diet (ISSUE 7): the cheap multi-slice layout tests stay
    def test_auto_quantized_gradients_on_dcn_fsdp(self, eight_devices):
        """zero_quantized_gradients="auto": the int8 grad exchange is
        selected exactly when the fsdp axis crosses the DCN."""
        import jax
        import numpy as np
        import deepspeed_tpu
        from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
        from deepspeed_tpu.parallel.mesh import (MeshConfig,
                                                 mesh_manager)

        def compiled_has_s8(num_slices, dcn_axes):
            mesh_manager.reset()
            mesh_manager.init(MeshConfig(data=1, fsdp=8,
                                         num_slices=num_slices,
                                         dcn_axes=dcn_axes),
                              devices=eight_devices)
            model = GPT2LMHeadModel(GPT2Config.tiny())
            engine, _, _, _ = deepspeed_tpu.initialize(
                model=model, config={
                    "train_micro_batch_size_per_gpu": 1,
                    "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                    "zero_optimization": {
                        "stage": 2, "zero_quantized_gradients": "auto"},
                    "steps_per_print": 0})
            ids = np.zeros((engine.train_batch_size(), 8), np.int32)
            b = {"input_ids": ids, "labels": ids.copy()}
            engine.init_params(b)
            engine._compile_train_step()
            db = engine._shard_batch(engine._split_microbatches(b),
                                     leading_gas=True)
            txt = engine._jit_train_step.lower(
                engine.state, db, jax.random.PRNGKey(0),
                (), False).compile().as_text()
            return any("s8[" in l for l in txt.splitlines()
                       if "all-to-all" in l or "all-gather" in l)

        assert compiled_has_s8(2, ("fsdp",)) is True
        assert compiled_has_s8(1, ()) is False
