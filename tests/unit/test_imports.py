"""Every subpackage and module must import cleanly.

Closes the round-1 hole where ``deepspeed_tpu.elasticity`` shipped
re-exporting modules that did not exist and nothing noticed.
"""

import importlib
import pkgutil

import deepspeed_tpu


def _iter_module_names():
    yield "deepspeed_tpu"
    for info in pkgutil.walk_packages(deepspeed_tpu.__path__,
                                      prefix="deepspeed_tpu."):
        yield info.name


def test_all_modules_importable():
    failures = []
    for name in _iter_module_names():
        try:
            importlib.import_module(name)
        except Exception as e:  # noqa: BLE001 — collecting all failures
            failures.append(f"{name}: {type(e).__name__}: {e}")
    assert not failures, "unimportable modules:\n" + "\n".join(failures)
