"""Cross-topology checkpoint restore (reference:
checkpoint/ds_to_universal.py:352 + universal_checkpoint.py:22 — any
(TP, PP, DP) target loads a checkpoint saved elsewhere).

TPU-native: checkpoints store logical arrays; the loader re-shards into
the CURRENT mesh via explicit per-leaf restore shardings
(checkpoint/engine.py load_checkpoint), so dp/fsdp/tp reshapes need no
offline step. Pipeline-topology changes re-stage the [stages, max_k]
stacked block leaves (PipelineEngine.load_checkpoint +
universal.restack_block_leaf).
"""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

SEED = 7
SEQ = 16


def _batch(engine, seed=SEED):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, 256, size=(engine.train_batch_size(), SEQ),
                       dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


def _make_engine(mesh_kwargs, stage=3):
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(**mesh_kwargs))
    # the GLOBAL batch is pinned so every topology trains/evals on the
    # identical logical batch (the per-device micro size reconciles
    # per mesh — the reference's batch invariant, runtime/config.py)
    config = {
        "train_batch_size": 16,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": stage},
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config=config)
    return engine


class TestMeshReshape:
    """Save on dp2 x fsdp2 x tp2, restore on pure-fsdp8 and on
    tp4 x data2: eval parity at load + identical subsequent losses."""

    @pytest.fixture(scope="class")
    def saved(self, tmp_path_factory):
        tmp = tmp_path_factory.mktemp("xtopo")
        eng = _make_engine({"data": 2, "fsdp": 2, "tensor": 2})
        b = _batch(eng)
        for _ in range(3):
            eng.train_batch(batch=b)
        eng.save_checkpoint(str(tmp))
        ref_eval = float(eng.eval_batch(batch=b))
        # the reference continuation on the ORIGINAL topology
        ref_cont = [float(eng.train_batch(batch=b)) for _ in range(3)]
        return {"dir": str(tmp), "eval": ref_eval, "cont": ref_cont,
                "steps": 3}

    # tier-1 diet (PR 5): every reshape rides the slow tier — the
    # sharded-checkpoint suite keeps the save/restore tier-1 smokes
    @pytest.mark.parametrize("mesh_kwargs", [
        pytest.param({"data": 1, "fsdp": 8},
                     marks=pytest.mark.slow),
        pytest.param({"data": 2, "tensor": 4},
                     marks=pytest.mark.slow),
        pytest.param({"data": 4, "fsdp": 2},
                     marks=pytest.mark.slow),
    ], ids=["fsdp8", "tp4xdata2", "data4xfsdp2"])
    def test_restore_on_new_topology(self, saved, mesh_kwargs,
                                     eight_devices):
        eng = _make_engine(mesh_kwargs)
        b = _batch(eng)
        eng.init_params(b)
        eng.load_checkpoint(saved["dir"])
        assert eng.global_steps == saved["steps"]
        got = float(eng.eval_batch(batch=b))
        np.testing.assert_allclose(got, saved["eval"], rtol=2e-3)
        # subsequent training reproduces the original topology's run
        # (reduction orders differ across meshes -> small fp drift)
        cont = [float(eng.train_batch(batch=b)) for _ in range(3)]
        np.testing.assert_allclose(cont, saved["cont"], rtol=5e-3)


from tests.conftest import SKIP_OLD_XLA_PIPE as _SPMD_PIPE


class TestPipelineReshape:
    """pipe2 x data4 -> pipe4 x data2: the stacked block leaves are
    re-staged and training continues at loss parity."""

    def _pipe_engine(self, pipe, data, n_blocks=4):
        import flax.linen as nn
        import jax
        import jax.numpy as jnp

        from deepspeed_tpu.runtime.pipe import (LayerSpec,
                                                PipelineEngine,
                                                PipelineModule)

        H, V = 16, 64

        class Embed(nn.Module):
            @nn.compact
            def __call__(self, ids):
                e = self.param("embedding",
                               nn.initializers.normal(0.02), (V, H))
                return e[ids]

        class Block(nn.Module):
            @nn.compact
            def __call__(self, x):
                return x + nn.Dense(H)(nn.relu(nn.Dense(2 * H)(x)))

        class Head(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(V)(x)

        def ce(logits, labels):
            lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
            return -jnp.mean(jnp.take_along_axis(
                lp, labels[..., None], axis=-1))

        mesh_manager.reset()
        mesh_manager.init(MeshConfig(pipe=pipe, data=data))
        mod = PipelineModule(
            [LayerSpec(Embed)] +
            [LayerSpec(Block) for _ in range(n_blocks)] +
            [LayerSpec(Head)], num_stages=pipe, loss_fn=ce)
        config = {
            "train_batch_size": 16,
            "gradient_accumulation_steps": 4,
            "optimizer": {"type": "AdamW", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 0},
            "steps_per_print": 0,
        }
        return PipelineEngine(mod, config=config)

    @_SPMD_PIPE
    def test_pipe2_to_pipe4(self, eight_devices, tmp_path):
        eng = self._pipe_engine(pipe=2, data=4)
        rng = np.random.default_rng(SEED)
        ids = rng.integers(0, 64,
                           size=(eng.train_batch_size(), SEQ),
                           dtype=np.int32)
        b = {"input_ids": ids, "labels": ids.copy()}
        eng.init_params(b)
        for _ in range(3):
            eng.train_batch(batch=b)
        eng.save_checkpoint(str(tmp_path))
        ref_cont = [float(eng.train_batch(batch=b)) for _ in range(2)]

        eng4 = self._pipe_engine(pipe=4, data=2)
        assert eng4.train_batch_size() == eng.train_batch_size()
        eng4.init_params(b)
        eng4.load_checkpoint(str(tmp_path))
        assert eng4.global_steps == 3
        # same global batch content on the new topology
        cont = [float(eng4.train_batch(batch=b)) for _ in range(2)]
        np.testing.assert_allclose(cont, ref_cont, rtol=5e-3)

    def test_restack_leaf_math(self):
        from deepspeed_tpu.checkpoint.universal import restack_block_leaf
        # 5 layers over 2 stages (3+2, max_k 3) -> 4 stages (2+1+1+1)
        arr = np.zeros((2, 3, 2))
        vals = np.arange(5, dtype=np.float64)
        pos = [(0, 0), (0, 1), (0, 2), (1, 0), (1, 1)]
        for v, (s, l) in zip(vals, pos):
            arr[s, l] = v
        out = restack_block_leaf(arr, [3, 2], [2, 1, 1, 1], 2)
        assert out.shape == (4, 2, 2)
        flat = [out[s, l] for s, c in enumerate([2, 1, 1, 1])
                for l in range(c)]
        np.testing.assert_array_equal(
            np.stack(flat)[:, 0], vals)
        with pytest.raises(ValueError, match="layers"):
            restack_block_leaf(arr, [3, 2], [2, 2, 2], 2)
