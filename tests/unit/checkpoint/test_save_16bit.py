"""engine.save_16bit_model parity (reference: engine.py save_16bit_model
— consolidates ZeRO-3 shards into one 16-bit state file, gated on
zero_optimization.gather_16bit_weights_on_model_save)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
from deepspeed_tpu.parallel.mesh import mesh_manager


def _engine(zero_overrides, seed=11):
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": zero_overrides,
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model, config=cfg, rng=jax.random.PRNGKey(seed))
    return engine


def _batch(rng):
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    return {"input_ids": ids, "labels": ids.copy()}


@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_zero3_gated_without_gather_flag(tmp_path, rng, eight_devices):
    engine = _engine({"stage": 3})
    engine.train_batch(batch=_batch(rng))
    assert engine.save_16bit_model(str(tmp_path)) is False
    assert not os.path.exists(tmp_path / "model_16bit.npz")


@pytest.mark.slow  # tier-1 diet (ISSUE 14)
def test_zero3_gathers_full_weights(tmp_path, rng, eight_devices):
    from deepspeed_tpu.checkpoint import load_16bit_state
    from deepspeed_tpu.utils.tree import flatten_with_names

    engine = _engine({"stage": 3, "gather_16bit_weights_on_model_save": True})
    engine.train_batch(batch=_batch(rng))
    assert engine.save_16bit_model(str(tmp_path)) is True
    data = load_16bit_state(tmp_path / "model_16bit.npz")
    # every master leaf present, in compute dtype, at FULL shape
    names, leaves, _ = flatten_with_names(engine.state.master_params)
    assert sorted(data) == sorted(names)
    for name, leaf in zip(names, leaves):
        arr = data[name]
        assert arr.shape == leaf.shape, name
        if np.issubdtype(np.asarray(leaf).dtype, np.floating):
            assert arr.dtype == jnp.bfloat16, (name, arr.dtype)


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_saved_weights_match_stage0_math(tmp_path, rng, eight_devices):
    """Stage-3 sharded training then save must produce the same 16-bit
    file as replicated training from the same seed — consolidation must
    not reorder or lose fragments."""
    from deepspeed_tpu.checkpoint import load_16bit_state

    batch = _batch(rng)
    files = {}
    for stage in (0, 3):
        mesh_manager.reset()
        engine = _engine({"stage": stage,
                          "gather_16bit_weights_on_model_save": True},
                         seed=5)
        for _ in range(3):
            engine.train_batch(batch=batch)
        out = tmp_path / f"s{stage}"
        assert engine.save_16bit_model(str(out)) is True
        files[stage] = load_16bit_state(out / "model_16bit.npz")
    for name in files[0]:
        a = files[0][name].astype(np.float32)
        b = files[3][name].astype(np.float32)
        np.testing.assert_allclose(a, b, rtol=2e-2, atol=1e-3,
                                   err_msg=name)


@pytest.mark.slow  # tier-1 diet (ISSUE 7): the zero3 gather smoke stays
def test_custom_filename_and_atomicity(tmp_path, rng, eight_devices):
    engine = _engine({"stage": 1})
    engine.train_batch(batch=_batch(rng))
    assert engine.save_16bit_model(str(tmp_path), "weights.npz") is True
    assert (tmp_path / "weights.npz").exists()
    # no tmp file left behind
    assert not any(".tmp" in p.name for p in tmp_path.iterdir())


def test_save_before_init_raises(tmp_path, eight_devices):
    import pytest
    engine = _engine({"stage": 1})
    with pytest.raises(ValueError, match="before parameters exist"):
        engine.save_16bit_model(str(tmp_path))


@pytest.mark.slow  # tier-1 diet (ISSUE 7)
def test_exclude_frozen_rejected(tmp_path, rng, eight_devices):
    import pytest
    engine = _engine({"stage": 1})
    engine.train_batch(batch=_batch(rng))
    with pytest.raises(NotImplementedError):
        engine.save_16bit_model(str(tmp_path), exclude_frozen_parameters=True)
