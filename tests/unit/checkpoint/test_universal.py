"""Universal-checkpoint fragment export/import tests (reference analog:
tests/unit/checkpoint/test_universal_checkpoint.py)."""

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.checkpoint.engine import save_checkpoint
from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                load_universal_params,
                                                zero_to_fp32)
from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel


@pytest.fixture(scope="module")
def trained_engine():
    model = GPT2LMHeadModel(GPT2Config.tiny())
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0,
    }
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config=config)
    ids = np.random.default_rng(0).integers(
        0, 256, size=(engine.train_batch_size(), 32), dtype=np.int32)
    engine.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    return engine


def test_ds_to_universal_roundtrip(trained_engine, tmp_path):
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), "step1", trained_engine.state,
                    client_state={"step": 1})
    out = tmp_path / "universal"
    ds_to_universal(str(ckpt), str(out), template_state=trained_engine.state)

    frags = load_universal_params(str(out))
    assert frags, "no fragments written"
    # every master param appears, fp32, with matching values
    from deepspeed_tpu.utils.tree import flatten_with_names
    names, leaves, _ = flatten_with_names(trained_engine.state.master_params)
    for name, leaf in zip(names, leaves):
        assert name in frags, f"missing fragment for {name}"
        assert frags[name].dtype == np.float32
        np.testing.assert_allclose(frags[name],
                                   np.asarray(leaf, np.float32), rtol=1e-6)
    # Adam moments exported alongside fp32 weights
    import os
    mom_files = []
    for dirpath, _, files in os.walk(out / "zero"):
        mom_files += [f for f in files if f.startswith("exp_avg")]
    assert mom_files, "no optimizer moments exported"


def test_zero_to_fp32(trained_engine, tmp_path):
    ckpt = tmp_path / "ckpt"
    save_checkpoint(str(ckpt), "final", trained_engine.state)
    sd = zero_to_fp32(str(ckpt), str(tmp_path / "fp32.pkl"),
                      template_state=trained_engine.state)
    assert sd and all(v.dtype == np.float32 for v in sd.values())


def test_fragment_paths_collision_free(tmp_path):
    """'a/b_c' and 'a_b/c'-style names must not collide (advisor finding:
    the old name.replace('/', '_') mapping collapsed them)."""
    from deepspeed_tpu.checkpoint.universal import _esc

    assert _esc("a.b") != _esc("a_b")
    assert _esc("..") not in (".", "..")
    # nested segments stay separate directories, so these trees differ
    t1 = {"a": {"b_c": np.ones(2, np.float32)}}
    t2 = {"a_b": {"c": np.zeros(2, np.float32)}}
    from deepspeed_tpu.utils.tree import flatten_with_name_parts
    p1, _, _ = flatten_with_name_parts(t1)
    p2, _, _ = flatten_with_name_parts(t2)
    import os
    d1 = os.path.join(*[_esc(s) for s in p1[0]])
    d2 = os.path.join(*[_esc(s) for s in p2[0]])
    assert d1 != d2


class TestCheckpointEngines:
    """Pluggable sync/async engines (reference:
    runtime/checkpoint_engine/ + nebula async tier)."""

    def test_async_engine_roundtrip(self, trained_engine, tmp_path):
        from deepspeed_tpu.checkpoint.checkpoint_engine import (
            AsyncCheckpointEngine)
        eng = AsyncCheckpointEngine()
        fut = eng.save(trained_engine.state, str(tmp_path / "ck"), "t1")
        assert eng.commit("t1")
        assert fut.done()
        state, _ = eng.load(str(tmp_path / "ck"), "t1",
                            trained_engine.state)
        import jax
        a = jax.tree_util.tree_leaves(trained_engine.state.master_params)
        b = jax.tree_util.tree_leaves(state.master_params)
        np.testing.assert_allclose(np.asarray(a[0]), np.asarray(b[0]))

    def test_engine_config_selection(self, trained_engine, tmp_path):
        from deepspeed_tpu.checkpoint.checkpoint_engine import (
            AsyncCheckpointEngine, SyncCheckpointEngine,
            get_checkpoint_engine)
        assert isinstance(get_checkpoint_engine({}), SyncCheckpointEngine)
        assert isinstance(
            get_checkpoint_engine({"checkpoint_engine": {"type": "async"}}),
            AsyncCheckpointEngine)

    def test_engine_save_checkpoint_via_plugin(self, trained_engine,
                                               tmp_path):
        import os
        trained_engine._checkpoint_engine = None
        trained_engine.save_checkpoint(str(tmp_path / "ck2"), tag="s")
        assert os.path.exists(tmp_path / "ck2" / "latest")
