"""Checkpoint fault injection (round-3 verdict weak item 4): kill a
training process mid-save and verify the crash-recovery contract — if
``latest`` exists it names a COMPLETE, loadable checkpoint (async saves
commit the ``latest`` pointer last, atomically)."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

WORKER = textwrap.dedent("""
    import os, sys, time
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

    ckpt = sys.argv[1]
    slow_ms = int(sys.argv[2])     # injected slowness inside the save

    if slow_ms:
        # fault injection: make the state write slow so SIGKILL lands
        # mid-save with high probability
        import deepspeed_tpu.checkpoint.engine as ce
        real = ce.save_checkpoint
        def slow_save(save_dir, tag, state, **kw):
            time.sleep(slow_ms / 1e3)
            return real(save_dir, tag, state, **kw)
        ce.save_checkpoint = slow_save
        import deepspeed_tpu.checkpoint.checkpoint_engine as cce
        cce.save_checkpoint = slow_save

    mesh_manager.init(MeshConfig(data=-1))
    config = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "checkpoint_engine": {"type": "async"},
        "steps_per_print": 0,
    }
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model,
                                               config=config)
    ids = np.zeros((engine.train_batch_size(), 16), np.int32)
    b = {"input_ids": ids, "labels": ids}
    for step in range(4):
        engine.train_batch(batch=b)
        engine.save_checkpoint(ckpt)   # async commit inside
    # fire one more async save and kill ourselves while it runs
    engine.train_batch(batch=b)
    engine.checkpoint_engine.create("t5")
    engine.checkpoint_engine.save(engine.state, ckpt, "t5",
                                  client_state={"global_steps": 5})
    # abrupt death with the async save still in flight (os._exit skips
    # every flush/atexit, emulating a kill; 137 = 128+SIGKILL so the
    # parent assert reads like a kill)
    os._exit(137)
""")


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_kill_mid_save_preserves_latest_integrity(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    ckpt = tmp_path / "ckpt"
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["DS_ACCELERATOR"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(script), str(ckpt), "800"],
        env=env, timeout=600)
    assert proc.returncode == 137    # died with the save in flight

    # the contract: latest (written atomically, after the state) names
    # a COMPLETE checkpoint — the in-flight t5 must not have corrupted it
    latest_path = ckpt / "latest"
    assert latest_path.exists()
    tag = latest_path.read_text().strip()
    assert tag != "t5", "latest advanced to an uncommitted save"

    import jax
    jax.config.update("jax_platforms", "cpu")
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager
    mesh_manager.reset()
    mesh_manager.init(MeshConfig(data=-1))
    model = GPT2LMHeadModel(GPT2Config.tiny())
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 0})
    ids = np.zeros((engine.train_batch_size(), 16), np.int32)
    engine.init_params({"input_ids": ids, "labels": ids})
    engine.load_checkpoint(str(ckpt))
    assert engine.global_steps == 4
    # training continues from the recovered state
    loss = float(engine.train_batch(batch={"input_ids": ids,
                                           "labels": ids}))
    assert np.isfinite(loss)


def test_atomic_latest_write(tmp_path):
    """The latest pointer is written via tmp+rename — no window where
    a reader sees a truncated file."""
    from deepspeed_tpu.checkpoint.engine import _atomic_write
    p = tmp_path / "latest"
    _atomic_write(str(p), "global_step7")
    assert p.read_text() == "global_step7"
    assert not list(tmp_path.glob("latest.tmp*"))
