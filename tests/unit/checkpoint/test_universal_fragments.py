"""Universal-checkpoint fragment machinery edge cases (reference:
deepspeed/checkpoint/ds_to_universal.py + reshape utils — path-segment
escaping must be collision-free, PP re-staging must be exact index
arithmetic with hard errors on layer-count mismatch)."""

import numpy as np
import pytest

from deepspeed_tpu.checkpoint.universal import (_esc, _unesc,
                                                restack_block_leaf)


@pytest.mark.parametrize("segment", [
    "weight", "layers_0", "a.b", "..", ".", "", "%empty", "a%2Eb",
    "a/b", "weird name", "ünïcode", "%", "%%", "a" * 200,
])
def test_escape_roundtrip_is_injective(segment):
    escaped = _esc(segment)
    assert _unesc(escaped) == segment
    # must be a safe single directory name
    assert "/" not in escaped and escaped not in (".", "..", "")


def test_escape_distinct_inputs_never_collide():
    tricky = ["a.b", "a%2Eb", "a%252Eb", "", "%empty", ".", "..",
              "a b", "a%20b"]
    escaped = [_esc(s) for s in tricky]
    assert len(set(escaped)) == len(escaped), escaped


def test_restack_identity():
    arr = np.arange(2 * 2 * 3, dtype=np.float32).reshape(2, 2, 3)
    out = restack_block_leaf(arr, src_counts=[2, 2], tgt_counts=[2, 2],
                             tgt_max_k=2)
    np.testing.assert_array_equal(out, arr)


def test_restack_4_stages_to_2():
    # 4 stages x 1 layer -> 2 stages x 2 layers, pipeline order kept
    arr = np.stack([np.full((1, 3), s, np.float32) for s in range(4)])
    out = restack_block_leaf(arr, src_counts=[1, 1, 1, 1],
                             tgt_counts=[2, 2], tgt_max_k=2)
    assert out.shape == (2, 2, 3)
    np.testing.assert_array_equal(out[0, 0], np.full(3, 0))
    np.testing.assert_array_equal(out[0, 1], np.full(3, 1))
    np.testing.assert_array_equal(out[1, 0], np.full(3, 2))
    np.testing.assert_array_equal(out[1, 1], np.full(3, 3))


def test_restack_nonuniform_with_padding():
    # src: stage0 has 3 layers, stage1 has 1 (padded to K=3)
    layers = [np.full((2,), v, np.float32) for v in range(4)]
    src = np.zeros((2, 3, 2), np.float32)
    src[0, :3] = np.stack(layers[:3])
    src[1, 0] = layers[3]
    out = restack_block_leaf(src, src_counts=[3, 1], tgt_counts=[1, 3],
                             tgt_max_k=3)
    np.testing.assert_array_equal(out[0, 0], layers[0])
    np.testing.assert_array_equal(out[1, 0], layers[1])
    np.testing.assert_array_equal(out[1, 2], layers[3])
    # padding slots stay zero
    np.testing.assert_array_equal(out[0, 1], np.zeros(2))


def test_restack_layer_count_mismatch_raises():
    arr = np.zeros((2, 2, 3), np.float32)
    with pytest.raises(ValueError, match="restack"):
        restack_block_leaf(arr, src_counts=[2, 2], tgt_counts=[3, 2],
                           tgt_max_k=3)


@pytest.mark.slow  # tier-1 diet (PR 5)
def test_fragment_explode_and_readback(tmp_path, rng, eight_devices):
    """End-to-end: train, save, explode to fragments, read back — every
    master leaf appears once at full shape with Adam moments."""
    import jax

    import deepspeed_tpu
    from deepspeed_tpu.checkpoint.universal import (ds_to_universal,
                                                    load_universal_params)
    from deepspeed_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel
    from deepspeed_tpu.utils.tree import flatten_with_names

    engine, _, _, _ = deepspeed_tpu.initialize(
        model=GPT2LMHeadModel(GPT2Config.tiny()),
        config={"train_batch_size": 8,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 2},
                "steps_per_print": 0},
        rng=jax.random.PRNGKey(0))
    ids = rng.integers(0, 256, size=(8, 16), dtype=np.int32)
    engine.train_batch(batch={"input_ids": ids, "labels": ids.copy()})
    ckpt = tmp_path / "ckpt"
    engine.save_checkpoint(str(ckpt), tag="u1")

    uni = tmp_path / "universal"
    ds_to_universal(str(ckpt), str(uni), tag="u1",
                    template_state=engine.state)
    frags = load_universal_params(str(uni))
    names, leaves, _ = flatten_with_names(engine.state.master_params)
    assert sorted(frags) == sorted(names)
    for name, leaf in zip(names, leaves):
        assert frags[name].shape == leaf.shape
        assert frags[name].dtype == np.float32
    # moments exist for at least the dense kernels
    import os
    mom_files = []
    for dirpath, _, files in os.walk(uni):
        mom_files += [f for f in files if f.startswith("exp_avg")]
    assert mom_files, "no Adam moment fragments written"
