"""Sequence parallelism tests: Ulysses all-to-all + ring attention
(reference test shape: tests/unit/ — numeric parity vs local math)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from deepspeed_tpu.utils.jax_compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.pallas_kernels.flash_attention import mha_reference
from deepspeed_tpu.parallel.mesh import (MeshConfig, SEQUENCE_AXIS,
                                         mesh_manager)
from deepspeed_tpu.sequence import (DistributedAttention, ring_attention,
                                    seq_all_to_all, ulysses_attention)


def _qkv(rng, B=2, T=32, Hq=8, Hkv=8, D=16):
    q = rng.standard_normal((B, T, Hq, D)).astype(np.float32)
    k = rng.standard_normal((B, T, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((B, T, Hkv, D)).astype(np.float32)
    return q, k, v


def test_seq_all_to_all_roundtrip(eight_devices, rng):
    mesh = mesh_manager.init(MeshConfig(data=2, sequence=4),
                             devices=eight_devices)
    x = rng.standard_normal((2, 32, 8, 4)).astype(np.float32)

    def fn(t):
        h = seq_all_to_all(t, 2, 1)   # heads scattered, seq gathered
        assert h.shape == (1, 32, 2, 4)  # per-shard view
        return seq_all_to_all(h, 1, 2)

    wrapped = shard_map(fn, mesh=mesh,
                        in_specs=(P("data", SEQUENCE_AXIS),),
                        out_specs=P("data", SEQUENCE_AXIS),
                        check_vma=False)
    np.testing.assert_allclose(np.asarray(jax.jit(wrapped)(x)), x)


def test_ulysses_collective_matches_reference(eight_devices, rng):
    mesh = mesh_manager.init(MeshConfig(data=2, sequence=4),
                             devices=eight_devices)
    q, k, v = _qkv(rng)
    ref = mha_reference(q, k, v, causal=True)

    attn = DistributedAttention(lambda a, b, c: mha_reference(a, b, c,
                                                              causal=True))
    wrapped = shard_map(attn, mesh=mesh,
                        in_specs=(P("data", SEQUENCE_AXIS),) * 3,
                        out_specs=P("data", SEQUENCE_AXIS),
                        check_vma=False)
    out = jax.jit(wrapped)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_spmd_matches_reference(eight_devices, rng):
    mesh = mesh_manager.init(MeshConfig(data=2, sequence=4),
                             devices=eight_devices)
    q, k, v = _qkv(rng)
    ref = mha_reference(q, k, v, causal=True)

    @jax.jit
    def fn(q, k, v):
        return ulysses_attention(
            lambda a, b, c: mha_reference(a, b, c, causal=True), q, k, v)

    seq_sh = NamedSharding(mesh, P(("data", "fsdp"), SEQUENCE_AXIS))
    args = [jax.device_put(t, seq_sh) for t in (q, k, v)]
    out = fn(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 2)])
@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(eight_devices, rng, Hq, Hkv, causal):
    mesh = mesh_manager.init(MeshConfig(data=2, sequence=4),
                             devices=eight_devices)
    q, k, v = _qkv(rng, Hq=Hq, Hkv=Hkv)
    ref = mha_reference(q, k, v, causal=causal)

    wrapped = shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=causal),
        mesh=mesh, in_specs=(P("data", SEQUENCE_AXIS),) * 3,
        out_specs=P("data", SEQUENCE_AXIS), check_vma=False)
    out = jax.jit(wrapped)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_single_shard(rng):
    """sp=1 degenerates to plain attention."""
    mesh = mesh_manager.init(MeshConfig(data=1), devices=jax.devices()[:1])
    q, k, v = _qkv(rng, B=1, T=16)
    ref = mha_reference(q, k, v, causal=True)
    wrapped = shard_map(ring_attention, mesh=mesh,
                        in_specs=(P(None, SEQUENCE_AXIS),) * 3,
                        out_specs=P(None, SEQUENCE_AXIS), check_vma=False)
    out = jax.jit(wrapped)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_attention_gradients_match_reference(eight_devices, rng):
    """Training THROUGH ring attention: reverse-mode AD through the
    scan+ppermute schedule must give the same q/k/v gradients as full
    attention — the long-context training path, not just inference."""
    mesh = mesh_manager.init(MeshConfig(data=2, sequence=4),
                             devices=eight_devices)
    q, k, v = _qkv(rng)

    def ref_loss(q, k, v):
        out = mha_reference(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    wrapped = shard_map(
        lambda a, b, c: ring_attention(a, b, c, causal=True),
        mesh=mesh, in_specs=(P("data", SEQUENCE_AXIS),) * 3,
        out_specs=P("data", SEQUENCE_AXIS), check_vma=False)

    def ring_loss(q, k, v):
        out = wrapped(q, k, v)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)
