"""Launcher tests (reference pattern: tests/unit/launcher/test_run.py).

The multi-process test is the repo's multi-host simulation: two real OS
processes rendezvous through jax.distributed (gRPC coordinator — the
TPU-pod bring-up path) on the CPU backend, each contributing fake local
devices, and run a global psum over the combined mesh.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from deepspeed_tpu.launcher.runner import fetch_hostfile, parse_args
from deepspeed_tpu.launcher.launch import build_env
from deepspeed_tpu.launcher.multinode_runner import (GcloudTPURunner,
                                                     PDSHRunner, SSHRunner)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))


def test_fetch_hostfile(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text(textwrap.dedent("""
        # comment
        worker-0 slots=4
        worker-1 slots=4   # trailing comment
    """))
    pool = fetch_hostfile(str(hf))
    assert pool == {"worker-0": 4, "worker-1": 4}


def test_fetch_hostfile_duplicate(tmp_path):
    hf = tmp_path / "hostfile"
    hf.write_text("w0 slots=2\nw0 slots=4\n")
    with pytest.raises(ValueError):
        fetch_hostfile(str(hf))


def test_build_env_ranks():
    args = parse_args(["--num_procs", "2", "train.py"])

    class A:
        node_rank, nnodes, nproc_per_node = 1, 2, 4
        master_addr, master_port = "10.0.0.1", 29500
        cpu_sim_devices = 0

    env = build_env(A, local_rank=3)
    assert env["RANK"] == "7"
    assert env["WORLD_SIZE"] == "8"
    assert env["LOCAL_RANK"] == "3"
    assert env["JAX_PROCESS_ID"] == "7"
    assert env["JAX_COORDINATOR_ADDRESS"] == "10.0.0.1:29500"


def test_ssh_runner_cmds():
    args = parse_args(["--master_port", "29501", "train.py", "--foo"])
    args.master_addr = "w0"
    args.user_script = "train.py"
    args.user_args = ["--foo"]
    r = SSHRunner(args, {"w0": 4, "w1": 4})
    cmds = r.get_cmd({"PYTHONPATH": "/x"}, None)
    assert len(cmds) == 2
    assert cmds[0][0] == "ssh" and cmds[0][1] == "w0"
    assert "--node_rank=1" in cmds[1][-1]
    assert "PYTHONPATH=/x" in cmds[1][-1]


def test_gcloud_runner_cmd():
    args = parse_args(["train.py"])
    args.master_addr = "w0"
    args.user_script = "train.py"
    args.user_args = []
    r = GcloudTPURunner(args, {"w0": 1, "w1": 1}, tpu_name="pod", zone="z")
    (cmd,) = r.get_cmd({}, None)
    assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "ssh"]
    assert "--worker=all" in cmd


def test_gcloud_runner_real_pod_topology():
    """v5e-16 pod shape: 4 hosts x 1 proc; the composed command must
    carry the full rendezvous (nnodes/nproc/master addr+port), a per-
    worker node_rank derivation, zone placement, quoted user args, and
    the env exports — the things a real `gcloud ... ssh --worker=all`
    launch needs to come up as one jax.distributed world."""
    import shlex
    args = parse_args(["--master_port", "29512", "train.py",
                       "--ds-config", "cfg with space.json"])
    args.master_addr = "t1v-n-abc-w-0"
    args.user_script = "train.py"
    args.user_args = ["--ds-config", "cfg with space.json"]
    pool = {f"w{i}": 1 for i in range(4)}
    r = GcloudTPURunner(args, pool, tpu_name="v5e-pod",
                        zone="us-west4-a")
    (cmd,) = r.get_cmd({"PYTHONPATH": "/repo",
                        "TPU_NAME": "v5e-pod"}, None)
    # gcloud surface: target + worker fan-out + zone before the command
    assert cmd[5] == "v5e-pod"
    zi = cmd.index("--zone=us-west4-a")
    ci = next(i for i, c in enumerate(cmd)
              if c.startswith("--command="))
    assert zi < ci
    remote = cmd[ci][len("--command="):]
    # per-worker rank derivation (hostname suffix -> node_rank)
    assert "--node_rank=$(hostname" in remote
    assert "--nnodes=4" in remote
    assert "--nproc_per_node=1" in remote
    assert "--master_addr=t1v-n-abc-w-0" in remote
    assert "--master_port=29512" in remote
    # env rides along, user args stay quoted through the remote shell
    assert "export PYTHONPATH=/repo;" in remote
    assert "export TPU_NAME=v5e-pod;" in remote
    assert shlex.quote("cfg with space.json") in remote
    # the remote shell parses back to a well-formed invocation
    toks = shlex.split(remote.replace(
        "$(hostname | grep -o '[0-9]*$')", "3"))
    assert "train.py" in toks and "cfg with space.json" in toks


WORKER = """
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import deepspeed_tpu.comm as dist
dist.init_distributed()  # consumes the launcher's rendezvous env
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P
from deepspeed_tpu.parallel.mesh import MeshConfig, mesh_manager

mesh_manager.reset()
mesh_manager.init(MeshConfig(data=jax.device_count()))
mesh = mesh_manager.mesh
n = jax.device_count()
x = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("data")),
    np.full((jax.local_device_count(),), jax.process_index() + 1.0,
            np.float32),
    (n,))
total = jax.jit(lambda v: v.sum(), out_shardings=NamedSharding(mesh, P()))(x)

# multi-host broadcast_object_list (comm.py:396 multi-process branch)
payload = [np.float32(41.0) if jax.process_index() == 0
           else np.float32(-1.0)]
payload = dist.broadcast_object_list(payload, src=0)
bcast_ok = float(np.asarray(payload[0])) == 41.0
# asserts on EVERY process: a failure on rank 1 exits nonzero and the
# launcher's fail-fast turns it into a test failure
assert bcast_ok, f"rank {{jax.process_index()}} got {{payload[0]}}"

# world=2 procs x 2 local devices: sum = 2*1 + 2*2 = 6
if jax.process_index() == 0:
    with open({out!r}, "w") as f:
        f.write(f"{{n}} {{float(total)}} {{int(bcast_ok)}}")
"""


@pytest.mark.slow
def test_multiprocess_cpu_launch(tmp_path):
    """dstpu --num_procs 2 --cpu_sim_devices 2: two processes rendezvous
    and psum over a 4-device global mesh (the multi-host bring-up path)."""
    out = tmp_path / "result.txt"
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO, out=str(out)))
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + ":" + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    r = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--num_procs", "2", "--cpu_sim_devices", "2",
         "--master_port", "29871", str(script)],
        env=env, capture_output=True, text=True, timeout=300, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    n, total, bcast_ok = out.read_text().split()
    assert n == "4" and float(total) == 6.0
    assert bcast_ok == "1", "broadcast_object_list multi-host failed"


def test_slurm_runner_cmd():
    """srun composes one fan-out with per-task rank derivation
    (reference: SlurmRunner multinode_runner.py:242)."""
    import shlex
    from deepspeed_tpu.launcher.multinode_runner import SlurmRunner
    args = parse_args(["--master_port", "29513", "train.py", "--x"])
    args.master_addr = "n0"
    args.user_script = "train.py"
    args.user_args = ["--x"]
    r = SlurmRunner(args, {"n0": 1, "n1": 1, "n2": 1})
    (cmd,) = r.get_cmd({"PYTHONPATH": "/repo"}, None)
    assert cmd[0] == "srun"
    assert "--nodes=3" in cmd and "--ntasks-per-node=1" in cmd
    assert "--nodelist=n0,n1,n2" in cmd
    remote = cmd[-1]
    assert "--node_rank=$SLURM_NODEID" in remote
    assert "--nnodes=3" in remote and "--master_port=29513" in remote
    # coordinator derives from slurm's own nodelist ordering so it can
    # never disagree with SLURM_NODEID==0
    assert "scontrol show hostnames" in remote
    assert "export PYTHONPATH=/repo;" in remote
    toks = shlex.split(remote
                       .replace("$SLURM_NODEID", "1")
                       .replace("$(scontrol show hostnames "
                                "$SLURM_JOB_NODELIST | head -n1)", "n0"))
    assert "train.py" in toks and "--x" in toks


def test_fanout_runners_forward_cpu_sim_devices():
    """--cpu_sim_devices must survive every fan-out runner's remote
    command (review finding: slurm dropped it)."""
    from deepspeed_tpu.launcher.multinode_runner import (GcloudTPURunner,
                                                         PDSHRunner,
                                                         SlurmRunner)
    args = parse_args(["--cpu_sim_devices", "4", "train.py"])
    args.master_addr = "n0"
    args.user_script = "train.py"
    args.user_args = []
    pool = {"n0": 1, "n1": 1}
    for cls, kw in ((PDSHRunner, {}), (SlurmRunner, {}),
                    (GcloudTPURunner, {"tpu_name": "pod"})):
        r = cls(args, pool, **kw)
        (cmd,) = r.get_cmd({}, None)
        remote = cmd[-1]
        assert "--cpu_sim_devices=4" in remote, cls.name
