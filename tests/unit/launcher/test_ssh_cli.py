"""``dstpu ssh`` fan-out CLI (reference: bin/ds_ssh — run one command
on every hostfile host)."""

import pytest

from deepspeed_tpu.launcher.runner import main as runner_main


@pytest.fixture
def hostfile(tmp_path):
    p = tmp_path / "hostfile"
    p.write_text("node1 slots=4\nnode2 slots=4\nnode3 slots=8\n")
    return str(p)


def test_dry_run_builds_one_ssh_per_host(hostfile, capsys):
    rc = runner_main(["ssh", "-f", hostfile, "--dry-run",
                      "hostname", "-f"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 3
    assert lines[0].startswith("ssh -o StrictHostKeyChecking=no node1")
    assert all("hostname -f" in l for l in lines)


def test_include_filters_hosts(hostfile, capsys):
    rc = runner_main(["ssh", "-f", hostfile, "--include", "node2",
                      "--dry-run", "uptime"])
    assert rc == 0
    lines = capsys.readouterr().out.strip().splitlines()
    assert len(lines) == 1 and "node2" in lines[0]


def test_include_matching_nothing_errors(hostfile):
    # a typo'd --include must not silently report success
    rc = runner_main(["ssh", "-f", hostfile, "--include", "nodeX",
                      "--dry-run", "pkill -f train"])
    assert rc == 2


def test_missing_hostfile_errors(tmp_path):
    rc = runner_main(["ssh", "-f", str(tmp_path / "nope"),
                      "--dry-run", "uptime"])
    assert rc == 2


def test_no_command_errors(hostfile):
    with pytest.raises(SystemExit):
        runner_main(["ssh", "-f", hostfile, "--dry-run"])
