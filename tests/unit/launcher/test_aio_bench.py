"""``dstpu bench --aio`` storage microbenchmark (reference:
csrc/aio/py_test/aio_bench_perf_sweep.py — the ds_io role)."""

import pytest

from deepspeed_tpu.launcher.comm_bench import bench_aio, main


def test_bench_aio_measures_both_directions(tmp_path):
    rows = bench_aio(str(tmp_path / "scratch.bin"), size_mb=2, trials=2,
                     n_threads=2, block_mb=1)
    ops = [r["op"] for r in rows]
    assert ops == ["write", "read"]
    for r in rows:
        assert r["GBps"] > 0 and r["time_ms"] > 0
    # scratch file cleaned up
    assert not (tmp_path / "scratch.bin").exists()


def test_cli_routes_aio_mode(tmp_path, capsys):
    rc = main(["--aio", str(tmp_path / "s.bin"), "--size-mb", "2",
               "--trials", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "write" in out and "read" in out and "GB/s" in out
