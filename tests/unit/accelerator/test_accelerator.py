"""Accelerator-abstraction contract tests (reference pattern:
tests/accelerator/ + tests/unit/accelerator/ — every backend must satisfy
the abstract surface and the autodetector must honor DS_ACCELERATOR)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu.accelerator.abstract_accelerator import DeepSpeedAccelerator
from deepspeed_tpu.accelerator.cpu_accelerator import CPU_Accelerator
from deepspeed_tpu.accelerator.real_accelerator import (
    SUPPORTED_ACCELERATOR_LIST, _validate_accelerator, set_accelerator)
from deepspeed_tpu.accelerator.tpu_accelerator import TPU_Accelerator


def test_singleton_honors_ds_accelerator_env():
    # conftest sets DS_ACCELERATOR=cpu before anything imports jax
    accel = get_accelerator()
    assert accel.device_name() == "cpu"
    assert get_accelerator() is accel  # singleton


def test_validate_rejects_unknown_backend():
    with pytest.raises(ValueError):
        _validate_accelerator("cuda")
    for name in SUPPORTED_ACCELERATOR_LIST:
        assert _validate_accelerator(name) == name


def test_set_accelerator_override_roundtrip():
    prev = get_accelerator()
    try:
        other = CPU_Accelerator()
        set_accelerator(other)
        assert get_accelerator() is other
    finally:
        set_accelerator(prev)


@pytest.mark.parametrize("accel_cls", [CPU_Accelerator, TPU_Accelerator])
def test_backend_satisfies_abstract_surface(accel_cls):
    """Every abstract method must be overridden — instantiating fails
    otherwise, and each concrete class must be a DeepSpeedAccelerator."""
    accel = accel_cls()
    assert isinstance(accel, DeepSpeedAccelerator)
    abstract = {m for m in dir(DeepSpeedAccelerator)
                if getattr(getattr(DeepSpeedAccelerator, m), "__isabstractmethod__", False)}
    for name in abstract:
        assert getattr(type(accel), name) is not getattr(DeepSpeedAccelerator, name), \
            f"{accel_cls.__name__} inherits abstract {name}"


def test_cpu_device_enumeration(eight_devices):
    accel = CPU_Accelerator()
    assert accel.device_count() >= 8
    assert accel.global_device_count() == jax.device_count()
    assert accel.device(0).platform == "cpu"
    assert accel.device_name() == "cpu"
    assert accel.device_name(3) == "cpu:3"
    assert accel.is_synchronized_device()


def test_cpu_memory_stats_shape():
    accel = CPU_Accelerator()
    stats = accel.memory_stats()
    assert stats["bytes_in_use"] > 0
    assert stats["bytes_limit"] >= stats["bytes_in_use"]
    assert accel.total_memory() == stats["bytes_limit"]
    assert accel.available_memory() == stats["bytes_limit"] - stats["bytes_in_use"]


def test_dtype_support_and_default():
    accel = CPU_Accelerator()
    assert accel.is_bf16_supported() and accel.is_fp16_supported()
    assert jnp.bfloat16 in accel.supported_dtypes()
    assert accel.default_dtype() in accel.supported_dtypes()


def test_device_put_and_host_put_roundtrip(eight_devices):
    accel = CPU_Accelerator()
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    on_dev = accel.device_put(x, 1)
    assert accel.on_accelerator(on_dev)
    assert list(on_dev.devices())[0] == accel.device(1)
    back = accel.host_put(on_dev)
    np.testing.assert_array_equal(back, x)


def test_rng_seed_is_functional():
    accel = CPU_Accelerator()
    k1, k2 = accel.initial_seed(7), accel.initial_seed(7)
    np.testing.assert_array_equal(np.asarray(k1), np.asarray(k2))
    k3 = accel.initial_seed(8)
    assert not np.array_equal(np.asarray(k1), np.asarray(k3))


def test_op_builder_namespace_importable():
    import importlib
    for accel in (CPU_Accelerator(), TPU_Accelerator()):
        pkg = accel.op_builder_dir()
        assert importlib.import_module(pkg) is not None


def test_comm_backend_names_differ_by_platform():
    assert CPU_Accelerator().communication_backend_name() == "xla-host"
    assert TPU_Accelerator().communication_backend_name() == "xla-ici"
    assert not CPU_Accelerator().supports_pallas()
