"""Kernel-vs-reference numeric tests (reference pattern:
tests/unit/ops/adam/test_cpu_adam.py _compare_optimizers).

Pallas kernels run in interpreter mode on the CPU test mesh; numerics
must match the jnp reference to fp32 tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.pallas_kernels import (apply_rotary_pos_emb,
                                              flash_attention, mha_reference,
                                              rms_norm, rms_norm_reference,
                                              rope_cos_sin)


class TestFlashAttention:

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        rng = np.random.default_rng(0)
        B, T, H, D = 2, 256, 2, 128
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        out = flash_attention(q, k, v, causal=causal, interpret=True,
                              block_q=128, block_k=128)
        ref = mha_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_gqa_forward(self):
        rng = np.random.default_rng(1)
        B, T, Hq, Hkv, D = 1, 256, 4, 2, 128
        q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
        out = flash_attention(q, k, v, causal=True, interpret=True,
                              block_q=128, block_k=128)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, causal):
        rng = np.random.default_rng(2)
        B, T, H, D = 1, 256, 2, 128
        q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

        def loss_kernel(q, k, v):
            o = flash_attention(q, k, v, causal=causal, interpret=True,
                                block_q=128, block_k=128)
            return jnp.sum(o * o)

        def loss_ref(q, k, v):
            o = mha_reference(q, k, v, causal=causal)
            return jnp.sum(o * o)

        g_kernel = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gk, gr, name in zip(g_kernel, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_fallback_on_untiled_shapes(self):
        # odd T -> jnp reference path, still correct
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.standard_normal((1, 37, 2, 16)), jnp.float32)
        out = flash_attention(q, q, q, causal=True)
        ref = mha_reference(q, q, q, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_force_pallas_raises_on_untiled(self):
        q = jnp.zeros((1, 37, 2, 16), jnp.float32)
        with pytest.raises(ValueError, match="cannot tile"):
            flash_attention(q, q, q, force_pallas=True)

    def test_causal_decode_alignment(self):
        # Tq != Tk with causal: bottom-right aligned (kv-cache decode)
        rng = np.random.default_rng(4)
        B, Tq, Tk, H, D = 1, 128, 384, 2, 128
        q = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
        out = flash_attention(q, k, v, causal=True, interpret=True,
                              block_q=128, block_k=128)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_causal_decode_gradients(self):
        # bwd kernels with Tq != Tk exercise the offset-dependent bounds
        rng = np.random.default_rng(6)
        B, Tq, Tk, H, D = 1, 128, 384, 2, 128
        q = jnp.asarray(rng.standard_normal((B, Tq, H, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, Tk, H, D)), jnp.float32)

        gk = jax.grad(lambda q, k, v: jnp.sum(flash_attention(
            q, k, v, causal=True, interpret=True,
            block_q=128, block_k=128) ** 2), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            mha_reference(q, k, v, causal=True) ** 2),
            argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gk, gr, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")

    def test_gqa_gradients(self):
        rng = np.random.default_rng(5)
        B, T, Hq, Hkv, D = 1, 256, 4, 2, 128
        q = jnp.asarray(rng.standard_normal((B, T, Hq, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)
        v = jnp.asarray(rng.standard_normal((B, T, Hkv, D)), jnp.float32)

        def loss(fn):
            return lambda q, k, v: jnp.sum(
                fn(q, k, v) ** 2)

        g_kernel = jax.grad(
            loss(lambda q, k, v: flash_attention(
                q, k, v, causal=True, interpret=True,
                block_q=128, block_k=128)), argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            loss(lambda q, k, v: mha_reference(q, k, v, causal=True)),
            argnums=(0, 1, 2))(q, k, v)
        for gk, gr, name in zip(g_kernel, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(gk), np.asarray(gr),
                                       atol=5e-4, rtol=5e-4,
                                       err_msg=f"d{name} mismatch")


class TestRMSNorm:

    def test_forward(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 64, 256)), jnp.float32)
        w = jnp.asarray(rng.standard_normal((256,)), jnp.float32)
        out = rms_norm(x, w, interpret=True)
        ref = rms_norm_reference(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_gradients(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((8, 128)), jnp.float32)
        w = jnp.asarray(1.0 + 0.1 * rng.standard_normal((128,)), jnp.float32)

        gk = jax.grad(lambda x, w: jnp.sum(rms_norm(x, w, interpret=True) ** 2),
                      argnums=(0, 1))(x, w)
        gr = jax.grad(lambda x, w: jnp.sum(rms_norm_reference(x, w) ** 2),
                      argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gk[0]), np.asarray(gr[0]),
                                   atol=1e-4, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(gk[1]), np.asarray(gr[1]),
                                   atol=1e-4, rtol=1e-4)


class TestRope:

    def test_rotation_preserves_norm(self):
        rng = np.random.default_rng(0)
        T, H, D = 16, 2, 8
        x = jnp.asarray(rng.standard_normal((1, T, H, D)), jnp.float32)
        cos, sin = rope_cos_sin(jnp.arange(T), D)
        y = apply_rotary_pos_emb(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1), atol=1e-5, rtol=1e-5)

    def test_position_zero_is_identity(self):
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((1, 1, 2, 8)), jnp.float32)
        cos, sin = rope_cos_sin(jnp.zeros((1,)), 8)
        y = apply_rotary_pos_emb(x, cos, sin)
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=1e-6)

    def test_relative_property(self):
        # <rope(q,m), rope(k,n)> depends only on m-n
        rng = np.random.default_rng(2)
        D = 16
        q = jnp.asarray(rng.standard_normal((1, 1, 1, D)), jnp.float32)
        k = jnp.asarray(rng.standard_normal((1, 1, 1, D)), jnp.float32)

        def dot_at(m, n):
            cq, sq = rope_cos_sin(jnp.array([m], jnp.float32), D)
            ck, sk = rope_cos_sin(jnp.array([n], jnp.float32), D)
            qr = apply_rotary_pos_emb(q, cq, sq)
            kr = apply_rotary_pos_emb(k, ck, sk)
            return float(jnp.sum(qr * kr))

        assert abs(dot_at(5, 3) - dot_at(12, 10)) < 1e-4
